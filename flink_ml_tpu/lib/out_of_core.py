"""Out-of-core training: epochs stream source chunks through one compiled
per-chunk device program, with host parse/pack/transfer prefetched one chunk
ahead of device compute.

The reference trains on datasets no node holds by streaming partitions
through Flink's network stack (the partitioned CSV read in
examples-batch/.../LinearRegression.java:91-102); every prior path here
materialized the whole dataset on the host (VERDICT r02 gap #1).  The
TPU-first replacement:

  * a :class:`~flink_ml_tpu.table.sources.ChunkedTable` yields bounded
    chunk Tables from a (possibly sharded) file source — host residency is
    ~two chunks, never the dataset;
  * chunks are re-buffered into fixed blocks of ``steps_per_chunk`` global
    SGD steps and packed step-major (``pack_minibatches``), so the
    row->update-step mapping is *identical* to the in-memory fused run —
    out-of-core results bit-match in-memory results by construction, for
    any chunk size;
  * one ``jit(shard_map(lax.scan(...)))`` program advances
    ``(params, loss_sum, weight_sum)`` through a block; whole-pad steps
    (the tail of the final block) are gated no-ops;
  * a background thread parses/packs/places block N+1 while the device runs
    block N (JAX dispatch is async, so device compute, host parse, and
    host->device DMA overlap);
  * per-epoch loss/delta stay on device; with ``tol == 0`` the entire
    multi-epoch run syncs exactly once, at the final fetch.

Works on 1-D (data) and 2-D (data x model) meshes: by default the weight
pytree replicates; the feature-sharded 2-D configuration passes a
``param_spec``/``place_params`` pair so rows stream over ``data`` while the
weight vector stays sharded over ``model`` — Criteo-scale data and a
wider-than-one-chip model at once.
"""

from __future__ import annotations

import contextlib
import os
import warnings
from collections import deque
from typing import Callable, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu import fault, obs
from flink_ml_tpu.lib.common import (
    TrainResult,
    _cache_get,
    _cache_put,
    _combined_view,
    _meta_converged,
    fetch_flat,
    make_sgd_update,
    pack_minibatches,
    pack_sparse_minibatches,
)
from flink_ml_tpu.ops.batch import CsrRows
from flink_ml_tpu.parallel.collectives import psum, shard_map
from flink_ml_tpu.table.table import Table
from flink_ml_tpu.utils.metrics import StepMetrics


def make_chunk_step_fn(key, mb_grad_step, mesh, learning_rate: float, reg: float,
                       param_spec=None):
    """One chunk — a ``lax.scan`` over its minibatch groups — as a single
    compiled device call: ``chunk_fn(carry, batch) -> (carry, tick)`` with
    ``carry = (params, loss_sum, weight_sum)`` and ``tick`` a scalar the
    engine blocks on to bound the async pipeline.

    The minibatch math and SGD update are the exact objects the in-memory
    fused loop uses (``mb_grad_step``, :func:`make_sgd_update`), so a live
    step's update is bit-identical; a whole-pad step (``weight sum == 0``,
    only possible in the final block's tail) is gated to a no-op so padding
    can never apply an extra decay step.  ``param_spec`` overrides the
    replicated param placement (feature-sharded weights on the ``model``
    axis — the 2-D Criteo configuration).
    """
    cached = _cache_get(key)
    if cached is not None:
        return cached
    sgd_update = make_sgd_update(learning_rate, reg)

    def local_chunk(carry, batch):
        def mb_step(c, xs):
            p, loss_acc, w_acc = c
            grads, loss_sum, w_sum = mb_grad_step(p, xs)
            grads = jax.tree_util.tree_map(lambda g: psum(g, "data"), grads)
            loss_sum = psum(loss_sum, "data")
            w_sum = psum(w_sum, "data")
            count = jnp.maximum(w_sum, 1.0)
            new_p = sgd_update(p, grads, count)
            live = w_sum > 0.0
            new_p = jax.tree_util.tree_map(
                lambda a, b: jnp.where(live, a, b), new_p, p
            )
            # accumulators stay f32 regardless of param dtype (x64 resume)
            return (
                new_p,
                loss_acc + loss_sum.astype(loss_acc.dtype),
                w_acc + w_sum.astype(w_acc.dtype),
            ), None

        carry, _ = jax.lax.scan(mb_step, carry, batch)
        # the tick: a scalar the engine can block on to bound the async
        # pipeline.  optimization_barrier guarantees a distinct buffer —
        # a folded alias of carry[2] would be deleted by the next call's
        # donation, breaking the block_until_ready contract
        return carry, jax.lax.optimization_barrier(carry[2])

    from jax.sharding import PartitionSpec as P

    carry_spec = (param_spec if param_spec is not None else P(), P(), P())
    sharded = shard_map(
        local_chunk,
        mesh=mesh,
        in_specs=(carry_spec, P("data")),
        out_specs=(carry_spec, P()),
        check_vma=True,
    )
    return _cache_put(key, jax.jit(sharded, donate_argnums=(0,)))


@jax.jit
def _l2_delta(params, start):
    return jnp.sqrt(
        sum(
            jnp.sum((a - b) ** 2)
            for a, b in zip(
                jax.tree_util.tree_leaves(params),
                jax.tree_util.tree_leaves(start),
            )
        )
    )


def _block_rows(chunks: Iterator[Table], extract, rows_per_block: int):
    """Re-buffer arbitrary-size source chunks into exact ``rows_per_block``
    row blocks (the final block may be short).  ``extract(table) ->
    per-column host arrays/lists``; yields tuples of re-sliced columns.

    Source chunk boundaries need not align with block boundaries — the
    carry-over buffer here is what makes the update schedule independent of
    how the files happen to be cut.
    """
    buffers: Optional[list] = None
    have = 0
    for t in chunks:
        cols = extract(t)
        if buffers is None:
            buffers = [[] for _ in cols]
        for buf, col in zip(buffers, cols):
            buf.append(col)
        have += len(cols[-1])
        while have >= rows_per_block:
            joined = [_join(parts) for parts in buffers]
            head = [j[:rows_per_block] for j in joined]
            rest = [j[rows_per_block:] for j in joined]
            buffers = [[r] for r in rest]
            have -= rows_per_block
            yield tuple(head)
    if have:
        yield tuple(_join(parts) for parts in buffers)


def _join(parts: list):
    if len(parts) == 1:
        return parts[0]
    if all(isinstance(p, CsrRows) for p in parts):
        return CsrRows.concat(parts)
    if isinstance(parts[0], np.ndarray) and parts[0].dtype != object:
        return np.concatenate(parts)
    out = []
    for p in parts:
        out.extend(p)
    return out


def _prefetch(items: Iterator, depth: int = 2) -> Iterator:
    """Run an iterator on a background thread, ``depth`` items ahead.

    The producer packs a block and places it on the mesh (an async DMA), so
    host parse + pack + transfer of block N+1 overlap device compute of
    block N.  Exceptions re-raise at the consumer; when the consumer
    abandons the stream early, the producer thread is joined and a recorded
    producer exception surfaces as a RuntimeWarning instead of being
    silently dropped with the queue (the ONE shared implementation lives in
    :func:`flink_ml_tpu.utils.prefetch.prefetch_iter` — the slab pool's
    double-buffered placement uses the same idiom)."""
    from flink_ml_tpu.utils.prefetch import prefetch_iter

    return prefetch_iter(items, depth=depth, name="oo-prefetch")


_serialized_chunks_warned = False


def _warn_serialized_chunks_once():
    """One-time notice that the async chunk pipeline is serialized (gloo
    rendezvous livelock workaround on multi-process CPU); set
    FLINK_ML_TPU_ASYNC_CPU_CHUNKS=1 to keep the pipeline async."""
    global _serialized_chunks_warned
    if not _serialized_chunks_warned:
        _serialized_chunks_warned = True
        warnings.warn(
            "multi-process CPU backend: serializing out-of-core chunk "
            "programs to avoid a gloo in-process rendezvous livelock; "
            "set FLINK_ML_TPU_ASYNC_CPU_CHUNKS=1 to keep the async "
            "pipeline on",
            stacklevel=3,
        )


def train_out_of_core(
    init_params,
    blocks_factory: Callable[[], Iterator[Tuple]],
    chunk_fn_factory: Callable[[], Callable],
    mesh,
    max_iter: int,
    tol: float,
    checkpoint=None,
    make_carry: Optional[Callable] = None,
    finalize: Optional[Callable] = None,
    place_params: Optional[Callable] = None,
    max_inflight_chunks: int = 4,
    meta_extra: Optional[dict] = None,
    validate_meta: Optional[Callable[[dict], None]] = None,
) -> TrainResult:
    """The streaming epoch engine.

    ``blocks_factory()`` restarts the chunk stream for an epoch, yielding
    host ``(batch, n_real_rows)``; the prefetch thread places each block on
    the mesh (async DMA) while the device runs the previous one.
    ``chunk_fn_factory()`` returns the compiled chunk program
    (``chunk_fn(carry, batch) -> (carry, tick)``).  Convergence
    (update-norm vs ``tol``) and checkpoint/resume semantics mirror the
    fused in-memory loop; with ``tol == 0`` and no checkpoint, the whole
    run syncs once at the end.

    SGD-shaped algorithms use the default carry ``(params, loss_sum,
    weight_sum)`` updated per minibatch.  Accumulate-then-finalize
    algorithms (KMeans' Lloyd step) pass ``make_carry(params) -> carry``
    (fresh per-epoch accumulators) and ``finalize(carry, epoch_start) ->
    (params, loss_sum, weight_sum, delta)`` (the per-epoch reduction, e.g.
    centroid division), both running on device.  ``place_params`` overrides
    the default replicated placement (feature-sharded weights live on the
    ``model`` axis); the default delta/loss math operates on global arrays,
    so it is sharding-agnostic.

    ``max_inflight_chunks`` bounds the async pipeline depth: JAX dispatch
    returns before transfer or compute finishes, so without a bound every
    block of an epoch can pile up in flight (host staging + HBM for each).
    The consumer blocks on a chunk-completion tick N chunks back before
    dispatching chunk N, capping live-block residency at ~(prefetch depth +
    max_inflight) while keeping the device busy.  (Note: on the tunneled
    axon backend the client itself retains per-transfer buffers beyond
    array lifetime — measured growth with ZERO live jax arrays, absent on
    the CPU backend — so peak RSS there overstates what this engine holds.)
    """
    from flink_ml_tpu.parallel.mesh import replicate, shard_batch

    # cross-process chunk programs carry collectives; letting several run
    # concurrently on the CPU gloo backend intermittently livelocks its
    # in-process rendezvous (observed: both workers wedge mid-epoch with
    # all programs dispatched).  Serialize there: each chunk completes —
    # collectives included — before the next dispatches (prefetch still
    # overlaps host parse/pack with device compute).  Scoped to the CPU
    # backend: multihost TPU collectives run on per-core hardware queues
    # where concurrent in-flight programs are the designed norm, so the
    # async pipeline stays on for the production platform.
    # Escape hatch for intentional multi-process CPU deployments that do
    # not hit the gloo livelock: FLINK_ML_TPU_ASYNC_CPU_CHUNKS=1 keeps the
    # async pipeline on.
    serialize_chunks = (
        jax.process_count() > 1
        and jax.default_backend() == "cpu"
        and os.environ.get("FLINK_ML_TPU_ASYNC_CPU_CHUNKS", "0") != "1"
    )
    if serialize_chunks:
        _warn_serialized_chunks_once()

    start_epoch = 0
    losses: list = []
    if checkpoint is not None:
        from flink_ml_tpu.iteration.checkpoint import (
            agreed_latest_checkpoint,
            load_checkpoint,
        )

        latest = agreed_latest_checkpoint(checkpoint.directory)
        if latest is not None:
            init_params, meta = load_checkpoint(latest, like=init_params)
            if validate_meta is not None:
                # the caller's chance to reject a checkpoint whose params
                # encode a configuration-dependent representation (e.g. the
                # hot/cold permuted layout) that no longer matches — a
                # shape-compatible mismatch would otherwise resume silently
                # wrong
                validate_meta(meta)
            start_epoch = int(meta["epoch"]) + 1
            losses = list(meta.get("losses", []))
            if _meta_converged(meta, tol) or start_epoch >= max_iter:
                delta = meta.get("final_delta")
                return TrainResult(
                    params=init_params, epochs=start_epoch, losses=losses,
                    final_delta=None if delta is None else float(delta),
                )

    metrics = StepMetrics("stream_train")
    metrics.start_step()
    params = (
        place_params(init_params) if place_params is not None
        else replicate(mesh, init_params)
    )
    params = jax.tree_util.tree_map(
        lambda p, o: jnp.copy(p) if isinstance(o, jax.Array) else p,
        params, init_params,
    )
    chunk_fn = chunk_fn_factory()
    pending: list = []  # (loss_sum, weight_sum) device scalars per epoch
    last_delta_dev = None
    total_rows = 0
    final_delta: Optional[float] = None
    epoch = start_epoch
    converged = False
    # checkpointed runs catch SIGTERM for the duration of the loop: the
    # flag is polled at epoch boundaries (the only points bit-identical to
    # an uninterrupted run), an emergency snapshot commits, and the process
    # exits cleanly for the existing resume path to continue
    scope = (
        fault.preemption_scope() if checkpoint is not None
        else contextlib.nullcontext()
    )
    with scope:
        while epoch < max_iter and not converged:
            epoch_start = jax.tree_util.tree_map(jnp.copy, params)
            # fresh accumulators every epoch: the chunk program donates its
            # carry, so a reused zero scalar would be a deleted buffer
            if make_carry is not None:
                carry = make_carry(params)
            else:
                carry = (params, jnp.zeros((), dtype=jnp.float32),
                         jnp.zeros((), dtype=jnp.float32))
            n_rows = 0

            def placed_blocks():
                from flink_ml_tpu.fault.retry import with_retry

                for batch, real in blocks_factory():
                    # per-block H2D placement is a transient-failure
                    # surface (device blips, injected chaos): retried with
                    # backoff so one hiccup doesn't abort the epoch
                    placed = with_retry(
                        lambda b=batch: shard_batch(mesh, b), "ooc.place"
                    )
                    yield placed, real

            inflight: deque = deque()
            for placed, real_rows in _prefetch(placed_blocks()):
                carry, tick = chunk_fn(carry, placed)
                n_rows += real_rows
                if serialize_chunks:
                    jax.block_until_ready(tick)
                    continue
                inflight.append(tick)
                if len(inflight) > max_inflight_chunks:
                    jax.block_until_ready(inflight.popleft())
            inflight.clear()
            if finalize is not None:
                params, loss_sum, w_sum, last_delta_dev = finalize(
                    carry, epoch_start
                )
            else:
                params, loss_sum, w_sum = carry
                last_delta_dev = _l2_delta(params, epoch_start)
            pending.append((loss_sum, w_sum))
            total_rows += n_rows
            epoch += 1
            obs.counter_add("train.ooc_epochs")
            obs.counter_add("train.ooc_rows", n_rows)
            if tol > 0.0:
                final_delta = float(last_delta_dev)  # per-epoch sync tol demands
                converged = final_delta <= tol
            # a run that just FINISHED (converged or out of epochs) at this
            # boundary returns its result instead of exiting for resume —
            # same rule as run_chunked_checkpoint's epilogue
            preempt_now = (
                checkpoint is not None and fault.preempted()
                and not converged and epoch < max_iter
            )
            at_boundary = checkpoint is not None and (
                (epoch - start_epoch) % checkpoint.every_n_epochs == 0
                or epoch == max_iter or converged
            )
            if at_boundary or preempt_now:
                from flink_ml_tpu.iteration.checkpoint import (
                    prune_checkpoints,
                    save_checkpoint,
                )

                losses.extend(_drain_pending(pending))
                leaves, treedef = jax.tree_util.tree_flatten(params)
                host_leaves = fetch_flat(*leaves)
                host_params = jax.tree_util.tree_unflatten(
                    treedef, host_leaves
                )
                # health BEFORE the snapshot: the latest checkpoint must
                # always be the last GOOD state, or the guard's rollback
                # would resume straight back into the divergence
                fault.check_health(
                    losses, host_leaves, where="stream_train"
                )

                def _snapshot():
                    save_checkpoint(
                        checkpoint.directory, epoch - 1, host_params,
                        meta={"losses": losses, "converged": converged,
                              "tol": tol, "final_delta": final_delta,
                              **(meta_extra or {})},
                    )
                    prune_checkpoints(checkpoint.directory, checkpoint.keep)

                if preempt_now:
                    fault.emergency_save(_snapshot)  # raises Preempted
                _snapshot()

    losses.extend(_drain_pending(pending))
    leaves, treedef = jax.tree_util.tree_flatten(params)
    if final_delta is None and last_delta_dev is not None:
        fetched = fetch_flat(*leaves, last_delta_dev)
        final_delta = float(fetched[-1])
        host_leaves = fetched[: len(leaves)]
    else:
        host_leaves = fetch_flat(*leaves)
    host_params = jax.tree_util.tree_unflatten(treedef, host_leaves)
    fault.check_health(losses, host_leaves, final_delta, where="stream_train")
    metrics.end_step(
        samples=total_rows, epochs=epoch - start_epoch,
        loss=losses[-1] if losses else 0.0,
    )
    return TrainResult(
        params=host_params, epochs=epoch, losses=losses,
        final_delta=final_delta, metrics=metrics,
    )


def _drain_pending(pending: list):
    """Fetch the per-epoch (loss, weight) device scalars accumulated so far
    and clear the list; returns the epoch mean losses."""
    if not pending:
        return []
    flat = []
    for loss_sum, w_sum in pending:
        flat.extend((loss_sum, w_sum))
    fetched = fetch_flat(*flat)
    out = []
    for i in range(0, len(fetched), 2):
        loss_sum, w_sum = float(fetched[i]), float(fetched[i + 1])
        out.append(loss_sum / max(w_sum, 1.0))
    pending.clear()
    return out


# -- block builders -----------------------------------------------------------


def _pad_stream_to(blocks: Iterator[Tuple], pad_to_blocks: Optional[int],
                   make_empty: Callable[[], Tuple]):
    """Append empty no-op blocks to a block stream up to the agreed
    per-epoch count — the ONE copy of the multi-process padding tail every
    block factory wraps its generator with.  ``make_empty()`` builds the
    (reusable) all-pad block lazily, after the stream pinned any
    data-derived shape it needs."""
    emitted = 0
    for item in blocks:
        yield item
        emitted += 1
    if pad_to_blocks is not None and emitted < pad_to_blocks:
        empty = make_empty()
        for _ in range(pad_to_blocks - emitted):
            yield empty, 0


def count_stream_rows(chunked_table) -> int:
    """Row count of a chunk stream — the dense multi-process pre-pass
    (the per-epoch block count must agree across processes; sparse fits
    get the count from their layout scan, dense fits only need this)."""
    n = 0
    chunks = chunked_table.chunks()
    try:
        for t in chunks:
            n += t.num_rows()
    finally:
        close = getattr(chunks, "close", None)
        if close is not None:
            close()
    return n


def dense_blocks_factory(
    chunked_table,
    extract: Callable[[Table], Tuple[np.ndarray, np.ndarray]],
    n_dev: int,
    mb: int,
    steps_per_chunk: int,
    pad_to_blocks: Optional[int] = None,
    pad_dim: Optional[int] = None,
):
    """Blocks of ``steps_per_chunk`` global steps in the combined dense
    layout, packed step-major; yields host ``(batch, n_rows)`` (the engine's
    prefetch thread does the mesh placement).  ``pad_to_blocks`` appends
    all-pad blocks (zero weight — the chunk program's live gate makes
    their steps exact no-ops) up to the agreed multi-process per-epoch
    count; ``pad_dim`` is the feature width for those pads."""
    rows_per_block = steps_per_chunk * mb * n_dev

    def factory():
        seen_dim = [pad_dim]

        def gen():
            for X, y in _block_rows(
                chunked_table.chunks(), extract, rows_per_block
            ):
                X = np.asarray(X)
                y = np.asarray(y)
                seen_dim[0] = X.shape[1]
                stack = pack_minibatches(
                    X, y, n_dev, global_batch_size=mb * n_dev,
                    min_steps=steps_per_chunk,
                )
                yield _combined_view(stack), stack.n_rows

        def make_empty():
            if seen_dim[0] is None:
                raise ValueError(
                    "cannot pad an empty stream to the agreed block "
                    "count without a known feature width"
                )
            return np.zeros(
                (n_dev * steps_per_chunk, mb, seen_dim[0] + 2),
                dtype=np.float32,
            )

        return _pad_stream_to(gen(), pad_to_blocks, make_empty)

    return factory


def _pack_sparse_block(vectors, y, n_dev: int, mb: int,
                       steps_per_chunk: int, dim: int, nnz_pad: int):
    """Pack one streamed block into the segment-CSR layout with the
    stream-wide fixed ``nnz_pad`` — the shared prologue of the sparse and
    hot/cold block factories.  A block denser than ``nnz_pad`` fails
    loudly rather than silently recompiling per block."""
    if not isinstance(vectors, CsrRows):
        vectors = list(vectors)
    stack = pack_sparse_minibatches(
        vectors, np.asarray(y), n_dev,
        global_batch_size=mb * n_dev, dim=dim,
        min_nnz_pad=nnz_pad, min_steps=steps_per_chunk,
    )
    if stack.nnz_pad != nnz_pad:
        raise ValueError(
            f"a minibatch holds {stack.nnz_pad} nnz > the configured "
            f"nnz_pad={nnz_pad}; raise nnz_pad (or lower the batch size) "
            f"so one compiled program covers the stream"
        )
    return stack


def _empty_sparse_block(n_groups: int, mb: int, nnz_pad: int):
    """An all-pad segment-CSR block (zero live rows): every entry carries
    the pad row id ``mb``, every weight is zero.  The chunk program's
    ``live = w_sum > 0`` gate makes its steps exact no-ops (no update, no
    decay) — the multi-process filler for shards with fewer blocks than
    the agreed per-epoch count (every process must dispatch the same
    number of collective chunk calls or the mesh hangs)."""
    ints = np.zeros((n_groups, 2, nnz_pad), dtype=np.int32)
    ints[:, 1, :] = mb
    floats = np.zeros((n_groups, nnz_pad + 2 * mb), dtype=np.float32)
    return ints, floats


def sparse_blocks_factory(
    chunked_table,
    extract: Callable[[Table], Tuple[list, np.ndarray]],
    n_dev: int,
    mb: int,
    steps_per_chunk: int,
    dim: int,
    nnz_pad: int,
    pad_to_blocks: Optional[int] = None,
):
    """Sparse counterpart: blocks in the segment-CSR layout with a fixed
    ``nnz_pad`` so every block reuses one compiled program (sizing via
    ``estimate_nnz_pad``, or :func:`scan_sparse_stream` + ``agree_max``
    multi-process; see :func:`_pack_sparse_block`).  ``pad_to_blocks``
    appends empty no-op blocks up to the agreed per-epoch count."""
    rows_per_block = steps_per_chunk * mb * n_dev

    def factory():
        def gen():
            for vectors, y in _block_rows(
                chunked_table.chunks(), extract, rows_per_block
            ):
                stack = _pack_sparse_block(
                    vectors, y, n_dev, mb, steps_per_chunk, dim, nnz_pad
                )
                yield (stack.ints, stack.floats), stack.n_rows

        return _pad_stream_to(
            gen(), pad_to_blocks,
            lambda: _empty_sparse_block(n_dev * steps_per_chunk, mb, nnz_pad),
        )

    return factory


def rows_blocks_factory(
    chunked_table,
    extract: Callable[[Table], Tuple[np.ndarray]],
    n_dev: int,
    rows_per_block: int,
    pad_to_blocks: Optional[int] = None,
    pad_dim: Optional[int] = None,
):
    """Plain padded row blocks ``(X, w)`` for whole-batch epoch algorithms
    (KMeans' Lloyd step): every block has exactly ``rows_per_block`` rows
    (multiple of ``n_dev``; the final block zero-weight-pads), so one
    compiled program covers the stream.  ``pad_to_blocks`` appends
    all-zero-weight blocks up to the agreed per-epoch count (multi-process
    short shards; zero-weight rows contribute nothing to the Lloyd
    accumulators exactly); ``pad_dim`` supplies the feature width when the
    local stream could be empty."""
    if rows_per_block % n_dev:
        raise ValueError("rows_per_block must be a multiple of n_dev")

    def factory():
        seen_dim = [pad_dim]

        def gen():
            for (X,) in _block_rows(
                chunked_table.chunks(), extract, rows_per_block
            ):
                X = np.asarray(X, dtype=np.float32)
                seen_dim[0] = X.shape[1]
                n = X.shape[0]
                Xp = np.zeros((rows_per_block, X.shape[1]), dtype=np.float32)
                wp = np.zeros((rows_per_block,), dtype=np.float32)
                Xp[:n] = X
                wp[:n] = 1.0
                yield (Xp, wp), n

        def make_empty():
            if seen_dim[0] is None:
                raise ValueError(
                    "cannot pad an empty stream to the agreed block "
                    "count without a known feature width"
                )
            return (
                np.zeros((rows_per_block, seen_dim[0]), dtype=np.float32),
                np.zeros((rows_per_block,), dtype=np.float32),
            )

        return _pad_stream_to(gen(), pad_to_blocks, make_empty)

    return factory


def make_kmeans_chunk_fn(key, k: int, mesh):
    """Lloyd accumulation over one row block as a compiled device call:
    ``chunk_fn(carry, (x, w)) -> (carry, tick)`` with ``carry = (centroids,
    sums, counts, cost)``.  Assignments are against the epoch's centroids
    (held fixed in the carry); per-cluster sums/counts/cost ``psum`` over
    the data axis and accumulate across blocks; the per-epoch centroid
    division happens in :func:`kmeans_finalize`.  Zero-weight padding rows
    contribute nothing exactly."""
    cached = _cache_get(key)
    if cached is not None:
        return cached

    def local_chunk(carry, batch):
        from flink_ml_tpu.lib.clustering import _pairwise_sq_dists

        c, sums, counts, cost = carry
        x, w = batch  # local shard: (rows_local, d), (rows_local,)
        d = _pairwise_sq_dists(x, c)
        assign = jnp.argmin(d, axis=1)
        cost = cost + psum(jnp.sum(jnp.min(d, axis=1) * w), "data")
        sums = sums + psum(
            jax.ops.segment_sum(x * w[:, None], assign, num_segments=k), "data"
        )
        counts = counts + psum(
            jax.ops.segment_sum(w, assign, num_segments=k), "data"
        )
        # tick: distinct buffer by construction (see make_chunk_step_fn)
        return (c, sums, counts, cost), jax.lax.optimization_barrier(cost)

    from jax.sharding import PartitionSpec as P

    sharded = shard_map(
        local_chunk,
        mesh=mesh,
        in_specs=(P(), P("data")),
        out_specs=(P(), P()),
        check_vma=True,
    )
    return _cache_put(key, jax.jit(sharded, donate_argnums=(0,)))


def kmeans_make_carry(centroids):
    """Fresh per-epoch Lloyd accumulators (sums, counts, cost)."""
    k, d = centroids.shape
    return (
        centroids,
        jnp.zeros((k, d), dtype=jnp.float32),
        jnp.zeros((k,), dtype=jnp.float32),
        jnp.zeros((), dtype=jnp.float32),
    )


@jax.jit
def kmeans_finalize(carry, epoch_start):
    """Per-epoch Lloyd reduction: divide sums by counts (empty clusters
    keep their previous centroid), centroid-shift norm for convergence.
    Returns the engine's ``(params, loss_sum, weight_sum, delta)``; the
    weight of 1 makes the drained epoch loss the total cost, matching the
    in-memory fused path."""
    c, sums, counts, cost = carry
    new_c = jnp.where(
        counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0), c
    )
    delta = jnp.sqrt(jnp.sum((new_c - epoch_start) ** 2))
    return new_c, cost, jnp.ones((), dtype=jnp.float32), delta


@contextlib.contextmanager
def maybe_spill(blocks_factory, enabled: bool):
    """Wrap a block factory in a :class:`BlockSpill` with a per-fit
    temporary directory, cleaned up on exit.  The single spill lifecycle
    shared by every out-of-core estimator; a no-op when ``enabled`` is
    false (single-epoch fits have no later epoch to amortize the disk
    copy)."""
    if not enabled:
        yield blocks_factory
        return
    import tempfile

    spill = BlockSpill(tempfile.mkdtemp(prefix="fmt_spill_"))
    try:
        yield spill.wrap(blocks_factory)
    finally:
        spill.close()


def reservoir_sample_rows(chunks: Iterator[Table], extract, cap: int, rng,
                          allow_empty: bool = False):
    """Uniform sample of ``cap`` rows over a chunk stream (vectorized
    Algorithm R), plus the true row count.

    The out-of-core replacement for ``rng.choice`` over a materialized
    array: one pass, O(cap) memory.  When the stream holds <= cap rows the
    sample IS the dataset (in order).  Used for k-means++ seeding, where
    the in-memory path draws a uniform subsample — a stream-head sample
    would bias the init toward the file's leading rows whenever the data
    is sorted or grouped.
    """
    sample: Optional[np.ndarray] = None
    filled = 0
    seen = 0
    for t in chunks:
        (X,) = extract(t)
        X = np.asarray(X)
        m = X.shape[0]
        if sample is None:
            sample = np.empty((cap, X.shape[1]), dtype=X.dtype)
        take = min(m, cap - filled)
        if take > 0:
            sample[filled : filled + take] = X[:take]
            filled += take
        if take < m:
            rest = X[take:]
            # row with global index i replaces a slot with prob cap/(i+1)
            idx = np.arange(seen + take, seen + m)
            j = (rng.random_sample(rest.shape[0]) * (idx + 1)).astype(np.int64)
            hit = j < cap
            sample[j[hit]] = rest[hit]
        seen += m
    if sample is None:
        if allow_empty:
            # multi-process: an empty local shard is legal — the caller
            # still owes its collectives, so it must not raise unilaterally
            return np.zeros((0, 0), dtype=np.float64), 0
        raise ValueError("empty source")
    return sample[:filled] if filled < cap else sample, seen


class _Crc32Writer:
    """File wrapper that CRCs and counts every byte as ``np.save`` streams
    it — the sidecar commit record in the SAME pass as the write.  Reading
    the file back to checksum it would double the save epoch's I/O, and
    spill-scale data is by definition too large for the page cache to
    absorb the second pass."""

    def __init__(self, f):
        self._f = f
        self.crc = 0
        self.size = 0

    def write(self, b):
        import zlib

        self.crc = zlib.crc32(b, self.crc)
        self.size += len(b)
        return self._f.write(b)

    def __getattr__(self, name):  # tell/seek/flush pass through
        return getattr(self._f, name)


class BlockSpill:
    """Parse once, stream binary thereafter — in final packed layout.

    Text parsing (CSV/LibSVM) is orders of magnitude slower than the device
    program, so re-parsing the source every epoch leaves the chip idle.
    Wrapping a host-block factory in a BlockSpill writes each packed
    block's leaves as raw ``.npy`` files during the first epoch; later
    epochs hand the device memory-MAPPED views of those files — the blocks
    are spilled in the exact layout the chunk program consumes, so a
    steady epoch does no repacking and no zip-layer copy (``np.load`` of
    an ``.npz`` streams every byte through the zip reader — measured ~1
    GB/s, slower than the chunk compute itself; a page-cache-warm mmap is
    a no-op until ``device_put`` pulls the pages, one copy total).  Host
    memory stays bounded at one block of pages; disk pays one packed copy
    of the dataset (the same trade Flink's runtime makes when it spills
    partitions to local disk between supersteps).

    The spill directory is owned by the caller and deleted via ``close()``
    (the estimator uses a per-fit temporary directory).

    **Fault tolerance** (PR 3): every block carries a sidecar
    ``block-NNNNNN.meta.json`` recording each leaf file's on-disk length
    and CRC32, written AFTER the leaf files as the block's commit record.
    Replay epochs validate the sidecars first — lengths every epoch (a
    handful of stats), checksums once per file (the first replay pays one
    extra read of pages ``device_put`` was about to pull anyway) — and a
    corrupted or truncated block downgrades the epoch to a transparent
    rebuild from the source factory instead of feeding the device garbage
    or crashing.  An INTERRUPTED first epoch (exception mid-save, a
    preemption) leaves ``complete=False`` with orphan block files on
    disk; the next wrap restarts the save cleanly — stale blocks from the
    dead attempt are truncated first, so a shorter re-run can never
    replay a longer dead run's tail.  Block writes and replay opens ride
    the transient-I/O retry policy (``fault.retry``).
    """

    def __init__(self, directory: str):
        import os

        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.complete = False
        self._meta: list = []  # (n_rows, n_leaves) per block
        self._treedef = None
        self._crc_checked = False  # first replay verifies checksums once

    def wrap(self, factory: Callable[[], Iterator]) -> Callable[[], Iterator]:
        def wrapped():
            if self.complete:
                if self._validate():
                    return self._load_iter()
                # corrupted/truncated spill: degrade to a rebuild from
                # the source, never crash the epoch (the factory is the
                # durable truth; the spill is just its binary cache)
                obs.counter_add("fault.spill_rebuilds")
                warnings.warn(
                    "spill block validation failed (corrupted or "
                    "truncated block files); rebuilding the spill from "
                    "the source factory for this epoch",
                    RuntimeWarning,
                    stacklevel=2,
                )
            return self._save_iter(factory())

        return wrapped

    def _path(self, i: int, j: int) -> str:
        import os

        return os.path.join(self.directory, f"block-{i:06d}-{j:03d}.npy")

    def _block_meta_path(self, i: int) -> str:
        import os

        return os.path.join(self.directory, f"block-{i:06d}.meta.json")

    def _reset_partial(self):
        """Truncate every artifact of a dead or invalid save attempt so
        the restarted save starts from a clean directory — re-wrapping
        after a mid-iteration failure must never interleave two attempts'
        blocks (the old attempt may have written MORE blocks than the new
        one will)."""
        import os

        self.complete = False
        self._meta.clear()
        self._crc_checked = False
        for name in os.listdir(self.directory):
            if name.startswith("block-"):
                try:
                    os.remove(os.path.join(self.directory, name))
                except OSError:
                    pass  # best effort; a leftover .tmp never replays

    def _save_iter(self, items: Iterator):
        import json
        import os

        from flink_ml_tpu.fault.injection import maybe_fail
        from flink_ml_tpu.fault.retry import with_retry

        self._reset_partial()
        i = 0
        for batch, n_rows in items:
            with obs.phase("spill.write_block"):
                leaves, treedef = jax.tree_util.tree_flatten(batch)
                self._treedef = treedef
                nbytes = 0
                leaf_meta = []
                for j, x in enumerate(leaves):
                    arr = np.asarray(x)
                    p = self._path(i, j)

                    def write(p=p, arr=arr):
                        # tmp + rename atomicity with the CRC computed in
                        # the same pass the bytes are written
                        maybe_fail("spill.write")
                        tmp = p + ".tmp"
                        with open(tmp, "wb") as f:
                            w = _Crc32Writer(f)
                            np.save(w, arr)
                            stats = {"size": w.size, "crc32": w.crc}
                        os.replace(tmp, p)
                        return stats

                    leaf_meta.append(with_retry(write, "spill.write"))
                    nbytes += arr.nbytes
                # sidecar last: the block's commit record (a crash between
                # leaf writes leaves no sidecar -> validation fails -> the
                # next wrap rebuilds); rides the same transient-I/O retry
                # as the leaf writes it commits
                def write_sidecar(i=i, n_rows=n_rows, leaf_meta=leaf_meta):
                    mp = self._block_meta_path(i)
                    with open(mp + ".tmp", "w") as f:
                        json.dump(
                            {"n_rows": int(n_rows), "leaves": leaf_meta}, f
                        )
                    os.replace(mp + ".tmp", mp)

                with_retry(write_sidecar, "spill.write")
            obs.counter_add("spill.blocks_written")
            obs.counter_add("spill.bytes_written", nbytes)
            self._meta.append((int(n_rows), len(leaves)))
            i += 1
            yield batch, n_rows
        self.complete = True

    def _validate(self) -> bool:
        """Do the on-disk blocks still match their commit records?

        Lengths are checked every replay (cheap stats); CRCs once, on the
        first replay (one extra read of pages the same epoch was about to
        pull through ``device_put`` anyway).  Any mismatch — or an
        injected ``spill.read`` fault — reports the spill as corrupt."""
        import json
        import os
        import zlib

        from flink_ml_tpu.fault.injection import InjectedFault, maybe_fail

        try:
            for i, (n_rows, n_leaves) in enumerate(self._meta):
                maybe_fail("spill.read")
                with open(self._block_meta_path(i)) as f:
                    side = json.load(f)
                if side["n_rows"] != n_rows or len(side["leaves"]) != n_leaves:
                    return False
                for j, leaf in enumerate(side["leaves"]):
                    p = self._path(i, j)
                    if os.path.getsize(p) != leaf["size"]:
                        return False
                    if not self._crc_checked:
                        # streamed CRC: one whole-file read() would spike
                        # host RSS by the largest leaf — spill-scale data
                        # is exactly what must not be materialized at once
                        crc = 0
                        with open(p, "rb") as f:
                            for chunk in iter(lambda: f.read(1 << 20), b""):
                                crc = zlib.crc32(chunk, crc)
                        if crc != leaf["crc32"]:
                            return False
        except (OSError, ValueError, KeyError, InjectedFault):
            return False
        self._crc_checked = True
        return True

    def _load_iter(self):
        from flink_ml_tpu.fault.retry import with_retry

        for i, (n_rows, n_leaves) in enumerate(self._meta):
            leaves = [
                with_retry(
                    lambda p=self._path(i, j): np.load(p, mmap_mode="r"),
                    "spill.read",
                )
                for j in range(n_leaves)
            ]
            obs.counter_add("spill.blocks_replayed")
            yield jax.tree_util.tree_unflatten(self._treedef, leaves), n_rows

    def close(self):
        import shutil

        # removes committed blocks AND any partial-save leftovers (.tmp
        # staging files, orphan leaves of an interrupted attempt)
        shutil.rmtree(self.directory, ignore_errors=True)
        self.complete = False
        self._meta.clear()


def scan_sparse_stream(chunked_table, vector_col: str, mb: int,
                       pad_multiple: int = 512,
                       count_dim: Optional[int] = None):
    """One full pass over the stream: (exact nnz_pad, total rows[, counts]).

    The multi-process replacement for :func:`estimate_nnz_pad`'s
    sampled+safety heuristic — processes must agree on EXACT block shapes,
    so each scans its whole shard (window max over the mb-aligned row
    windows the packer budgets; block boundaries are mb-aligned, so the
    window set equals the packer's group set) and ``agree_max`` reconciles
    the results.  Also the row count, from which the per-epoch block count
    derives (short shards pad their epochs with empty no-op blocks).

    ``count_dim`` additionally accumulates the per-feature frequency
    vector in the SAME pass (the hot/cold selection input) — out-of-core
    means every pass is a full disk/network read, so the hot/cold
    multi-process path must not pay two."""
    worst = 1
    n_rows = 0
    carry = np.zeros((0,), dtype=np.int64)  # partial trailing mb-window
    freq = (
        np.zeros((count_dim,), dtype=np.int64)
        if count_dim is not None else None
    )
    from flink_ml_tpu.lib.common import sparse_row_counts

    chunks = chunked_table.chunks()
    try:
        for t in chunks:
            col = t.col(vector_col)
            counts = sparse_row_counts(col)
            if freq is not None:
                if isinstance(col, CsrRows):
                    idx = col.indices
                else:
                    idx = np.concatenate(
                        [v.indices for v in col]
                    ) if len(col) else np.zeros((0,), np.int64)
                if idx.size and (
                    int(idx.min()) < 0 or int(idx.max()) >= count_dim
                ):
                    raise ValueError(
                        "feature index out of range for "
                        f"numFeatures={count_dim}"
                    )
                freq += np.bincount(idx, minlength=count_dim)
            n_rows += len(counts)
            arr = np.concatenate([carry, np.asarray(counts, np.int64)])
            n_full = len(arr) // mb
            if n_full:
                sums = arr[: n_full * mb].reshape(n_full, mb).sum(axis=1)
                worst = max(worst, int(sums.max()))
            carry = arr[n_full * mb:]
    finally:
        close = getattr(chunks, "close", None)
        if close is not None:
            close()
    if carry.size:
        worst = max(worst, int(carry.sum()))
    nnz_pad = -(-worst // pad_multiple) * pad_multiple
    if freq is not None:
        return nnz_pad, n_rows, freq
    return nnz_pad, n_rows


def hotcold_blocks_factory(
    chunked_table,
    extract: Callable[[Table], Tuple[list, np.ndarray]],
    n_dev: int,
    mb: int,
    steps_per_chunk: int,
    dim: int,
    nnz_pad: int,
    hot_k: int,
    feature_plan: dict,
    pad_to_blocks: Optional[int] = None,
):
    """Hot/cold counterpart of :func:`sparse_blocks_factory`: each block
    packs to the segment-CSR layout, then splits into (hot ints, hot vals,
    cold ints, cold floats) using the stream-wide ``feature_plan`` (one
    permutation for the whole fit) with BOTH pads fixed at ``nnz_pad`` —
    a group's hot (or cold) entries can never exceed its total entries, so
    the ceiling is safe and every block reuses one compiled program.  Cold
    ids are in PERMUTED space; the chunk program's weight vector lives
    there too."""
    from flink_ml_tpu.lib.common import split_hot_cold

    rows_per_block = steps_per_chunk * mb * n_dev

    def factory():
        def gen():
            for vectors, y in _block_rows(
                chunked_table.chunks(), extract, rows_per_block
            ):
                stack = _pack_sparse_block(
                    vectors, y, n_dev, mb, steps_per_chunk, dim, nnz_pad
                )
                h = split_hot_cold(
                    stack, hot_k, feature_plan=feature_plan,
                    min_hot_pad=nnz_pad, min_cold_pad=nnz_pad,
                )
                if (h.hot_ints.shape[2] != nnz_pad
                        or h.cold.nnz_pad != nnz_pad):
                    # only possible when nnz_pad is not pad-multiple-aligned
                    raise ValueError(
                        f"hot/cold block pads ({h.hot_ints.shape[2]}, "
                        f"{h.cold.nnz_pad}) diverged from nnz_pad="
                        f"{nnz_pad}; nnz_pad must be a pad-multiple-"
                        "aligned ceiling"
                    )
                yield (
                    (h.hot_ints, h.hot_vals, h.cold.ints, h.cold.floats),
                    stack.n_rows,
                )

        def make_empty():
            n_groups = n_dev * steps_per_chunk
            ci, cf = _empty_sparse_block(n_groups, mb, nnz_pad)
            hi = np.zeros((n_groups, 2, nnz_pad), dtype=np.int32)
            hi[:, 1, :] = mb  # pad rows -> the scatter sink row
            hv = np.zeros((n_groups, nnz_pad), dtype=np.float32)
            return hi, hv, ci, cf

        return _pad_stream_to(gen(), pad_to_blocks, make_empty)

    return factory


def estimate_nnz_pad(
    chunked_table, vector_col: str, mb: int, n_dev: int,
    pad_multiple: int = 512, sample_chunks: int = 2, safety: float = 1.5,
) -> int:
    """Size the per-minibatch nnz budget from the stream's head.

    Reads ``sample_chunks`` chunks, takes the max nnz over the mb-row
    per-device minibatch windows (the unit ``pack_sparse_minibatches``
    budgets — step-major groups start at mb-row boundaries), and pads by
    ``safety`` then up to ``pad_multiple``.  For Criteo-style fixed-slots
    data (constant nnz per row) the estimate is exact; for skewed data a
    denser later block fails loudly in :func:`sparse_blocks_factory` and
    the caller re-fits with a bigger pad.
    """
    del n_dev  # the window is per-device (mb rows), not per-step (mb*n_dev)
    worst = 1
    chunks = chunked_table.chunks()
    counts: list = []
    try:
        for _ in range(sample_chunks):
            t = next(chunks, None)
            if t is None:
                break
            col = t.col(vector_col)
            if isinstance(col, CsrRows):
                counts.extend(col.nnz_per_row().tolist())
            else:
                for v in col:
                    counts.append(len(v.indices))
    finally:
        close = getattr(chunks, "close", None)
        if close is not None:
            close()
    if not counts:
        raise ValueError("empty source: cannot size the sparse layout")
    counts_arr = np.asarray(counts, dtype=np.int64)
    for lo in range(0, len(counts_arr), mb):
        worst = max(worst, int(counts_arr[lo : lo + mb].sum()))
    padded = int(np.ceil(worst * safety))
    return -(-padded // pad_multiple) * pad_multiple
