"""Algorithm hyper-parameter vocabulary.

Extends the shared column mixins (params/shared.py, cf.
flink-ml-lib/.../params/shared/) with the training hyper-parameters the
BASELINE workloads need.  Same mixin pattern as the reference
(HasSelectedCol.java:33-47): one ParamInfo class attribute + typed accessors
per interface, composable by inheritance.
"""

from __future__ import annotations

from flink_ml_tpu.params.params import ParamInfo, WithParams, param_info


class HasLabelCol(WithParams):
    LABEL_COL: ParamInfo = param_info(
        "labelCol", "Name of the label column.", default="label", value_type=str,
    )

    def get_label_col(self) -> str:
        return self.get(self.LABEL_COL)

    def set_label_col(self, value: str):
        return self.set(self.LABEL_COL, value)


class HasVectorColDefaultAsNull(WithParams):
    VECTOR_COL: ParamInfo = param_info(
        "vectorCol", "Name of a vector column holding the features.",
        default=None, value_type=str,
    )

    def get_vector_col(self):
        return self.get(self.VECTOR_COL)

    def set_vector_col(self, value: str):
        return self.set(self.VECTOR_COL, value)


class HasFeatureColsDefaultAsNull(WithParams):
    FEATURE_COLS: ParamInfo = param_info(
        "featureCols", "Names of numeric feature columns.",
        default=None, value_type=list,
    )

    def get_feature_cols(self):
        return self.get(self.FEATURE_COLS)

    def set_feature_cols(self, value):
        return self.set(self.FEATURE_COLS, list(value) if value is not None else None)


class HasMaxIter(WithParams):
    MAX_ITER: ParamInfo = param_info(
        "maxIter", "Maximum number of training epochs.",
        default=100, value_type=int,
        validator=lambda v: v > 0,
    )

    def get_max_iter(self) -> int:
        return self.get(self.MAX_ITER)

    def set_max_iter(self, value: int):
        return self.set(self.MAX_ITER, value)


class HasLearningRate(WithParams):
    LEARNING_RATE: ParamInfo = param_info(
        "learningRate", "SGD learning rate.",
        default=0.1, value_type=float,
        validator=lambda v: v > 0,
    )

    def get_learning_rate(self) -> float:
        return self.get(self.LEARNING_RATE)

    def set_learning_rate(self, value: float):
        return self.set(self.LEARNING_RATE, value)


class HasGlobalBatchSize(WithParams):
    GLOBAL_BATCH_SIZE: ParamInfo = param_info(
        "globalBatchSize",
        "Rows per SGD mini-batch across the whole mesh; 0 means full batch.",
        default=0, value_type=int,
        validator=lambda v: v >= 0,
    )

    def get_global_batch_size(self) -> int:
        return self.get(self.GLOBAL_BATCH_SIZE)

    def set_global_batch_size(self, value: int):
        return self.set(self.GLOBAL_BATCH_SIZE, value)


class HasTol(WithParams):
    TOL: ParamInfo = param_info(
        "tol",
        "Convergence tolerance on the parameter-update norm; 0 disables "
        "early stopping.",
        default=0.0, value_type=float,
        validator=lambda v: v >= 0,
    )

    def get_tol(self) -> float:
        return self.get(self.TOL)

    def set_tol(self, value: float):
        return self.set(self.TOL, value)


class HasReg(WithParams):
    REG: ParamInfo = param_info(
        "reg", "L2 regularization strength.", default=0.0, value_type=float,
        validator=lambda v: v >= 0,
    )

    def get_reg(self) -> float:
        return self.get(self.REG)

    def set_reg(self, value: float):
        return self.set(self.REG, value)


class HasWithIntercept(WithParams):
    WITH_INTERCEPT: ParamInfo = param_info(
        "withIntercept", "Whether to fit an intercept term.",
        default=True, value_type=bool,
    )

    def get_with_intercept(self) -> bool:
        return self.get(self.WITH_INTERCEPT)

    def set_with_intercept(self, value: bool):
        return self.set(self.WITH_INTERCEPT, value)


class HasSeed(WithParams):
    SEED: ParamInfo = param_info(
        "seed", "Random seed for reproducible runs.", default=0, value_type=int,
    )

    def get_seed(self) -> int:
        return self.get(self.SEED)

    def set_seed(self, value: int):
        return self.set(self.SEED, value)


class HasCheckpoint(WithParams):
    CHECKPOINT_DIR: ParamInfo = param_info(
        "checkpointDir",
        "Directory for periodic training snapshots; None disables "
        "checkpointing. An existing snapshot there resumes training.",
        default=None, value_type=str,
    )
    CHECKPOINT_INTERVAL: ParamInfo = param_info(
        "checkpointInterval", "Snapshot every N completed epochs.",
        default=1, value_type=int,
        validator=lambda v: v > 0,
    )

    def get_checkpoint_dir(self):
        return self.get(self.CHECKPOINT_DIR)

    def set_checkpoint_dir(self, value: str):
        return self.set(self.CHECKPOINT_DIR, value)

    def get_checkpoint_interval(self) -> int:
        return self.get(self.CHECKPOINT_INTERVAL)

    def set_checkpoint_interval(self, value: int):
        return self.set(self.CHECKPOINT_INTERVAL, value)


class HasNumFeatures(WithParams):
    NUM_FEATURES: ParamInfo = param_info(
        "numFeatures",
        "Feature-space dimension for sparse vectors; None infers from data.",
        default=None, value_type=int,
    )

    def get_num_features(self):
        return self.get(self.NUM_FEATURES)

    def set_num_features(self, value: int):
        return self.set(self.NUM_FEATURES, value)


class HasNumHotFeatures(WithParams):
    NUM_HOT_FEATURES: ParamInfo = param_info(
        "numHotFeatures",
        "Hot/cold sparse training: the this-many most frequent features "
        "stream through a dense bf16 MXU slab instead of random "
        "gather/scatter (0 disables the split). Pick roughly the size of "
        "the frequency head; the slab costs ~2*numHotFeatures bytes/row "
        "of HBM traffic and rows*numHotFeatures*2 bytes of HBM residency.",
        default=0, value_type=int,
    )

    def get_num_hot_features(self) -> int:
        return self.get(self.NUM_HOT_FEATURES)

    def set_num_hot_features(self, value: int):
        return self.set(self.NUM_HOT_FEATURES, int(value))

    HOT_SLAB_MODE: ParamInfo = param_info(
        "hotSlabMode",
        "Hot/cold in-memory formulation: 'resident' pre-densifies every "
        "minibatch's slab once and keeps them HBM-resident across epochs "
        "(fastest; footprint rows*numHotFeatures*2 bytes grows with the "
        "dataset), 'stream' densifies each slab in-program per step (HBM "
        "holds only the packed entries — the scalable formulation), "
        "'auto' picks resident only while the slabs fit the budget "
        "(FMT_HOT_SLAB_BUDGET_MB, default 4096).",
        default="auto", value_type=str,
        validator=lambda v: v in ("auto", "resident", "stream"),
    )

    def get_hot_slab_mode(self) -> str:
        return self.get(self.HOT_SLAB_MODE)

    def set_hot_slab_mode(self, value: str):
        return self.set(self.HOT_SLAB_MODE, value)


class HasWindowMs(WithParams):
    WINDOW_MS: ParamInfo = param_info(
        "windowMs", "Event-time tumbling window size in milliseconds.",
        default=5000, value_type=int,
        validator=lambda v: v > 0,
    )

    def get_window_ms(self) -> int:
        return self.get(self.WINDOW_MS)

    def set_window_ms(self, value: int):
        return self.set(self.WINDOW_MS, value)


class HasBf16Distances(WithParams):
    BF16_DISTANCES: ParamInfo = param_info(
        "bf16Distances",
        "Compute the distance-matrix cross term (x . c^T) in bf16 with f32 "
        "accumulation — ~2x MXU throughput on the matmul-bound Knn "
        "transform. Opt-in: distances lose ~8 bits of mantissa, so exact "
        "tie-breaking and bit-parity with the f32 path are not guaranteed "
        "(neighbor SETS can differ when distances are closer than the bf16 "
        "rounding of the cross term). The norm terms stay f32.",
        default=False, value_type=bool,
    )

    def get_bf16_distances(self) -> bool:
        return self.get(self.BF16_DISTANCES)

    def set_bf16_distances(self, value: bool):
        return self.set(self.BF16_DISTANCES, value)


class HasShardModelData(WithParams):
    SHARD_MODEL_DATA: ParamInfo = param_info(
        "shardModelData",
        "Shard the model data over the mesh's data axis instead of "
        "replicating it, for models (e.g. a Knn reference set) too large "
        "for one device's memory.",
        default=False, value_type=bool,
    )

    def get_shard_model_data(self) -> bool:
        return self.get(self.SHARD_MODEL_DATA)

    def set_shard_model_data(self, value: bool):
        return self.set(self.SHARD_MODEL_DATA, value)


class HasAllowedLateness(WithParams):
    ALLOWED_LATENESS_MS: ParamInfo = param_info(
        "allowedLatenessMs",
        "Bounded event-time out-of-orderness: the watermark trails the max "
        "event time seen by this much, so records up to this late still land "
        "in their window (later ones go to the late-data side output).",
        default=0, value_type=int,
        validator=lambda v: v >= 0,
    )

    def get_allowed_lateness_ms(self) -> int:
        return self.get(self.ALLOWED_LATENESS_MS)

    def set_allowed_lateness_ms(self, value: int):
        return self.set(self.ALLOWED_LATENESS_MS, value)


class HasK(WithParams):
    K: ParamInfo = param_info(
        "k", "Number of clusters / neighbors.", default=2, value_type=int,
        validator=lambda v: v > 0,
    )

    def get_k(self) -> int:
        return self.get(self.K)

    def set_k(self, value: int):
        return self.set(self.K, value)
