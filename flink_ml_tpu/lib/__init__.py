from flink_ml_tpu.lib.classification import LogisticRegression, LogisticRegressionModel
from flink_ml_tpu.lib.clustering import KMeans, KMeansModel
from flink_ml_tpu.lib.feature import StandardScaler, StandardScalerModel
from flink_ml_tpu.lib.knn import Knn, KnnModel
from flink_ml_tpu.lib.online import OnlineLogisticRegression
from flink_ml_tpu.lib.regression import LinearRegression, LinearRegressionModel

__all__ = [
    "LinearRegression",
    "LinearRegressionModel",
    "LogisticRegression",
    "LogisticRegressionModel",
    "KMeans",
    "KMeansModel",
    "Knn",
    "KnnModel",
    "OnlineLogisticRegression",
    "StandardScaler",
    "StandardScalerModel",
]
