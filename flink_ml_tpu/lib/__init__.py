from flink_ml_tpu.lib.classification import LogisticRegression, LogisticRegressionModel
from flink_ml_tpu.lib.clustering import KMeans, KMeansModel
from flink_ml_tpu.lib.encoding import (
    BinaryClassificationEvaluator,
    OneHotEncoder,
    OneHotEncoderModel,
    StringIndexer,
    StringIndexerModel,
)
from flink_ml_tpu.lib.feature import (
    MinMaxScaler,
    MinMaxScalerModel,
    StandardScaler,
    StandardScalerModel,
    VectorAssembler,
)
from flink_ml_tpu.lib.knn import Knn, KnnModel
from flink_ml_tpu.lib.online import OnlineLogisticRegression
from flink_ml_tpu.lib.regression import LinearRegression, LinearRegressionModel

__all__ = [
    "LinearRegression",
    "LinearRegressionModel",
    "LogisticRegression",
    "LogisticRegressionModel",
    "KMeans",
    "KMeansModel",
    "Knn",
    "KnnModel",
    "BinaryClassificationEvaluator",
    "OneHotEncoder",
    "OneHotEncoderModel",
    "StringIndexer",
    "StringIndexerModel",
    "MinMaxScaler",
    "MinMaxScalerModel",
    "OnlineLogisticRegression",
    "StandardScaler",
    "StandardScalerModel",
    "VectorAssembler",
]
