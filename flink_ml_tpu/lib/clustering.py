"""KMeans — Lloyd iterations on the device mesh (BASELINE configs[1], k=100).

The reference has no KMeans; this is the workload BASELINE.json names, built
on the same bounded-iteration + in-step-psum pattern as the GLMs: centroids
replicated, rows sharded over the ``data`` axis, one epoch = one device call
computing assignments (argmin over an MXU-friendly x·cᵀ distance matrix) and
the psum'd per-cluster sums/counts that yield the next centroids.

Init is k-means++ on a host sample (seeded, reproducible); empty clusters
keep their previous centroid.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu import fault, obs
from flink_ml_tpu.api.core import Estimator
from flink_ml_tpu.common.mapper import ModelMapper
from flink_ml_tpu.lib.common import apply_sharded, resolve_features
from flink_ml_tpu.lib.model_base import TableModelBase
from flink_ml_tpu.lib.params import (
    HasCheckpoint,
    HasFeatureColsDefaultAsNull,
    HasK,
    HasMaxIter,
    HasSeed,
    HasTol,
    HasVectorColDefaultAsNull,
)
from flink_ml_tpu.ops.vector import DenseVector
from flink_ml_tpu.parallel.collectives import psum
from flink_ml_tpu.params.shared import (
    HasPredictionCol,
    HasPredictionDetailCol,
    HasReservedCols,
)
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table
from flink_ml_tpu.utils.environment import MLEnvironmentFactory

CENTROID_SCHEMA = Schema.of(
    ("clusterId", DataTypes.LONG), ("centroid", DataTypes.DENSE_VECTOR)
)


class KMeansParams(
    HasVectorColDefaultAsNull,
    HasFeatureColsDefaultAsNull,
    HasK,
    HasReservedCols,
    HasPredictionCol,
    HasPredictionDetailCol,
):
    """Shared column/k vocabulary for estimator and model."""


def _pairwise_sq_dists(x, c):
    """(n, k) squared distances; the x·cᵀ term is the MXU matmul."""
    x2 = jnp.sum(x * x, axis=1, keepdims=True)
    c2 = jnp.sum(c * c, axis=1)
    return jnp.maximum(x2 - 2.0 * (x @ c.T) + c2, 0.0)


# module-level + memoized so the jit cache survives across mapper instances
def _assign_fn(x, c):
    d = _pairwise_sq_dists(x, c)
    return jnp.stack(
        [jnp.argmin(d, axis=1).astype(jnp.float64),
         jnp.min(d, axis=1).astype(jnp.float64)],
        axis=1,
    )


from functools import lru_cache


@lru_cache(maxsize=32)
def _assign_apply(mesh):
    """Mesh-sharded assignment: rows over 'data', centroids replicated
    (plain jit on a single chip)."""
    from flink_ml_tpu.parallel.collectives import make_data_parallel_apply

    return make_data_parallel_apply(_assign_fn, mesh, n_args=2)


def make_kmeans_train_fn(mesh, k: int, max_iter: int, tol: float):
    """The WHOLE Lloyd run as one compiled device program.

    Reuses the GLM fused-loop scaffolding (lib/common.py
    ``_build_fused_train_fn``) with a Lloyd ``epoch_fn``: epochs are a
    ``lax.while_loop`` with the convergence test (centroid-shift norm vs
    tol) evaluated on device, so training runs start-to-finish with zero
    host round-trips — one transfer in (rows + weights), one out (centroids
    + cost history + epochs).  Rows shard over ``data``; the per-cluster
    sums/counts/cost ``psum`` over it (the reference's reduce-average round,
    SURVEY.md §3.3, fused on-chip); empty clusters keep their previous
    centroid.
    """
    from flink_ml_tpu.lib.common import _build_fused_train_fn

    key = ("kmeans", mesh, int(k), int(max_iter), float(tol))

    def lloyd_epoch(c, batch):
        x, w = batch  # local shards: (rows, d), (rows,)
        d = _pairwise_sq_dists(x, c)
        assign = jnp.argmin(d, axis=1)
        cost = psum(jnp.sum(jnp.min(d, axis=1) * w), "data")
        sums = psum(
            jax.ops.segment_sum(x * w[:, None], assign, num_segments=k),
            "data",
        )
        counts = psum(jax.ops.segment_sum(w, assign, num_segments=k), "data")
        new_c = jnp.where(
            counts[:, None] > 0,
            sums / jnp.maximum(counts[:, None], 1.0),
            c,
        )
        delta = jnp.sqrt(jnp.sum((new_c - c) ** 2))
        return new_c, cost, delta

    return _build_fused_train_fn(
        key, None, mesh, 0.0, 0.0, max_iter, tol, epoch_fn=lloyd_epoch
    )


def train_kmeans(
    init_centroids,
    k: int,
    Xp: np.ndarray,
    wp: np.ndarray,
    mesh,
    max_iter: int,
    tol: float,
    n_rows: int,
    checkpoint=None,
    device_batch=None,
):
    """Drive fused Lloyd iterations to termination (TrainResult contract).

    ``init_centroids`` may be a thunk (the k-means++ host pass): it is only
    resolved on a fresh start — a checkpoint resume (or a finished-run no-op
    re-fit) never pays for it.  With a CheckpointConfig the run executes as
    fused chunks with centroid snapshots between them, through the same
    chunked-checkpoint driver as the sparse GLM path (lib/common.py
    ``run_chunked_checkpoint``)."""
    from flink_ml_tpu.lib.common import (
        _resolve_thunk,
        _run_fused_train,
        run_chunked_checkpoint,
    )

    batch = (Xp, wp)

    def run(n_epochs, cents, dev_batch=None):
        return _run_fused_train(
            make_kmeans_train_fn(mesh, k, n_epochs, tol),
            jnp.asarray(cents, dtype=jnp.float32),
            batch if dev_batch is None else dev_batch, mesh,
            batch_preplaced=dev_batch is not None, n_rows=n_rows,
        )

    if checkpoint is None:
        cents0 = np.asarray(_resolve_thunk(init_centroids), dtype=np.float32)
        return run(max_iter, cents0, _resolve_thunk(device_batch))
    dim = Xp.shape[1]
    return run_chunked_checkpoint(
        run, init_centroids, max_iter, tol, checkpoint, mesh, batch,
        device_batch=device_batch,
        like=np.zeros((k, dim), dtype=np.float32),  # structure template only
    )


def _allgather_sample_pool(local_sample: np.ndarray, per: int, dim: int,
                           k: int) -> np.ndarray:
    """Build the cross-process k-means++ init pool: every process ships a
    mask-padded ``per``-row block of its local sample (gathers need equal
    shapes, but shards may be skewed — a small shard contributes all its
    rows instead of capping everyone else), and the concatenated masked
    rows are identical on every process.  Shared by the in-memory and
    out-of-core multi-process fits."""
    from jax.experimental import multihost_utils

    s_p = int(local_sample.shape[0])
    local = np.zeros((per, dim), dtype=np.float64)
    mask = np.zeros((per,), dtype=bool)
    if s_p:
        local[:s_p] = np.asarray(local_sample, dtype=np.float64)
        mask[:s_p] = True
    pool_rows = multihost_utils.process_allgather(
        np.ascontiguousarray(local)
    ).reshape(-1, dim)
    pool_mask = multihost_utils.process_allgather(mask).ravel()
    pool = pool_rows[pool_mask]
    if pool.shape[0] < k:
        raise ValueError(
            f"k={k} exceeds the {pool.shape[0]}-row init pool "
            f"(raise INIT_SAMPLE_CAP or lower k)"
        )
    return pool


def kmeans_plus_plus(X: np.ndarray, k: int, rng: np.random.RandomState) -> np.ndarray:
    """Standard k-means++ seeding on the host (runs on a bounded sample)."""
    n = X.shape[0]
    first = rng.randint(n)
    centers = [X[first]]
    d2 = np.sum((X - X[first]) ** 2, axis=1)
    for _ in range(1, k):
        total = d2.sum()
        if total <= 0:
            centers.append(X[rng.randint(n)])
            continue
        probs = d2 / total
        idx = rng.choice(n, p=probs)
        centers.append(X[idx])
        d2 = np.minimum(d2, np.sum((X - X[idx]) ** 2, axis=1))
    return np.stack(centers)


class KMeansModelMapper(ModelMapper):
    """Batched nearest-centroid assignment."""

    def __init__(self, model: "KMeansModel", data_schema: Schema):
        self._model_stage = model
        super().__init__([CENTROID_SCHEMA], data_schema, model.get_params())

    def reserved_cols(self) -> Optional[list]:
        return self._model_stage.get_reserved_cols()

    def output_cols(self):
        model = self._model_stage
        names = [model.get_prediction_col()]
        types = [DataTypes.LONG]
        if model.get_prediction_detail_col() is not None:
            names.append(model.get_prediction_detail_col())
            types.append(DataTypes.DOUBLE)
        return names, types

    def load_model(self, *model_tables: Table) -> None:
        (t,) = model_tables
        order = np.argsort(np.asarray(t.col("clusterId"), dtype=np.int64))
        cents = np.stack(
            [t.col("centroid")[i].to_dense().values for i in order]
        )
        self._centroids = jnp.asarray(cents, dtype=jnp.float32)
        # host copy for the circuit-breaker CPU fallback
        self._centroids_np = np.asarray(cents, dtype=np.float32)

    def serve_validation_spec(self):
        model = self._model_stage
        return {
            "dim": int(self._centroids.shape[1]),
            "vector_col": model.get_vector_col(),
            "feature_cols": model.get_feature_cols(),
        }

    def map_batch(self, batch: Table):
        from flink_ml_tpu import serve

        model = self._model_stage
        X, _ = resolve_features(batch, model, dim=int(self._centroids.shape[1]))
        X = X.astype(np.float32)
        n = X.shape[0]
        both = serve.dispatch(
            self.serve_name(),
            device=lambda: apply_sharded(_assign_apply, X, self._centroids),
            fallback=lambda: self._assign_cpu(X),
        )
        return self._assign_cols(both[:n])

    def _assign_cols(self, both):
        model = self._model_stage
        out = {model.get_prediction_col(): both[:, 0].astype(np.int64)}
        detail = model.get_prediction_detail_col()
        if detail is not None:
            out[detail] = np.sqrt(both[:, 1])
        return out

    def fused_kernel(self):
        from flink_ml_tpu.common.fused import FusedInput, FusedKernel

        model = self._model_stage
        feature_cols = model.get_feature_cols()

        def fn(x, cents):
            return {"assign": _assign_fn(x, cents)}

        return FusedKernel(
            inputs=[FusedInput(
                dim=int(self._centroids.shape[1]),
                vector_col=model.get_vector_col(),
                feature_cols=tuple(feature_cols) if feature_cols else None,
            )],
            fn=fn,
            out_keys=("assign",),
            model_args=(self._centroids,),
            finalize=lambda fetched, n: self._assign_cols(
                fetched["assign"]
            ),
        )

    def _assign_cpu(self, X: np.ndarray) -> np.ndarray:
        """NumPy nearest-centroid fallback (same distance formula and
        lowest-id tie-break as the device argmin)."""
        c = self._centroids_np
        d = np.maximum(
            np.sum(X * X, axis=1, keepdims=True)
            - 2.0 * (X @ c.T)
            + np.sum(c * c, axis=1),
            0.0,
        )
        return np.stack(
            [np.argmin(d, axis=1).astype(np.float64), np.min(d, axis=1)],
            axis=1,
        )


class KMeansModel(TableModelBase, KMeansParams):
    """Nearest-centroid assignment model; model data = the centroid table."""

    REQUIRED_MODEL_COL = "centroid"

    def centroids(self) -> np.ndarray:
        (t,) = self.get_model_data()
        order = np.argsort(np.asarray(t.col("clusterId"), dtype=np.int64))
        return np.stack([t.col("centroid")[i].to_dense().values for i in order])

    def _make_mapper(self, data_schema: Schema) -> KMeansModelMapper:
        return KMeansModelMapper(self, data_schema)


class KMeans(Estimator, KMeansParams, HasMaxIter, HasTol, HasSeed, HasCheckpoint):
    """Estimator: k-means++ init + FUSED data-parallel Lloyd iterations.

    The whole run is one device program (:func:`make_kmeans_train_fn`) — no
    per-epoch host sync; with a checkpoint dir configured, fused chunks with
    centroid snapshots between them (resume restores the latest snapshot and
    skips re-init)."""

    INIT_SAMPLE_CAP = 100_000  # k-means++ host sample bound

    def _checkpoint_config(self):
        directory = self.get_checkpoint_dir()
        if directory is None:
            return None
        from flink_ml_tpu.iteration.checkpoint import CheckpointConfig

        return CheckpointConfig(
            directory=directory, every_n_epochs=self.get_checkpoint_interval()
        )

    def fit(self, *inputs) -> KMeansModel:
        import time as _time

        from flink_ml_tpu.table import slab_pool

        self._fit_pool_stats0 = (
            *slab_pool.pool().counters(), _time.perf_counter()
        )
        (table,) = inputs
        if getattr(table, "is_chunked", False):
            return self._fit_out_of_core(table)
        X, dim = resolve_features(table, self)
        k = self.get_k()
        n = X.shape[0]
        n_proc = jax.process_count()

        checkpoint = self._checkpoint_config()

        env = MLEnvironmentFactory.get_default()
        mesh = env.get_mesh()
        from flink_ml_tpu.parallel.mesh import (
            agree_max,
            agree_sum,
            local_data_parallel_size,
        )

        n_global = int(agree_sum(np.asarray([n]))[0]) if n_proc > 1 else n
        if n_global < k:
            raise ValueError(f"k={k} exceeds number of rows {n_global}")
        n_dev = local_data_parallel_size(mesh)

        if n_proc > 1:
            # cross-process consistent seeding: each process contributes an
            # equal-size deterministic sample of ITS shard; the allgathered
            # pool is identical on every process, so the same-seeded
            # k-means++ pass picks the same replicated centroids everywhere.
            # Eager (not inside the init thunk): the gather is a collective
            # every process must reach, never skipped by a lazy resolve.
            rng = np.random.RandomState(self.get_seed())
            per = -(-self.INIT_SAMPLE_CAP // n_proc)
            s_p = min(n, per)
            local_sample = (
                X if n == s_p else X[rng.choice(n, s_p, replace=False)]
            )
            pool = _allgather_sample_pool(local_sample, per, dim, k)

            def init():
                return kmeans_plus_plus(
                    pool, k, np.random.RandomState(self.get_seed())
                )
        else:
            def init():
                # the k-means++ host pass, as a thunk: resolved by
                # train_kmeans only on a fresh start — a snapshot resume
                # skips it entirely
                rng = np.random.RandomState(self.get_seed())
                sample = X if n <= self.INIT_SAMPLE_CAP else X[
                    rng.choice(n, self.INIT_SAMPLE_CAP, replace=False)
                ]
                return kmeans_plus_plus(sample.astype(np.float64), k, rng)

        # local rows pad to a per-shard row count agreed across processes
        # (shard_batch needs identically-shaped local blocks; pad rows
        # carry zero weight)
        rows_per_shard = -(-n // n_dev)
        if n_proc > 1:
            (rows_per_shard,) = agree_max(rows_per_shard)

        def build():
            n_pad = rows_per_shard * n_dev
            Xp = np.zeros((n_pad, dim), dtype=np.float32)
            Xp[:n] = X
            wp = np.zeros((n_pad,), dtype=np.float32)
            wp[:n] = 1.0
            return Xp, wp

        layout_key = ("kmeans", self.get_vector_col(),
                      tuple(self.get_feature_cols() or ()), n_dev,
                      rows_per_shard)
        Xp, wp = table.cached_pack(layout_key, build)
        # a thunk: a no-op resume (finished snapshot) must not pay the
        # host->device transfer, so placement resolves lazily downstream;
        # the placement itself rides the cross-fit slab pool (re-fitting
        # the same table content skips the transfer) and double-buffers
        # the H2D hop
        from flink_ml_tpu.parallel.mesh import shard_batch_prefetched
        from flink_ml_tpu.table import slab_pool

        kmeans_cols = (
            [self.get_vector_col()] if self.get_vector_col() is not None
            else list(self.get_feature_cols() or ())
        )
        device_batch = lambda: slab_pool.get_or_place(  # noqa: E731
            table, layout_key + ("dev",), mesh,
            lambda: shard_batch_prefetched(mesh, (Xp, wp)),
            cols=kmeans_cols or None,
        )

        # guarded for the health sentinel's diagnostics, but with NO retry
        # budget: KMeans has no learning rate to back off, so a replay
        # would re-diverge bit-identically — fail fast with the guard's
        # framing instead of multiplying time-to-error
        result = fault.run_guarded(
            lambda _lr_scale: train_kmeans(
                init, k, Xp, wp, mesh,
                max_iter=self.get_max_iter(), tol=self.get_tol(),
                n_rows=n_global,
                checkpoint=checkpoint, device_batch=device_batch,
            ),
            what=type(self).__name__, max_retries=0,
        )
        return self._finish(result, k)

    def _finish(self, result, k: int) -> KMeansModel:
        from flink_ml_tpu.lib.common import fit_pool_extra

        centroids = np.asarray(result.params, dtype=np.float64)
        model_table = Table.from_rows(
            [(int(i), DenseVector(centroids[i])) for i in range(k)],
            CENTROID_SCHEMA,
        )
        model = KMeansModel()
        model.get_params().merge(self.get_params())
        model.set_model_data(model_table)
        model.train_epochs_ = result.epochs
        model.train_cost_ = float(result.losses[-1]) if result.losses else 0.0
        model.train_metrics_ = result.metrics
        obs.fit_report(
            type(self).__name__,
            step_metrics=result.metrics,
            extra={"epochs": result.epochs, "cost": model.train_cost_,
                   "k": int(k), **fit_pool_extra(self, result)},
        )
        return model

    def _fit_out_of_core(self, table) -> KMeansModel:
        """Streaming Lloyd over a ChunkedTable: per-epoch passes accumulate
        cluster sums/counts chunk by chunk on device (lib/out_of_core.py),
        so the dataset never materializes on the host.

        Matches the in-memory fit to float accumulation order: chunked
        partial segment-sums add in a different order than one whole-shard
        segment_sum, so centroids agree to ~1e-5 relative, not bit-for-bit
        (unlike the GLM paths, whose minibatch structure chunking preserves
        exactly).  The k-means++ init draws a UNIFORM reservoir sample of
        up to INIT_SAMPLE_CAP rows over one full stream pass (sorted or
        grouped files must not bias the seeding); under the cap the sample
        is the whole dataset, matching the in-memory path.
        """
        from flink_ml_tpu.table.sources import chunk_cache

        # the reservoir init is a full stream pass: record binary chunks
        # there so the first training epoch replays pages instead of
        # re-parsing text — one text read total (VERDICT r4 #3)
        with chunk_cache(table) as table:
            return self._fit_out_of_core_impl(table)

    def _fit_out_of_core_impl(self, table) -> KMeansModel:
        from flink_ml_tpu.lib import out_of_core as oc
        from flink_ml_tpu.parallel.mesh import (
            agree_max,
            agree_sum,
            local_data_parallel_size,
        )

        env = MLEnvironmentFactory.get_default()
        mesh = env.get_mesh()
        n_proc = jax.process_count()
        n_dev = local_data_parallel_size(mesh)
        # on a 2-D mesh the centroids replicate over 'model' (like the
        # in-memory Lloyd path); rows shard over 'data' only
        k = self.get_k()
        checkpoint = self._checkpoint_config()

        def extract(t):
            X, _ = resolve_features(t, self)
            return (np.asarray(X),)

        # init from a uniform reservoir sample; skipped entirely on resume
        # single-process.  Multi-process always runs the sampling pass:
        # the per-epoch block count derives from the row count it returns
        # (every process must dispatch the same number of collective chunk
        # calls — short shards pad with zero-weight blocks), and the
        # allgather is a collective every process must reach.
        resuming = False
        if checkpoint is not None:
            from flink_ml_tpu.iteration.checkpoint import (
                agreed_latest_checkpoint,
            )

            resuming = agreed_latest_checkpoint(checkpoint.directory) is not None
        rng = np.random.RandomState(self.get_seed())
        rows_per_block = max(n_dev, (table.chunk_rows // n_dev) * n_dev)
        pad_to_blocks = None
        if n_proc > 1:
            per = -(-self.INIT_SAMPLE_CAP // n_proc)
            sample, n_seen = oc.reservoir_sample_rows(
                table.chunks(), extract, per, rng, allow_empty=True
            )
            # an empty local shard cannot know the feature width, but it
            # still owes every collective: agree the width first, then
            # contribute an empty masked block to the pool
            (dim,) = agree_max(sample.shape[1] if n_seen else 0)
            if dim == 0:
                raise ValueError("empty source")
            # the row-count check precedes the pool build so an under-k
            # dataset reports 'k exceeds number of rows', not the pool's
            # 'raise INIT_SAMPLE_CAP' (which could not help) — matching
            # the in-memory path's diagnostic order
            n_global = int(agree_sum(np.asarray([n_seen]))[0])
            if n_global < k:
                raise ValueError(f"k={k} exceeds number of rows {n_global}")
            pool = _allgather_sample_pool(
                sample.reshape(-1, dim) if n_seen else
                np.zeros((0, dim), dtype=np.float64),
                per, dim, k,
            )
            (pad_to_blocks,) = agree_max(-(-n_seen // rows_per_block))
            cents0 = kmeans_plus_plus(
                pool, k, np.random.RandomState(self.get_seed())
            )
        elif resuming:
            first = next(iter(table.chunks()), None)
            if first is None:
                raise ValueError("empty source")
            dim = extract(first)[0].shape[1]
            cents0 = np.zeros((k, dim), dtype=np.float32)  # template only
        else:
            sample, n_seen = oc.reservoir_sample_rows(
                table.chunks(), extract, self.INIT_SAMPLE_CAP, rng
            )
            dim = sample.shape[1]
            if n_seen < k:
                raise ValueError(f"k={k} exceeds number of rows {n_seen}")
            cents0 = kmeans_plus_plus(sample.astype(np.float64), k, rng)

        blocks = oc.rows_blocks_factory(table, extract, n_dev, rows_per_block,
                                        pad_to_blocks=pad_to_blocks,
                                        pad_dim=dim)
        key = ("chunk-kmeans", mesh, int(k), rows_per_block, dim)
        use_spill = getattr(table, "spill", False) and self.get_max_iter() > 1
        with oc.maybe_spill(blocks, use_spill) as blocks:
            result = fault.run_guarded(
                lambda _lr_scale: oc.train_out_of_core(
                    jnp.asarray(cents0, dtype=jnp.float32),
                    blocks,
                    lambda: oc.make_kmeans_chunk_fn(key, k, mesh),
                    mesh,
                    max_iter=self.get_max_iter(),
                    tol=self.get_tol(),
                    checkpoint=checkpoint,
                    make_carry=oc.kmeans_make_carry,
                    finalize=oc.kmeans_finalize,
                ),
                what=type(self).__name__, max_retries=0,  # no lr to back off
            )
        return self._finish(result, k)
