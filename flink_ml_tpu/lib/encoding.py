"""Categorical encoding stages + evaluation — the Criteo-shaped pipeline
head (categorical columns -> indices -> one sparse feature vector) and the
quality metric the benchmarks assert.

The reference snapshot ships no concrete transformers (SURVEY.md §0.3);
these follow its stage conventions exactly: selectedCols vocabulary
(HasSelectedCol.java:33-47 pattern), OutputColsHelper merge rules
(OutputColsHelper.java:32-52), model-as-table persistence
(Model.java:102-122).

TPU-first shapes:

* ``StringIndexer.transform`` is one vectorized ``searchsorted`` over the
  stringified column per output — no per-record dictionary lookups.
* ``OneHotEncoder`` emits ONE combined sparse vector column for all its
  input columns (offset-stacked slots) backed by :class:`CsrRows` — three
  contiguous arrays, zero per-row Python objects — which is exactly the
  column form the sparse trainer's vectorized packer consumes, so
  indexer -> encoder -> sparse LogisticRegression runs columnar
  end-to-end.  (A per-column one-hot + dense assembly would materialize
  the full vocabulary width per row — unusable at hashed-feature scale.)
* ``BinaryClassificationEvaluator`` is an AlgoOperator (not a Model):
  one rank-based AUC over the scored table, tie-aware.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from flink_ml_tpu.api.core import AlgoOperator, Estimator
from flink_ml_tpu.common.mapper import ModelMapper
from flink_ml_tpu.lib.model_base import TableModelBase
from flink_ml_tpu.params import param_info
from flink_ml_tpu.params.params import ParamInfo, WithParams
from flink_ml_tpu.params.shared import (
    HasOutputCol,
    HasReservedCols,
    HasSelectedCols,
)
from flink_ml_tpu.ops.batch import CsrRows
from flink_ml_tpu.table.output_cols import OutputColsHelper
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table

INDEXER_MODEL_SCHEMA = Schema.of(
    ("colName", DataTypes.STRING),
    ("value", DataTypes.STRING),
    ("index", DataTypes.DOUBLE),
)

ENCODER_MODEL_SCHEMA = Schema.of(
    ("colName", DataTypes.STRING), ("size", DataTypes.DOUBLE)
)


class HasStringOrderType(WithParams):
    STRING_ORDER_TYPE: ParamInfo = param_info(
        "stringOrderType",
        "Vocabulary order: frequencyDesc | frequencyAsc | alphabetAsc | "
        "alphabetDesc (ties always break lexicographically ascending).",
        default="frequencyDesc",
        value_type=str,
        validator=lambda v: v in (
            "frequencyDesc", "frequencyAsc", "alphabetAsc", "alphabetDesc"
        ),
    )

    def get_string_order_type(self) -> str:
        return self.get(self.STRING_ORDER_TYPE)

    def set_string_order_type(self, value: str):
        return self.set(self.STRING_ORDER_TYPE, value)


class HasHandleInvalid(WithParams):
    HANDLE_INVALID: ParamInfo = param_info(
        "handleInvalid",
        "What to do with values unseen at fit time: 'error' raises, "
        "'keep' maps them to one extra slot past the vocabulary.",
        default="error",
        value_type=str,
        validator=lambda v: v in ("error", "keep"),
    )

    def get_handle_invalid(self) -> str:
        return self.get(self.HANDLE_INVALID)

    def set_handle_invalid(self, value: str):
        return self.set(self.HANDLE_INVALID, value)


class HasOutputColsDefaultAsNull(WithParams):
    OUTPUT_COLS: ParamInfo = param_info(
        "outputCols",
        "Names of the output columns; null overwrites selectedCols in "
        "place.",
        default=None,
        value_type=list,
        optional=True,
    )

    def get_output_cols(self) -> Optional[list]:
        return self.get(self.OUTPUT_COLS)

    def set_output_cols(self, value: list):
        return self.set(self.OUTPUT_COLS, list(value))


class StringIndexerParams(
    HasSelectedCols,
    HasOutputColsDefaultAsNull,
    HasReservedCols,
    HasStringOrderType,
    HasHandleInvalid,
):
    """Shared vocabulary for the indexer estimator and model."""

    def resolved_output_cols(self) -> list:
        out = self.get_output_cols()
        if out is None:
            return list(self.get_selected_cols())
        if len(out) != len(self.get_selected_cols()):
            raise ValueError(
                f"outputCols arity {len(out)} != selectedCols arity "
                f"{len(self.get_selected_cols())}"
            )
        return list(out)


def _stringify(column) -> np.ndarray:
    """A column's values by their string form — the indexing key.  Numeric
    categories index by str(value) (documented; '1.0' and '1' differ)."""
    return np.asarray([str(v) for v in column], dtype=object).astype(str)


def _vocab_order(values: np.ndarray, counts: np.ndarray, order: str):
    if order == "frequencyDesc":
        return np.lexsort((values, -counts))
    if order == "frequencyAsc":
        return np.lexsort((values, counts))
    if order == "alphabetAsc":
        return np.argsort(values)
    return np.argsort(values)[::-1]  # alphabetDesc


class StringIndexerModelMapper(ModelMapper):
    def __init__(self, model: "StringIndexerModel", data_schema: Schema):
        self._model_stage = model
        super().__init__(
            [INDEXER_MODEL_SCHEMA], data_schema, model.get_params()
        )

    def reserved_cols(self) -> Optional[list]:
        return self._model_stage.get_reserved_cols()

    def output_cols(self) -> Tuple[list, list]:
        outs = self._model_stage.resolved_output_cols()
        return outs, [DataTypes.DOUBLE] * len(outs)

    def load_model(self, *model_tables: Table) -> None:
        (t,) = model_tables
        col_names = _stringify(t.col("colName"))
        values = _stringify(t.col("value"))
        indices = np.asarray(t.col("index"), dtype=np.float64)
        # per column: vocab sorted by value, with its index vector — the
        # searchsorted lookup form (one vectorized lookup per transform)
        self._lookup = {}
        for c in np.unique(col_names):
            mask = col_names == c
            vals = values[mask]
            order = np.argsort(vals)
            self._lookup[str(c)] = (vals[order], indices[mask][order])

    def fused_kernel(self):
        # pure host lookup (vectorized searchsorted — there is no device
        # dispatch to fuse away): joins a fused run as a pre-kernel so an
        # indexer -> encoder -> model chain still compiles to one dispatch
        from flink_ml_tpu.common.fused import FusedKernel

        return FusedKernel(host=True)

    def map_batch(self, batch: Table):
        model = self._model_stage
        invalid = model.get_handle_invalid()
        result = {}
        for c, out in zip(model.get_selected_cols(),
                          model.resolved_output_cols()):
            entry = self._lookup.get(c)
            if entry is None:
                raise ValueError(
                    f"column {c!r} has no fitted vocabulary in the model "
                    "data (the model was fit without this column, or its "
                    "model rows were filtered out)"
                )
            sorted_vals, idx = entry
            vals = _stringify(batch.col(c))
            pos = np.searchsorted(sorted_vals, vals)
            pos_safe = np.clip(pos, 0, len(sorted_vals) - 1)
            hit = (
                (pos < len(sorted_vals))
                & (sorted_vals[pos_safe] == vals)
            ) if len(sorted_vals) else np.zeros(len(vals), dtype=bool)
            if invalid == "error" and not np.all(hit):
                missing = vals[~hit][:5]
                raise ValueError(
                    f"column {c!r} holds values unseen at fit time "
                    f"(e.g. {list(missing)}); set handleInvalid='keep' to "
                    "map them to the extra slot"
                )
            out_idx = np.where(hit, idx[pos_safe], float(len(sorted_vals)))
            result[out] = out_idx.astype(np.float64)
        return result


class StringIndexerModel(TableModelBase, StringIndexerParams):
    """Maps each selected column's values to double vocabulary indices."""

    REQUIRED_MODEL_COL = "colName"

    def _make_mapper(self, data_schema: Schema) -> StringIndexerModelMapper:
        return StringIndexerModelMapper(self, data_schema)

    def vocab_sizes(self) -> dict:
        """Per-column vocabulary size (excludes the handleInvalid='keep'
        extra slot)."""
        (t,) = self.get_model_data()
        col_names = _stringify(t.col("colName"))
        out = {}
        for c in np.unique(col_names):
            out[str(c)] = int(np.sum(col_names == c))
        return out


class StringIndexer(Estimator, StringIndexerParams):
    """Estimator: one vectorized unique+count pass per selected column.

    Vocabulary order follows ``stringOrderType`` (default frequencyDesc —
    index 0 is the most frequent value, the layout a downstream hot/cold
    split likes); ties always break lexicographically ascending, so the
    fit is deterministic.
    """

    def fit(self, *inputs) -> StringIndexerModel:
        (table,) = inputs
        order = self.get_string_order_type()
        cols = list(self.get_selected_cols())
        rows = []
        if getattr(table, "is_chunked", False):
            # out-of-core fit: one streaming pass, per-column value counts
            # merged across chunks — the ordering is a pure function of the
            # total counts, so the result matches the in-memory fit exactly
            tallies: list = [{} for _ in cols]
            for t in table.chunks():
                for tally, c in zip(tallies, cols):
                    uniq, counts = np.unique(
                        _stringify(t.col(c)), return_counts=True
                    )
                    for v, n in zip(uniq, counts):
                        tally[str(v)] = tally.get(str(v), 0) + int(n)
            for tally, c in zip(tallies, cols):
                uniq = np.asarray(sorted(tally), dtype=str)
                counts = np.asarray([tally[v] for v in uniq])
                for i, j in enumerate(_vocab_order(uniq, counts, order)):
                    rows.append((c, str(uniq[j]), float(i)))
        else:
            for c in cols:
                vals = _stringify(table.col(c))
                uniq, counts = np.unique(vals, return_counts=True)
                for i, j in enumerate(_vocab_order(uniq, counts, order)):
                    rows.append((c, str(uniq[j]), float(i)))
        model = StringIndexerModel()
        model.get_params().merge(self.get_params())
        model.set_model_data(Table.from_rows(rows, INDEXER_MODEL_SCHEMA))
        return model


class OneHotEncoderParams(
    HasSelectedCols,
    HasOutputCol,
    HasReservedCols,
    HasHandleInvalid,
):
    """Shared vocabulary for the encoder estimator and model."""


class OneHotEncoderModelMapper(ModelMapper):
    def __init__(self, model: "OneHotEncoderModel", data_schema: Schema):
        self._model_stage = model
        super().__init__(
            [ENCODER_MODEL_SCHEMA], data_schema, model.get_params()
        )

    def reserved_cols(self) -> Optional[list]:
        return self._model_stage.get_reserved_cols()

    def output_cols(self) -> Tuple[list, list]:
        return (
            [self._model_stage.get_output_col()],
            [DataTypes.SPARSE_VECTOR],
        )

    def load_model(self, *model_tables: Table) -> None:
        (t,) = model_tables
        names = [str(v) for v in t.col("colName")]
        sizes = {
            n: int(s) for n, s in zip(names, t.col("size"))
        }
        keep = self._model_stage.get_handle_invalid() == "keep"
        cols = list(self._model_stage.get_selected_cols())
        # slot budget per column (+1 invalid bucket under 'keep'), offsets
        # in selectedCols order
        self._sizes = np.asarray(
            [sizes[c] + (1 if keep else 0) for c in cols], dtype=np.int64
        )
        self._offsets = np.concatenate(
            [[0], np.cumsum(self._sizes)[:-1]]
        )
        self._dim = int(self._sizes.sum())

    def fused_kernel(self):
        # host pre-kernel: the offset-stacked CSR build is integer numpy
        # with no device call of its own (see StringIndexerModelMapper)
        from flink_ml_tpu.common.fused import FusedKernel

        return FusedKernel(host=True)

    def map_batch(self, batch: Table):
        model = self._model_stage
        cols = list(model.get_selected_cols())
        keep = model.get_handle_invalid() == "keep"
        n = batch.num_rows()
        k = len(cols)
        idx = np.empty((n, k), dtype=np.int64)
        for j, c in enumerate(cols):
            v = np.asarray(batch.col(c), dtype=np.float64)
            vi = v.astype(np.int64)
            size = self._sizes[j] - (1 if keep else 0)
            bad = (vi < 0) | (vi >= size) | (vi != v)
            if np.any(bad):
                if not keep:
                    raise ValueError(
                        f"column {c!r} holds indices outside [0, {size}) "
                        f"(e.g. {v[bad][:5].tolist()}); set "
                        "handleInvalid='keep' to bucket them"
                    )
                vi = np.where(bad, size, vi)
            idx[:, j] = vi + self._offsets[j]
        # offsets ascend in column order, so each row's indices are already
        # sorted — the CsrRows contract — and the whole batch is three
        # contiguous arrays (zero per-row objects)
        csr = CsrRows(
            self._dim,
            np.arange(0, (n + 1) * k, k, dtype=np.int64),
            idx.reshape(-1),
            np.ones(n * k, dtype=np.float64),
        )
        return {model.get_output_col(): csr}


class OneHotEncoderModel(TableModelBase, OneHotEncoderParams):
    """Encodes the selected index columns into ONE offset-stacked sparse
    vector column (CsrRows-backed)."""

    REQUIRED_MODEL_COL = "colName"

    def _make_mapper(self, data_schema: Schema) -> OneHotEncoderModelMapper:
        return OneHotEncoderModelMapper(self, data_schema)

    def total_size(self) -> int:
        """The output vector width (includes 'keep' buckets when set) —
        what a downstream estimator's numFeatures should be."""
        (t,) = self.get_model_data()
        keep = self.get_handle_invalid() == "keep"
        return int(sum(
            int(s) + (1 if keep else 0) for s in t.col("size")
        ))


class OneHotEncoder(Estimator, OneHotEncoderParams):
    """Estimator: per-column slot count = max observed index + 1."""

    @staticmethod
    def _check_indices(c: str, v: np.ndarray) -> None:
        if len(v) and (np.any(v < 0) or np.any(v != v.astype(np.int64))):
            raise ValueError(
                f"column {c!r} must hold non-negative integer indices "
                "(use StringIndexer upstream)"
            )

    def fit(self, *inputs) -> OneHotEncoderModel:
        (table,) = inputs
        cols = list(self.get_selected_cols())
        if getattr(table, "is_chunked", False):
            # out-of-core fit: slot count = running max over the stream
            maxes = np.full(len(cols), -1.0)
            for t in table.chunks():
                for j, c in enumerate(cols):
                    v = np.asarray(t.col(c), dtype=np.float64)
                    self._check_indices(c, v)
                    if len(v):
                        maxes[j] = max(maxes[j], float(v.max()))
            rows = [
                (c, float(int(m) + 1 if m >= 0 else 1))
                for c, m in zip(cols, maxes)
            ]
        else:
            rows = []
            for c in cols:
                v = np.asarray(table.col(c), dtype=np.float64)
                self._check_indices(c, v)
                size = int(v.max()) + 1 if len(v) else 1
                rows.append((c, float(size)))
        model = OneHotEncoderModel()
        model.get_params().merge(self.get_params())
        model.set_model_data(Table.from_rows(rows, ENCODER_MODEL_SCHEMA))
        return model


class HasRawPredictionCol(WithParams):
    RAW_PREDICTION_COL: ParamInfo = param_info(
        "rawPredictionCol",
        "Column holding the positive-class score (higher = more positive).",
        default="rawPrediction",
        value_type=str,
    )

    def get_raw_prediction_col(self) -> str:
        return self.get(self.RAW_PREDICTION_COL)

    def set_raw_prediction_col(self, value: str):
        return self.set(self.RAW_PREDICTION_COL, value)


class HasLabelColEval(WithParams):
    LABEL_COL: ParamInfo = param_info(
        "labelCol", "The binary label column (0/1).",
        default="label", value_type=str,
    )

    def get_label_col(self) -> str:
        return self.get(self.LABEL_COL)

    def set_label_col(self, value: str):
        return self.set(self.LABEL_COL, value)


EVAL_SCHEMA = Schema.of(
    ("areaUnderROC", DataTypes.DOUBLE), ("count", DataTypes.DOUBLE)
)


def binary_auc(labels: np.ndarray, scores: np.ndarray) -> float:
    """Tie-aware rank AUC: P(score+ > score-) + 0.5 P(tie) — the same
    statistic the bench harness asserts parity on."""
    labels = np.asarray(labels, dtype=np.float64)
    scores = np.asarray(scores, dtype=np.float64)
    pos = labels > 0.5
    n_pos = int(pos.sum())
    n_neg = len(labels) - n_pos
    if n_pos == 0 or n_neg == 0:
        raise ValueError("AUC needs both classes present")
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty(len(scores), dtype=np.float64)
    sorted_scores = scores[order]
    # average ranks over ties, fully vectorized: group equal scores, then
    # each group's average rank is (first_rank + last_rank) / 2
    new_group = np.r_[True, sorted_scores[1:] != sorted_scores[:-1]]
    group_id = np.cumsum(new_group) - 1
    counts = np.bincount(group_id)
    ends = np.cumsum(counts).astype(np.float64)  # 1-based rank of group end
    avg_rank = ends - (counts - 1) / 2.0
    ranks[order] = avg_rank[group_id]
    return float(
        (ranks[pos].sum() - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg)
    )


class BinaryClassificationEvaluator(
    AlgoOperator, HasLabelColEval, HasRawPredictionCol
):
    """AlgoOperator: scored table in, one metrics row out (areaUnderROC).

    An AlgoOperator rather than a Model — it has no model data, matching
    the reference's api-level AlgoOperator contract
    (AlgoOperator.java:153-161: multi-in/multi-out transform)."""

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        labels = np.asarray(table.col(self.get_label_col()), dtype=np.float64)
        scores = np.asarray(
            table.col(self.get_raw_prediction_col()), dtype=np.float64
        )
        auc = binary_auc(labels, scores)
        return (
            Table.from_rows([(auc, float(len(labels)))], EVAL_SCHEMA),
        )


# keep OutputColsHelper imported name referenced for mapper machinery users
_ = OutputColsHelper
