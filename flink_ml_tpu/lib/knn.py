"""Knn — brute-force k-nearest-neighbors classification (BASELINE configs[3]).

Model data is the training set itself (vectors + labels), following the
model-as-table convention.  ``transform`` is the benchmark workload: each
query batch computes one (batch, train) distance matrix — the x·cᵀ term is a
single MXU matmul — then ``lax.top_k`` + a one-hot vote picks the label.
Per-record distance loops (the reference's Mapper shape) never appear.

Large training sets are chunked on device to bound the distance-matrix
footprint; the running top-k is merged across chunks, so memory is
O(batch × chunk) instead of O(batch × train).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu import obs
from flink_ml_tpu.api.core import Estimator
from flink_ml_tpu.common.mapper import ModelMapper
from flink_ml_tpu.lib.common import apply_sharded, resolve_features
from flink_ml_tpu.parallel.collectives import pvary, shard_map
from flink_ml_tpu.lib.model_base import TableModelBase
from flink_ml_tpu.lib.params import (
    HasBf16Distances,
    HasFeatureColsDefaultAsNull,
    HasK,
    HasLabelCol,
    HasShardModelData,
    HasVectorColDefaultAsNull,
)
from flink_ml_tpu.params.shared import (
    HasPredictionCol,
    HasPredictionDetailCol,
    HasReservedCols,
)
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table

KNN_MODEL_SCHEMA = Schema.of(
    ("features", DataTypes.DENSE_VECTOR), ("label", DataTypes.DOUBLE)
)


class KnnParams(
    HasVectorColDefaultAsNull,
    HasFeatureColsDefaultAsNull,
    HasK,
    HasBf16Distances,
    HasShardModelData,
    HasReservedCols,
    HasPredictionCol,
    HasPredictionDetailCol,
):
    """Shared vocabulary for the Knn estimator and model."""


@partial(jax.jit, static_argnums=(3, 4, 5))
def _knn_chunked(xq, xt, yt, k, chunk, bf16=False):
    """Top-k labels for query batch xq against chunked training data.

    Returns (labels (n, k), dists (n, k)).  xt/yt are padded to a multiple of
    ``chunk``; padded rows carry +inf distance so they never enter the top-k.

    Tie-breaking is canonical by (distance, global row index), including for
    exact distance ties, by induction over the scan: ``lax.top_k`` keeps the
    lower-*position* element on ties, and every merge's concatenation is in
    global-row-index order within tied groups — the carry holds the running
    best lex-sorted by (d, idx) (top_k returns sorted output), and each new
    chunk's rows appear in index order with indices larger than everything
    already carried.  The sharded path's cross-shard merge preserves the same
    invariant (shard order = row-block order), so replicated and sharded
    selections match bit-for-bit even on tied data — asserted by the
    duplicate-row tie test in tests/test_parallel_inference.py
    (test_exact_distance_ties_match_across_paths).
    """
    n = xq.shape[0]
    n_chunks = xt.shape[0] // chunk
    xq2 = jnp.sum(xq * xq, axis=1, keepdims=True)
    is_real = jnp.isfinite(yt)

    xq_mm = xq.astype(jnp.bfloat16) if bf16 else xq

    def scan_chunk(carry, idx):
        best_d, best_y = carry
        xc = jax.lax.dynamic_slice_in_dim(xt, idx * chunk, chunk)
        yc = jax.lax.dynamic_slice_in_dim(yt, idx * chunk, chunk)
        valid = jax.lax.dynamic_slice_in_dim(is_real, idx * chunk, chunk)
        if bf16:
            # bf16Distances: the cross term on the MXU in bf16 with f32
            # accumulation; norms stay f32 (HasBf16Distances contract)
            cross = jax.lax.dot_general(
                xq_mm, xc.astype(jnp.bfloat16),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        else:
            cross = xq @ xc.T
        d = xq2 - 2.0 * cross + jnp.sum(xc * xc, axis=1)
        d = jnp.where(valid, d, jnp.inf)
        # merge running best with this chunk, re-select top-k
        cat_d = jnp.concatenate([best_d, d], axis=1)
        cat_y = jnp.concatenate([best_y, jnp.broadcast_to(yc, (n, chunk))], axis=1)
        neg_top, pos = jax.lax.top_k(-cat_d, k)
        return (-neg_top, jnp.take_along_axis(cat_y, pos, axis=1)), None

    # the +0 broadcasts inherit the inputs' varying-manual-axes (vma) status,
    # so the scan carry type-checks both under plain jit and inside a
    # shard_map where xt/yt vary over the mesh
    init = (
        jnp.full((n, k), jnp.inf, dtype=xq.dtype) + 0.0 * xq[:, :1],
        jnp.zeros((n, k), dtype=yt.dtype) + 0.0 * yt[:1],
    )
    (best_d, best_y), _ = jax.lax.scan(scan_chunk, init, jnp.arange(n_chunks))
    return best_y, best_d


@lru_cache(maxsize=32)
def _knn_apply_model_sharded(mesh, k, chunk, n_classes, bf16=False):
    """Reference-set-sharded kNN: the model (xt/yt) shards over 'data' so it
    need not fit one chip's HBM; queries replicate.

    Each device computes the full query batch's top-k against its local
    reference shard (the per-shard candidates), then one ``all_gather`` of
    the (n, k) candidate sets over ICI merges them into the global top-k —
    broadcast-variable semantics (ModelMapperAdapter.java:53-61) scaled past
    one device's memory.  Work parallelizes over the reference dimension
    instead of the query dimension; total FLOPs are identical to the
    replicated path and the candidate exchange is k/|shard| of the distance
    traffic a naive gather of distances would move.
    """
    from jax.sharding import PartitionSpec as P

    def local_candidates(xq, xt_local, yt_local):
        # queries are replicated (unvarying) but meet the varying reference
        # shard inside the top-k scan carry: mark them varying up front
        xq = pvary(xq, ("data",))
        labels, dists = _knn_chunked(xq, xt_local, yt_local, k, chunk, bf16)
        # leading size-1 axis: the shard_map output gather stacks shards
        # there, giving (n_dev, n, k, 2) without any in-program collective
        return jnp.stack([labels, dists], axis=2)[None]

    sharded = shard_map(
        local_candidates,
        mesh=mesh,
        in_specs=(P(), P("data"), P("data")),
        out_specs=P("data"),
        check_vma=True,
    )

    def apply(xq, xt, yt):
        cand = sharded(xq, xt, yt)  # (n_dev, n, k, 2) per-shard candidates
        n = xq.shape[0]
        # concat in mesh-device order = global row-block order, each shard's
        # candidates lex-sorted by (d, idx): positional top_k tie-break
        # therefore equals the canonical (d, global idx) selection the
        # replicated scan makes (see _knn_chunked docstring)
        cat_y = jnp.transpose(cand[..., 0], (1, 0, 2)).reshape(n, -1)
        cat_d = jnp.transpose(cand[..., 1], (1, 0, 2)).reshape(n, -1)
        neg_top, pos = jax.lax.top_k(-cat_d, k)
        best_d = -neg_top
        best_y = jnp.take_along_axis(cat_y, pos, axis=1)
        pred = _majority_vote(best_y.astype(jnp.int32), best_d, n_classes)
        return jnp.concatenate(
            [pred[:, None].astype(xq.dtype), best_d.astype(xq.dtype)], axis=1
        )

    return jax.jit(apply)


@lru_cache(maxsize=32)
def _knn_apply(mesh, k, chunk, n_classes, bf16=False):
    """Mesh-sharded kNN transform: query rows shard over 'data', the training
    set (the model) replicates to every device — the broadcast-variable
    analog (ModelMapperAdapter.java:53-61) for the benchmark transform
    workload.  Plain jit on a single chip."""
    from flink_ml_tpu.parallel.collectives import make_data_parallel_apply

    def forward(xq, xt, yt):
        labels, dists = _knn_chunked(xq, xt, yt, k, chunk, bf16)
        pred = _majority_vote(labels.astype(jnp.int32), dists, n_classes)
        # class ids and distances are exact in f32 (ids are small ints);
        # staying f32 avoids per-call x64 truncation on TPU
        return jnp.concatenate(
            [pred[:, None].astype(xq.dtype), dists.astype(xq.dtype)], axis=1
        )

    return make_data_parallel_apply(forward, mesh, n_args=3)


@partial(jax.jit, static_argnums=(2,))
def _majority_vote(labels, dists, n_classes):
    """Mode of each row of integer class ids via one-hot sum (ties -> lowest id).

    Slots that never matched a real training row (distance inf — possible when
    k exceeds the training-set size) carry no vote: one_hot of an out-of-range
    id contributes all-zeros.
    """
    labels = jnp.where(jnp.isfinite(dists), labels, n_classes)
    one_hot = jax.nn.one_hot(labels, n_classes, dtype=jnp.float32)
    votes = jnp.sum(one_hot, axis=1)
    return jnp.argmax(votes, axis=1)


class KnnModelMapper(ModelMapper):
    def __init__(self, model: "KnnModel", data_schema: Schema):
        self._model_stage = model
        super().__init__([KNN_MODEL_SCHEMA], data_schema, model.get_params())

    def reserved_cols(self) -> Optional[list]:
        return self._model_stage.get_reserved_cols()

    def output_cols(self):
        model = self._model_stage
        names = [model.get_prediction_col()]
        types = [DataTypes.DOUBLE]
        if model.get_prediction_detail_col() is not None:
            names.append(model.get_prediction_detail_col())
            types.append(DataTypes.DOUBLE)
        return names, types

    def load_model(self, *model_tables: Table) -> None:
        (t,) = model_tables
        X = t.features_dense("features")  # matrix-backed or object column
        y = np.asarray(t.col("label"), dtype=np.float64)
        k = self._model_stage.get_k()
        if k > len(y):
            raise ValueError(f"k={k} exceeds training-set size {len(y)}")
        # class-id encoding for the vote
        self._classes = np.unique(y)
        y_ids = np.searchsorted(self._classes, y)
        # host references for the circuit-breaker CPU fallback (the
        # reference set IS the model; a dead device path must still answer
        # queries).  References, not f32 copies: the fallback converts one
        # reference chunk at a time, so the healthy path pays no extra
        # host residency beyond the model table it already holds
        self._xt_host = X
        self._yt_ids = np.asarray(y_ids, dtype=np.int64)

        from flink_ml_tpu.parallel.mesh import (
            data_parallel_size,
            inference_mesh,
        )
        from flink_ml_tpu.utils.environment import MLEnvironmentFactory

        # multi-process, the model places on the process-LOCAL mesh: each
        # process holds its own full model copy and scores its own rows
        # (subtask-local ModelMapperAdapter semantics); shardModelData then
        # spreads the reference set over this process's chips only
        mesh = inference_mesh(MLEnvironmentFactory.get_default().get_mesh())
        n_dev = data_parallel_size(mesh)
        self._sharded = (
            bool(self._model_stage.get_shard_model_data()) and n_dev > 1
        )
        shards = n_dev if self._sharded else 1
        # chunk bounds the per-device distance-matrix slice; under model
        # sharding it is sized on the LOCAL shard, so per-device HBM holds
        # 1/n_dev of the reference set
        local = -(-max(X.shape[0], 1) // shards)
        chunk = min(8192, max(256, 1 << int(np.ceil(np.log2(local)))))
        n_pad = shards * (-(-local // chunk) * chunk)

        def place_model():
            Xp = np.zeros((n_pad, X.shape[1]), dtype=np.float32)
            Xp[: X.shape[0]] = X
            # inf marks padding (never wins top-k); f32 holds class ids
            # exactly
            yp = np.full((n_pad,), np.inf, dtype=np.float32)
            yp[: y.shape[0]] = y_ids
            if self._sharded:
                # direct local placement (not shard_batch, whose
                # multi-process branch assembles GLOBAL batches): the
                # inference mesh is fully addressable by this process in
                # every configuration
                from jax.sharding import NamedSharding, PartitionSpec as P

                return (
                    jax.device_put(Xp, NamedSharding(mesh, P("data"))),
                    jax.device_put(yp, NamedSharding(mesh, P("data"))),
                )
            return jnp.asarray(Xp), jnp.asarray(yp)

        # the placed reference set IS the model; for Knn that is the whole
        # training table, so re-loading the same model content (a fresh
        # mapper over the same model table) must hit the slab pool instead
        # of re-transferring the training set
        from flink_ml_tpu.table import slab_pool

        if slab_pool.enabled():
            refs: list = []
            token = (slab_pool.array_token(X, refs),
                     slab_pool.array_token(y, refs))
            # agreed=False: model load happens on the process-LOCAL
            # inference mesh with no cross-process collectives — the pool
            # must not add one
            self._xt, self._yt = slab_pool.pool().get_or_build(
                ("knn-model", mesh, self._sharded, chunk, n_pad, token),
                place_model, refs=refs, agreed=False,
            )
        else:
            self._xt, self._yt = place_model()
        self._chunk = chunk

    def serve_validation_spec(self):
        model = self._model_stage
        return {
            "dim": int(self._xt.shape[1]),
            "vector_col": model.get_vector_col(),
            "feature_cols": model.get_feature_cols(),
        }

    def map_batch(self, batch: Table):
        from flink_ml_tpu import serve

        model = self._model_stage
        k = model.get_k()
        X, _ = resolve_features(batch, model, dim=int(self._xt.shape[1]))
        X = X.astype(np.float32)
        n = X.shape[0]
        apply_factory = (
            _knn_apply_model_sharded if self._sharded else _knn_apply
        )
        out = serve.dispatch(
            self.serve_name(),
            device=lambda: apply_sharded(
                lambda mesh: apply_factory(
                    mesh, k, self._chunk, len(self._classes),
                    bool(model.get_bf16_distances()),
                ),
                X, self._xt, self._yt,
            ),
            fallback=lambda: self._map_cpu(X, k),
        )
        return self._vote_cols(out[:n])

    def _vote_cols(self, out):
        model = self._model_stage
        pred_ids = out[:, 0].astype(np.int64)
        result = {model.get_prediction_col(): self._classes[pred_ids]}
        detail = model.get_prediction_detail_col()
        if detail is not None:
            result[detail] = np.sqrt(np.maximum(out[:, 1], 0.0))  # nearest distance
        return result

    def fused_kernel(self):
        if self._sharded:
            # a data-axis-sharded reference set computes under its own
            # collective-bearing apply; it cannot ride a replicated-args
            # fused program — the plan splits and serves as today
            return None
        from flink_ml_tpu.common.fused import FusedInput, FusedKernel

        model = self._model_stage
        k = model.get_k()
        chunk = self._chunk
        n_classes = len(self._classes)
        bf16 = bool(model.get_bf16_distances())
        feature_cols = model.get_feature_cols()

        def fn(xq, xt, yt):
            labels, dists = _knn_chunked(xq, xt, yt, k, chunk, bf16)
            pred = _majority_vote(labels.astype(jnp.int32), dists, n_classes)
            return {"knn": jnp.concatenate(
                [pred[:, None].astype(xq.dtype), dists.astype(xq.dtype)],
                axis=1,
            )}

        return FusedKernel(
            inputs=[FusedInput(
                dim=int(self._xt.shape[1]),
                vector_col=model.get_vector_col(),
                feature_cols=tuple(feature_cols) if feature_cols else None,
            )],
            fn=fn,
            out_keys=("knn",),
            # fn closes over program-shaping constants invisible in the
            # arg shapes — they must key the warm-artifact entry
            cache_token=(k, chunk, n_classes, bf16),
            model_args=(self._xt, self._yt),
            finalize=lambda fetched, n: self._vote_cols(fetched["knn"]),
        )

    #: reference rows per CPU-fallback chunk — bounds the fallback's
    #: distance-matrix slice to O(batch x chunk), mirroring the device scan
    CPU_FALLBACK_CHUNK = 8192

    def _map_cpu(self, X: np.ndarray, k: int) -> np.ndarray:
        """NumPy top-k + vote fallback with the device scan's memory bound:
        the reference set streams through in chunks, a running best-k
        carries across them, and memory stays O(batch x chunk) — never the
        full (batch, train) matrix (a million-row model's fallback must
        not OOM the serving host during the exact outage it exists for).
        Tie-break parity with the device scan: the carry is sorted by
        (distance, global row index) and each chunk appends rows in index
        order, so a stable selection keeps the lower global index on exact
        ties; votes break ties toward the lowest class id."""
        xt, yt = self._xt_host, self._yt_ids
        n = X.shape[0]
        chunk = self.CPU_FALLBACK_CHUNK
        x2 = np.sum(X * X, axis=1, keepdims=True, dtype=np.float32)
        best_d = np.full((n, k), np.inf, dtype=np.float32)
        best_y = np.zeros((n, k), dtype=np.int64)
        for a in range(0, xt.shape[0], chunk):
            xc = np.asarray(xt[a : a + chunk], dtype=np.float32)
            yc = yt[a : a + chunk]
            d = x2 - 2.0 * (X @ xc.T) + np.sum(xc * xc, axis=1)
            cat_d = np.concatenate([best_d, d.astype(np.float32)], axis=1)
            cat_y = np.concatenate(
                [best_y, np.broadcast_to(yc, (n, yc.shape[0]))], axis=1
            )
            order = np.argsort(cat_d, axis=1, kind="stable")[:, :k]
            best_d = np.take_along_axis(cat_d, order, axis=1)
            best_y = np.take_along_axis(cat_y, order, axis=1)
        n_classes = len(self._classes)
        votes = np.zeros((n, n_classes), dtype=np.int64)
        for c in range(n_classes):
            votes[:, c] = np.sum(
                np.logical_and(best_y == c, np.isfinite(best_d)), axis=1
            )
        pred = np.argmax(votes, axis=1)  # argmax keeps the lowest id on ties
        return np.concatenate(
            [pred[:, None].astype(np.float32), best_d], axis=1
        )


class KnnModel(TableModelBase, KnnParams):
    """Brute-force kNN classifier; model data = the training table."""

    REQUIRED_MODEL_COL = "features"

    def _make_mapper(self, data_schema: Schema) -> KnnModelMapper:
        return KnnModelMapper(self, data_schema)


class Knn(Estimator, KnnParams, HasLabelCol):
    """Estimator: fit = pack the training table into the model-data layout."""

    def fit(self, *inputs: Table) -> KnnModel:
        (table,) = inputs
        X, dim = resolve_features(table, self)
        y = np.asarray(table.col(self.get_label_col()), dtype=np.float64)
        model = KnnModel()
        model.get_params().merge(self.get_params())
        # matrix-backed model column: the training set stays one contiguous
        # array end-to-end (fit -> model table -> device placement)
        model.set_model_data(Table.from_columns(
            KNN_MODEL_SCHEMA, {"features": np.asarray(X), "label": y}
        ))
        obs.fit_report(
            type(self).__name__,
            extra={"n_train": int(len(y)), "dim": int(dim)},
        )
        return model
