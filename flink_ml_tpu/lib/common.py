"""Shared training/inference harness for the algorithm library.

This is where the reference's training topology (SURVEY.md §3.3: per-record
gradient map -> network-shuffle reduce -> average -> rebroadcast, repeated
per round) becomes one compiled TPU program per epoch:

  * rows are packed ONCE into device-major minibatch stacks (static shapes,
    padded with zero-weight rows so padding never biases gradients);
  * one epoch = one ``make_data_parallel_step`` call: each mesh slice scans
    its local minibatches with ``lax.scan``, gradients are ``psum``'d over
    the ``data`` axis inside the step (the allreduce rides ICI), parameters
    stay replicated — the whole round trip that Flink does through its
    network stack never leaves the chip;
  * epochs surface through the bounded iteration runtime, so listeners and
    termination (max epochs / tol on update norm — the device-friendly analog
    of the empty-termination-criteria-stream rule) keep reference semantics.

Inference: model packed to device arrays once (the broadcast-variable analog),
rows applied in padded power-of-two buckets to bound jit recompiles.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.iteration.bounded import (
    IterationBodyResult,
    ReplayableInputs,
    iterate_bounded,
)
from flink_ml_tpu.iteration.config import IterationConfig
from flink_ml_tpu.parallel.collectives import make_data_parallel_step, psum
from flink_ml_tpu.table.table import Table


def resolve_features(
    table: Table, stage, dim: Optional[int] = None
) -> Tuple[np.ndarray, int]:
    """Feature matrix from either ``vectorCol`` or ``featureCols`` params.

    The column-selection convention of the shared param vocabulary
    (SURVEY.md §2.3.5): an algorithm reads its features from one vector
    column or a list of numeric columns.  ``dim`` pins the vector width at
    inference time (the trained model's dimension).
    """
    vector_col = stage.get_vector_col()
    feature_cols = stage.get_feature_cols()
    if (vector_col is None) == (feature_cols is None):
        raise ValueError("set exactly one of vectorCol / featureCols")
    if vector_col is not None:
        X = table.features_dense(vector_col, dim=dim)
    else:
        X = table.numeric_matrix(feature_cols)
    return X, X.shape[1]


@dataclass
class MinibatchStack:
    """Device-major stacked minibatches with a padding mask.

    ``x``/``y``/``w`` have leading dims ``(n_dev * steps, mb)`` — dim 0 is
    sharded over the ``data`` mesh axis, so each device scans ``steps`` local
    minibatches of ``mb`` rows.  ``w`` is 1.0 for real rows, 0.0 for padding.
    """

    x: np.ndarray  # (n_dev*steps, mb, d)
    y: np.ndarray  # (n_dev*steps, mb)
    w: np.ndarray  # (n_dev*steps, mb)
    steps: int
    mb: int


def pack_minibatches(
    X: np.ndarray,
    y: np.ndarray,
    n_dev: int,
    global_batch_size: int = 0,
    dtype=np.float32,
) -> MinibatchStack:
    """Pack rows into the device-major minibatch layout.

    ``global_batch_size`` rows are consumed per SGD step across the whole
    mesh (0 = full batch).  Rows are padded to fill the last minibatch; pad
    rows carry weight 0 so sums/counts are exact.
    """
    n, d = X.shape
    if global_batch_size <= 0:
        global_batch_size = max(n, n_dev)
    mb = max(1, -(-global_batch_size // n_dev))  # per-device minibatch rows
    steps = max(1, -(-n // (mb * n_dev)))
    n_pad = steps * mb * n_dev

    Xp = np.zeros((n_pad, d), dtype=dtype)
    yp = np.zeros((n_pad,), dtype=dtype)
    wp = np.zeros((n_pad,), dtype=dtype)
    Xp[:n] = X
    yp[:n] = y
    wp[:n] = 1.0

    # device-major: device k owns rows [k*steps*mb, (k+1)*steps*mb), scanned
    # as `steps` minibatches — row order within a device is preserved
    Xp = Xp.reshape(n_dev, steps, mb, d).reshape(n_dev * steps, mb, d)
    yp = yp.reshape(n_dev, steps, mb).reshape(n_dev * steps, mb)
    wp = wp.reshape(n_dev, steps, mb).reshape(n_dev * steps, mb)
    return MinibatchStack(x=Xp, y=yp, w=wp, steps=steps, mb=mb)


# A gradient function: (params, x_mb, y_mb, w_mb) ->
#   (grads pytree matching params, weighted loss sum, weight sum)
GradFn = Callable


@dataclass
class SparseMinibatchStack:
    """Device-major sparse minibatches in padded segment-CSR layout.

    The Criteo-scale replacement for per-record SparseVector math
    (BLAS.java:205-233, SURVEY.md §7.3 'sparse features at Criteo scale'):
    every minibatch is a fixed-size segment-COO block, so the whole training
    set is two dense arrays XLA can shard and scan — no ragged shapes.

      ints   (n_dev*steps, 2, nnz_pad) int32 — [col index, local row id] per
             stored value; pad entries carry row id ``mb`` (dropped by
             segment_sum) and col index 0 with value 0.
      floats (n_dev*steps, nnz_pad + 2*mb) — [values | y | w] concatenated so
             the host->device hop is one float and one int transfer.
    """

    ints: np.ndarray
    floats: np.ndarray
    steps: int
    mb: int
    nnz_pad: int
    dim: int


def pack_sparse_minibatches(
    vectors: Sequence,
    y: np.ndarray,
    n_dev: int,
    global_batch_size: int = 0,
    dim: Optional[int] = None,
    pad_multiple: int = 512,
) -> SparseMinibatchStack:
    """Pack SparseVector rows into the device-major sparse layout.

    Out-of-range feature indices fail loudly here: XLA's gather clamps and
    segment_sum drops them, which would silently train a corrupted model.
    """
    n = len(vectors)
    max_idx = -1
    for v in vectors:
        if len(v.indices):
            max_idx = max(max_idx, int(v.indices.max()))
    if dim is None:
        dim = max_idx + 1
        for v in vectors:
            size = v.size()
            if size >= 0:
                dim = max(dim, size)
    elif max_idx >= dim:
        raise ValueError(
            f"feature index {max_idx} out of range for numFeatures={dim}"
        )
    dim = max(dim, 1)
    if global_batch_size <= 0:
        global_batch_size = max(n, n_dev)
    mb = max(1, -(-global_batch_size // n_dev))
    steps = max(1, -(-n // (mb * n_dev)))
    n_groups = n_dev * steps

    # max nnz over minibatches, padded to a bucket multiple (shared static shape)
    nnz_max = 1
    for g in range(n_groups):
        k, s = divmod(g, steps)
        lo = k * steps * mb + s * mb
        nnz_max = max(
            nnz_max,
            sum(len(vectors[i].indices) for i in range(lo, min(lo + mb, n))),
        )
    nnz_pad = -(-nnz_max // pad_multiple) * pad_multiple

    ints = np.zeros((n_groups, 2, nnz_pad), dtype=np.int32)
    ints[:, 1, :] = mb  # pad row id -> dropped segment
    floats = np.zeros((n_groups, nnz_pad + 2 * mb), dtype=np.float32)
    for g in range(n_groups):
        k, s = divmod(g, steps)
        lo = k * steps * mb + s * mb
        pos = 0
        for j in range(mb):
            i = lo + j
            if i >= n:
                break
            v = vectors[i]
            cnt = len(v.indices)
            ints[g, 0, pos : pos + cnt] = v.indices
            ints[g, 1, pos : pos + cnt] = j
            floats[g, pos : pos + cnt] = v.vals
            pos += cnt
            floats[g, nnz_pad + j] = y[i]
            floats[g, nnz_pad + mb + j] = 1.0
    return SparseMinibatchStack(
        ints=ints, floats=floats, steps=steps, mb=mb, nnz_pad=nnz_pad, dim=dim
    )


# Compiled epoch steps are reused across fit() calls: rebuilding the jitted
# shard_map per fit would force a fresh XLA compile every time (~1s), which
# dominates short training runs.  Keyed on (grad_fn, mesh, lr, reg) — grad-fn
# factories are memoized by their hyper-flags so equal configs hit the cache.
_EPOCH_STEP_CACHE: dict = {}


def make_glm_epoch_step(
    grad_fn: GradFn,
    mesh,
    learning_rate: float,
    reg: float = 0.0,
):
    """One epoch (all local minibatches, SGD updates with in-step psum) as a
    single data-parallel device call.

    Returns a callable ``epoch_step(params, batch) -> (params, (loss, delta))``
    where ``batch`` is the sharded MinibatchStack pytree ``(x, y, w)``,
    ``loss`` is the epoch's mean training loss and ``delta`` the L2 norm of
    the epoch's total parameter update (the convergence criterion).
    """
    key = (grad_fn, mesh, float(learning_rate), float(reg))
    cached = _EPOCH_STEP_CACHE.get(key)
    if cached is not None:
        return cached
    lr = float(learning_rate)
    l2 = float(reg)

    def local_epoch(params, batch):
        x, y, w = batch  # local: (steps, mb, d), (steps, mb), (steps, mb)

        def mb_step(p, xs):
            xb, yb, wb = xs
            grads, loss_sum, w_sum = grad_fn(p, xb, yb, wb)
            grads = jax.tree_util.tree_map(lambda g: psum(g, "data"), grads)
            loss_sum = psum(loss_sum, "data")
            w_sum = psum(w_sum, "data")
            count = jnp.maximum(w_sum, 1.0)
            new_p = jax.tree_util.tree_map(
                lambda pi, gi: pi - lr * (gi / count + l2 * pi), p, grads
            )
            return new_p, (loss_sum / count, w_sum)

        start = params
        params, (losses, counts) = jax.lax.scan(mb_step, params, (x, y, w))
        # weighted mean loss over the epoch; update norm for convergence
        total = jnp.maximum(jnp.sum(counts), 1.0)
        loss = jnp.sum(losses * counts) / total
        delta = jnp.sqrt(
            sum(
                jnp.sum((a - b) ** 2)
                for a, b in zip(
                    jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(start),
                )
            )
        )
        return params, (loss, delta)

    step = make_data_parallel_step(local_epoch, mesh)
    _EPOCH_STEP_CACHE[key] = step
    return step


@dataclass
class TrainResult:
    params: tuple
    epochs: int
    losses: list


def _combined_view(stack: MinibatchStack) -> np.ndarray:
    """x, y, w packed into one (n_dev*steps, mb, d+2) array — a single
    host->device transfer instead of three (transfer latency dominates on
    tunneled devices)."""
    return np.concatenate(
        [stack.x, stack.y[..., None], stack.w[..., None]], axis=2
    )


def _build_fused_train_fn(key, mb_grad_step, mesh, learning_rate, reg,
                          max_iter, tol):
    """The WHOLE training run as one compiled device program.

    Epochs are a ``lax.while_loop`` around the minibatch ``lax.scan``; the
    convergence test (update norm vs tol — the criteria-stream-empty analog)
    evaluates on device, so training runs start-to-finish with zero host
    round-trips: one transfer in (the packed batch), one out (params +
    per-epoch losses + epochs-run).  This is the fast path ``train_glm``
    takes when no per-epoch listeners are registered; the epoch watermark
    degenerates to the loop-carried epoch counter.

    ``mb_grad_step(params, mb_slice) -> (grads, loss_sum, w_sum)`` consumes
    one scanned minibatch slice of the batch pytree — the dense and sparse
    layouts differ only there.
    """
    cached = _EPOCH_STEP_CACHE.get(key)
    if cached is not None:
        return cached
    lr = float(learning_rate)
    l2 = float(reg)
    tol_ = float(tol)

    def local_train(params, batch):
        def mb_step(p, xs):
            grads, loss_sum, w_sum = mb_grad_step(p, xs)
            grads = jax.tree_util.tree_map(lambda g: psum(g, "data"), grads)
            loss_sum = psum(loss_sum, "data")
            w_sum = psum(w_sum, "data")
            count = jnp.maximum(w_sum, 1.0)
            new_p = jax.tree_util.tree_map(
                lambda pi, gi: pi - lr * (gi / count + l2 * pi), p, grads
            )
            return new_p, (loss_sum / count, w_sum)

        def run_epoch(params):
            start = params
            params, (losses, counts) = jax.lax.scan(mb_step, params, batch)
            total = jnp.maximum(jnp.sum(counts), 1.0)
            loss = jnp.sum(losses * counts) / total
            delta = jnp.sqrt(
                sum(
                    jnp.sum((a - b) ** 2)
                    for a, b in zip(
                        jax.tree_util.tree_leaves(params),
                        jax.tree_util.tree_leaves(start),
                    )
                )
            )
            return params, loss, delta

        def cond(carry):
            _, epoch, delta, _ = carry
            not_done = epoch < max_iter
            if tol_ > 0.0:
                not_done = jnp.logical_and(
                    not_done, jnp.logical_or(epoch == 0, delta > tol_)
                )
            return not_done

        def body(carry):
            params, epoch, _, loss_hist = carry
            params, loss, delta = run_epoch(params)
            loss_hist = loss_hist.at[epoch].set(loss)
            return params, epoch + 1, delta, loss_hist

        loss_hist0 = jnp.zeros((max_iter,), dtype=jnp.float32)
        params, epochs, _, loss_hist = jax.lax.while_loop(
            cond, body, (params, jnp.asarray(0), jnp.asarray(jnp.inf), loss_hist0)
        )
        return params, loss_hist, epochs

    from jax.sharding import PartitionSpec as P

    sharded = jax.shard_map(
        local_train,
        mesh=mesh,
        in_specs=(P(), P("data")),
        out_specs=(P(), P(), P()),
        check_vma=True,
    )
    fn = jax.jit(sharded, donate_argnums=(0,))
    _EPOCH_STEP_CACHE[key] = fn
    return fn


def _run_fused_train(train_fn, init_params, batch, mesh) -> TrainResult:
    """Shared epilogue: run the fused program and fetch params + loss
    history + epoch count back in ONE transfer."""
    from flink_ml_tpu.parallel.mesh import replicate, shard_batch

    params, loss_hist, epochs = train_fn(
        replicate(mesh, init_params), shard_batch(mesh, batch)
    )
    leaves, treedef = jax.tree_util.tree_flatten(params)
    fetched = fetch_flat(*leaves, loss_hist, jnp.asarray(epochs, jnp.float64))
    n_epochs = int(fetched[-1])
    host_params = jax.tree_util.tree_unflatten(treedef, fetched[: len(leaves)])
    return TrainResult(
        params=host_params,
        epochs=n_epochs,
        losses=[float(x) for x in fetched[-2][:n_epochs]],
    )


def make_glm_train_fn(
    grad_fn: GradFn,
    mesh,
    learning_rate: float,
    reg: float,
    max_iter: int,
    tol: float,
):
    """Fused training over the dense combined layout
    (see :func:`_build_fused_train_fn` for the program structure)."""
    key = ("train", grad_fn, mesh, float(learning_rate), float(reg),
           int(max_iter), float(tol))

    def mb_grad_step(p, mb):
        return grad_fn(p, mb[..., :-2], mb[..., -2], mb[..., -1])

    return _build_fused_train_fn(
        key, mb_grad_step, mesh, learning_rate, reg, max_iter, tol
    )


def make_sparse_glm_train_fn(
    kind: str,
    mesh,
    mb: int,
    nnz_pad: int,
    dim: int,
    learning_rate: float,
    reg: float,
    max_iter: int,
    tol: float,
    with_intercept: bool = True,
):
    """Fused training over :class:`SparseMinibatchStack` batches.

    ``kind`` picks the loss ('logistic' | 'squared').  The minibatch forward
    is ``segment_sum(values * gather(w))`` — the batched static-shape
    replacement for the reference's hand-rolled sparse gemv
    (BLAS.java:205-233); the gradient scatters back through the same
    segments.  Program structure is shared with the dense path via
    :func:`_build_fused_train_fn`.
    """
    if kind not in ("logistic", "squared"):
        raise ValueError(f"unknown loss kind {kind!r}")
    key = ("sparse", kind, mesh, mb, nnz_pad, dim,
           float(learning_rate), float(reg), int(max_iter), float(tol),
           bool(with_intercept))
    keep_b = 1.0 if with_intercept else 0.0

    def mb_grad_step(params, xs):
        ints, floats = xs  # (2, nnz_pad), (nnz_pad + 2*mb,)
        idx = ints[0]
        rid = ints[1]
        vals = floats[:nnz_pad]
        y = floats[nnz_pad : nnz_pad + mb]
        w = floats[nnz_pad + mb :]
        wts, b = params
        contrib = vals * jnp.take(wts, idx, axis=0)
        logits = jax.ops.segment_sum(contrib, rid, num_segments=mb) + b
        if kind == "logistic":
            p = jax.nn.sigmoid(logits)
            err = (p - y) * w
            loss_sum = jnp.sum(w * (jnp.logaddexp(0.0, logits) - y * logits))
        else:
            err = (logits - y) * w
            loss_sum = 0.5 * jnp.sum(err * (logits - y))
        err_ext = jnp.concatenate([err, jnp.zeros((1,), err.dtype)])
        g_w = jax.ops.segment_sum(
            vals * jnp.take(err_ext, rid, axis=0), idx, num_segments=dim
        )
        g_b = jnp.sum(err) * keep_b
        return (g_w, g_b), loss_sum, jnp.sum(w)

    return _build_fused_train_fn(
        key, mb_grad_step, mesh, learning_rate, reg, max_iter, tol
    )


def train_glm_sparse(
    init_params,
    sstack: SparseMinibatchStack,
    kind: str,
    mesh,
    learning_rate: float,
    max_iter: int,
    reg: float = 0.0,
    tol: float = 0.0,
    with_intercept: bool = True,
) -> TrainResult:
    """Sparse counterpart of :func:`train_glm` (always the fused device loop)."""
    train_fn = make_sparse_glm_train_fn(
        kind, mesh, sstack.mb, sstack.nnz_pad, sstack.dim,
        learning_rate, reg, max_iter, tol, with_intercept,
    )
    return _run_fused_train(
        train_fn, init_params, (sstack.ints, sstack.floats), mesh
    )


def fetch_flat(*arrays):
    """Fetch device arrays in ONE transfer (concatenated flat), then split.

    Per-array device->host reads each pay a full round-trip on tunneled
    backends; bundling them makes the readback latency constant.
    """
    shapes = [a.shape for a in arrays]
    sizes = [int(np.prod(s)) for s in shapes]
    flat = jnp.concatenate(
        [jnp.ravel(a).astype(jnp.float64) for a in arrays]
    )
    buf = np.asarray(flat)
    out = []
    off = 0
    for shape, size in zip(shapes, sizes):
        out.append(buf[off : off + size].reshape(shape))
        off += size
    return out


def train_glm(
    init_params,
    stack: MinibatchStack,
    grad_fn: GradFn,
    mesh,
    learning_rate: float,
    max_iter: int,
    reg: float = 0.0,
    tol: float = 0.0,
    listeners: Sequence = (),
) -> TrainResult:
    """Drive GLM training to termination.

    Termination mirrors the reference's two bounded modes: a max epoch count,
    and — when ``tol`` > 0 — an empty-criteria round, realized as "parameter
    update norm below tol" (SURVEY.md §3.5, IterationBodyResult.java:44-48).

    Without listeners the entire run is ONE device program (fused epoch
    while_loop, single transfer each way).  With listeners, epochs go through
    the bounded iteration runtime so per-epoch watermark callbacks fire.
    """
    from flink_ml_tpu.parallel.mesh import replicate, shard_batch

    if not listeners:
        train_fn = make_glm_train_fn(
            grad_fn, mesh, learning_rate, reg, max_iter, tol
        )
        return _run_fused_train(train_fn, init_params, _combined_view(stack), mesh)

    epoch_step = make_glm_epoch_step(grad_fn, mesh, learning_rate, reg)
    batch = shard_batch(mesh, (stack.x, stack.y, stack.w))
    params0 = replicate(mesh, init_params)
    losses: list = []

    def body(params, inputs, epoch):
        new_params, (loss, delta) = epoch_step(params, inputs["batch"])
        criteria = None
        if tol > 0.0:
            # convergence needs the value on host: one readback per epoch —
            # the device-friendly "criteria stream empty" check
            criteria = [1] if float(delta) > tol else []
        # keep the loss as a device value: converting here would sync every
        # epoch and collapse the async dispatch pipeline
        losses.append(loss)
        return IterationBodyResult(
            feedback=new_params,
            outputs={"loss": loss},
            termination_criteria=criteria,
        )

    result = iterate_bounded(
        params0,
        ReplayableInputs.replay(batch=batch),
        body,
        IterationConfig(max_epochs=max_iter),
        listeners=listeners,
    )
    final = jax.tree_util.tree_map(np.asarray, result.final_variables)
    return TrainResult(
        params=final, epochs=result.epochs_run, losses=[float(x) for x in losses]
    )


def bucket_rows(n: int, minimum: int = 256) -> int:
    """Next power-of-two row count >= n (bounds the jit cache for inference)."""
    b = minimum
    while b < n:
        b *= 2
    return b


def apply_batched(fn, X: np.ndarray, *args, bucket_minimum: int = 256) -> np.ndarray:
    """Run a jitted row function over X padded to a power-of-two bucket.

    ``fn(x_padded, *args)`` must be row-aligned; the result is sliced back to
    the true row count.  Padding rows are zeros.  A 0-row input still runs one
    padded bucket so the output keeps fn's true rank (sliced to 0 rows).
    """
    n = X.shape[0]
    b = bucket_rows(max(n, 1), bucket_minimum)
    if b != n:
        Xp = np.zeros((b,) + X.shape[1:], dtype=X.dtype)
        Xp[:n] = X
    else:
        Xp = X
    out = fn(jnp.asarray(Xp), *args)
    return np.asarray(out)[:n]
