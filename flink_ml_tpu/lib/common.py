"""Shared training/inference harness for the algorithm library.

This is where the reference's training topology (SURVEY.md §3.3: per-record
gradient map -> network-shuffle reduce -> average -> rebroadcast, repeated
per round) becomes one compiled TPU program per epoch:

  * rows are packed ONCE into device-major minibatch stacks (static shapes,
    padded with zero-weight rows so padding never biases gradients);
  * one epoch = one ``make_data_parallel_step`` call: each mesh slice scans
    its local minibatches with ``lax.scan``, gradients are ``psum``'d over
    the ``data`` axis inside the step (the allreduce rides ICI), parameters
    stay replicated — the whole round trip that Flink does through its
    network stack never leaves the chip;
  * epochs surface through the bounded iteration runtime, so listeners and
    termination (max epochs / tol on update norm — the device-friendly analog
    of the empty-termination-criteria-stream rule) keep reference semantics.

Inference: model packed to device arrays once (the broadcast-variable analog),
rows applied in padded power-of-two buckets to bound jit recompiles.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu import fault, obs
from flink_ml_tpu.iteration.bounded import (
    IterationBodyResult,
    ReplayableInputs,
    iterate_bounded,
)
from flink_ml_tpu.iteration.config import IterationConfig
from flink_ml_tpu.parallel.collectives import (
    make_data_parallel_step,
    psum,
    shard_map,
)
from flink_ml_tpu.table.table import Table
from flink_ml_tpu.utils.metrics import StepMetrics


def resolve_features(
    table: Table, stage, dim: Optional[int] = None
) -> Tuple[np.ndarray, int]:
    """Feature matrix from either ``vectorCol`` or ``featureCols`` params.

    The column-selection convention of the shared param vocabulary
    (SURVEY.md §2.3.5): an algorithm reads its features from one vector
    column or a list of numeric columns.  ``dim`` pins the vector width at
    inference time (the trained model's dimension).
    """
    vector_col = stage.get_vector_col()
    feature_cols = stage.get_feature_cols()
    if (vector_col is None) == (feature_cols is None):
        raise ValueError("set exactly one of vectorCol / featureCols")
    if vector_col is not None:
        X = table.features_dense(vector_col, dim=dim)
    else:
        X = table.numeric_matrix(feature_cols)
    return X, X.shape[1]


@dataclass
class MinibatchStack:
    """Device-major stacked minibatches with a padding mask.

    ``x``/``y``/``w`` have leading dims ``(n_dev * steps, mb)`` — dim 0 is
    sharded over the ``data`` mesh axis, so each device scans ``steps`` local
    minibatches of ``mb`` rows.  ``w`` is 1.0 for real rows, 0.0 for padding.
    """

    x: np.ndarray  # (n_dev*steps, mb, d)
    y: np.ndarray  # (n_dev*steps, mb)
    w: np.ndarray  # (n_dev*steps, mb)
    steps: int
    mb: int
    n_rows: int = 0  # true (un-padded) row count, for throughput metrics


@obs.phased("pack_dense")
def pack_minibatches(
    X: np.ndarray,
    y: np.ndarray,
    n_dev: int,
    global_batch_size: int = 0,
    dtype=np.float32,
    min_steps: int = 0,
) -> MinibatchStack:
    """Pack rows into the device-major minibatch layout.

    ``global_batch_size`` rows are consumed per SGD step across the whole
    mesh (0 = full batch).  Rows are padded to fill the last minibatch; pad
    rows carry weight 0 so sums/counts are exact.  ``min_steps`` floors the
    step count (whole-pad steps are all-zero-weight) — the out-of-core feed
    uses it so every chunk shares one compiled program shape.
    """
    n, d = X.shape
    if global_batch_size <= 0:
        global_batch_size = max(n, n_dev)
    mb = max(1, -(-global_batch_size // n_dev))  # per-device minibatch rows
    steps = max(max(1, -(-n // (mb * n_dev))), int(min_steps))
    n_pad = steps * mb * n_dev

    Xp = np.zeros((n_pad, d), dtype=dtype)
    yp = np.zeros((n_pad,), dtype=dtype)
    wp = np.zeros((n_pad,), dtype=dtype)
    Xp[:n] = X
    yp[:n] = y
    wp[:n] = 1.0

    # step-major rows in a device-contiguous layout: global SGD step s
    # consumes rows [s*G, (s+1)*G) where G = n_dev*mb — the reference's
    # record order — and device k takes the k-th mb-slice of each step
    # window.  Dim 0 stays device-contiguous so it shards over the 'data'
    # axis; crucially the step->rows mapping does not depend on the total
    # row count, so a chunked (out-of-core) feed cut at G-row boundaries
    # replays the identical update schedule (lib/out_of_core.py).
    Xp = Xp.reshape(steps, n_dev, mb, d).transpose(1, 0, 2, 3).reshape(n_dev * steps, mb, d)
    yp = yp.reshape(steps, n_dev, mb).transpose(1, 0, 2).reshape(n_dev * steps, mb)
    wp = wp.reshape(steps, n_dev, mb).transpose(1, 0, 2).reshape(n_dev * steps, mb)
    return MinibatchStack(x=Xp, y=yp, w=wp, steps=steps, mb=mb, n_rows=n)


# A gradient function: (params, x_mb, y_mb, w_mb) ->
#   (grads pytree matching params, weighted loss sum, weight sum)
GradFn = Callable


def make_sgd_update(learning_rate: float, l2: float):
    """``update(params, grads, count)``: one SGD step with L2 weight decay.

    Weight decay skips scalar leaves (the intercept) — the sklearn/Spark
    convention of not regularizing the bias term.  Shared by every training
    path (dense/sparse fused loops, epoch step, streaming SGD) so the update
    rule cannot drift between them.
    """
    lr = float(learning_rate)
    l2 = float(l2)

    def update(params, grads, count):
        return jax.tree_util.tree_map(
            lambda pi, gi: pi - lr * (gi / count + (l2 if pi.ndim else 0.0) * pi),
            params, grads,
        )

    return update


@dataclass
class SparseMinibatchStack:
    """Device-major sparse minibatches in padded segment-CSR layout.

    The Criteo-scale replacement for per-record SparseVector math
    (BLAS.java:205-233, SURVEY.md §7.3 'sparse features at Criteo scale'):
    every minibatch is a fixed-size segment-COO block, so the whole training
    set is two dense arrays XLA can shard and scan — no ragged shapes.

      ints   (n_dev*steps, 2, nnz_pad) int32 — [col index, local row id] per
             stored value; pad entries carry row id ``mb`` (dropped by
             segment_sum) and col index 0 with value 0.
      floats (n_dev*steps, nnz_pad + 2*mb) — [values | y | w] concatenated so
             the host->device hop is one float and one int transfer.
    """

    ints: np.ndarray
    floats: np.ndarray
    steps: int
    mb: int
    nnz_pad: int
    dim: int
    n_rows: int = 0  # true (un-padded) row count, for throughput metrics


@obs.phased("pack_sparse")
def pack_sparse_minibatches(
    vectors: Sequence,
    y: np.ndarray,
    n_dev: int,
    global_batch_size: int = 0,
    dim: Optional[int] = None,
    pad_multiple: int = 512,
    min_nnz_pad: int = 0,
    min_steps: int = 0,
) -> SparseMinibatchStack:
    """Pack sparse rows into the device-major sparse layout.

    ``vectors`` is a sequence of SparseVector (per-row Python loop) or a
    :class:`~flink_ml_tpu.ops.batch.CsrRows` column (fully vectorized — the
    fast path the native streaming loader feeds).  Out-of-range feature
    indices fail loudly here: XLA's gather clamps and segment_sum drops
    them, which would silently train a corrupted model.  ``min_nnz_pad``
    floors the padded nnz width — the out-of-core feed uses it to keep one
    static shape (one compiled program) across chunks.
    """
    from flink_ml_tpu.ops.batch import CsrRows

    if isinstance(vectors, CsrRows):
        return _pack_sparse_minibatches_csr(
            vectors, y, n_dev, global_batch_size, dim, pad_multiple,
            min_nnz_pad, min_steps,
        )
    n = len(vectors)
    max_idx = -1
    for r, v in enumerate(vectors):
        if len(v.indices):
            if int(v.indices.min()) < 0:
                raise ValueError(f"row {r}: negative feature index")
            max_idx = max(max_idx, int(v.indices.max()))
    if dim is None:
        dim = max_idx + 1
        for v in vectors:
            size = v.size()
            if size >= 0:
                dim = max(dim, size)
    elif max_idx >= dim:
        raise ValueError(
            f"feature index {max_idx} out of range for numFeatures={dim}"
        )
    dim = max(dim, 1)
    mb, steps, n_groups, _group_lo = _sparse_layout(
        n, n_dev, global_batch_size, min_steps
    )

    # max nnz over minibatches, padded to a bucket multiple (shared static shape)
    nnz_max = 1
    for g in range(n_groups):
        lo = _group_lo(g)
        nnz_max = max(
            nnz_max,
            sum(len(vectors[i].indices) for i in range(lo, min(lo + mb, n))),
        )
    nnz_pad = max(-(-nnz_max // pad_multiple) * pad_multiple, int(min_nnz_pad))

    ints = np.zeros((n_groups, 2, nnz_pad), dtype=np.int32)
    ints[:, 1, :] = mb  # pad row id -> dropped segment
    floats = np.zeros((n_groups, nnz_pad + 2 * mb), dtype=np.float32)
    for g in range(n_groups):
        lo = _group_lo(g)
        pos = 0
        for j in range(mb):
            i = lo + j
            if i >= n:
                break
            v = vectors[i]
            cnt = len(v.indices)
            ints[g, 0, pos : pos + cnt] = v.indices
            ints[g, 1, pos : pos + cnt] = j
            floats[g, pos : pos + cnt] = v.vals
            pos += cnt
            floats[g, nnz_pad + j] = y[i]
            floats[g, nnz_pad + mb + j] = 1.0
    return SparseMinibatchStack(
        ints=ints, floats=floats, steps=steps, mb=mb, nnz_pad=nnz_pad, dim=dim,
        n_rows=n,
    )


def _sparse_layout(n: int, n_dev: int, global_batch_size: int, min_steps: int):
    """The ONE copy of the sparse stack's scalar layout math: per-device
    minibatch rows, step count, group count, and the step-major group->row
    mapping (group g = device k, local step s covers rows
    [s*G + k*mb, s*G + (k+1)*mb) with G = n_dev*mb).  Shared by the per-row
    and vectorized CSR packers so their layouts cannot drift (their outputs
    are asserted byte-identical in tests)."""
    if global_batch_size <= 0:
        global_batch_size = max(n, n_dev)
    mb = max(1, -(-global_batch_size // n_dev))
    steps = max(max(1, -(-n // (mb * n_dev))), int(min_steps))

    def group_lo(g: int) -> int:
        k, s = divmod(g, steps)
        return s * (n_dev * mb) + k * mb

    return mb, steps, n_dev * steps, group_lo


def sparse_row_counts(vectors) -> np.ndarray:
    """Stored-entry count per row (CsrRows: vectorized; else per object)."""
    from flink_ml_tpu.ops.batch import CsrRows

    if isinstance(vectors, CsrRows):
        return vectors.nnz_per_row()
    return np.fromiter(
        (len(v.indices) for v in vectors), np.int64, len(vectors)
    )


def sparse_layout_floors(counts: np.ndarray, n_dev: int,
                         global_batch_size: int,
                         pad_multiple: int = 512):
    """(nnz_pad, steps) the pack WOULD choose for these row counts — without
    materializing the stack.  The multi-process agreement pre-scan: each
    process computes its local value here, ``agree_max`` reconciles them,
    and the single pack runs with the agreed floors (no throwaway pack)."""
    n = int(len(counts))
    mb, steps, n_groups, group_lo = _sparse_layout(
        n, n_dev, global_batch_size, 0
    )
    csum = np.concatenate([[0], np.cumsum(np.asarray(counts, np.int64))])
    los = np.minimum(
        np.asarray([group_lo(g) for g in range(n_groups)], np.int64), n
    )
    his = np.minimum(los + mb, n)
    nnz_max = max(1, int((csum[his] - csum[los]).max(initial=0)))
    return -(-nnz_max // pad_multiple) * pad_multiple, steps


@obs.phased("pack_csr")
def _pack_sparse_minibatches_csr(
    rows, y, n_dev: int, global_batch_size: int, dim, pad_multiple: int,
    min_nnz_pad: int, min_steps: int,
) -> SparseMinibatchStack:
    """Vectorized packing from a CSR column: identical layout and validation
    to the per-row path (shared tests assert bit-equality), but the inner
    work is numpy slice copies — O(groups) Python instead of O(rows)."""
    n = len(rows)
    indptr, indices, values = rows.indptr, rows.indices, rows.values
    nnz_total = int(indptr[-1]) if n else 0
    max_idx = int(indices.max()) if nnz_total else -1
    if nnz_total and int(indices.min()) < 0:
        first_bad = int(np.argmax(indices < 0))
        row = int(np.searchsorted(indptr, first_bad, side="right")) - 1
        raise ValueError(f"row {row}: negative feature index")
    if nnz_total:
        # per-row ascending ids are a layout invariant downstream (the
        # hot-slab scatter declares its (rid, pos) tuples sorted); the
        # SparseVector path sorts at construction, but CSR columns from
        # the native loader carry file order verbatim — sort here when a
        # file violates it (one vectorized pass detects; per-row argsort
        # only runs on violation)
        adjacent_same_row = np.ones(nnz_total - 1, dtype=bool)
        row_ends = indptr[1:-1] - 1  # pair (i, i+1) crosses a row boundary
        # empty leading rows repeat indptr[i]=0 (row_ends -1) and empty
        # trailing rows repeat indptr[i]=nnz_total (row_ends nnz_total-1,
        # past the last PAIR) — both carry no adjacent pair to mask
        adjacent_same_row[
            row_ends[(row_ends >= 0) & (row_ends < nnz_total - 1)]
        ] = False
        if np.any((np.diff(indices.astype(np.int64)) <= 0)
                  & adjacent_same_row):
            order = np.argsort(
                indices + (np.repeat(
                    np.arange(n, dtype=np.int64), np.diff(indptr)
                ) << 32),
                kind="stable",
            )
            indices = indices[order]
            values = values[order]
    if dim is None:
        dim = max(max_idx + 1, rows.dim)
    elif max_idx >= dim:
        raise ValueError(
            f"feature index {max_idx} out of range for numFeatures={dim}"
        )
    dim = max(dim, 1)
    mb, steps, n_groups, _group_lo = _sparse_layout(
        n, n_dev, global_batch_size, min_steps
    )

    counts = rows.nnz_per_row()
    nnz_max = 1
    bounds = []
    for g in range(n_groups):
        lo = _group_lo(g)
        hi = min(lo + mb, n)
        if lo >= n:
            bounds.append((lo, lo, 0, 0))
            continue
        e0, e1 = int(indptr[lo]), int(indptr[hi])
        bounds.append((lo, hi, e0, e1))
        nnz_max = max(nnz_max, e1 - e0)
    nnz_pad = max(-(-nnz_max // pad_multiple) * pad_multiple, int(min_nnz_pad))

    ints = np.zeros((n_groups, 2, nnz_pad), dtype=np.int32)
    ints[:, 1, :] = mb  # pad row id -> dropped segment
    floats = np.zeros((n_groups, nnz_pad + 2 * mb), dtype=np.float32)
    for g, (lo, hi, e0, e1) in enumerate(bounds):
        if lo >= n:
            continue
        cnt = e1 - e0
        ints[g, 0, :cnt] = indices[e0:e1]
        ints[g, 1, :cnt] = np.repeat(
            np.arange(hi - lo, dtype=np.int32), counts[lo:hi]
        )
        floats[g, :cnt] = values[e0:e1]
        floats[g, nnz_pad : nnz_pad + (hi - lo)] = y[lo:hi]
        floats[g, nnz_pad + mb : nnz_pad + mb + (hi - lo)] = 1.0
    return SparseMinibatchStack(
        ints=ints, floats=floats, steps=steps, mb=mb, nnz_pad=nnz_pad, dim=dim,
        n_rows=n,
    )


# Compiled epoch steps are reused across fit() calls: rebuilding the jitted
# shard_map per fit would force a fresh XLA compile every time (~1s), which
# dominates short training runs.  Keyed on (grad_fn, mesh, lr, reg) — grad-fn
# factories are memoized by their hyper-flags so equal configs hit the cache.
# LRU-bounded: long-lived processes sweeping hyperparameters (or chunked
# checkpoint runs with varying chunk sizes) would otherwise retain every
# compiled executable forever.
from collections import OrderedDict

_EPOCH_STEP_CACHE: OrderedDict = OrderedDict()
_EPOCH_STEP_CACHE_CAPACITY = 32

#: builds consumed by the most recent fused run (compile-run attribution)
_RUN_BUILDS_SEEN = 0


def _cache_get(key):
    fn = _EPOCH_STEP_CACHE.get(key)
    if fn is not None:
        _EPOCH_STEP_CACHE.move_to_end(key)
    return fn


#: monotonic count of FUSED-train program builds this process (independent
#: of the obs registry so it survives ``obs.reset()`` and runs with obs
#: off).  Only programs consumed by :func:`_run_fused_train` count — chunk
#: programs (out_of_core) share the cache but have their own driver, and
#: attributing their builds here would mark a cache-warm fused fit as
#: compile-bearing whenever the paths interleave.
_FUSED_PROGRAM_BUILDS = 0


def _cache_put(key, fn, fused: bool = False):
    global _FUSED_PROGRAM_BUILDS
    _EPOCH_STEP_CACHE[key] = fn
    while len(_EPOCH_STEP_CACHE) > _EPOCH_STEP_CACHE_CAPACITY:
        _EPOCH_STEP_CACHE.popitem(last=False)
    # a build here means the next dispatch pays an XLA compile — the
    # counter lets a RunReport distinguish compile-bearing fits from
    # cache-warm ones
    if fused:
        _FUSED_PROGRAM_BUILDS += 1
    obs.counter_add("train.program_builds")
    return fn


def make_glm_epoch_step(
    grad_fn: GradFn,
    mesh,
    learning_rate: float,
    reg: float = 0.0,
):
    """One epoch (all local minibatches, SGD updates with in-step psum) as a
    single data-parallel device call.

    Returns a callable ``epoch_step(params, batch) -> (params, (loss, delta))``
    where ``batch`` is the sharded MinibatchStack pytree ``(x, y, w)``,
    ``loss`` is the epoch's mean training loss and ``delta`` the L2 norm of
    the epoch's total parameter update (the convergence criterion).
    """
    check_vma = getattr(grad_fn, "shard_map_check_vma", True)
    key = (grad_fn, mesh, float(learning_rate), float(reg))
    cached = _cache_get(key)
    if cached is not None:
        return cached
    sgd_update = make_sgd_update(learning_rate, reg)

    def local_epoch(params, batch):
        x, y, w = batch  # local: (steps, mb, d), (steps, mb), (steps, mb)

        def mb_step(p, xs):
            xb, yb, wb = xs
            grads, loss_sum, w_sum = grad_fn(p, xb, yb, wb)
            grads = jax.tree_util.tree_map(lambda g: psum(g, "data"), grads)
            loss_sum = psum(loss_sum, "data")
            w_sum = psum(w_sum, "data")
            count = jnp.maximum(w_sum, 1.0)
            new_p = sgd_update(p, grads, count)
            return new_p, (loss_sum / count, w_sum)

        start = params
        params, (losses, counts) = jax.lax.scan(mb_step, params, (x, y, w))
        # weighted mean loss over the epoch; update norm for convergence
        total = jnp.maximum(jnp.sum(counts), 1.0)
        loss = jnp.sum(losses * counts) / total
        delta = jnp.sqrt(
            sum(
                jnp.sum((a - b) ** 2)
                for a, b in zip(
                    jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(start),
                )
            )
        )
        return params, (loss, delta)

    return _cache_put(
        key, make_data_parallel_step(local_epoch, mesh, check_vma=check_vma)
    )


@dataclass
class TrainResult:
    params: tuple
    epochs: int
    losses: list
    final_delta: Optional[float] = None
    #: StepMetrics recorded by the driver (SURVEY §5.5: samples/sec/chip is
    #: first-class) — fused runs record one step per device program, host-loop
    #: runs one step per epoch.  Zero-work resumes carry an empty recorder so
    #: ``metrics.summary()`` is always safe to call.
    metrics: StepMetrics = field(default_factory=lambda: StepMetrics("fused_train"))


def _combined_view(stack: MinibatchStack) -> np.ndarray:
    """x, y, w packed into one (n_dev*steps, mb, d+2) array — a single
    host->device transfer instead of three (transfer latency dominates on
    tunneled devices)."""
    return np.concatenate(
        [stack.x, stack.y[..., None], stack.w[..., None]], axis=2
    )


def _combined_view_memo(stack: MinibatchStack) -> np.ndarray:
    """Per-stack memo of :func:`_combined_view`: repeated fused fits from
    the SAME stack must present the SAME host array, or the slab pool's
    identity keying would see a fresh buffer (and re-place) every call.
    Estimator paths supply a pooled ``device_batch`` and never reach this;
    it serves direct ``train_glm`` callers (tests, sweeps over a retained
    stack)."""
    comb = getattr(stack, "_combined_memo", None)
    if comb is None:
        comb = _combined_view(stack)
        stack._combined_memo = comb
    return comb


def _build_fused_train_fn(key, mb_grad_step, mesh, learning_rate, reg,
                          max_iter, tol, in_specs=None, out_specs=None,
                          delta_fn=None, epoch_fn=None, check_vma=True,
                          bundle=False, donate_batch=False):
    """The WHOLE training run as one compiled device program.

    Epochs are a ``lax.while_loop`` around the minibatch ``lax.scan``; the
    convergence test (update norm vs tol — the criteria-stream-empty analog)
    evaluates on device, so training runs start-to-finish with zero host
    round-trips: one transfer in (the packed batch), one out (params +
    per-epoch losses + epochs-run).  This is the fast path ``train_glm``
    takes when no per-epoch listeners are registered; the epoch watermark
    degenerates to the loop-carried epoch counter.

    ``mb_grad_step(params, mb_slice) -> (grads, loss_sum, w_sum)`` consumes
    one scanned minibatch slice of the batch pytree — the dense, sparse, and
    feature-sharded layouts differ only there.  ``in_specs``/``out_specs``
    override the default replicated-params/data-sharded-batch placement
    (feature sharding puts the weight leaf on the ``model`` axis) and
    ``delta_fn(params, start)`` overrides the convergence norm when params
    are sharded.  Non-SGD algorithms (KMeans' Lloyd step) pass ``epoch_fn
    (params, batch) -> (params, loss, delta)`` instead of ``mb_grad_step`` to
    reuse the identical while_loop/termination/history scaffolding.

    ``bundle`` folds the result packing INTO the training program: the four
    outputs (params pytree, loss history, epochs, delta) ravel and
    concatenate in-program into ONE flat device buffer, so the driver's
    readback is a single ``np.asarray`` — :func:`fetch_flat`'s separate
    concat program (an extra dispatch on the per-fit critical path)
    disappears.  Bundled fns return that flat buffer instead of the 4-tuple
    and carry ``bundle_fetch=True`` / ``loss_hist_len`` / ``donates_batch``
    attrs for :func:`_run_fused_train`; direct callers (diagnose_perf, the
    graft entry) keep the default unbundled 4-tuple contract.  Bundling
    requires the default replicated out_specs — custom placements (feature
    sharding) would concatenate MIXED shardings, the exact miscompile
    :func:`fetch_flat` guards against — so custom ``out_specs`` forces it
    off.  ``donate_batch`` additionally donates the batch argument to XLA
    (the placed minibatch slab is dead after the run's first read, so its
    HBM recycles into program temporaries instead of staying live for the
    whole while_loop); only honored with ``bundle`` because the driver must
    see ``donates_batch`` to place a FRESH never-pooled batch — donating a
    slab-pooled buffer would delete it under the pool's feet.
    """
    bundle = bundle and out_specs is None
    key = key + (bool(bundle), bool(bundle and donate_batch))
    cached = _cache_get(key)
    if cached is not None:
        return cached
    sgd_update = make_sgd_update(learning_rate, reg)
    tol_ = float(tol)

    def local_train(params, batch):
        def mb_step(p, xs):
            grads, loss_sum, w_sum = mb_grad_step(p, xs)
            grads = jax.tree_util.tree_map(lambda g: psum(g, "data"), grads)
            loss_sum = psum(loss_sum, "data")
            w_sum = psum(w_sum, "data")
            count = jnp.maximum(w_sum, 1.0)
            new_p = sgd_update(p, grads, count)
            return new_p, (loss_sum / count, w_sum)

        def sgd_epoch(params):
            start = params
            params, (losses, counts) = jax.lax.scan(mb_step, params, batch)
            total = jnp.maximum(jnp.sum(counts), 1.0)
            loss = jnp.sum(losses * counts) / total
            if delta_fn is not None:
                delta = delta_fn(params, start)
            else:
                delta = jnp.sqrt(
                    sum(
                        jnp.sum((a - b) ** 2)
                        for a, b in zip(
                            jax.tree_util.tree_leaves(params),
                            jax.tree_util.tree_leaves(start),
                        )
                    )
                )
            return params, loss, delta

        if epoch_fn is not None:
            def run_epoch(params):
                return epoch_fn(params, batch)
        else:
            run_epoch = sgd_epoch

        def cond(carry):
            _, epoch, delta, _ = carry
            not_done = epoch < max_iter
            if tol_ > 0.0:
                not_done = jnp.logical_and(
                    not_done, jnp.logical_or(epoch == 0, delta > tol_)
                )
            return not_done

        def body(carry):
            params, epoch, _, loss_hist = carry
            params, loss, delta = run_epoch(params)
            loss_hist = loss_hist.at[epoch].set(loss.astype(loss_hist.dtype))
            return params, epoch + 1, delta, loss_hist

        loss_hist0 = jnp.zeros((max_iter,), dtype=jnp.float32)
        params, epochs, delta, loss_hist = jax.lax.while_loop(
            cond, body, (params, jnp.asarray(0), jnp.asarray(jnp.inf), loss_hist0)
        )
        return params, loss_hist, epochs, delta

    from jax.sharding import PartitionSpec as P

    sharded = shard_map(
        local_train,
        mesh=mesh,
        in_specs=in_specs if in_specs is not None else (P(), P("data")),
        out_specs=(
            out_specs if out_specs is not None else (P(), P(), P(), P())
        ),
        # relaxed only for grad fns that declare it (interpret-mode pallas,
        # see make_pallas_grad_fn) — every other path stays strict
        check_vma=check_vma,
    )
    if not bundle:
        return _cache_put(key, jax.jit(sharded, donate_argnums=(0,)),
                          fused=True)

    # the dispatch-diet program (ISSUE 17): all four outputs are replicated
    # under the default out_specs, so raveling them into one buffer is
    # sharding-safe.  The fetch dtype mirrors fetch_flat (f64 only on the
    # x64 CPU test mesh) so bundled and unbundled fits return bit-identical
    # host values.
    fetch_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32

    def bundled(params, batch):
        params, loss_hist, epochs, delta = sharded(params, batch)
        pieces = [
            jnp.ravel(a).astype(fetch_dtype)
            for a in jax.tree_util.tree_leaves(params)
        ]
        pieces.append(loss_hist.astype(fetch_dtype))
        pieces.append(jnp.reshape(epochs, (1,)).astype(fetch_dtype))
        pieces.append(jnp.reshape(delta, (1,)).astype(fetch_dtype))
        return jnp.concatenate(pieces)

    jitted = jax.jit(
        bundled,
        donate_argnums=(0, 1) if donate_batch else (0,),
    )

    def train_fn(placed, device_batch):
        return jitted(placed, device_batch)

    # attrs ride a plain closure: jit wrappers don't reliably accept them
    train_fn.bundle_fetch = True
    train_fn.loss_hist_len = int(max_iter)
    train_fn.donates_batch = bool(donate_batch)
    return _cache_put(key, train_fn, fused=True)


def _run_fused_train(train_fn, init_params, batch, mesh,
                     place_params=None, batch_preplaced=False,
                     n_rows: int = 0) -> TrainResult:
    """Shared epilogue: run the fused program and fetch params + loss
    history + epoch count + final update norm back in ONE transfer.
    ``place_params`` overrides the default replicated placement (feature
    sharding); ``batch_preplaced`` skips the device transfer when the caller
    already sharded the batch (chunked checkpoint loops place it once).
    ``n_rows`` (true rows per epoch) feeds the recorded throughput metrics —
    a fused run is ONE device program, so it records one StepMetrics step
    covering all epochs (the fetch is the sync point).

    A ``train_fn`` built with ``bundle=True`` returns one flat device
    buffer instead of the 4-tuple; the driver reads its ``bundle_fetch`` /
    ``loss_hist_len`` / ``donates_batch`` attrs, splits the single
    ``np.asarray`` readback by the placed leaves' (donation-surviving)
    shape metadata, and — when the program donates its batch — places a
    FRESH batch outside the slab pool and skips the pool pin (there is no
    pooled entry to protect, and the buffers are gone after the call
    anyway)."""
    import contextlib
    from flink_ml_tpu.parallel.mesh import replicate
    from flink_ml_tpu.table import slab_pool

    import time as _time

    metrics = StepMetrics("fused_train")
    metrics.start_step()
    t_call0 = _time.perf_counter()
    placed = (
        place_params(init_params) if place_params is not None
        else replicate(mesh, init_params)
    )
    # the train fn donates its params: when the caller passes already-placed
    # device arrays, placement may alias their buffers (device_put returns a
    # view-like Array for no-op placements) and donation would delete the
    # CALLER's data — a second fit from the same initial params would crash.
    # Copy any leaf whose origin is a device array (host-sourced leaves were
    # freshly copied by placement already).
    placed = jax.tree_util.tree_map(
        lambda p, o: jnp.copy(p) if isinstance(o, jax.Array) else p,
        placed, init_params,
    )
    global _RUN_BUILDS_SEEN

    donate_batch = (
        getattr(train_fn, "donates_batch", False) and not batch_preplaced
    )
    t_place = _time.perf_counter()
    if batch_preplaced:
        device_batch = batch
        place_s = 0.0
    elif donate_batch:
        # the program donates its batch arg: the buffers must never enter
        # the slab pool (donation deletes them; the pool would hand the
        # dead entry to the next warm fit).  Same double-buffered chunked
        # H2D as the pooled path, minus the pool bookkeeping.
        from flink_ml_tpu.parallel.mesh import shard_batch_prefetched

        device_batch = shard_batch_prefetched(mesh, batch)
        place_s = _time.perf_counter() - t_place
    else:
        # pooled + double-buffered: a warm re-fit of the same host arrays
        # skips the transfer entirely (slab_pool hit); a cold placement
        # overlaps host staging with the async H2D DMA
        device_batch = slab_pool.place_batch(mesh, batch)
        place_s = _time.perf_counter() - t_place
    # pin the (possibly pooled) batch for the whole dispatch+fetch window:
    # budget eviction must never drop the pool's reference while a donating
    # program is in flight over these buffers.  A donated fresh batch was
    # never pooled — nothing to pin.
    pin = (contextlib.nullcontext() if donate_batch
           else slab_pool.pool().pinned(device_batch))
    with pin:
        t_run = _time.perf_counter()
        if getattr(train_fn, "bundle_fetch", False):
            flat = train_fn(placed, device_batch)
            dispatch_s = _time.perf_counter() - t_run
            t_fetch = _time.perf_counter()
            # ONE readback for the whole result: param leaves + loss
            # history + epochs + delta ride a single flat buffer packed
            # in-program.  Split by the placed leaves' shapes — shape
            # metadata survives donation even though the buffers don't.
            leaves, treedef = jax.tree_util.tree_flatten(placed)
            hist_len = int(train_fn.loss_hist_len)
            buf = np.asarray(flat)
            fetched = []
            off = 0
            for a in leaves:
                size = int(np.prod(a.shape))
                fetched.append(buf[off : off + size].reshape(a.shape))
                off += size
            fetched.append(buf[off : off + hist_len])
            fetched.append(buf[off + hist_len])
            fetched.append(buf[off + hist_len + 1])
            sync_s = _time.perf_counter() - t_fetch
        else:
            params, loss_hist, epochs, delta = train_fn(placed, device_batch)
            dispatch_s = _time.perf_counter() - t_run
            t_fetch = _time.perf_counter()
            leaves, treedef = jax.tree_util.tree_flatten(params)
            fetched = fetch_flat(
                *leaves, loss_hist, jnp.asarray(epochs), jnp.asarray(delta)
            )
            # fetch_flat is the single sync point: it absorbs transfer +
            # program + readback (no extra block_until_ready round-trips
            # on tunneled devices)
            sync_s = _time.perf_counter() - t_fetch
    n_epochs = int(fetched[-2])
    losses = [float(x) for x in fetched[-3][:n_epochs]]
    # call_latency_ms: the DRIVER's device-call window — param placement,
    # any driver-internal batch placement, dispatch, sync.  Estimator
    # paths place their batch via the slab pool BEFORE this driver runs;
    # that cost lands in the slab_pool.build timing and in the fit-level
    # fit_wall_ms (fit_pool_extra), which is what the warm-fit telemetry
    # reads end-to-end.
    metrics.end_step(
        samples=n_rows * n_epochs, epochs=n_epochs,
        loss=losses[-1] if losses else 0.0,
        dispatch_seconds=dispatch_s, sync_seconds=sync_s,
        place_seconds=place_s,
        call_latency_ms=(_time.perf_counter() - t_call0) * 1e3,
    )
    # the compile/steady split: dispatch absorbs trace+compile (cold
    # program) or just the enqueue (warm); sync is device execution +
    # readback.  A run whose program was built since the previous fused
    # run (the factory runs strictly before this driver) pays the XLA
    # compile — count it so reports separate compile-bearing fits from
    # cache-warm ones.
    obs.observe("train.dispatch", dispatch_s)
    obs.observe("train.sync", sync_s)
    if not batch_preplaced:
        obs.observe("train.place", place_s)
    # the same split as spans under the fit's trace (FMT_TRACE): post-hoc
    # records with the measured windows, so a guarded fit's waterfall
    # shows place -> dispatch -> sync the way a served request shows
    # place_h2d -> fused_dispatch -> device_sync
    parents = obs.trace.current()
    if parents:
        obs.trace.record_span(parents, "train.sync", sync_s,
                              {"epochs": n_epochs})
        obs.trace.record_span(parents, "train.dispatch", dispatch_s,
                              end_ts=_time.time() - sync_s)
        if not batch_preplaced:
            obs.trace.record_span(
                parents, "train.place", place_s,
                end_ts=_time.time() - sync_s - dispatch_s,
            )
    obs.counter_add("train.fused_runs")
    obs.counter_add("train.epochs", n_epochs)
    obs.counter_add("train.rows", n_rows * n_epochs)
    if _FUSED_PROGRAM_BUILDS > _RUN_BUILDS_SEEN:
        obs.counter_add("train.compile_runs")
    _RUN_BUILDS_SEEN = _FUSED_PROGRAM_BUILDS
    obs.record_hbm_gauges()
    host_params = jax.tree_util.tree_unflatten(treedef, fetched[: len(leaves)])
    # numeric-health sentinel on the values just fetched (free: no extra
    # sync): a diverged fit raises here and the estimator-level guard
    # rolls back / retries with a backed-off learning rate
    fault.check_health(
        losses, fetched[: len(leaves)],
        float(fetched[-1]) if n_epochs else None,  # 0-epoch delta is inf
        where="fused_train",
    )
    return TrainResult(
        params=host_params,
        epochs=n_epochs,
        losses=losses,
        final_delta=float(fetched[-1]),
        metrics=metrics,
    )


def make_glm_train_fn(
    grad_fn: GradFn,
    mesh,
    learning_rate: float,
    reg: float,
    max_iter: int,
    tol: float,
    bundle: bool = False,
    donate_batch: bool = False,
):
    """Fused training over the dense combined layout
    (see :func:`_build_fused_train_fn` for the program structure;
    ``bundle``/``donate_batch`` select the single-buffer-fetch /
    batch-donating program variant driven by :func:`_run_fused_train` —
    direct callers that unpack the 4-tuple keep the defaults)."""
    check_vma = getattr(grad_fn, "shard_map_check_vma", True)
    key = ("train", grad_fn, mesh, float(learning_rate), float(reg),
           int(max_iter), float(tol), check_vma)

    def mb_grad_step(p, mb):
        return grad_fn(p, mb[..., :-2], mb[..., -2], mb[..., -1])

    return _build_fused_train_fn(
        key, mb_grad_step, mesh, learning_rate, reg, max_iter, tol,
        check_vma=check_vma, bundle=bundle, donate_batch=donate_batch,
    )


def _sparse_loss(kind: str, logits, y, w):
    """Shared loss/error math for the sparse paths."""
    if kind == "logistic":
        prob = jax.nn.sigmoid(logits)
        err = (prob - y) * w
        loss_sum = jnp.sum(w * (jnp.logaddexp(0.0, logits) - y * logits))
    else:
        err = (logits - y) * w
        loss_sum = 0.5 * jnp.sum(err * (logits - y))
    return err, loss_sum


def make_sparse_mb_grad_step(kind: str, mb: int, nnz_pad: int, dim: int,
                             with_intercept: bool = True):
    """The sparse minibatch gradient: ``(params, (ints, floats) slice) ->
    (grads, weighted loss sum, weight sum)``.

    The forward is ``segment_sum(values * gather(w))`` — the batched
    static-shape replacement for the reference's hand-rolled sparse gemv
    (BLAS.java:205-233); the gradient scatters back through the same
    segments.  Shared by the fused in-memory loop and the out-of-core chunk
    program so the two paths cannot drift.
    """
    keep_b = 1.0 if with_intercept else 0.0

    def mb_grad_step(params, xs):
        ints, floats = xs  # (2, nnz_pad), (nnz_pad + 2*mb,)
        idx, rid, vals, y, w = _segment_csr_unpack(ints, floats, nnz_pad, mb)
        wts, b = params
        logits = _segment_csr_forward(wts, idx, rid, vals, mb) + b
        err, loss_sum = _sparse_loss(kind, logits, y, w)
        g_w = _segment_csr_backward(err, idx, rid, vals, dim)
        g_b = jnp.sum(err) * keep_b
        return (g_w, g_b), loss_sum, jnp.sum(w)

    return mb_grad_step


def _segment_csr_unpack(ints, floats, nnz_pad: int, mb: int):
    """Unpack one packed sparse minibatch slice into (idx, rid, vals, y, w)
    — the ONE copy of the [values | y | w] layout decode (sparse, 2-D, and
    hot/cold builders all read it, so the layouts cannot drift)."""
    idx = ints[0]
    rid = ints[1]
    vals = floats[:nnz_pad]
    y = floats[nnz_pad : nnz_pad + mb]
    w = floats[nnz_pad + mb :]
    return idx, rid, vals, y, w


def _segment_csr_forward(wts, idx, rid, vals, mb: int):
    """Partial logits from stored entries: segment_sum(values * gather(w))
    — pad entries carry rid == mb and drop out of the segment range.
    Entries are packed row-major (rid non-decreasing, pads at the tail —
    asserted by the pack tests), so the segment reduction takes the
    sorted-indices lowering."""
    return jax.ops.segment_sum(
        vals * jnp.take(wts, idx, axis=0), rid, num_segments=mb,
        indices_are_sorted=True,
    )


def _segment_csr_backward(err, idx, rid, vals, dim: int):
    """Feature-gradient scatter through the same segments; the appended
    zero row is the pad sink (rid == mb gathers it, contributing nothing)."""
    err_ext = jnp.concatenate([err, jnp.zeros((1,), err.dtype)])
    return jax.ops.segment_sum(
        vals * jnp.take(err_ext, rid, axis=0), idx, num_segments=dim
    )


def make_sparse_glm_train_fn(
    kind: str,
    mesh,
    mb: int,
    nnz_pad: int,
    dim: int,
    learning_rate: float,
    reg: float,
    max_iter: int,
    tol: float,
    with_intercept: bool = True,
):
    """Fused training over :class:`SparseMinibatchStack` batches.

    ``kind`` picks the loss ('logistic' | 'squared'); the minibatch math is
    :func:`make_sparse_mb_grad_step`.  Program structure is shared with the
    dense path via :func:`_build_fused_train_fn`.
    """
    if kind not in ("logistic", "squared"):
        raise ValueError(f"unknown loss kind {kind!r}")
    key = ("sparse", kind, mesh, mb, nnz_pad, dim,
           float(learning_rate), float(reg), int(max_iter), float(tol),
           bool(with_intercept))
    mb_grad_step = make_sparse_mb_grad_step(kind, mb, nnz_pad, dim, with_intercept)

    return _build_fused_train_fn(
        key, mb_grad_step, mesh, learning_rate, reg, max_iter, tol
    )


def make_sparse_mb_grad_step_2d(kind: str, mb: int, nnz_pad: int,
                                dim_local: int, with_intercept: bool = True):
    """Feature-sharded counterpart of :func:`make_sparse_mb_grad_step`:
    shard i of the ``model`` axis owns features [i*dim_local, (i+1)*dim_local);
    partial logits complete with one ``psum`` over ``model`` (the TP
    allreduce riding ICI) and gradients scatter only into the local shard.
    Shared by the fused in-memory 2-D loop and the out-of-core chunk
    program."""
    keep_b = 1.0 if with_intercept else 0.0

    def mb_grad_step(params, xs):
        ints, floats = xs
        idx = ints[0]
        rid = ints[1]
        vals = floats[:nnz_pad]
        y = floats[nnz_pad : nnz_pad + mb]
        w = floats[nnz_pad + mb :]
        wts_local, b = params
        lo = jax.lax.axis_index("model") * dim_local
        local_idx = idx - lo
        mine = jnp.logical_and(local_idx >= 0, local_idx < dim_local)
        safe_idx = jnp.clip(local_idx, 0, dim_local - 1)
        contrib = jnp.where(
            mine, vals * jnp.take(wts_local, safe_idx, axis=0), 0.0
        )
        partial = jax.ops.segment_sum(contrib, rid, num_segments=mb)
        # the TP allreduce: complete logits across feature shards
        logits = jax.lax.psum(partial, "model") + b
        err, loss_sum = _sparse_loss(kind, logits, y, w)
        err_ext = jnp.concatenate([err, jnp.zeros((1,), err.dtype)])
        scatter = jnp.where(mine, vals * jnp.take(err_ext, rid, axis=0), 0.0)
        g_w = jax.ops.segment_sum(scatter, safe_idx, num_segments=dim_local)
        g_b = jnp.sum(err) * keep_b
        return (g_w, g_b), loss_sum, jnp.sum(w)

    return mb_grad_step


def make_sparse_glm_train_fn_2d(
    kind: str,
    mesh,
    mb: int,
    nnz_pad: int,
    dim: int,
    learning_rate: float,
    reg: float,
    max_iter: int,
    tol: float,
    with_intercept: bool = True,
):
    """Feature-dimension-sharded sparse training over a ('data','model') mesh.

    For models too wide for one chip's HBM (Criteo-scale hashed features,
    SURVEY.md §5.7): the weight vector is sharded over the ``model`` axis —
    shard i owns the contiguous feature range [i*dim_local, (i+1)*dim_local).
    Each minibatch forward computes partial logits from locally-owned
    features and one ``psum`` over ``model`` (the tensor-parallel allreduce,
    riding ICI) completes them; gradients scatter back only into the local
    shard, so weight traffic never crosses chips.  ``dim`` must be divisible
    by the model-axis size (pad the feature space up).  Loop scaffolding is
    shared with every other path via :func:`_build_fused_train_fn`.
    """
    if kind not in ("logistic", "squared"):
        raise ValueError(f"unknown loss kind {kind!r}")
    model_size = dict(mesh.shape)["model"]
    if dim % model_size != 0:
        raise ValueError(
            f"dim={dim} not divisible by model axis size {model_size}"
        )
    dim_local = dim // model_size
    key = ("sparse2d", kind, mesh, mb, nnz_pad, dim,
           float(learning_rate), float(reg), int(max_iter), float(tol),
           bool(with_intercept))
    mb_grad_step = make_sparse_mb_grad_step_2d(
        kind, mb, nnz_pad, dim_local, with_intercept
    )

    from jax.sharding import PartitionSpec as P

    return _build_fused_train_fn(
        key, mb_grad_step, mesh, learning_rate, reg, max_iter, tol,
        in_specs=((P("model"), P()), P("data")),
        out_specs=((P("model"), P()), P(), P(), P()),
        delta_fn=_feature_sharded_delta,
    )


def _feature_sharded_delta(params, start):
    """Convergence norm for a ``model``-axis-sharded (w, b) pytree:
    shard-local weight squares summed across 'model'; the replicated
    intercept counts once.  Shared by the sparse and dense 2-D builders."""
    return jnp.sqrt(
        jax.lax.psum(jnp.sum((params[0] - start[0]) ** 2), "model")
        + (params[1] - start[1]) ** 2
    )


@dataclass
class HotColdStack:
    """Hot/cold split of a :class:`SparseMinibatchStack` (VERDICT r3 item 1).

    The v5e has no SparseCore: random gathers/scatters run at ~100M
    accesses/s (~10 cycles each), which caps the all-segment-CSR path at
    <1M rows/s on the Criteo shape while a CPU keeps the ~200KB hot set in
    L2.  The escape is to make the hot traffic STREAM instead of hop: the
    ``hot_k`` most frequent features become a dense per-minibatch slab
    ``(mb, hot_k)`` in bf16 — built once on device — and the forward/
    backward over them are two MXU GEMMs reading the slab at HBM stream
    bandwidth; only the cold tail (a few nnz/row) still pays random access.
    Measured on v5e: 1.75x the segment-CSR step, 1.3x the strengthened CSR
    CPU baseline at the bench shape.

    Features are permuted so hot ids occupy [0, hot_k) (slab position =
    feature id) and cold ids [hot_k, dim); ``perm``/``inv_perm`` map
    original->permuted and back — training runs in permuted space, the
    returned coefficients are unpermuted.

    Numerics: the slab and the two GEMM operands are bf16 with f32
    accumulation (exact for 0/1-valued hashed features, ~2^-8 relative
    rounding otherwise); everything else stays f32.  ``slab_dtype``
    exists for equivalence tests (f32 slab).

    With ``model_size > 1`` the layout is feature-sharded over a
    ('data','model') mesh: slab columns split evenly (shard i owns columns
    [i*hot_k_local, (i+1)*hot_k_local)) and the permuted weight space
    interleaves per shard — shard i owns permuted ids
    [i*dim_local, (i+1)*dim_local), locally [0, hot_k_local) hot and
    [hot_k_local, dim_local) cold — so each shard's weight slice is
    [its slab columns | its cold range] and weight traffic never crosses
    chips.  ``dim_pad >= dim`` absorbs the rounding (dead positions carry
    zero weight and zero gradient forever).  ``model_size == 1`` reduces to
    the single-chip layout above (``dim_pad == dim``).
    """

    hot_ints: np.ndarray   # (n_groups, 2, hot_pad) int32 [slab col, row id]
    hot_vals: np.ndarray   # (n_groups, hot_pad) f32; pad rows carry rid=mb
    cold: SparseMinibatchStack  # permuted cold entries + [y | w] tail
    perm: np.ndarray       # original feature id -> permuted id [0, dim_pad)
    inv_perm: np.ndarray   # permuted id -> original feature id (dead -> 0)
    hot_k: int             # slab columns (incl. dead tail when rounded up)
    slab_dtype: Any = jnp.bfloat16
    model_size: int = 1    # 'model' mesh-axis size the layout targets
    dim_pad: int = 0       # permuted weight-space size (== dim when 1-D)

    @property
    def mb(self) -> int:
        return self.cold.mb

    @property
    def dim(self) -> int:
        """Permuted weight-space size (``cold.dim == dim_pad``); the
        original feature count is ``len(perm)``."""
        return self.cold.dim

    @property
    def n_rows(self) -> int:
        return self.cold.n_rows

    @property
    def hot_k_local(self) -> int:
        return self.hot_k // self.model_size

    @property
    def dim_local(self) -> int:
        return self.dim_pad // self.model_size


def hotcold_entry_counts(sstack: SparseMinibatchStack) -> np.ndarray:
    """Stored-entry count per feature over the stack's valid entries — THE
    frequency vector the hot/cold split selects from (multi-process callers
    ``agree_sum`` this before splitting)."""
    valid = sstack.ints[:, 1, :] < sstack.mb
    return np.bincount(
        sstack.ints[:, 0, :][valid].ravel(), minlength=sstack.dim
    )


def hotcold_hot_k_eff(dim: int, hot_k: int, model_size: int) -> int:
    """The effective slab width the feature plan will choose — the ONE
    rounding rule (clamp to [1, dim], round up to a model-axis multiple),
    shared with :func:`hotcold_feature_plan` so budget estimates cannot
    drift from the real layout."""
    model_size = int(max(model_size, 1))
    n_hot = int(min(max(hot_k, 1), dim))
    return -(-n_hot // model_size) * model_size


def hotcold_feature_plan(dim: int, hot_k: int, model_size: int,
                         counts: np.ndarray) -> dict:
    """The feature-level half of the hot/cold split — hot selection and
    permutation from a frequency vector, independent of any packed stack.
    Deterministic in ``counts``, so out-of-core fits compute it ONCE from
    a counting pre-pass and reuse it for every streamed block (and a
    checkpoint resume re-derives the identical permutation)."""
    model_size = int(max(model_size, 1))
    counts = np.asarray(counts)
    if counts.shape != (dim,):
        raise ValueError(
            f"counts must have shape ({dim},), got {counts.shape}"
        )
    n_hot = int(min(max(hot_k, 1), dim))
    hot_k_eff = hotcold_hot_k_eff(dim, hot_k, model_size)
    hk_l = hot_k_eff // model_size
    cold_count = dim - n_hot
    cold_l = -(-cold_count // model_size) if cold_count else 0
    dim_local = hk_l + cold_l
    dim_pad = model_size * dim_local

    order = np.lexsort((np.arange(dim), -counts))  # by count desc, id asc
    hot_ids = np.sort(order[:n_hot])
    # slab column per hot feature (rank in id order); -1 marks cold
    slab_col = np.full(dim, -1, dtype=np.int32)
    slab_col[hot_ids] = np.arange(n_hot, dtype=np.int32)
    perm = np.empty(dim, dtype=np.int32)
    c = np.arange(n_hot, dtype=np.int32)
    perm[hot_ids] = (c // hk_l) * dim_local + (c % hk_l)
    cold_mask_ids = np.ones(dim, dtype=bool)
    cold_mask_ids[hot_ids] = False
    cold_ids = np.nonzero(cold_mask_ids)[0]
    if cold_ids.size:
        r = np.arange(cold_ids.size, dtype=np.int32)
        perm[cold_ids] = (r // cold_l) * dim_local + hk_l + (r % cold_l)
    inv_perm = np.zeros(dim_pad, dtype=np.int32)
    inv_perm[perm] = np.arange(dim, dtype=np.int32)
    return dict(
        hot_k_eff=hot_k_eff, dim_pad=dim_pad, perm=perm, inv_perm=inv_perm,
        slab_col=slab_col,
    )


def _hotcold_plan(sstack: SparseMinibatchStack, hot_k: int,
                  pad_multiple: int, model_size: int,
                  counts: Optional[np.ndarray],
                  feature_plan: Optional[dict] = None):
    """The deterministic first half of the hot/cold split: hot selection,
    permutation, per-entry masks, and the NATURAL pad widths — everything
    except materializing the entry arrays.  Shared by :func:`split_hot_cold`
    (which fills) and :func:`hotcold_layout_floors` (the multi-process
    pre-scan), so the two cannot drift.  ``counts`` overrides the local
    frequency analysis with externally-agreed (global) counts;
    ``feature_plan`` short-circuits the feature-level work entirely (the
    out-of-core per-block path, which reuses one plan across the stream)."""
    ints = sstack.ints
    mb, dim = sstack.mb, sstack.dim
    if feature_plan is None:
        if counts is None:
            counts = hotcold_entry_counts(sstack)
        feature_plan = hotcold_feature_plan(dim, hot_k, model_size, counts)
    slab_col = feature_plan["slab_col"]
    perm = feature_plan["perm"]

    idx = ints[:, 0, :]
    rid = ints[:, 1, :]
    valid = rid < mb
    ranks = np.where(valid, slab_col[idx], -1)
    new_idx = np.where(valid, perm[idx], 0)
    is_hot = ranks >= 0
    is_cold = valid & (ranks < 0)
    hot_counts = is_hot.sum(axis=1)
    cold_counts = is_cold.sum(axis=1)
    hot_pad = max(-(-int(hot_counts.max(initial=1)) // pad_multiple)
                  * pad_multiple, pad_multiple)
    cold_pad = max(-(-int(cold_counts.max(initial=1)) // pad_multiple)
                   * pad_multiple, pad_multiple)
    return dict(
        feature_plan,
        ranks=ranks, new_idx=new_idx, is_hot=is_hot, is_cold=is_cold,
        hot_counts=hot_counts, cold_counts=cold_counts,
        hot_pad=hot_pad, cold_pad=cold_pad,
    )


def hotcold_layout_floors(sstack: SparseMinibatchStack, hot_k: int,
                          pad_multiple: int = 512, model_size: int = 1,
                          counts: Optional[np.ndarray] = None):
    """((hot_pad, cold_pad), plan) the split WOULD choose — the
    multi-process pre-scan (same contract as :func:`sparse_layout_floors`):
    each process computes its local pads from the globally-agreed
    ``counts``, agree_max reconciles them, and the one split runs with the
    agreed floors.  Pass the returned ``plan`` back to
    :func:`split_hot_cold` so the O(entries) mask/permutation work runs
    once, not twice."""
    plan = _hotcold_plan(sstack, hot_k, pad_multiple, model_size, counts)
    return (plan["hot_pad"], plan["cold_pad"]), plan


@obs.phased("split_hot_cold")
def split_hot_cold(sstack: SparseMinibatchStack, hot_k: int,
                   pad_multiple: int = 512,
                   slab_dtype=jnp.bfloat16,
                   model_size: int = 1,
                   counts: Optional[np.ndarray] = None,
                   min_hot_pad: int = 0,
                   min_cold_pad: int = 0,
                   plan: Optional[dict] = None,
                   feature_plan: Optional[dict] = None) -> HotColdStack:
    """Frequency analysis + feature permutation + per-group entry split.

    The ``hot_k`` features with the most stored entries (ties broken by
    lower id) become slab columns; everything else keeps segment-CSR form
    with ids remapped into the permuted cold range.  ``model_size > 1``
    produces the feature-sharded layout documented on
    :class:`HotColdStack` (``hot_k`` rounds up to a model-axis multiple;
    the extra slab columns are dead).  Multi-process: pass the globally
    summed ``counts`` (every process must select the same hot set) and the
    agreed pad floors (``min_hot_pad``/``min_cold_pad``) so all processes
    fill identical shapes.  ``plan`` short-circuits the analysis phase with
    the plan :func:`hotcold_layout_floors` already computed — the caller
    owns the invariant that it came from the same (sstack, hot_k,
    model_size, counts)."""
    ints, floats = sstack.ints, sstack.floats
    mb, nnz_pad, dim = sstack.mb, sstack.nnz_pad, sstack.dim
    n_groups = ints.shape[0]
    model_size = int(max(model_size, 1))
    if plan is None:
        plan = _hotcold_plan(sstack, hot_k, pad_multiple, model_size, counts,
                             feature_plan=feature_plan)
    hot_k_eff = plan["hot_k_eff"]
    dim_pad = plan["dim_pad"]
    perm, inv_perm = plan["perm"], plan["inv_perm"]
    ranks, new_idx = plan["ranks"], plan["new_idx"]
    is_hot, is_cold = plan["is_hot"], plan["is_cold"]
    hot_counts, cold_counts = plan["hot_counts"], plan["cold_counts"]
    rid = ints[:, 1, :]
    hot_pad = max(plan["hot_pad"], int(min_hot_pad))
    cold_pad = max(plan["cold_pad"], int(min_cold_pad))

    hot_ints = np.zeros((n_groups, 2, hot_pad), dtype=np.int32)
    hot_ints[:, 1, :] = mb  # pad row id -> dropped row
    hot_vals = np.zeros((n_groups, hot_pad), dtype=np.float32)
    cold_ints = np.zeros((n_groups, 2, cold_pad), dtype=np.int32)
    cold_ints[:, 1, :] = mb
    cold_floats = np.zeros((n_groups, cold_pad + 2 * mb), dtype=np.float32)
    vals = floats[:, :nnz_pad]
    for g in range(n_groups):
        h = is_hot[g]
        c = is_cold[g]
        nh, nc = int(hot_counts[g]), int(cold_counts[g])
        hot_ints[g, 0, :nh] = ranks[g, h]  # global slab column
        hot_ints[g, 1, :nh] = rid[g, h]
        hot_vals[g, :nh] = vals[g, h]
        cold_ints[g, 0, :nc] = new_idx[g, c]  # permuted feature id
        cold_ints[g, 1, :nc] = rid[g, c]
        cold_floats[g, :nc] = vals[g, c]
        cold_floats[g, cold_pad:] = floats[g, nnz_pad:]  # [y | w] tail

    # the cold stack's ids live in PERMUTED space [hot ranges excluded],
    # which spans [0, dim_pad) — dim must be dim_pad (== dim when 1-D) or
    # a rounded-up 2-D layout would violate the col-index < dim invariant
    cold = SparseMinibatchStack(
        ints=cold_ints, floats=cold_floats, steps=sstack.steps, mb=mb,
        nnz_pad=cold_pad, dim=dim_pad, n_rows=sstack.n_rows,
    )
    return HotColdStack(
        hot_ints=hot_ints, hot_vals=hot_vals, cold=cold, perm=perm,
        inv_perm=inv_perm, hot_k=hot_k_eff, slab_dtype=slab_dtype,
        model_size=model_size, dim_pad=dim_pad,
    )


@obs.phased("densify_hot_slabs")
def densify_hot_slabs(mesh, hstack: HotColdStack):
    """Build the per-minibatch hot slabs ON DEVICE, sharded over 'data'
    (and over 'model' on slab columns when the layout is feature-sharded).

    The host ships only the compact hot entry arrays (~entries x 12B); the
    10s-of-GB slab materializes device-side via one sequential scatter pass
    (zeros + at[].add per group), so the tunneled host->device hop stays
    the size of the sparse data, not the slab."""
    from jax.sharding import PartitionSpec as P

    from flink_ml_tpu.parallel.mesh import shard_batch

    mb, hot_k, dtype = hstack.mb, hstack.hot_k, hstack.slab_dtype

    hot_ints_d, hot_vals_d = shard_batch(
        mesh, (hstack.hot_ints, hstack.hot_vals)
    )
    if hstack.model_size > 1:
        if dict(mesh.shape).get("model", 1) != hstack.model_size:
            raise ValueError(
                f"HotColdStack laid out for model_size={hstack.model_size} "
                f"but mesh has model axis {dict(mesh.shape).get('model', 1)}"
            )
        hk_l = hstack.hot_k_local

        def local_sharded(hot_ints, hot_vals):
            lo = jax.lax.axis_index("model") * hk_l

            def one(args):
                ig, vg = args
                pos, rid = ig[0], ig[1]
                lpos = pos - lo
                mine = jnp.logical_and(lpos >= 0, lpos < hk_l)
                slab = jnp.zeros((mb + 1, hk_l), dtype)  # row mb = pad sink
                return slab.at[
                    jnp.where(mine, rid, mb), jnp.clip(lpos, 0, hk_l - 1)
                ].add(jnp.where(mine, vg, 0.0).astype(dtype))[:mb]

            return jax.lax.map(one, (hot_ints, hot_vals))

        fn = jax.jit(shard_map(
            local_sharded, mesh=mesh, in_specs=(P("data"), P("data")),
            out_specs=P("data", None, "model"), check_vma=True,
        ))
        return fn(hot_ints_d, hot_vals_d)

    def local(hot_ints, hot_vals):
        def one(args):
            ig, vg = args
            pos, rid = ig[0], ig[1]
            slab = jnp.zeros((mb + 1, hot_k), dtype)  # row mb = pad sink
            return slab.at[rid, pos].add(vg.astype(dtype))[:mb]

        return jax.lax.map(one, (hot_ints, hot_vals))

    if dict(mesh.shape).get("data", 1) > 1:
        fn = jax.jit(shard_map(
            local, mesh=mesh, in_specs=(P("data"), P("data")),
            out_specs=P("data"), check_vma=True,
        ))
    else:
        fn = jax.jit(local)
    return fn(hot_ints_d, hot_vals_d)


def hotcold_device_batch(mesh, hstack: HotColdStack):
    """Device placement for the hot/cold batch: build the slab on device,
    shard the cold segment-CSR arrays over 'data'."""
    from flink_ml_tpu.parallel.mesh import shard_batch

    slab = densify_hot_slabs(mesh, hstack)
    cold_ints, cold_floats = shard_batch(
        mesh, (hstack.cold.ints, hstack.cold.floats)
    )
    return (slab, cold_ints, cold_floats)


def _hotcold_core(kind: str, slab, wts, b, idx, rid, vals, y, w,
                  mb: int, hot_k: int, dim: int, keep_b: float):
    """The hot/cold minibatch math: two MXU GEMMs over the slab (forward
    logits, backward feature gradient) + segment-CSR for the cold tail.
    The vectors are widened to 128 GEMM columns — the N=1 matvec lowers to
    a catastrophic lane-reduction on TPU (measured 400x slower), while
    N=128 engages the MXU at stream bandwidth; the extra columns are free
    (the pass is memory-bound on the slab).  Shared by the in-memory step
    (slab pre-densified, HBM-resident across epochs) and the out-of-core
    step (slab densified in-program per minibatch)."""
    dtype = slab.dtype
    w_hot = jnp.broadcast_to(
        wts[:hot_k].astype(dtype)[:, None], (hot_k, 128)
    )
    hot_logits = jax.lax.dot_general(
        slab, w_hot, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )[:, 0]
    logits = hot_logits + _segment_csr_forward(wts, idx, rid, vals, mb) + b
    err, loss_sum = _sparse_loss(kind, logits, y, w)
    err_m = jnp.broadcast_to(err.astype(dtype)[:, None], (mb, 128))
    g_hot = jax.lax.dot_general(
        slab, err_m, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )[:, 0]
    g_w = _segment_csr_backward(err, idx, rid, vals, dim)
    g_w = g_w.at[:hot_k].add(g_hot)
    g_b = jnp.sum(err) * keep_b
    return (g_w, g_b), loss_sum, jnp.sum(w)


def make_hotcold_mb_grad_step(kind: str, mb: int, cold_nnz_pad: int,
                              hot_k: int, dim: int,
                              with_intercept: bool = True):
    """The in-memory hot/cold minibatch gradient over a PRE-DENSIFIED slab
    (built once on device, resident across epochs — see
    :func:`densify_hot_slabs`); math in :func:`_hotcold_core`."""
    keep_b = 1.0 if with_intercept else 0.0

    def mb_grad_step(params, xs):
        slab, ints, floats = xs
        wts, b = params
        idx, rid, vals, y, w = _segment_csr_unpack(
            ints, floats, cold_nnz_pad, mb
        )
        return _hotcold_core(
            kind, slab, wts, b, idx, rid, vals, y, w, mb, hot_k, dim, keep_b
        )

    return mb_grad_step


def make_hotcold_stream_mb_grad_step(kind: str, mb: int,
                                     cold_nnz_pad: int, hot_k: int,
                                     dim: int,
                                     with_intercept: bool = True,
                                     slab_dtype=jnp.bfloat16):
    """Out-of-core hot/cold minibatch gradient: the slab densifies
    IN-PROGRAM from the minibatch's packed hot entries (one scatter over
    ~hot entries), then the same GEMM+segment-CSR math as the in-memory
    step runs (:func:`_hotcold_core`).

    The in-memory path builds slabs once and keeps them HBM-resident
    across epochs; out-of-core the data must not stay resident anywhere,
    so each epoch re-streams the entries and pays one scatter per
    minibatch — still one random-access pass where the all-segment-CSR
    step pays three (weight gather, forward segment_sum, gradient
    scatter) over the hot traffic.  ``xs`` is one scanned slice of the
    hot/cold block layout: (hot ints (2, hot_pad), hot vals (hot_pad,),
    cold ints (2, cold_nnz_pad), cold floats (cold_nnz_pad + 2*mb,));
    pad entries carry row id ``mb`` (the scatter sink row, sliced away).
    """
    keep_b = 1.0 if with_intercept else 0.0
    dtype = jnp.dtype(slab_dtype)

    def mb_grad_step(params, xs):
        h_ints, h_vals, ints, floats = xs
        wts, b = params
        pos, hrid = h_ints[0], h_ints[1]
        # (rid, pos) tuples are lexicographically sorted by construction
        # (row-major packing; per-row feature ids ascending; pads at the
        # tail with rid == mb) — the sorted lowering keeps the scatter's
        # writes row-localized instead of random over the whole slab
        slab = (
            jnp.zeros((mb + 1, hot_k), dtype)  # row mb = pad sink
            .at[hrid, pos]
            .add(h_vals.astype(dtype), indices_are_sorted=True)[:mb]
        )
        idx, rid, vals, y, w = _segment_csr_unpack(
            ints, floats, cold_nnz_pad, mb
        )
        return _hotcold_core(
            kind, slab, wts, b, idx, rid, vals, y, w, mb, hot_k, dim, keep_b
        )

    return mb_grad_step


def hotcold_entries_device_batch(mesh, hstack: HotColdStack):
    """Device placement for the SCALABLE hot/cold formulation: the packed
    entry arrays (hot + cold) shard over 'data' and stay the only resident
    copy of the data — HBM holds O(nnz), never O(n_rows x hot_k).  The
    slab materializes in-program per minibatch
    (:func:`make_hotcold_stream_mb_grad_step`)."""
    from flink_ml_tpu.parallel.mesh import shard_batch

    return shard_batch(
        mesh,
        (hstack.hot_ints, hstack.hot_vals,
         hstack.cold.ints, hstack.cold.floats),
    )


def hotcold_slab_bytes(n_rows: int, hot_k: int,
                       slab_dtype=jnp.bfloat16) -> int:
    """HBM footprint of the resident-slab formulation's slabs — the number
    the auto policy compares against the budget (the packed entry arrays
    are negligible next to it)."""
    return int(n_rows) * int(hot_k) * jnp.dtype(slab_dtype).itemsize


def make_hotcold_stream_glm_train_fn(
    kind: str,
    mesh,
    mb: int,
    cold_nnz_pad: int,
    hot_k: int,
    dim: int,
    learning_rate: float,
    reg: float,
    max_iter: int,
    tol: float,
    with_intercept: bool = True,
    slab_dtype=jnp.bfloat16,
):
    """Fused training over packed hot/cold ENTRY batches (slab densified
    in-program per minibatch) — the scalable in-memory formulation: the
    resident-slab variant's HBM cost grows O(n_rows x hot_k) (~100 GB at
    1M rows x 50k hot), this one holds only the entries (~12 B/nnz).  Same
    loop scaffolding as every other path (:func:`_build_fused_train_fn`);
    the per-step extra over the resident variant is one zeros+scatter
    (~3x slab traffic per step vs 2x)."""
    if kind not in ("logistic", "squared"):
        raise ValueError(f"unknown loss kind {kind!r}")
    key = ("hotcold-stream", kind, mesh, mb, cold_nnz_pad, hot_k, dim,
           float(learning_rate), float(reg), int(max_iter), float(tol),
           bool(with_intercept), jnp.dtype(slab_dtype).name)
    mb_grad_step = make_hotcold_stream_mb_grad_step(
        kind, mb, cold_nnz_pad, hot_k, dim, with_intercept,
        slab_dtype=slab_dtype,
    )
    return _build_fused_train_fn(
        key, mb_grad_step, mesh, learning_rate, reg, max_iter, tol
    )


def make_hotcold_glm_train_fn(
    kind: str,
    mesh,
    mb: int,
    cold_nnz_pad: int,
    hot_k: int,
    dim: int,
    learning_rate: float,
    reg: float,
    max_iter: int,
    tol: float,
    with_intercept: bool = True,
    slab_dtype=jnp.bfloat16,
):
    """Fused training over (slab, cold ints, cold floats) batches; loop
    scaffolding shared with every other path via
    :func:`_build_fused_train_fn`."""
    if kind not in ("logistic", "squared"):
        raise ValueError(f"unknown loss kind {kind!r}")
    key = ("hotcold", kind, mesh, mb, cold_nnz_pad, hot_k, dim,
           float(learning_rate), float(reg), int(max_iter), float(tol),
           bool(with_intercept), jnp.dtype(slab_dtype).name)
    mb_grad_step = make_hotcold_mb_grad_step(
        kind, mb, cold_nnz_pad, hot_k, dim, with_intercept
    )
    return _build_fused_train_fn(
        key, mb_grad_step, mesh, learning_rate, reg, max_iter, tol
    )


def make_hotcold_mb_grad_step_2d(kind: str, mb: int, cold_nnz_pad: int,
                                 hot_k_local: int, dim_local: int,
                                 with_intercept: bool = True):
    """Feature-sharded hot/cold minibatch gradient.

    Shard i of the ``model`` axis owns slab columns
    [i*hot_k_local, (i+1)*hot_k_local) (arriving pre-sliced: the slab leaf
    is sharded on its column axis) and the permuted weight range
    [i*dim_local, (i+1)*dim_local) — locally [0, hot_k_local) are its slab
    columns, [hot_k_local, dim_local) its cold features.  The slab GEMMs
    stay node-local; cold entries are masked to local ownership exactly
    like :func:`make_sparse_mb_grad_step_2d`; one ``psum`` over ``model``
    (the TP allreduce riding ICI) completes the logits.  The 128-column
    GEMM widening matches the 1-D step (the N=1 matvec lowers to a
    catastrophic lane reduction)."""
    keep_b = 1.0 if with_intercept else 0.0

    def mb_grad_step(params, xs):
        slab, ints, floats = xs  # slab local: (mb, hot_k_local)
        wts_local, b = params    # (dim_local,), ()
        idx, rid, vals, y, w = _segment_csr_unpack(
            ints, floats, cold_nnz_pad, mb
        )
        return _hotcold_core_2d(
            kind, slab, wts_local, b, idx, rid, vals, y, w,
            mb, hot_k_local, dim_local, keep_b,
        )

    return mb_grad_step


def _hotcold_core_2d(kind: str, slab, wts_local, b, idx, rid, vals, y, w,
                     mb: int, hot_k_local: int, dim_local: int,
                     keep_b: float):
    """The feature-sharded hot/cold minibatch math (the model-axis analog
    of :func:`_hotcold_core`): shard-local slab GEMMs + cold entries masked
    to local ownership + one psum over ``model`` completing the logits.
    Shared by the in-memory step (pre-densified slab) and the out-of-core
    step (slab densified in-program), so the two cannot drift — the
    streamed-vs-in-memory bit-match contract depends on it."""
    lo = jax.lax.axis_index("model") * dim_local
    local_idx = idx - lo
    mine = jnp.logical_and(local_idx >= 0, local_idx < dim_local)
    safe_idx = jnp.clip(local_idx, 0, dim_local - 1)
    dtype = slab.dtype
    w_hot = jnp.broadcast_to(
        wts_local[:hot_k_local].astype(dtype)[:, None], (hot_k_local, 128)
    )
    hot_partial = jax.lax.dot_general(
        slab, w_hot, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )[:, 0]
    contrib = jnp.where(
        mine, vals * jnp.take(wts_local, safe_idx, axis=0), 0.0
    )
    cold_partial = jax.ops.segment_sum(contrib, rid, num_segments=mb)
    # the TP allreduce: complete logits across feature shards
    logits = jax.lax.psum(hot_partial + cold_partial, "model") + b
    err, loss_sum = _sparse_loss(kind, logits, y, w)
    err_m = jnp.broadcast_to(err.astype(dtype)[:, None], (mb, 128))
    g_hot = jax.lax.dot_general(
        slab, err_m, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )[:, 0]
    err_ext = jnp.concatenate([err, jnp.zeros((1,), err.dtype)])
    scatter = jnp.where(mine, vals * jnp.take(err_ext, rid, axis=0), 0.0)
    g_w = jax.ops.segment_sum(scatter, safe_idx, num_segments=dim_local)
    g_w = g_w.at[:hot_k_local].add(g_hot)
    g_b = jnp.sum(err) * keep_b
    return (g_w, g_b), loss_sum, jnp.sum(w)


def make_hotcold_stream_mb_grad_step_2d(kind: str, mb: int,
                                        cold_nnz_pad: int, hot_k_local: int,
                                        dim_local: int,
                                        with_intercept: bool = True,
                                        slab_dtype=jnp.bfloat16):
    """Feature-sharded out-of-core hot/cold minibatch gradient: the
    model-axis composition of :func:`make_hotcold_stream_mb_grad_step`
    (in-program slab densify from packed entries) and
    :func:`make_hotcold_mb_grad_step_2d` (shard-local slab columns + cold
    range, one psum completing logits).  Consumes the SAME block layout as
    the 1-D stream step — entries carry global slab columns / permuted
    ids, and each shard masks to its ownership in-program."""
    keep_b = 1.0 if with_intercept else 0.0
    dtype = jnp.dtype(slab_dtype)

    def mb_grad_step(params, xs):
        h_ints, h_vals, ints, floats = xs
        wts_local, b = params  # (dim_local,), ()
        pos, hrid = h_ints[0], h_ints[1]
        lo_col = jax.lax.axis_index("model") * hot_k_local
        lpos = pos - lo_col
        mine_h = jnp.logical_and(lpos >= 0, lpos < hot_k_local)
        slab = (
            jnp.zeros((mb + 1, hot_k_local), dtype)  # row mb = pad sink
            .at[
                jnp.where(mine_h, hrid, mb),
                jnp.clip(lpos, 0, hot_k_local - 1),
            ]
            .add(jnp.where(mine_h, h_vals, 0.0).astype(dtype))[:mb]
        )
        idx, rid, vals, y, w = _segment_csr_unpack(
            ints, floats, cold_nnz_pad, mb
        )
        return _hotcold_core_2d(
            kind, slab, wts_local, b, idx, rid, vals, y, w,
            mb, hot_k_local, dim_local, keep_b,
        )

    return mb_grad_step


def make_hotcold_glm_train_fn_2d(
    kind: str,
    mesh,
    mb: int,
    cold_nnz_pad: int,
    hot_k: int,
    dim_pad: int,
    learning_rate: float,
    reg: float,
    max_iter: int,
    tol: float,
    with_intercept: bool = True,
    slab_dtype=jnp.bfloat16,
):
    """Fused hot/cold training over a ('data','model') mesh: minibatch
    groups shard over ``data``, slab columns and the permuted weight vector
    over ``model``.  Loop scaffolding shared with every other path via
    :func:`_build_fused_train_fn`."""
    if kind not in ("logistic", "squared"):
        raise ValueError(f"unknown loss kind {kind!r}")
    model_size = dict(mesh.shape)["model"]
    if hot_k % model_size or dim_pad % model_size:
        raise ValueError(
            f"hot_k={hot_k} / dim_pad={dim_pad} not divisible by model "
            f"axis size {model_size} (use split_hot_cold(model_size=...))"
        )
    key = ("hotcold2d", kind, mesh, mb, cold_nnz_pad, hot_k, dim_pad,
           float(learning_rate), float(reg), int(max_iter), float(tol),
           bool(with_intercept), jnp.dtype(slab_dtype).name)
    mb_grad_step = make_hotcold_mb_grad_step_2d(
        kind, mb, cold_nnz_pad, hot_k // model_size, dim_pad // model_size,
        with_intercept,
    )

    from jax.sharding import PartitionSpec as P

    return _build_fused_train_fn(
        key, mb_grad_step, mesh, learning_rate, reg, max_iter, tol,
        in_specs=(
            (P("model"), P()),
            (P("data", None, "model"), P("data"), P("data")),
        ),
        out_specs=((P("model"), P()), P(), P(), P()),
        delta_fn=_feature_sharded_delta,
    )


def make_hotcold_stream_glm_train_fn_2d(
    kind: str,
    mesh,
    mb: int,
    cold_nnz_pad: int,
    hot_k: int,
    dim_pad: int,
    learning_rate: float,
    reg: float,
    max_iter: int,
    tol: float,
    with_intercept: bool = True,
    slab_dtype=jnp.bfloat16,
):
    """Feature-sharded counterpart of
    :func:`make_hotcold_stream_glm_train_fn`: packed entries shard over
    ``data`` (replicated over ``model`` — they carry global slab columns;
    each shard masks to its ownership in-program), the permuted weight
    vector over ``model``."""
    if kind not in ("logistic", "squared"):
        raise ValueError(f"unknown loss kind {kind!r}")
    model_size = dict(mesh.shape)["model"]
    if hot_k % model_size or dim_pad % model_size:
        raise ValueError(
            f"hot_k={hot_k} / dim_pad={dim_pad} not divisible by model "
            f"axis size {model_size} (use split_hot_cold(model_size=...))"
        )
    key = ("hotcold-stream2d", kind, mesh, mb, cold_nnz_pad, hot_k, dim_pad,
           float(learning_rate), float(reg), int(max_iter), float(tol),
           bool(with_intercept), jnp.dtype(slab_dtype).name)
    mb_grad_step = make_hotcold_stream_mb_grad_step_2d(
        kind, mb, cold_nnz_pad, hot_k // model_size, dim_pad // model_size,
        with_intercept, slab_dtype=slab_dtype,
    )

    from jax.sharding import PartitionSpec as P

    return _build_fused_train_fn(
        key, mb_grad_step, mesh, learning_rate, reg, max_iter, tol,
        in_specs=(
            (P("model"), P()),
            (P("data"), P("data"), P("data"), P("data")),
        ),
        out_specs=((P("model"), P()), P(), P(), P()),
        delta_fn=_feature_sharded_delta,
    )


def train_glm_sparse_hotcold(
    init_params,
    hstack: HotColdStack,
    kind: str,
    mesh,
    learning_rate: float,
    max_iter: int,
    reg: float = 0.0,
    tol: float = 0.0,
    with_intercept: bool = True,
    checkpoint=None,
    device_batch=None,
    resident_slabs: bool = True,
) -> TrainResult:
    """Hot/cold counterpart of :func:`train_glm_sparse`.  Training runs in
    permuted feature space; ``run`` unpermutes before returning, so BOTH
    the returned coefficients and any saved checkpoints are in the
    ORIGINAL feature space (each chunk's placement re-permutes on entry —
    the permutation is deterministic from the packed data).  ``hstack``
    may be a zero-arg thunk: the expensive host split is resolved only
    when training actually runs, so a no-op checkpoint resume skips it
    entirely.  A stack laid out with ``model_size > 1`` trains
    feature-sharded over the mesh's ``model`` axis (slab columns and the
    permuted weight vector sharded, one psum completing logits).

    ``resident_slabs=False`` selects the SCALABLE formulation: HBM holds
    only the packed entry arrays and each minibatch's slab densifies
    in-program — O(nnz) device memory instead of O(n_rows x hot_k), the
    only variant that exists at shapes where the slabs cannot fit (the
    estimator's ``hotSlabMode`` auto policy decides; see
    :func:`hotcold_slab_bytes`)."""
    resolved: list = [None]

    def hs() -> HotColdStack:
        if resolved[0] is None:
            resolved[0] = _resolve_thunk(hstack)
        return resolved[0]

    def place(params):
        from jax.sharding import PartitionSpec as P

        from flink_ml_tpu.parallel.mesh import global_put, replicate

        w0, b0 = params
        h = hs()
        # scatter (not gather-by-inv_perm): dead positions of a rounded-up
        # 2-D layout must hold zero, not a duplicated weight
        w_perm = np.zeros((h.dim_pad,), np.float32)
        w_perm[h.perm] = np.asarray(w0, np.float32)
        if h.model_size > 1:
            # multi-process-safe: every process derives the same permuted
            # vector and materializes only its model-axis slice
            return (
                global_put(mesh, w_perm, P("model")),
                global_put(mesh, np.asarray(b0, np.float32), P()),
            )
        return replicate(
            mesh, (jnp.asarray(w_perm), jnp.asarray(b0, jnp.float32))
        )

    def trim(params):
        return (np.asarray(params[0])[hs().perm], params[1])

    def factory(n_epochs):
        h = hs()
        if h.model_size > 1:
            maker = (
                make_hotcold_glm_train_fn_2d if resident_slabs
                else make_hotcold_stream_glm_train_fn_2d
            )
            return maker(
                kind, mesh, h.cold.mb, h.cold.nnz_pad, h.hot_k, h.dim_pad,
                learning_rate, reg, n_epochs, tol, with_intercept,
                slab_dtype=h.slab_dtype,
            )
        maker = (
            make_hotcold_glm_train_fn if resident_slabs
            else make_hotcold_stream_glm_train_fn
        )
        return maker(
            kind, mesh, h.cold.mb, h.cold.nnz_pad, h.hot_k, h.cold.dim,
            learning_rate, reg, n_epochs, tol, with_intercept,
            slab_dtype=h.slab_dtype,
        )

    def default_batch():
        if resident_slabs:
            return hotcold_device_batch(mesh, hs())
        return hotcold_entries_device_batch(mesh, hs())

    def run(n_epochs, params, dev_batch=None):
        r = _run_fused_train(
            factory(n_epochs), params,
            dev_batch if dev_batch is not None else default_batch(),
            mesh, place_params=place, batch_preplaced=True,
            n_rows=hs().n_rows,
        )
        return TrainResult(params=trim(r.params), epochs=r.epochs,
                           losses=r.losses, final_delta=r.final_delta,
                           metrics=r.metrics)

    if checkpoint is None:
        return run(max_iter, init_params, _resolve_thunk(device_batch))
    return run_chunked_checkpoint(
        run, init_params, max_iter, tol, checkpoint, mesh, None,
        device_batch=(
            device_batch if device_batch is not None else default_batch
        ),
    )


def make_dense_mb_grad_step_2d(kind: str, with_intercept: bool = True):
    """Feature-sharded DENSE minibatch gradient (VERDICT r3 item 5).

    Shard i of the ``model`` axis owns columns [i*d_local, (i+1)*d_local) of
    both the minibatch and the weight vector; each step is a local
    ``(mb, d_local) @ (d_local,)`` matvec producing partial logits, one
    ``psum`` over ``model`` (the TP allreduce riding ICI) completes them,
    and the backward ``x.T @ err`` lands only in the local column range —
    weight traffic never crosses chips.  The wide-dense analog of
    :func:`make_sparse_mb_grad_step_2d`, sharing its loss math.
    """
    keep_b = 1.0 if with_intercept else 0.0

    def mb_grad_step(params, xs):
        xb, yb, wb = xs  # (mb, d_local), (mb,), (mb,)
        wts_local, b = params
        partial = xb @ wts_local
        logits = jax.lax.psum(partial, "model") + b
        err, loss_sum = _sparse_loss(kind, logits, yb, wb)
        g_w = xb.T @ err
        g_b = jnp.sum(err) * keep_b
        return (g_w, g_b), loss_sum, jnp.sum(wb)

    return mb_grad_step


def make_dense_glm_train_fn_2d(
    kind: str,
    mesh,
    learning_rate: float,
    reg: float,
    max_iter: int,
    tol: float,
    with_intercept: bool = True,
):
    """Fused dense training over a ('data','model') mesh: rows shard over
    ``data``, feature columns (and the weight vector) over ``model``.  The
    loop scaffolding (while_loop epochs, tol, loss history) is shared with
    every other path via :func:`_build_fused_train_fn`."""
    if kind not in ("logistic", "squared"):
        raise ValueError(f"unknown loss kind {kind!r}")
    key = ("dense2d", kind, mesh, float(learning_rate), float(reg),
           int(max_iter), float(tol), bool(with_intercept))
    mb_grad_step = make_dense_mb_grad_step_2d(kind, with_intercept)

    from jax.sharding import PartitionSpec as P

    return _build_fused_train_fn(
        key, mb_grad_step, mesh, learning_rate, reg, max_iter, tol,
        in_specs=((P("model"), P()), (P("data", None, "model"), P("data"), P("data"))),
        out_specs=((P("model"), P()), P(), P(), P()),
        delta_fn=_feature_sharded_delta,
    )


def place_dense_2d_batch(mesh, stack: MinibatchStack, dim_pad: int):
    """Device placement for the feature-sharded dense layout: x's feature
    dim pads to the model-axis multiple and shards over ('data', -, 'model');
    y/w shard over 'data' only (replicated across feature shards).

    Multi-process, ``stack`` holds this process's LOCAL rows (the
    per-process file-shard contract): each process owns whole data-axis
    positions spanning ALL model columns, so its local block is its full
    addressable portion and rides the same local-block assembly as every
    other batch (:func:`~flink_ml_tpu.parallel.mesh.shard_batch_specs`)."""
    from jax.sharding import PartitionSpec as P

    from flink_ml_tpu.parallel.mesh import shard_batch_specs

    x = stack.x
    if dim_pad != x.shape[2]:
        xp = np.zeros((x.shape[0], x.shape[1], dim_pad), dtype=x.dtype)
        xp[..., : x.shape[2]] = x
        x = xp
    return shard_batch_specs(
        mesh, (x, stack.y, stack.w),
        (P("data", None, "model"), P("data"), P("data")),
    )


def train_glm_dense_2d(
    init_params,
    stack: MinibatchStack,
    kind: str,
    mesh,
    learning_rate: float,
    max_iter: int,
    reg: float = 0.0,
    tol: float = 0.0,
    with_intercept: bool = True,
    checkpoint=None,
    device_batch=None,
) -> TrainResult:
    """Dense counterpart of the 2-D branch of :func:`train_glm_sparse`: a
    wide dense GLM whose weight vector (and activations) shard over the
    ``model`` axis — the wider-than-one-chip story for dense features
    (SURVEY §5.7).  Numerics match the replicated path to ulp-level f32
    rounding: splitting the d-dim contraction into per-shard partials
    changes only the summation grouping, not the update schedule."""
    model_size = dict(mesh.shape).get("model", 1)
    if model_size < 2:
        raise ValueError(
            "train_glm_dense_2d needs a mesh with a >1 'model' axis; use "
            "train_glm (replicated params) on a data-only mesh"
        )
    dim = stack.x.shape[2]
    place, trim, dim_pad = make_feature_shard_placer(mesh, dim, model_size)
    batch = (stack.x, stack.y, stack.w)

    def factory(n_epochs):
        return make_dense_glm_train_fn_2d(
            kind, mesh, learning_rate, reg, n_epochs, tol, with_intercept
        )

    def run(n_epochs, params, dev_batch=None):
        r = _run_fused_train(
            factory(n_epochs), params,
            place_dense_2d_batch(mesh, stack, dim_pad)
            if dev_batch is None else dev_batch,
            mesh, place_params=place, batch_preplaced=True,
            n_rows=stack.n_rows,
        )
        return TrainResult(params=trim(r.params), epochs=r.epochs,
                           losses=r.losses, final_delta=r.final_delta,
                           metrics=r.metrics)

    if checkpoint is None:
        return run(max_iter, init_params, _resolve_thunk(device_batch))
    return run_chunked_checkpoint(
        run, init_params, max_iter, tol, checkpoint, mesh, batch,
        device_batch=device_batch
        if device_batch is not None
        else (lambda: place_dense_2d_batch(mesh, stack, dim_pad)),
    )


def make_feature_shard_placer(mesh, dim: int, model_size: int):
    """Placement for a ``model``-axis-sharded GLM parameter pytree.

    Returns ``(place, trim, dim_pad)``: ``place`` pads the weight vector up
    to ``dim_pad`` (the model-axis multiple) and device_puts (w sharded over
    'model', intercept replicated); ``trim`` slices the padding back off.
    The ONE copy of this logic — the in-memory 2-D driver and the
    out-of-core 2-D path both use it, so their placements cannot drift.
    Multi-process-safe: every process derives the identical full weight
    vector and materializes only its model-axis slice
    (:func:`~flink_ml_tpu.parallel.mesh.global_put`).
    """
    from jax.sharding import PartitionSpec as P

    from flink_ml_tpu.parallel.mesh import global_put

    dim_pad = -(-dim // model_size) * model_size

    def place(params):
        w0, b0 = params
        w0 = np.asarray(w0, dtype=np.float32)
        if dim_pad != int(w0.shape[0]):
            w0 = np.concatenate(
                [w0, np.zeros((dim_pad - w0.shape[0],), w0.dtype)]
            )
        return (
            global_put(mesh, w0, P("model")),
            global_put(mesh, np.asarray(b0, dtype=np.float32), P()),
        )

    def trim(params):
        return (params[0][:dim], params[1])

    return place, trim, dim_pad


def train_glm_sparse(
    init_params,
    sstack: SparseMinibatchStack,
    kind: str,
    mesh,
    learning_rate: float,
    max_iter: int,
    reg: float = 0.0,
    tol: float = 0.0,
    with_intercept: bool = True,
    checkpoint=None,
    device_batch=None,
) -> TrainResult:
    """Sparse counterpart of :func:`train_glm` (always the fused device loop).

    On a mesh with a >1-sized ``model`` axis the weight vector is sharded
    over it (:func:`make_sparse_glm_train_fn_2d`); the feature dimension is
    padded up to a multiple of the axis size.  With a
    :class:`~flink_ml_tpu.iteration.checkpoint.CheckpointConfig` the run
    executes as fused chunks of ``every_n_epochs`` epochs with a snapshot
    between chunks (and resumes from the latest snapshot).
    """
    model_size = dict(mesh.shape).get("model", 1)
    dim = sstack.dim
    if model_size > 1:
        place, trim, dim_pad = make_feature_shard_placer(mesh, dim, model_size)

        def factory(n_epochs):
            return make_sparse_glm_train_fn_2d(
                kind, mesh, sstack.mb, sstack.nnz_pad, dim_pad,
                learning_rate, reg, n_epochs, tol, with_intercept,
            )
    else:
        def place(params):
            from flink_ml_tpu.parallel.mesh import replicate

            return replicate(mesh, params)

        def factory(n_epochs):
            return make_sparse_glm_train_fn(
                kind, mesh, sstack.mb, sstack.nnz_pad, dim,
                learning_rate, reg, n_epochs, tol, with_intercept,
            )

        def trim(params):
            return params

    batch = (sstack.ints, sstack.floats)

    def run(n_epochs, params, dev_batch=None):
        r = _run_fused_train(
            factory(n_epochs), params,
            batch if dev_batch is None else dev_batch, mesh,
            place_params=place, batch_preplaced=dev_batch is not None,
            n_rows=sstack.n_rows,
        )
        return TrainResult(params=trim(r.params), epochs=r.epochs,
                           losses=r.losses, final_delta=r.final_delta,
                           metrics=r.metrics)

    if checkpoint is None:
        return run(max_iter, init_params, _resolve_thunk(device_batch))
    return run_chunked_checkpoint(
        run, init_params, max_iter, tol, checkpoint, mesh, batch,
        device_batch=device_batch,
    )


def _resolve_thunk(x):
    """Zero-arg callables stand in for expensive values (k-means++ init,
    device placement) that must not be computed on paths that skip them
    (no-op checkpoint resume); everything else passes through unchanged."""
    return x() if callable(x) else x


def run_chunked_checkpoint(
    run, init_params, max_iter: int, tol: float, checkpoint, mesh, batch,
    device_batch=None, like=None,
) -> TrainResult:
    """Shared chunked-checkpoint driver for fused training programs.

    Executes ``run(n_epochs, params, device_batch) -> TrainResult`` in fused
    chunks of ``checkpoint.every_n_epochs`` epochs with a snapshot between
    chunks; resumes from the latest snapshot in ``checkpoint.directory``.
    ``init_params`` may be a thunk (expensive host init, e.g. k-means++):
    it is resolved only when there is no snapshot to resume from — pass
    ``like`` (a structure template; values unused) for the resume load.
    A finished run (recorded tol convergence at this-or-stricter tolerance,
    or max epochs reached) resumes to a no-op — the fused while_loop always
    executes a chunk's epoch 0, which would drift from the uninterrupted
    result.  The batch is placed on the mesh ONCE across all chunks.  Used
    by the sparse GLM and KMeans paths (one copy of the resume semantics).
    """
    from flink_ml_tpu.iteration.checkpoint import (
        agreed_latest_checkpoint,
        load_checkpoint,
        prune_checkpoints,
        save_checkpoint,
    )
    from flink_ml_tpu.parallel.mesh import shard_batch

    start_epoch = 0
    losses: list = []
    latest = agreed_latest_checkpoint(checkpoint.directory)
    if latest is None:
        params = _resolve_thunk(init_params)
    else:
        template = like if like is not None else init_params
        params, meta = load_checkpoint(latest, like=template)
        start_epoch = int(meta["epoch"]) + 1
        losses = list(meta.get("losses", []))
        if _meta_converged(meta, tol) or start_epoch >= max_iter:
            # no-op re-fit: self-describing result from the snapshot meta
            # (final_delta persisted at save time; metrics default empty)
            delta = meta.get("final_delta")
            return TrainResult(
                params=params, epochs=start_epoch, losses=losses,
                final_delta=None if delta is None else float(delta),
            )

    chunk_metrics = StepMetrics("fused_train")
    # pin the training dtype across chunk boundaries: under x64 the fetch
    # returns f64 copies of f32 device params, and re-placing those would
    # silently promote every chunk after the first to double precision —
    # a continuous checkpointed run would then drift from both the
    # unchunked fused run and a kill-and-resumed one (load_checkpoint
    # casts back to the template dtype for the same reason).  The f64
    # copies hold the f32 values exactly, so the cast is lossless.
    _chunk_dtypes = [
        getattr(x, "dtype", None)
        for x in jax.tree_util.tree_leaves(params)
    ]

    def _pin_dtypes(pytree):
        leaves, treedef = jax.tree_util.tree_flatten(pytree)
        leaves = [
            np.asarray(x, dtype=dt) if dt is not None else x
            for x, dt in zip(leaves, _chunk_dtypes)
        ]
        return jax.tree_util.tree_unflatten(treedef, leaves)

    # placement happens AFTER the no-op-resume early return above: a finished
    # run must not pay the host->device transfer just to return the snapshot.
    # ``device_batch`` may be a thunk (lazy placement) for the same reason.
    device_batch = _resolve_thunk(device_batch)
    if device_batch is None:
        from flink_ml_tpu.fault.retry import with_retry

        # place ONCE across all chunks; cold H2D is a transient surface
        device_batch = with_retry(
            lambda: shard_batch(mesh, batch), "place"
        )
    last_delta = None
    with fault.preemption_scope():
        while start_epoch < max_iter:
            chunk = min(checkpoint.every_n_epochs, max_iter - start_epoch)
            r = run(chunk, params, device_batch)
            params = _pin_dtypes(r.params)
            losses.extend(r.losses)
            start_epoch += r.epochs
            last_delta = r.final_delta
            chunk_metrics.extend(r.metrics)
            converged = r.epochs < chunk or (  # mid-chunk or at boundary
                tol > 0.0 and r.final_delta is not None
                and r.final_delta <= tol
            )
            # health precedes the snapshot: the latest checkpoint is by
            # construction the last GOOD state, so a guard rollback never
            # resumes into the divergence (the fused runner checked the
            # same values already; this guards custom `run` callables too)
            fault.check_health(
                r.losses, jax.tree_util.tree_leaves(params),
                where="chunked_train",
            )
            save_checkpoint(
                checkpoint.directory, start_epoch - 1, params,
                meta={"losses": losses, "converged": converged, "tol": tol,
                      "final_delta": r.final_delta},
            )
            prune_checkpoints(checkpoint.directory, checkpoint.keep)
            if fault.preempted() and not converged and start_epoch < max_iter:
                # the boundary snapshot just committed IS the emergency
                # checkpoint; exit cleanly for the resume path
                fault.emergency_save(lambda: None)
            if converged:
                break
    return TrainResult(params=params, epochs=start_epoch, losses=losses,
                       final_delta=last_delta, metrics=chunk_metrics)


def _meta_converged(meta: dict, tol: float) -> bool:
    """Does a checkpoint's recorded convergence satisfy the CURRENT tol?

    A run stamped converged at a looser tolerance must keep training when
    re-fit with a tighter (or zero) tol, so the early return fires only when
    the stored criterion is at least as strict as the requested one.
    """
    if not meta.get("converged") or tol <= 0.0:
        return False
    stored_tol = float(meta.get("tol") or 0.0)
    return 0.0 < stored_tol <= tol


def fit_pool_extra(stage, result) -> dict:
    """Per-fit slab-pool + latency extras for the fit RunReport.

    ``stage._fit_pool_stats0`` is the (hits, misses, t0) snapshot the
    estimator's ``fit`` took on entry; the delta is THIS fit's pool
    traffic and ``fit_wall_ms`` its TRUE end-to-end wall — pack, pooled
    placement (which happens before the fused driver runs), dispatch, and
    sync.  ``call_latency_ms`` sums the driver-recorded device-call
    windows; a broken pool shows up in ``fit_wall_ms`` (and in the
    ``slab_pool.build`` timing) even when the device-call window alone
    looks healthy."""
    import time as _time

    from flink_ml_tpu.table import slab_pool

    h, m = slab_pool.pool().counters()
    now = _time.perf_counter()
    h0, m0, t0 = getattr(stage, "_fit_pool_stats0", (h, m, now))
    hits, misses = max(h - h0, 0), max(m - m0, 0)
    extra = {"slab_pool_hits": hits, "slab_pool_misses": misses,
             "fit_wall_ms": round((now - t0) * 1e3, 3)}
    if hits + misses:
        extra["slab_pool_hit_rate"] = round(hits / (hits + misses), 4)
    steps = getattr(result.metrics, "steps", None) or []
    latency = sum(
        float(s["call_latency_ms"]) for s in steps if "call_latency_ms" in s
    )
    if latency:
        extra["call_latency_ms"] = round(latency, 3)
    return extra


def fetch_flat(*arrays):
    """Fetch device arrays in ONE transfer (concatenated flat), then split.

    Per-array device->host reads each pay a full round-trip on tunneled
    backends; bundling them makes the readback latency constant.  The fetch
    dtype follows the backend: f64 only when x64 is enabled (CPU test mesh) —
    requesting f64 on TPU would just truncate to f32 with a warning per call.
    """
    from flink_ml_tpu.parallel.collectives import HAS_NATIVE_SHARD_MAP

    fetch_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    if not HAS_NATIVE_SHARD_MAP:
        # legacy JAX (pre-jax.shard_map): concatenating arrays with MIXED
        # shardings — a 'model'-sharded weight vector next to a replicated
        # loss history — miscompiles, returning values multiplied by the
        # unmentioned mesh axis size (observed on 0.4.x, eager AND jitted).
        # Per-array fetches are correct there; the bundled single-transfer
        # fast path stays on for current JAX.
        return [np.asarray(a).astype(fetch_dtype) for a in arrays]
    shapes = [a.shape for a in arrays]
    sizes = [int(np.prod(s)) for s in shapes]
    flat = jnp.concatenate(
        [jnp.ravel(a).astype(fetch_dtype) for a in arrays]
    )
    buf = np.asarray(flat)
    out = []
    off = 0
    for shape, size in zip(shapes, sizes):
        out.append(buf[off : off + size].reshape(shape))
        off += size
    return out


#: the dense GLM training pressure surface (ISSUE 9) — shared by the
#: estimator's pooled placement gate and the micro-batch fallback
_TRAIN_PRESSURE_SURFACE = "train.glm"


def _pressure_window_fn(grad_fn: GradFn, mesh, learning_rate: float,
                        reg: float, w: int):
    """``w`` consecutive global SGD steps as ONE compiled program over a
    window batch of shape ``(n_dev*w, mb, d+2)`` — the resident-memory
    knob of the pressure fallback.  The scanned minibatch body is
    verbatim the fused program's (same grad math, same psum, same update,
    same loss bookkeeping), so streaming a run through windows of ANY
    size replays the identical per-step floating-point computation:
    final params match the whole-batch fused run exactly."""
    check_vma = getattr(grad_fn, "shard_map_check_vma", True)
    key = ("pressure_win", grad_fn, mesh, float(learning_rate),
           float(reg), int(w), check_vma)
    cached = _cache_get(key)
    if cached is not None:
        return cached
    sgd_update = make_sgd_update(learning_rate, reg)

    def local_window(params, batch):  # local (w, mb, d+2)
        def mb_step(p, mb):
            grads, loss_sum, w_sum = grad_fn(
                p, mb[..., :-2], mb[..., -2], mb[..., -1]
            )
            grads = jax.tree_util.tree_map(lambda g: psum(g, "data"), grads)
            loss_sum = psum(loss_sum, "data")
            w_sum = psum(w_sum, "data")
            count = jnp.maximum(w_sum, 1.0)
            return sgd_update(p, grads, count), (loss_sum / count, w_sum)

        params, (losses, counts) = jax.lax.scan(mb_step, params, batch)
        return params, losses, counts

    from jax.sharding import PartitionSpec as P

    sharded = shard_map(
        local_window, mesh=mesh,
        in_specs=(P(), P("data")),
        out_specs=(P(), P(), P()),
        check_vma=check_vma,
    )
    return _cache_put(key, jax.jit(sharded))


def _pressure_grad_fn(grad_fn: GradFn, mesh, c: int):
    """psum'd gradient SUMS over one ``c``-row micro-chunk per device (no
    update) — the accumulation half of micro-batch gradient accumulation
    for a single SGD step that exceeds device capacity on its own."""
    check_vma = getattr(grad_fn, "shard_map_check_vma", True)
    key = ("pressure_grad", grad_fn, mesh, int(c), check_vma)
    cached = _cache_get(key)
    if cached is not None:
        return cached

    def local_grad(params, chunk):  # local (1, c, d+2)
        mb = chunk[0]
        grads, loss_sum, w_sum = grad_fn(
            params, mb[..., :-2], mb[..., -2], mb[..., -1]
        )
        grads = jax.tree_util.tree_map(lambda g: psum(g, "data"), grads)
        return grads, psum(loss_sum, "data"), psum(w_sum, "data")

    from jax.sharding import PartitionSpec as P

    sharded = shard_map(
        local_grad, mesh=mesh,
        in_specs=(P(), P("data")),
        out_specs=(P(), P(), P()),
        check_vma=check_vma,
    )
    return _cache_put(key, jax.jit(sharded))


def _pressure_update_fn(learning_rate: float, reg: float):
    """One SGD update from accumulated gradient sums (+ the step's mean
    loss) — the apply half of gradient accumulation."""
    key = ("pressure_upd", float(learning_rate), float(reg))
    cached = _cache_get(key)
    if cached is not None:
        return cached
    sgd_update = make_sgd_update(learning_rate, reg)

    def upd(params, grads, loss_sum, w_sum):
        count = jnp.maximum(w_sum, 1.0)
        return sgd_update(params, grads, count), loss_sum / count

    return _cache_put(key, jax.jit(upd))


def _pressure_accum_step(params, step_rows: np.ndarray, mesh,
                         grad_fn: GradFn, learning_rate: float, reg: float):
    """One SGD step whose minibatch alone exceeds device capacity:
    sum-based gradient accumulation over contiguous row micro-chunks
    (ascending ranges — a bitwise-stable accumulation order, identical on
    every run), one psum'd grad program per resident chunk, then a single
    update.  ``step_rows`` is the step's host minibatch
    ``(n_dev, mb, d+2)``."""
    from flink_ml_tpu.fault import pressure
    from flink_ml_tpu.fault.retry import with_retry
    from flink_ml_tpu.parallel.mesh import shard_batch

    n_dev, mb = step_rows.shape[0], step_rows.shape[1]

    def chunk_call(lo: int, hi: int):
        chunk = np.ascontiguousarray(step_rows[:, lo:hi])
        fault.maybe_oom(n_dev * (hi - lo))
        win = with_retry(lambda: shard_batch(mesh, chunk), "place")
        return _pressure_grad_fn(grad_fn, mesh, hi - lo)(params, win)

    def accum(pieces):
        grads, loss_sum, w_sum = pieces[0]
        for g2, l2, w2 in pieces[1:]:
            grads = jax.tree_util.tree_map(jnp.add, grads, g2)
            loss_sum = loss_sum + l2
            w_sum = w_sum + w2
        return grads, loss_sum, w_sum

    grads, loss_sum, w_sum = pressure.run_bisected(
        chunk_call, mb, surface=_TRAIN_PRESSURE_SURFACE + ".accum",
        concat=accum, evict=False,
    )
    obs.counter_add("pressure.accum_steps")
    new_params, loss = _pressure_update_fn(learning_rate, reg)(
        params, grads, loss_sum, w_sum
    )
    return new_params, loss, w_sum


def _train_glm_pressure(init_params, stack: MinibatchStack,
                        grad_fn: GradFn, mesh, learning_rate: float,
                        reg: float, max_iter: int, tol: float) -> TrainResult:
    """Micro-batch GLM training under HBM pressure (ISSUE 9).

    The whole-run fused program needs the entire packed batch
    device-resident; when that allocation OOMs, this driver streams the
    SAME update schedule through bounded windows instead: per pass, the
    rows of ``w`` consecutive global steps are placed and scanned by
    :func:`_pressure_window_fn` (per-step math verbatim the fused
    program's — exact-parity contract), shrinking ``w`` on further OOM
    down to one step, below which :func:`_pressure_accum_step` splits the
    single minibatch into accumulated gradient micro-chunks.  The
    ``train.glm`` pressure state remembers the workable window across
    fits and AIMD-probes back toward the whole-batch fused path."""
    from flink_ml_tpu.fault import pressure
    from flink_ml_tpu.fault.retry import with_retry
    from flink_ml_tpu.parallel.mesh import replicate, shard_batch

    comb = _combined_view_memo(stack)
    steps, mb = stack.steps, stack.mb
    n_dev = comb.shape[0] // max(steps, 1)
    group_rows = n_dev * mb
    st = pressure.state(_TRAIN_PRESSURE_SURFACE)
    metrics = StepMetrics("pressure_train")
    metrics.start_step()
    params = replicate(mesh, init_params)
    losses_dev: list = []
    delta = None
    tol_ = float(tol)
    epoch = 0

    def window_steps() -> int:
        # limit_rows converts the per-device cap back to mesh-global rows
        # (ISSUE 15): an 8-device window shrinks to what one device
        # couldn't hold, not to a 1-device budget for the whole mesh
        cap = st.limit_rows(n_dev)
        if cap is None:
            return steps
        return max(1, min(steps, cap // max(group_rows, 1)))

    while epoch < max_iter:
        if tol_ > 0.0 and epoch > 0 and float(delta) <= tol_:
            break
        # AIMD up-probe between epochs
        st.admit(comb.shape[0] * mb, n_dev=n_dev)
        start = params
        ep_losses: list = []
        ep_counts: list = []
        s = 0
        while s < steps:
            w = min(window_steps(), steps - s)
            cap = st.limit_rows(n_dev)
            if w == 1 and cap is not None and cap < group_rows:
                # the cap already says ONE step cannot fit: go straight
                # to gradient accumulation instead of paying a doomed
                # full-minibatch placement (and an OOM event) per step
                idx = np.arange(n_dev) * steps + s
                params, loss1, count1 = _pressure_accum_step(
                    params, comb[idx], mesh, grad_fn, learning_rate, reg
                )
                ep_losses.append(jnp.reshape(loss1, (1,)))
                ep_counts.append(jnp.reshape(count1, (1,)))
                s += 1
                continue
            # device-major gather: global step s' uses dim-0 rows
            # {k*steps + s'} — window rows stay device-contiguous so the
            # 'data'-axis shard sees its own steps in order
            idx = (np.arange(n_dev)[:, None] * steps
                   + (s + np.arange(w))[None, :]).reshape(-1)
            host_win = np.ascontiguousarray(comb[idx])
            rows = n_dev * w * mb
            try:
                fault.maybe_oom(rows)
                win = with_retry(
                    lambda hw=host_win: shard_batch(mesh, hw), "place"
                )
                params, losses_w, counts_w = _pressure_window_fn(
                    grad_fn, mesh, learning_rate, reg, w
                )(params, win)
            except Exception as exc:  # noqa: BLE001 - OOM-filtered
                if not fault.is_oom(exc):
                    raise
                if w > 1:
                    pressure.note_oom(_TRAIN_PRESSURE_SURFACE, rows, exc,
                                      floor=group_rows, n_dev=n_dev)
                    obs.counter_add("pressure.bisections")
                    obs.counter_add(
                        f"pressure.bisections.{_TRAIN_PRESSURE_SURFACE}"
                    )
                    continue  # same step range, smaller window
                # a single step is too big on its own: accumulate
                pressure.note_oom(_TRAIN_PRESSURE_SURFACE, rows, exc,
                                  n_dev=n_dev)
                params, loss1, count1 = _pressure_accum_step(
                    params, comb[idx], mesh, grad_fn, learning_rate, reg
                )
                ep_losses.append(jnp.reshape(loss1, (1,)))
                ep_counts.append(jnp.reshape(count1, (1,)))
                s += 1
                continue
            ep_losses.append(losses_w)
            ep_counts.append(counts_w)
            s += w
        losses_all = jnp.concatenate(ep_losses)
        counts_all = jnp.concatenate(ep_counts)
        total = jnp.maximum(jnp.sum(counts_all), 1.0)
        losses_dev.append(jnp.sum(losses_all * counts_all) / total)
        delta = jnp.sqrt(sum(
            jnp.sum((a - b) ** 2)
            for a, b in zip(jax.tree_util.tree_leaves(params),
                            jax.tree_util.tree_leaves(start))
        ))
        epoch += 1

    leaves, treedef = jax.tree_util.tree_flatten(params)
    loss_hist = (
        jnp.stack(losses_dev) if losses_dev
        else jnp.zeros((0,), dtype=jnp.float32)
    )
    fetched = fetch_flat(
        *leaves, loss_hist,
        jnp.asarray(delta if delta is not None else jnp.inf),
    )
    losses = [float(x) for x in fetched[-2]]
    host_params = jax.tree_util.tree_unflatten(
        treedef, fetched[: len(leaves)]
    )
    metrics.end_step(
        samples=stack.n_rows * epoch, epochs=epoch,
        loss=losses[-1] if losses else 0.0,
    )
    obs.counter_add("train.pressure_runs")
    obs.counter_add("train.epochs", epoch)
    obs.counter_add("train.rows", stack.n_rows * epoch)
    obs.record_hbm_gauges()
    fault.check_health(
        losses, fetched[: len(leaves)],
        float(fetched[-1]) if epoch else None,
        where="pressure_train",
    )
    return TrainResult(
        params=host_params,
        epochs=epoch,
        losses=losses,
        final_delta=float(fetched[-1]),
        metrics=metrics,
    )


def train_glm(
    init_params,
    stack: MinibatchStack,
    grad_fn: GradFn,
    mesh,
    learning_rate: float,
    max_iter: int,
    reg: float = 0.0,
    tol: float = 0.0,
    listeners: Sequence = (),
    checkpoint=None,
    device_batch=None,
) -> TrainResult:
    """Drive GLM training to termination.

    Termination mirrors the reference's two bounded modes: a max epoch count,
    and — when ``tol`` > 0 — an empty-criteria round, realized as "parameter
    update norm below tol" (SURVEY.md §3.5, IterationBodyResult.java:44-48).

    Without listeners or checkpointing the entire run is ONE device program
    (fused epoch while_loop, single transfer each way).  With listeners or a
    :class:`~flink_ml_tpu.iteration.checkpoint.CheckpointConfig`, epochs go
    through the bounded iteration runtime so per-epoch watermark callbacks
    fire and snapshots land at the configured cadence; an existing snapshot
    in ``checkpoint.directory`` resumes the run from its epoch, and the
    deterministic packing order makes resumed runs bit-match uninterrupted
    ones.
    """
    from flink_ml_tpu.parallel.mesh import replicate, shard_batch

    if not listeners and checkpoint is None:
        from flink_ml_tpu.fault import pressure
        from flink_ml_tpu.parallel.mesh import data_parallel_size

        row_slots = stack.x.shape[0] * stack.mb
        # per-device-denominated caps (ISSUE 15): an OOM shrinks what ONE
        # device could not hold, so the mesh width scales the global cap
        n_dev_mesh = data_parallel_size(mesh)
        st = pressure.state(_TRAIN_PRESSURE_SURFACE)
        if pressure.enabled() and st.capped_below(row_slots,
                                                 n_dev=n_dev_mesh):
            # known pressure from an earlier fit: go straight to the
            # micro-batch path at the remembered window (no failing
            # whole-batch probe); the AIMD up-probe inside restores the
            # fused path once the cap recovers
            return _train_glm_pressure(
                init_params, stack, grad_fn, mesh, learning_rate, reg,
                max_iter, tol,
            )
        from flink_ml_tpu.utils import knobs

        # dispatch diet (ISSUE 17): the fast path always bundles the
        # result fetch into the training program; the batch is donated
        # too when THIS driver places it (an estimator-supplied
        # device_batch is slab-pooled — donation would delete the pool's
        # entry) and donation isn't inert (CPU ignores it, warning per
        # call — same contract as FusedRun._donate_argnums).
        donate_batch = (
            device_batch is None
            and knobs.knob_bool("FMT_FUSE_DONATE")
            and jax.default_backend() != "cpu"
        )
        train_fn = make_glm_train_fn(
            grad_fn, mesh, learning_rate, reg, max_iter, tol,
            bundle=True, donate_batch=donate_batch,
        )
        try:
            fault.maybe_oom(row_slots)
            # device_batch may be a thunk (lib/glm.py passes one so no
            # caller frame pins the placed slab): resolve it HERE, inside
            # the pressure scope, so a placement OOM recovers too
            device_batch = _resolve_thunk(device_batch)
            return _run_fused_train(
                train_fn, init_params,
                device_batch if device_batch is not None
                else _combined_view_memo(stack),
                mesh, batch_preplaced=device_batch is not None,
                n_rows=stack.n_rows,
            )
        except Exception as exc:  # noqa: BLE001 - OOM-filtered below
            if not (pressure.enabled() and fault.is_oom(exc)):
                raise
            # the whole-batch resident program exhausted the allocator:
            # DROP the placed slab (our local is the last strong
            # reference — the pool entry goes with evict_for_pressure, so
            # the runtime can actually free the HBM the windows need),
            # remember the pressure, and stream the identical update
            # schedule through bounded windows
            from flink_ml_tpu.table import slab_pool

            device_batch = None
            slab_pool.evict_for_pressure()
            pressure.note_oom(_TRAIN_PRESSURE_SURFACE, row_slots, exc,
                              n_dev=n_dev_mesh)
            return _train_glm_pressure(
                init_params, stack, grad_fn, mesh, learning_rate, reg,
                max_iter, tol,
            )

    start_epoch = 0
    losses: list = []
    if checkpoint is not None:
        from flink_ml_tpu.iteration.checkpoint import (
            agreed_latest_checkpoint,
            load_checkpoint,
        )

        latest = agreed_latest_checkpoint(checkpoint.directory)
        if latest is not None:
            init_params, meta = load_checkpoint(latest, like=init_params)
            start_epoch = int(meta["epoch"]) + 1
            losses = list(meta.get("losses", []))
            if _meta_converged(meta, tol) or start_epoch >= max_iter:
                # finished run (max epochs or recorded tol convergence at
                # this-or-stricter tolerance): re-fitting runs nothing more
                return TrainResult(
                    params=jax.tree_util.tree_map(np.asarray, init_params),
                    epochs=start_epoch,
                    losses=[float(x) for x in losses],
                )

    from flink_ml_tpu.fault.retry import with_retry

    epoch_step = make_glm_epoch_step(grad_fn, mesh, learning_rate, reg)
    # cold H2D placement is a transient surface on this path too (the
    # pooled and streamed paths already retry theirs)
    batch = with_retry(
        lambda: shard_batch(mesh, (stack.x, stack.y, stack.w)), "place"
    )
    params0 = replicate(mesh, init_params)
    converted: list = list(losses)  # float prefix (resumed history)
    metrics = StepMetrics("epoch_train")

    tol_converged = [False]  # last epoch's delta <= tol (for the final stamp)

    def body(params, inputs, epoch):
        # per-epoch wall time; without a sync (tol/checkpoint off) this times
        # the async dispatch, which is the honest host-side cost of the epoch
        metrics.start_step()
        new_params, (loss, delta) = epoch_step(params, inputs["batch"])
        criteria = None
        if tol > 0.0:
            # convergence needs the value on host: one readback per epoch —
            # the device-friendly "criteria stream empty" check
            tol_converged[0] = float(delta) <= tol
            criteria = [] if tol_converged[0] else [1]
        # keep the loss as a device value: converting here would sync every
        # epoch and collapse the async dispatch pipeline
        losses.append(loss)
        if checkpoint is not None:
            true_epoch = start_epoch + epoch
            at_interval = (true_epoch + 1) % checkpoint.every_n_epochs == 0
            if at_interval or fault.preempted():
                from flink_ml_tpu.iteration.checkpoint import (
                    prune_checkpoints,
                    save_checkpoint,
                )

                # convert only the not-yet-converted tail (the save itself
                # syncs anyway; re-converting the whole history each time
                # would be O(E^2) blocking float() calls)
                converted.extend(float(x) for x in losses[len(converted):])
                host = jax.tree_util.tree_map(np.asarray, new_params)
                # health precedes the snapshot (last checkpoint = last
                # good state); the guard's rollback relies on it
                fault.check_health(
                    converted, jax.tree_util.tree_leaves(host),
                    where="epoch_train",
                )

                def _snapshot():
                    save_checkpoint(
                        checkpoint.directory, true_epoch, host,
                        meta={"losses": list(converted)},
                    )
                    prune_checkpoints(checkpoint.directory, checkpoint.keep)

                # a run that just FINISHED (tol converged this epoch, or
                # this was the final epoch) returns its result instead of
                # exiting for resume — the same rule as the other drivers;
                # exiting here would also skip the converged stamp below
                if fault.preempted() and not tol_converged[0] \
                        and true_epoch + 1 < max_iter:
                    metrics.end_step(samples=stack.n_rows)
                    fault.emergency_save(_snapshot)  # raises Preempted
                _snapshot()
        metrics.end_step(samples=stack.n_rows)
        return IterationBodyResult(
            feedback=new_params,
            outputs={"loss": loss},
            termination_criteria=criteria,
        )

    import contextlib as _contextlib

    scope = (
        fault.preemption_scope() if checkpoint is not None
        else _contextlib.nullcontext()
    )
    with scope:
        result = iterate_bounded(
            params0,
            ReplayableInputs.replay(batch=batch),
            body,
            IterationConfig(max_epochs=max_iter - start_epoch),
            listeners=listeners,
        )
    final = jax.tree_util.tree_map(np.asarray, result.final_variables)
    total_epochs = start_epoch + result.epochs_run
    float_losses = [float(x) for x in losses]
    fault.check_health(
        float_losses, jax.tree_util.tree_leaves(final), where="epoch_train"
    )
    if checkpoint is not None and tol_converged[0]:
        # terminated by tol (including convergence landing exactly on the
        # final permitted epoch): stamp the final state as converged so a
        # re-fit resumes to a no-op instead of running extra epochs
        from flink_ml_tpu.iteration.checkpoint import (
            prune_checkpoints,
            save_checkpoint,
        )

        save_checkpoint(
            checkpoint.directory, total_epochs - 1, final,
            meta={"losses": float_losses, "converged": True, "tol": tol},
        )
        prune_checkpoints(checkpoint.directory, checkpoint.keep)
    return TrainResult(
        params=final,
        epochs=total_epochs,
        losses=float_losses,
        metrics=metrics,
    )


def apply_sharded(apply_factory, X: np.ndarray, *args,
                  bucket_minimum: int = 256, pool_key=None):
    """Run a mesh-sharded model apply over the default environment's mesh.

    ``apply_factory(mesh)`` returns the (memoized) row-aligned device fn for
    that mesh (built via
    :func:`~flink_ml_tpu.parallel.collectives.make_data_parallel_apply`);
    rows pad to a multiple of the data-axis size so the shard_map sees equal
    shards.  The single shared entry point for every ModelMapper hot path.
    Multi-process it runs on the process-LOCAL mesh
    (:func:`~flink_ml_tpu.parallel.mesh.inference_mesh`): each process
    scores its own rows with its own model copy, no collectives.

    ``pool_key`` opts the placement of ``X`` into the device slab pool:
    re-scoring the same rows (bench loops, repeated transforms over a
    retained table) reuses the padded device copy instead of re-padding and
    re-transferring.  The key must capture what the placement depends on
    beyond X's own identity (column name, model dim); correctness never
    depends on it (a pool miss just places).
    """
    from flink_ml_tpu.parallel.mesh import data_parallel_size, inference_mesh
    from flink_ml_tpu.utils.environment import MLEnvironmentFactory

    mesh = inference_mesh(MLEnvironmentFactory.get_default().get_mesh())
    fn = apply_factory(mesh)
    row_multiple = data_parallel_size(mesh)
    if pool_key is not None:
        from flink_ml_tpu.fault import pressure
        from flink_ml_tpu.table import slab_pool

        if not slab_pool.enabled():
            pool_key = None  # skip tokenization entirely: pooling is off
        elif pressure.state("apply").capped_below(X.shape[0],
                                                  n_dev=row_multiple):
            # active memory pressure: the pooled path would place the
            # FULL padded batch the cap says cannot fit — go straight to
            # the bisected unpooled path below
            pool_key = None
    if pool_key is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from flink_ml_tpu.table import slab_pool

        n = X.shape[0]
        b = _bucket_for(n, bucket_minimum, row_multiple)

        def build():
            Xp = _pad_rows_to(X, b)
            if row_multiple > 1:
                return jax.device_put(Xp, NamedSharding(mesh, P("data")))
            return jnp.asarray(Xp)

        refs: list = []
        token = slab_pool.array_token(X, refs)
        try:
            fault.maybe_oom(n)
            # agreed=False: inference is collective-free by contract (each
            # process scores its own rows on its own local mesh, with batch
            # counts no peer mirrors) — a pool-level allgather here would
            # hang
            Xd = slab_pool.pool().get_or_build(
                ("apply", mesh, pool_key, token, b), build, refs=refs,
                agreed=False,
            )
            with slab_pool.pool().pinned(Xd):
                out = fn(Xd, *args)
                return np.asarray(out)[:n]
        except Exception as exc:  # noqa: BLE001 - OOM-filtered below
            if not fault.is_oom(exc):
                raise
            # allocator exhaustion on the pooled full-batch placement:
            # the bisected path below rediscovers the workable chunk size
            # (and records the pressure telemetry as it does)
    return apply_batched(
        fn, X, *args,
        bucket_minimum=bucket_minimum,
        row_multiple=row_multiple,
    )


def bucket_rows(n: int, minimum: int = 256) -> int:
    """Next power-of-two row count >= n (bounds the jit cache for inference)."""
    b = minimum
    while b < n:
        b *= 2
    return b


def _bucket_for(n: int, bucket_minimum: int, row_multiple: int) -> int:
    """The ONE copy of the inference bucket rule — the pooled and unpooled
    apply paths must choose identical padded shapes or pool_key callers
    would compile different programs than plain callers.

    Delegates to the shared batch-shape ladder
    (:func:`~flink_ml_tpu.utils.compile_cache.bucket_batch_rows`), which
    the fused pipeline plans and the serving runtime's coalesced
    micro-batches also pad to: a 3-row serving request and a 3-row staged
    apply dispatch the same compiled program.  ``bucket_minimum`` is
    retained for signature stability but the ladder (whose bottom rungs
    sit below the old 256-row floor exactly so single-row serving requests
    stop padding to training-shaped buckets) owns the rule now."""
    del bucket_minimum  # the shared ladder owns the rung choice
    from flink_ml_tpu.utils.compile_cache import bucket_batch_rows

    return bucket_batch_rows(n, row_multiple)


def _pad_rows_to(X: np.ndarray, b: int) -> np.ndarray:
    """Zero-pad X's rows up to ``b`` (pass-through when already there)."""
    n = X.shape[0]
    if b == n:
        return X
    Xp = np.zeros((b,) + X.shape[1:], dtype=X.dtype)
    Xp[:n] = X
    return Xp


def apply_batched(
    fn, X: np.ndarray, *args, bucket_minimum: int = 256, row_multiple: int = 1
) -> np.ndarray:
    """Run a jitted row function over X padded to a power-of-two bucket.

    ``fn(x_padded, *args)`` must be row-aligned; the result is sliced back to
    the true row count.  Padding rows are zeros.  A 0-row input still runs one
    padded bucket so the output keeps fn's true rank (sliced to 0 rows).
    ``row_multiple`` rounds the bucket up so mesh-sharded applies
    (:func:`~flink_ml_tpu.parallel.collectives.make_data_parallel_apply`)
    always see a row count divisible by the data-axis size.

    Memory-pressure resilient (ISSUE 9): the dispatch runs under the
    shared ``apply`` pressure surface — an allocator OOM chunks X's rows
    (KMeans assign, the Knn reference scan, scaler applies all route
    here), each chunk padded to its own ladder bucket, and the sliced
    results concatenate host-side.  Row-aligned fns are row-independent,
    so the concatenation is bit-identical to the unsplit call.
    """
    n = X.shape[0]

    def run(lo: int, hi: int) -> np.ndarray:
        sub = X[lo:hi]
        fault.maybe_oom(hi - lo)
        Xp = _pad_rows_to(sub, _bucket_for(hi - lo, bucket_minimum,
                                           row_multiple))
        out = fn(jnp.asarray(Xp), *args)
        return np.asarray(out)[: hi - lo]

    return fault.run_bisected(run, n, surface="apply",
                              floor=max(1, row_multiple),
                              n_dev=row_multiple)
