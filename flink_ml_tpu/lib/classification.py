"""LogisticRegression — binary log-loss GLM (BASELINE configs[0], the
flagship workload of the north star: LogisticRegression.fit samples/sec/chip).

Labels are {0, 1}. Training is the same data-parallel SGD harness as
LinearRegression with the logistic gradient; prediction emits the argmax
label into ``predictionCol`` and, optionally, the positive-class probability
into ``predictionDetailCol``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.lib.glm import GlmEstimatorBase, GlmModelBase, LinearScoreMapper
from flink_ml_tpu.table.schema import DataTypes, Schema


def _stable_sigmoid(scores: np.ndarray) -> np.ndarray:
    """Overflow-free sigmoid: ``np.exp(-scores)`` overflows (with a runtime
    warning and an inf that rounds through to 0.0) once a score passes
    ~-745 in f64 / ~-88 in f32 — scores a wide model on unnormalized
    serving traffic produces routinely.  Exponentiate only the negative
    half-line instead."""
    scores = np.asarray(scores, dtype=np.float64)
    out = np.empty_like(scores)
    pos = scores >= 0
    out[pos] = 1.0 / (1.0 + np.exp(-scores[pos]))
    e = np.exp(scores[~pos])
    out[~pos] = e / (1.0 + e)
    return out


class LogisticRegressionModel(GlmModelBase):
    """Predicts the {0,1} label; optional probability detail column."""

    def _make_mapper(self, data_schema: Schema):
        model = self
        detail = model.get_prediction_detail_col()

        class _Mapper(LinearScoreMapper):
            def output_cols(self):
                names = [model.get_prediction_col()]
                types = [DataTypes.DOUBLE]
                if detail is not None:
                    names.append(detail)
                    types.append(DataTypes.DOUBLE)
                return names, types

            def map_batch(self, batch):
                scores = self._scores(batch)
                return self._score_cols(scores)

            def _score_cols(self, scores):
                out = {model.get_prediction_col(): (scores > 0).astype(np.float64)}
                if detail is not None:
                    out[detail] = _stable_sigmoid(scores)
                return out

            def _fused_finalize(self, fetched, n):
                # fused-plan host tail: identical to the map_batch tail —
                # (scores > 0) is bit-stable under the f32->f64 fetch cast,
                # so fused discrete predictions match the staged path
                return self._score_cols(fetched["scores"])

        return _Mapper(self, data_schema)

    def predict_proba(self, table) -> np.ndarray:
        """Positive-class probabilities for a feature table (convenience)."""
        mapper = self._make_mapper(table.schema)
        mapper.load_model(*self.get_model_data())
        scores = mapper._scores(table)
        return _stable_sigmoid(scores)


from functools import lru_cache


@lru_cache(maxsize=None)
def _log_loss_grads(with_intercept: bool):
    keep_b = 1.0 if with_intercept else 0.0

    def grad_fn(params, x, y, w):
        wts, b = params
        logits = x @ wts + b
        p = jax.nn.sigmoid(logits)
        err = (p - y) * w
        g_w = x.T @ err
        g_b = jnp.sum(err) * keep_b
        # numerically-stable weighted log-loss sum
        loss = jnp.sum(
            w * (jnp.logaddexp(0.0, logits) - y * logits)
        )
        return (g_w, g_b), loss, jnp.sum(w)

    return grad_fn


class LogisticRegression(GlmEstimatorBase):
    """Estimator: binary log loss, minibatch SGD over the data-parallel mesh."""

    LOSS_KIND = "logistic"

    def _grad_fn(self):
        return _log_loss_grads(self.get_with_intercept())

    def _make_model(self) -> LogisticRegressionModel:
        return LogisticRegressionModel()
