"""LinearRegression — squared-loss GLM (BASELINE configs[2]).

The productized form of the reference's only trainer
(examples-batch/.../LinearRegression.java): the per-record gradient step
(SubUpdate:215-231), sum-reduce (UpdateAccumulator:235-246) and average
(Update:249-256) become one jitted epoch with in-step psum; the broadcast of
new parameters (withBroadcastSet:114) is the replicated params placement.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.lib.glm import GlmEstimatorBase, GlmModelBase, LinearScoreMapper
from flink_ml_tpu.table.schema import DataTypes, Schema


class LinearRegressionModel(GlmModelBase):
    """Predicts x·w + b into ``predictionCol``.

    Serving robustness (quarantine of bad feature rows, the dispatch
    circuit breaker, and the NumPy CPU fallback) rides the shared
    :class:`~flink_ml_tpu.lib.glm.LinearScoreMapper` machinery."""

    def _make_mapper(self, data_schema: Schema):
        model = self

        class _Mapper(LinearScoreMapper):
            def output_cols(self):
                return [model.get_prediction_col()], [DataTypes.DOUBLE]

            def map_batch(self, batch):
                # explicit f64 cast: the declared output type is DOUBLE and
                # the device/fallback paths hand back f32 scores
                scores = np.asarray(self._scores(batch), dtype=np.float64)
                return {model.get_prediction_col(): scores}

            def _fused_finalize(self, fetched, n):
                return {model.get_prediction_col(): np.asarray(
                    fetched["scores"], dtype=np.float64
                )}

        return _Mapper(self, data_schema)


from functools import lru_cache


@lru_cache(maxsize=None)
def _squared_loss_grads(with_intercept: bool):
    keep_b = 1.0 if with_intercept else 0.0

    def grad_fn(params, x, y, w):
        wts, b = params
        pred = x @ wts + b
        err = (pred - y) * w
        # d/dw of 0.5*sum(w*(pred-y)^2)
        g_w = x.T @ err
        g_b = jnp.sum(err) * keep_b
        loss_sum = 0.5 * jnp.sum(err * (pred - y))
        return (g_w, g_b), loss_sum, jnp.sum(w)

    return grad_fn


class LinearRegression(GlmEstimatorBase):
    """Estimator: squared loss, minibatch SGD over the data-parallel mesh."""

    LOSS_KIND = "squared"

    def _grad_fn(self):
        return _squared_loss_grads(self.get_with_intercept())

    def _make_model(self) -> LinearRegressionModel:
        return LinearRegressionModel()
