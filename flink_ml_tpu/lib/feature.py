"""Feature transformers — concrete Transformer stages for pipeline chains.

This is the stage family the reference's shared colname vocabulary exists to
serve: a Transformer chained AHEAD of an estimator, fed forward by
``Pipeline.fit``'s transform branch (Pipeline.java:80-94), reading one column
(HasSelectedCol.java:33-47) and merging its output into the input table by
the OutputColsHelper rules (OutputColsHelper.java:32-52).

``StandardScaler``: fit computes per-dimension mean/std of the selected
vector column in one streamed device pass (a materialized Table or a
ChunkedTable both work — the accumulator is (count, sum, sum-of-squares)
per chunk, so fit is out-of-core capable); the fitted
``StandardScalerModel`` normalizes batches on device, sharded over the
mesh's data axis like every other ModelMapper hot path.

The reference snapshot ships no concrete feature transformer, so the
statistics semantics are stated here rather than cited: std is the corrected
sample standard deviation (ddof=1; 0.0 when count < 2), and zero-variance
dimensions pass through unscaled (divide by 1) instead of producing NaNs.
Model data is one row — (means, stds, count) — following the
model-as-table convention (Model.java:102-122).
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.api.core import Estimator
from flink_ml_tpu.common.mapper import ModelMapper
from flink_ml_tpu.lib.common import apply_sharded
from flink_ml_tpu.lib.model_base import TableModelBase
from flink_ml_tpu.params import param_info
from flink_ml_tpu.params.params import ParamInfo, WithParams
from flink_ml_tpu.params.shared import (
    HasOutputColDefaultAsNull,
    HasReservedCols,
    HasSelectedCol,
)
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table

SCALER_MODEL_SCHEMA = Schema.of(
    ("means", DataTypes.DENSE_VECTOR),
    ("stds", DataTypes.DENSE_VECTOR),
    ("count", DataTypes.DOUBLE),
)


class HasWithMean(WithParams):
    WITH_MEAN: ParamInfo = param_info(
        "withMean", "Whether to center the data to zero mean.",
        default=True, value_type=bool,
    )

    def get_with_mean(self) -> bool:
        return self.get(self.WITH_MEAN)

    def set_with_mean(self, value: bool):
        return self.set(self.WITH_MEAN, bool(value))


class HasWithStd(WithParams):
    WITH_STD: ParamInfo = param_info(
        "withStd", "Whether to scale the data to unit standard deviation.",
        default=True, value_type=bool,
    )

    def get_with_std(self) -> bool:
        return self.get(self.WITH_STD)

    def set_with_std(self, value: bool):
        return self.set(self.WITH_STD, bool(value))


class StandardScalerParams(
    HasSelectedCol,
    HasOutputColDefaultAsNull,
    HasReservedCols,
    HasWithMean,
    HasWithStd,
):
    """Shared vocabulary for the scaler estimator and model."""

    def resolved_output_col(self) -> str:
        """outputCol defaults to overwriting selectedCol in place — the
        OutputColsHelper collision rule then replaces it at its position."""
        out = self.get_output_col()
        return out if out is not None else self.get_selected_col()


@jax.jit
def _chunk_moments(x, pivot):
    """One chunk's per-dimension shifted moments on device: sums of
    ``(x - pivot)`` and ``(x - pivot)^2``.

    The pivot (first data row) keeps the squares near the data's spread
    instead of its magnitude — squaring raw values in f32 suffers
    catastrophic cancellation for large-mean features (a timestamp-scale
    column, mean ~1.7e9 / std ~1e4, came out 92x wrong in the unshifted
    formulation).  The tiny (d,) partials accumulate across chunks in
    float64 on the host, so a long chunk stream never loses precision to
    f32 running sums either."""
    xc = x - pivot
    return jnp.sum(xc, axis=0), jnp.sum(xc * xc, axis=0)


@lru_cache(maxsize=32)
def _scale_apply(mesh):
    """Mesh-sharded normalize: rows over 'data', statistics replicated."""
    from flink_ml_tpu.parallel.collectives import make_data_parallel_apply

    def normalize(x, shift, inv_scale):
        return (x - shift) * inv_scale

    return make_data_parallel_apply(normalize, mesh, n_args=3)


class StandardScalerModelMapper(ModelMapper):
    def __init__(self, model: "StandardScalerModel", data_schema: Schema):
        self._model_stage = model
        super().__init__([SCALER_MODEL_SCHEMA], data_schema, model.get_params())

    def reserved_cols(self) -> Optional[list]:
        return self._model_stage.get_reserved_cols()

    def output_cols(self) -> Tuple[list, list]:
        return [self._model_stage.resolved_output_col()], [DataTypes.DENSE_VECTOR]

    def load_model(self, *model_tables: Table) -> None:
        (t,) = model_tables
        model = self._model_stage
        means = np.asarray(t.features_dense("means")[0], dtype=np.float32)
        stds = np.asarray(t.features_dense("stds")[0], dtype=np.float32)
        self._dim = means.shape[0]
        # fold the withMean/withStd flags into (shift, 1/scale) once, so the
        # device step is always one fused subtract-multiply
        shift = means if model.get_with_mean() else np.zeros_like(means)
        if model.get_with_std():
            scale = np.where(stds > 0.0, stds, 1.0)
        else:
            scale = np.ones_like(stds)
        self._shift = jnp.asarray(shift)
        self._inv_scale = jnp.asarray(1.0 / scale)

    def map_batch(self, batch: Table):
        model = self._model_stage
        X = batch.features_dense(model.get_selected_col(), dim=self._dim)
        # apply_sharded already returns a host array sliced to the batch rows;
        # matrix-backed vector column: stays one contiguous array end-to-end
        out = apply_sharded(
            _scale_apply, X.astype(np.float32), self._shift, self._inv_scale
        )
        return {model.resolved_output_col(): out}


class StandardScalerModel(TableModelBase, StandardScalerParams):
    """Normalizes the selected vector column with the fitted statistics."""

    REQUIRED_MODEL_COL = "means"

    def _make_mapper(self, data_schema: Schema) -> StandardScalerModelMapper:
        return StandardScalerModelMapper(self, data_schema)


class StandardScaler(Estimator, StandardScalerParams):
    """Estimator: one streamed pass accumulating per-dimension moments."""

    def fit(self, *inputs) -> StandardScalerModel:
        (table,) = inputs
        col = self.get_selected_col()
        if getattr(table, "is_chunked", False):
            chunks = table.chunks()
        else:
            chunks = (table,)

        n = 0
        s = ss = pivot = None
        for chunk in chunks:
            if chunk.num_rows() == 0:
                continue
            X = chunk.features_dense(col)
            if pivot is None:
                pivot = np.ascontiguousarray(X[0], dtype=np.float32)
                s = np.zeros(X.shape[1], dtype=np.float64)
                ss = np.zeros(X.shape[1], dtype=np.float64)
            cs, css = _chunk_moments(
                jnp.asarray(X, dtype=jnp.float32), jnp.asarray(pivot)
            )
            n += X.shape[0]
            s += np.asarray(cs, dtype=np.float64)
            ss += np.asarray(css, dtype=np.float64)
        if s is None:
            raise ValueError("cannot fit StandardScaler on an empty input")
        means = pivot.astype(np.float64) + s / n
        if n > 1:
            # shifted-data variance formula; clamped because residual
            # rounding can push an exactly-constant column slightly negative
            var = np.maximum(ss - s * s / n, 0.0) / (n - 1)
        else:
            var = np.zeros_like(means)
        stds = np.sqrt(var)

        model = StandardScalerModel()
        model.get_params().merge(self.get_params())
        model.set_model_data(Table.from_columns(
            SCALER_MODEL_SCHEMA,
            {
                "means": means.reshape(1, -1),
                "stds": stds.reshape(1, -1),
                "count": np.asarray([float(n)]),
            },
        ))
        return model
