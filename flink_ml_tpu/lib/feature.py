"""Feature transformers — concrete Transformer stages for pipeline chains.

This is the stage family the reference's shared colname vocabulary exists to
serve: a Transformer chained AHEAD of an estimator, fed forward by
``Pipeline.fit``'s transform branch (Pipeline.java:80-94), reading one column
(HasSelectedCol.java:33-47) and merging its output into the input table by
the OutputColsHelper rules (OutputColsHelper.java:32-52).

``StandardScaler``: fit computes per-dimension mean/std of the selected
vector column in one streamed device pass (a materialized Table or a
ChunkedTable both work — the accumulator is (count, sum, sum-of-squares)
per chunk, so fit is out-of-core capable); the fitted
``StandardScalerModel`` normalizes batches on device, sharded over the
mesh's data axis like every other ModelMapper hot path.

The reference snapshot ships no concrete feature transformer, so the
statistics semantics are stated here rather than cited: std is the corrected
sample standard deviation (ddof=1; 0.0 when count < 2), and zero-variance
dimensions pass through unscaled (divide by 1) instead of producing NaNs.
Model data is one row — (means, stds, count) — following the
model-as-table convention (Model.java:102-122).
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.api.core import Estimator, Transformer
from flink_ml_tpu.common.mapper import ModelMapper
from flink_ml_tpu.lib.common import apply_sharded
from flink_ml_tpu.lib.model_base import TableModelBase
from flink_ml_tpu.params import param_info
from flink_ml_tpu.params.params import ParamInfo, WithParams
from flink_ml_tpu.params.shared import (
    HasOutputCol,
    HasOutputColDefaultAsNull,
    HasReservedCols,
    HasSelectedCol,
    HasSelectedCols,
)
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table

SCALER_MODEL_SCHEMA = Schema.of(
    ("means", DataTypes.DENSE_VECTOR),
    ("stds", DataTypes.DENSE_VECTOR),
    ("count", DataTypes.DOUBLE),
)


class HasWithMean(WithParams):
    WITH_MEAN: ParamInfo = param_info(
        "withMean", "Whether to center the data to zero mean.",
        default=True, value_type=bool,
    )

    def get_with_mean(self) -> bool:
        return self.get(self.WITH_MEAN)

    def set_with_mean(self, value: bool):
        return self.set(self.WITH_MEAN, bool(value))


class HasWithStd(WithParams):
    WITH_STD: ParamInfo = param_info(
        "withStd", "Whether to scale the data to unit standard deviation.",
        default=True, value_type=bool,
    )

    def get_with_std(self) -> bool:
        return self.get(self.WITH_STD)

    def set_with_std(self, value: bool):
        return self.set(self.WITH_STD, bool(value))


class StandardScalerParams(
    HasSelectedCol,
    HasOutputColDefaultAsNull,
    HasReservedCols,
    HasWithMean,
    HasWithStd,
):
    """Shared vocabulary for the scaler estimator and model."""

    def resolved_output_col(self) -> str:
        """outputCol defaults to overwriting selectedCol in place — the
        OutputColsHelper collision rule then replaces it at its position."""
        out = self.get_output_col()
        return out if out is not None else self.get_selected_col()


@jax.jit
def _chunk_moments(x, pivot):
    """One chunk's per-dimension shifted moments on device: sums of
    ``(x - pivot)`` and ``(x - pivot)^2``.

    The pivot (first data row) keeps the squares near the data's spread
    instead of its magnitude — squaring raw values in f32 suffers
    catastrophic cancellation for large-mean features (a timestamp-scale
    column, mean ~1.7e9 / std ~1e4, came out 92x wrong in the unshifted
    formulation).  The tiny (d,) partials accumulate across chunks in
    float64 on the host, so a long chunk stream never loses precision to
    f32 running sums either."""
    xc = x - pivot
    return jnp.sum(xc, axis=0), jnp.sum(xc * xc, axis=0)


@lru_cache(maxsize=32)
def _scale_apply(mesh):
    """Mesh-sharded normalize: rows over 'data', statistics replicated."""
    from flink_ml_tpu.parallel.collectives import make_data_parallel_apply

    def normalize(x, shift, inv_scale):
        return (x - shift) * inv_scale

    return make_data_parallel_apply(normalize, mesh, n_args=3)


class StandardScalerModelMapper(ModelMapper):
    def __init__(self, model: "StandardScalerModel", data_schema: Schema):
        self._model_stage = model
        super().__init__([SCALER_MODEL_SCHEMA], data_schema, model.get_params())

    def reserved_cols(self) -> Optional[list]:
        return self._model_stage.get_reserved_cols()

    def output_cols(self) -> Tuple[list, list]:
        return [self._model_stage.resolved_output_col()], [DataTypes.DENSE_VECTOR]

    def load_model(self, *model_tables: Table) -> None:
        (t,) = model_tables
        model = self._model_stage
        means = np.asarray(t.features_dense("means")[0], dtype=np.float32)
        stds = np.asarray(t.features_dense("stds")[0], dtype=np.float32)
        self._dim = means.shape[0]
        # fold the withMean/withStd flags into (shift, 1/scale) once, so the
        # device step is always one fused subtract-multiply
        shift = means if model.get_with_mean() else np.zeros_like(means)
        if model.get_with_std():
            scale = np.where(stds > 0.0, stds, 1.0)
        else:
            scale = np.ones_like(stds)
        self._shift = jnp.asarray(shift)
        self._inv_scale = jnp.asarray(1.0 / scale)
        # host copies for the circuit-breaker CPU fallback; the fused
        # subtract-multiply is elementwise, so fallback parity is exact
        self._shift_np = np.asarray(shift, dtype=np.float32)
        self._inv_scale_np = np.asarray(1.0 / scale, dtype=np.float32)

    def serve_validation_spec(self):
        return {
            "dim": self._dim,
            "vector_col": self._model_stage.get_selected_col(),
        }

    def map_batch(self, batch: Table):
        from flink_ml_tpu import serve

        model = self._model_stage
        X = batch.features_dense(model.get_selected_col(), dim=self._dim)
        # apply_sharded already returns a host array sliced to the batch rows;
        # matrix-backed vector column: stays one contiguous array end-to-end
        Xf = X.astype(np.float32)
        out = serve.dispatch(
            self.serve_name(),
            device=lambda: apply_sharded(
                _scale_apply, Xf, self._shift, self._inv_scale
            ),
            fallback=lambda: (Xf - self._shift_np) * self._inv_scale_np,
        )
        return {model.resolved_output_col(): out}

    def fused_kernel(self):
        from flink_ml_tpu.common.fused import FusedInput, FusedKernel

        model = self._model_stage
        out_col = model.resolved_output_col()

        def fn(x, shift, inv_scale):
            return {"out": (x - shift) * inv_scale}

        return FusedKernel(
            inputs=[FusedInput(dim=self._dim,
                               vector_col=model.get_selected_col())],
            fn=fn,
            out_keys=("out",),
            model_args=(self._shift, self._inv_scale),
            # cast back to the staged output dtype (the bundled fetch may
            # ride an f64 lane on x64 hosts; f32->f64->f32 is value-exact)
            finalize=lambda fetched, n, _c=out_col: {
                _c: np.asarray(fetched["out"], dtype=np.float32)
            },
            env_outputs={"out": (out_col, self._dim)},
            pallas_op="affine_sub_mul",  # (x - shift) * inv_scale
        )


class StandardScalerModel(TableModelBase, StandardScalerParams):
    """Normalizes the selected vector column with the fitted statistics."""

    REQUIRED_MODEL_COL = "means"

    def _make_mapper(self, data_schema: Schema) -> StandardScalerModelMapper:
        return StandardScalerModelMapper(self, data_schema)


MINMAX_MODEL_SCHEMA = Schema.of(
    ("mins", DataTypes.DENSE_VECTOR),
    ("maxs", DataTypes.DENSE_VECTOR),
    ("count", DataTypes.DOUBLE),
)


class MinMaxScalerParams(
    HasSelectedCol,
    HasOutputColDefaultAsNull,
    HasReservedCols,
):
    """Vocabulary for MinMaxScaler: rescale each dimension of the selected
    vector column into [outputMin, outputMax]."""

    OUTPUT_MIN: ParamInfo = param_info(
        "outputMin", "Lower bound of the output range.",
        default=0.0, value_type=float,
    )
    OUTPUT_MAX: ParamInfo = param_info(
        "outputMax", "Upper bound of the output range.",
        default=1.0, value_type=float,
    )

    def get_output_min(self) -> float:
        return self.get(self.OUTPUT_MIN)

    def set_output_min(self, value: float):
        return self.set(self.OUTPUT_MIN, float(value))

    def get_output_max(self) -> float:
        return self.get(self.OUTPUT_MAX)

    def set_output_max(self, value: float):
        return self.set(self.OUTPUT_MAX, float(value))

    def resolved_output_col(self) -> str:
        out = self.get_output_col()
        return out if out is not None else self.get_selected_col()


@jax.jit
def _chunk_minmax(x):
    return jnp.min(x, axis=0), jnp.max(x, axis=0)


@lru_cache(maxsize=32)
def _affine_apply(mesh):
    """Mesh-sharded per-dimension affine map x*a + b (rows over 'data')."""
    from flink_ml_tpu.parallel.collectives import make_data_parallel_apply

    def affine(x, a, b):
        return x * a + b

    return make_data_parallel_apply(affine, mesh, n_args=3)


class MinMaxScalerModelMapper(ModelMapper):
    def __init__(self, model: "MinMaxScalerModel", data_schema: Schema):
        self._model_stage = model
        super().__init__([MINMAX_MODEL_SCHEMA], data_schema, model.get_params())

    def reserved_cols(self) -> Optional[list]:
        return self._model_stage.get_reserved_cols()

    def output_cols(self) -> Tuple[list, list]:
        return [self._model_stage.resolved_output_col()], [DataTypes.DENSE_VECTOR]

    def load_model(self, *model_tables: Table) -> None:
        (t,) = model_tables
        model = self._model_stage
        mins = np.asarray(t.features_dense("mins")[0], dtype=np.float64)
        maxs = np.asarray(t.features_dense("maxs")[0], dtype=np.float64)
        self._dim = mins.shape[0]
        lo, hi = model.get_output_min(), model.get_output_max()
        if lo >= hi:
            # validated here too: range params can be (re)set after fit or
            # on a loaded model, and inverted scaling is silently wrong
            raise ValueError("outputMin must be < outputMax")
        span = maxs - mins
        varying = span > 0.0
        # folded per-dim affine: varying dims rescale into [lo, hi];
        # constant dims map to the range midpoint (no spread to preserve)
        a = np.where(varying, (hi - lo) / np.where(varying, span, 1.0), 0.0)
        b = np.where(varying, lo - mins * a, 0.5 * (lo + hi))
        self._a = jnp.asarray(a, dtype=jnp.float32)
        self._b = jnp.asarray(b, dtype=jnp.float32)
        # host copies for the circuit-breaker CPU fallback (elementwise
        # affine: exact parity with the device path)
        self._a_np = np.asarray(a, dtype=np.float32)
        self._b_np = np.asarray(b, dtype=np.float32)

    def serve_validation_spec(self):
        return {
            "dim": self._dim,
            "vector_col": self._model_stage.get_selected_col(),
        }

    def map_batch(self, batch: Table):
        from flink_ml_tpu import serve

        model = self._model_stage
        X = batch.features_dense(model.get_selected_col(), dim=self._dim)
        Xf = X.astype(np.float32)
        out = serve.dispatch(
            self.serve_name(),
            device=lambda: apply_sharded(_affine_apply, Xf, self._a, self._b),
            fallback=lambda: Xf * self._a_np + self._b_np,
        )
        return {model.resolved_output_col(): out}

    def fused_kernel(self):
        from flink_ml_tpu.common.fused import FusedInput, FusedKernel

        model = self._model_stage
        out_col = model.resolved_output_col()

        def fn(x, a, b):
            return {"out": x * a + b}

        return FusedKernel(
            inputs=[FusedInput(dim=self._dim,
                               vector_col=model.get_selected_col())],
            fn=fn,
            out_keys=("out",),
            model_args=(self._a, self._b),
            finalize=lambda fetched, n, _c=out_col: {
                _c: np.asarray(fetched["out"], dtype=np.float32)
            },
            env_outputs={"out": (out_col, self._dim)},
            pallas_op="affine_mul_add",  # x * a + b
        )


class MinMaxScalerModel(TableModelBase, MinMaxScalerParams):
    """Rescales the selected vector column with the fitted min/max."""

    REQUIRED_MODEL_COL = "mins"

    def _make_mapper(self, data_schema: Schema) -> MinMaxScalerModelMapper:
        return MinMaxScalerModelMapper(self, data_schema)


class MinMaxScaler(Estimator, MinMaxScalerParams):
    """Estimator: one streamed pass accumulating per-dimension min/max
    (chunked input welcome, like StandardScaler)."""

    def fit(self, *inputs) -> MinMaxScalerModel:
        (table,) = inputs
        col = self.get_selected_col()
        if self.get_output_min() >= self.get_output_max():
            raise ValueError("outputMin must be < outputMax")
        chunks = table.chunks() if getattr(table, "is_chunked", False) else (table,)
        n = 0
        mins = maxs = None
        for chunk in chunks:
            if chunk.num_rows() == 0:
                continue
            X = chunk.features_dense(col)
            cmin, cmax = _chunk_minmax(jnp.asarray(X, dtype=jnp.float32))
            cmin = np.asarray(cmin, dtype=np.float64)
            cmax = np.asarray(cmax, dtype=np.float64)
            if mins is None:
                mins, maxs = cmin, cmax
            else:
                mins = np.minimum(mins, cmin)
                maxs = np.maximum(maxs, cmax)
            n += X.shape[0]
        if mins is None:
            raise ValueError("cannot fit MinMaxScaler on an empty input")

        model = MinMaxScalerModel()
        model.get_params().merge(self.get_params())
        model.set_model_data(Table.from_columns(
            MINMAX_MODEL_SCHEMA,
            {
                "mins": mins.reshape(1, -1),
                "maxs": maxs.reshape(1, -1),
                "count": np.asarray([float(n)]),
            },
        ))
        return model


class VectorAssembler(
    Transformer, HasSelectedCols, HasOutputCol, HasReservedCols
):
    """Concatenate numeric and/or vector columns into one dense vector
    column — the canonical pipeline head stage (selectedCols -> outputCol).

    Stateless (no fit): the output is a matrix-backed column built by one
    columnar hstack, so a downstream estimator's ``features_dense`` is
    zero-copy.  Dense vector inputs contribute their full width; numeric
    columns contribute one dimension each, in selectedCols order.
    """

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        cols = self.get_selected_cols()
        parts = []
        for c in cols:
            typ = table.schema.type_of(c)
            if DataTypes.is_vector(typ):
                parts.append(np.asarray(table.features_dense(c), dtype=np.float64))
            else:
                parts.append(
                    np.asarray(table.col(c), dtype=np.float64).reshape(-1, 1)
                )
        out = (
            np.hstack(parts) if parts
            else np.zeros((table.num_rows(), 0))
        )

        from flink_ml_tpu.table.output_cols import OutputColsHelper

        helper = OutputColsHelper(
            table.schema, [self.get_output_col()], [DataTypes.DENSE_VECTOR],
            reserved_col_names=self.get_reserved_cols(),
        )
        return (helper.get_result_table(table, {self.get_output_col(): out}),)


class StandardScaler(Estimator, StandardScalerParams):
    """Estimator: one streamed pass accumulating per-dimension moments."""

    def fit(self, *inputs) -> StandardScalerModel:
        (table,) = inputs
        col = self.get_selected_col()
        if getattr(table, "is_chunked", False):
            chunks = table.chunks()
        else:
            chunks = (table,)

        n = 0
        s = ss = pivot = None
        for chunk in chunks:
            if chunk.num_rows() == 0:
                continue
            X = chunk.features_dense(col)
            if pivot is None:
                pivot = np.ascontiguousarray(X[0], dtype=np.float32)
                s = np.zeros(X.shape[1], dtype=np.float64)
                ss = np.zeros(X.shape[1], dtype=np.float64)
            cs, css = _chunk_moments(
                jnp.asarray(X, dtype=jnp.float32), jnp.asarray(pivot)
            )
            n += X.shape[0]
            s += np.asarray(cs, dtype=np.float64)
            ss += np.asarray(css, dtype=np.float64)
        if s is None:
            raise ValueError("cannot fit StandardScaler on an empty input")
        means = pivot.astype(np.float64) + s / n
        if n > 1:
            # shifted-data variance formula; clamped because residual
            # rounding can push an exactly-constant column slightly negative
            var = np.maximum(ss - s * s / n, 0.0) / (n - 1)
        else:
            var = np.zeros_like(means)
        stds = np.sqrt(var)

        model = StandardScalerModel()
        model.get_params().merge(self.get_params())
        model.set_model_data(Table.from_columns(
            SCALER_MODEL_SCHEMA,
            {
                "means": means.reshape(1, -1),
                "stds": stds.reshape(1, -1),
                "count": np.asarray([float(n)]),
            },
        ))
        return model
