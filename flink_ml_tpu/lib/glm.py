"""Generalized-linear-model Estimator/Model base.

The reference ships the *infrastructure* for such estimators but no concrete
implementation (SURVEY.md §0.3); its only trainer is the hand-rolled BGD
LinearRegression example (examples-batch/.../LinearRegression.java:108-121).
This module is that training topology productized: Estimator.fit packs rows
once, runs the data-parallel SGD epochs (in-step psum allreduce — the
UpdateAccumulator/Update reduce-average pair fused on device), and returns a
Model whose transform is a batched mapper apply.

Model data follows the reference convention — rows of a table
(Model.getModelData, Model.java:48): one row holding the coefficient vector
and the intercept, persisted via the columnar table codec.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu import fault, obs
from flink_ml_tpu.api.core import Estimator
from flink_ml_tpu.common.mapper import ModelMapper
from flink_ml_tpu.lib.common import (
    apply_sharded,
    fit_pool_extra,
    pack_minibatches,
    pack_sparse_minibatches,
    resolve_features,
    train_glm,
    train_glm_sparse,
)
from flink_ml_tpu.lib.model_base import TableModelBase
from flink_ml_tpu.lib.params import (
    HasCheckpoint,
    HasFeatureColsDefaultAsNull,
    HasNumFeatures,
    HasNumHotFeatures,
    HasGlobalBatchSize,
    HasLabelCol,
    HasLearningRate,
    HasMaxIter,
    HasReg,
    HasSeed,
    HasTol,
    HasVectorColDefaultAsNull,
    HasWithIntercept,
)
from flink_ml_tpu.ops.vector import DenseVector, SparseVector
from flink_ml_tpu.params.shared import (
    HasPredictionCol,
    HasPredictionDetailCol,
    HasReservedCols,
)
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table
from flink_ml_tpu.utils.environment import MLEnvironmentFactory
from flink_ml_tpu.utils import knobs

MODEL_SCHEMA = Schema.of(
    ("coefficients", DataTypes.DENSE_VECTOR), ("intercept", DataTypes.DOUBLE)
)


class GlmFeatureParams(
    HasVectorColDefaultAsNull,
    HasFeatureColsDefaultAsNull,
    HasReservedCols,
    HasPredictionCol,
    HasPredictionDetailCol,
):
    """Input/output column vocabulary shared by GLM estimators and models."""


class GlmTrainParams(
    GlmFeatureParams,
    HasLabelCol,
    HasLearningRate,
    HasMaxIter,
    HasGlobalBatchSize,
    HasTol,
    HasReg,
    HasWithIntercept,
    HasNumFeatures,
    HasNumHotFeatures,
    HasCheckpoint,
    HasSeed,
):
    """Training vocabulary for GLM estimators."""


class GlmModelBase(TableModelBase, GlmFeatureParams):
    """Model over (coefficients, intercept) model-data tables
    (model-as-table contract implemented by TableModelBase)."""

    REQUIRED_MODEL_COL = "coefficients"

    # convenience for algorithm code
    def coefficients(self) -> np.ndarray:
        (t,) = self.get_model_data()
        return np.asarray(t.col("coefficients")[0].to_dense().values)

    def intercept(self) -> float:
        (t,) = self.get_model_data()
        return float(t.col("intercept")[0])


def make_model_table(weights: np.ndarray, intercept: float) -> Table:
    return Table.from_rows(
        [(DenseVector(np.asarray(weights, dtype=np.float64)), float(intercept))],
        MODEL_SCHEMA,
    )


# module-level + memoized so the jit cache is shared across mapper instances —
# a fresh jit() per load_model would recompile on every transform call
def _score_fn(x, w, b):
    return x @ w + b


from functools import lru_cache


@lru_cache(maxsize=32)
def _score_apply(mesh):
    """Mesh-sharded scorer: query rows over the 'data' axis, model replicated
    (the ModelMapperAdapter.java:53-61 parallel-apply analog; plain jit on a
    single chip)."""
    from flink_ml_tpu.parallel.collectives import make_data_parallel_apply

    return make_data_parallel_apply(_score_fn, mesh, n_args=3)


@jax.jit
def _sparse_score_fn(csr, w, b):
    return csr.matvec(w.astype(jnp.float32)) + b


def _col_is_sparse(table: Table, col: str) -> bool:
    values = table.col(col)
    return len(values) > 0 and isinstance(values[0], SparseVector)


class LinearScoreMapper(ModelMapper):
    """Batched x·w + b scorer; subclasses shape the output columns.

    The replacement for the reference's per-record ModelMapper hot loop
    (ModelMapperAdapter.java:58-61): one jitted matvec per row bucket.
    """

    def __init__(self, model: GlmModelBase, data_schema: Schema):
        self._model_stage = model
        super().__init__([MODEL_SCHEMA], data_schema, model.get_params())

    def reserved_cols(self) -> Optional[list]:
        return self._model_stage.get_reserved_cols()

    def load_model(self, *model_tables: Table) -> None:
        (t,) = model_tables
        w = np.asarray(t.col("coefficients")[0].to_dense().values)
        self._w = jnp.asarray(w, dtype=jnp.float32)
        self._b = jnp.asarray(float(t.col("intercept")[0]), dtype=jnp.float32)
        # host copies for the circuit-breaker CPU fallback: when the device
        # path is open-circuited, scoring must not touch device memory at all
        self._w_np = np.asarray(w, dtype=np.float32)
        self._b_np = np.float32(t.col("intercept")[0])

    def serve_validation_spec(self):
        model = self._model_stage
        return {
            "dim": int(self._w.shape[0]),
            "vector_col": model.get_vector_col(),
            "feature_cols": model.get_feature_cols(),
        }

    #: subclasses turn fetched fused scores into their output columns
    #: (mirroring their map_batch tail); None keeps the mapper out of
    #: fused plans — a custom LinearScoreMapper subclass with its own
    #: map_batch but no finalize must split the plan, never be mis-served
    _fused_finalize = None

    def fused_kernel(self):
        if type(self)._fused_finalize is None:
            return None
        from flink_ml_tpu.common.fused import FusedInput, FusedKernel

        model = self._model_stage
        feature_cols = model.get_feature_cols()

        def dense_fn(x, w, b):
            return {"scores": _score_fn(x, w, b)}

        def csr_fn(csr, w, b):
            return {"scores": csr.matvec(w.astype(jnp.float32)) + b}

        return FusedKernel(
            inputs=[FusedInput(
                dim=int(self._w.shape[0]),
                vector_col=model.get_vector_col(),
                feature_cols=tuple(feature_cols) if feature_cols else None,
            )],
            fn=dense_fn,
            csr_fn=csr_fn,
            out_keys=("scores",),
            model_args=(self._w, self._b),
            finalize=self._fused_finalize,
            pallas_op="glm_score",  # x @ w + b
        )

    def _scores(self, batch: Table) -> np.ndarray:
        model = self._model_stage
        vector_col = model.get_vector_col()
        if vector_col is not None and _col_is_sparse(batch, vector_col):
            # wide models never densify: segment-CSR matvec on device.  Row
            # count is bucketed (power of two) so varying batch sizes reuse
            # one compiled program; pad rows receive only zero contributions
            # and are sliced away.
            from flink_ml_tpu import serve
            from flink_ml_tpu.lib.common import bucket_rows
            from flink_ml_tpu.ops.batch import CsrBatch

            csr = batch.features_csr(vector_col, n_cols=int(self._w.shape[0]))
            n = csr.n_rows
            padded = CsrBatch(
                csr.indices, csr.values, csr.row_ids,
                n_rows=bucket_rows(max(n, 1)), n_cols=csr.n_cols,
            )
            return serve.dispatch(
                self.serve_name(),
                device=lambda: np.asarray(
                    _sparse_score_fn(padded, self._w, self._b)
                )[:n],
                fallback=lambda: self._scores_cpu_sparse(csr, n),
            )
        X, _ = resolve_features(batch, model, dim=int(self._w.shape[0]))
        # asarray, not astype: a matrix-backed f32 column passes through
        # zero-copy, so the slab pool sees a STABLE buffer and re-scoring
        # the same table reuses the placed padded batch.  Pool ONLY that
        # case — a freshly materialized buffer (f64 column, object rows,
        # featureCols matrix) gets a new identity every batch, so pooling
        # it would be pure tokenize+insert overhead with zero possible hits
        X = np.asarray(X, dtype=np.float32)
        col = (
            batch.col(vector_col) if vector_col is not None
            and batch.schema.contains(vector_col) else None
        )
        pool_key = (
            ("linear_scores", vector_col, int(self._w.shape[0]))
            if X is col else None
        )
        from flink_ml_tpu import serve

        return serve.dispatch(
            self.serve_name(),
            device=lambda: apply_sharded(
                _score_apply, X, self._w, self._b, pool_key=pool_key
            ),
            fallback=lambda: X @ self._w_np + self._b_np,
        )

    def _scores_cpu_sparse(self, csr, n: int) -> np.ndarray:
        """NumPy segment-matvec fallback (same math as _sparse_score_fn;
        f32 accumulation order may differ by summation grouping)."""
        out = np.zeros(n + 1, dtype=np.float32)  # slot n absorbs pad entries
        np.add.at(
            out,
            np.minimum(np.asarray(csr.row_ids), n),
            np.asarray(csr.values, dtype=np.float32)
            * self._w_np[np.asarray(csr.indices)],
        )
        return out[:n] + self._b_np


class GlmEstimatorBase(Estimator, GlmTrainParams):
    """Shared fit: rows -> minibatch stack -> data-parallel SGD epochs."""

    def _grad_fn(self):
        """(params, x, y, w) -> (grads, weighted loss sum, weight sum)."""
        raise NotImplementedError

    def _make_model(self) -> GlmModelBase:
        raise NotImplementedError

    def _labels(self, table: Table) -> np.ndarray:
        return np.asarray(table.col(self.get_label_col()), dtype=np.float64)

    def _checkpoint_config(self):
        directory = self.get_checkpoint_dir()
        if directory is None:
            return None
        from flink_ml_tpu.iteration.checkpoint import CheckpointConfig

        return CheckpointConfig(
            directory=directory, every_n_epochs=self.get_checkpoint_interval()
        )

    #: loss kind for the sparse fused path ('logistic' | 'squared')
    LOSS_KIND: str = ""

    def fit(self, *inputs) -> GlmModelBase:
        # scope the slab-pool stats + wall clock to THIS fit: _finish
        # stamps the delta (hits/misses/hit rate/fit_wall_ms) into the
        # RunReport so warm fits are self-identifying (the CI warm-path
        # gate reads exactly this)
        import time as _time

        from flink_ml_tpu.table import slab_pool

        self._fit_pool_stats0 = (
            *slab_pool.pool().counters(), _time.perf_counter()
        )
        (table,) = inputs
        if getattr(table, "is_chunked", False):
            return self._fit_out_of_core(table)
        y = self._labels(table)
        env = MLEnvironmentFactory.get_default()
        mesh = env.get_mesh()
        # rows shard over the data axis only; other mesh axes replicate.
        # Multi-process, `table` is this process's file shard: packing
        # targets the LOCAL share of the data axis and batch size, and
        # shard_batch assembles the global batch from per-process slices.
        from flink_ml_tpu.parallel.mesh import (
            local_batch_share,
            local_data_parallel_size,
        )

        n_dev = local_data_parallel_size(mesh)
        batch_share = local_batch_share(self.get_global_batch_size())

        vector_col = self.get_vector_col()
        if (vector_col is None) == (self.get_feature_cols() is None):
            raise ValueError("set exactly one of vectorCol / featureCols")
        if vector_col is not None and _col_is_sparse(table, vector_col):
            return self._fit_sparse(table, y, mesh, n_dev, batch_share)

        if int(self.get_num_hot_features() or 0) > 0:
            raise ValueError(
                "numHotFeatures applies only to sparse vector columns "
                "(dense features already stream through the MXU); unset it "
                "for dense training"
            )
        model_sharded = dict(mesh.shape).get("model", 1) > 1
        X, dim = resolve_features(table, self)
        layout_key = ("dense", vector_col, tuple(self.get_feature_cols() or ()),
                      self.get_label_col(), n_dev, batch_share)
        # the columns this layout READS — pool tokens scope to them, so a
        # select()/with_column() re-wrap sharing these buffers still hits
        layout_cols = (
            [vector_col] if vector_col is not None
            else list(self.get_feature_cols() or ())
        ) + [self.get_label_col()]
        self._layout_cols = layout_cols
        stack = table.cached_pack(
            layout_key,
            lambda: pack_minibatches(X, y, n_dev, batch_share),
        )
        if model_sharded:
            # wide-dense story: weight vector + feature columns shard over
            # the 'model' axis (train_glm_dense_2d) instead of replicating
            return self._fit_dense_2d(stack, mesh, layout_key, dim, table)
        # device residency: re-fits of the same table CONTENT (sweeps,
        # benches, a re-wrapped Table over the same buffers) skip the
        # host->device hop via the process-wide slab pool — the analog of
        # the CPU path's data already sitting in RAM.  Keyed by mesh: a
        # different mesh is a different placement.  Only the fused path
        # consumes this layout; the checkpointed path shards (x, y, w)
        # itself, so placing the combined view there would transfer the
        # dataset twice.
        checkpoint = self._checkpoint_config()
        device_batch = None
        if checkpoint is None:
            from flink_ml_tpu.lib.common import _combined_view
            from flink_ml_tpu.parallel.mesh import shard_batch_prefetched
            from flink_ml_tpu.table import slab_pool

            # a THUNK, resolved inside train_glm's memory-pressure scope:
            # this closure must hold no reference to the placed whole-
            # batch slab — an OOM fallback that streams windows has to be
            # able to actually FREE that allocation first — and under an
            # already-known pressure cap train_glm skips the placement
            # entirely
            device_batch = lambda: slab_pool.get_or_place(  # noqa: E731
                table, layout_key + ("dev",), mesh,
                lambda: shard_batch_prefetched(mesh, _combined_view(stack)),
                cols=layout_cols,
            )

        w0 = jnp.zeros((dim,), dtype=jnp.float32)
        b0 = jnp.zeros((), dtype=jnp.float32)
        # guarded: a NaN/Inf fit rolls back to the last good checkpoint
        # (or the zero init) and retries at a backed-off learning rate
        lr = self.get_learning_rate()
        result = fault.run_guarded(
            lambda lr_scale: train_glm(
                (w0, b0),
                stack,
                self._grad_fn(),
                mesh,
                learning_rate=lr * lr_scale,
                max_iter=self.get_max_iter(),
                reg=self.get_reg(),
                tol=self.get_tol(),
                checkpoint=checkpoint,
                device_batch=device_batch,
            ),
            what=type(self).__name__,
        )
        return self._finish(result)

    def _fit_dense_2d(self, stack, mesh, layout_key, dim, table) -> GlmModelBase:
        """Dense feature-sharded (data x model) fit — VERDICT r3 item 5."""
        if not self.LOSS_KIND:
            raise NotImplementedError(
                f"{type(self).__name__} has no fused loss kind for the "
                "feature-sharded dense path"
            )
        from flink_ml_tpu.lib.common import (
            make_feature_shard_placer,
            place_dense_2d_batch,
            train_glm_dense_2d,
        )
        from flink_ml_tpu.table import slab_pool

        model_size = dict(mesh.shape)["model"]
        _, _, dim_pad = make_feature_shard_placer(mesh, dim, model_size)
        # thunk: resolved lazily so a no-op checkpoint resume skips the hop
        device_batch = lambda: slab_pool.get_or_place(  # noqa: E731
            table, layout_key + ("dev2d",), mesh,
            lambda: place_dense_2d_batch(mesh, stack, dim_pad),
            cols=getattr(self, "_layout_cols", None),
        )
        w0 = jnp.zeros((dim,), dtype=jnp.float32)
        b0 = jnp.zeros((), dtype=jnp.float32)
        lr = self.get_learning_rate()
        result = fault.run_guarded(
            lambda lr_scale: train_glm_dense_2d(
                (w0, b0),
                stack,
                self.LOSS_KIND,
                mesh,
                learning_rate=lr * lr_scale,
                max_iter=self.get_max_iter(),
                reg=self.get_reg(),
                tol=self.get_tol(),
                with_intercept=self.get_with_intercept(),
                checkpoint=self._checkpoint_config(),
                device_batch=device_batch,
            ),
            what=type(self).__name__,
        )
        return self._finish(result)

    def _fit_sparse(
        self, table: Table, y, mesh, n_dev: int, batch_share: int
    ) -> GlmModelBase:
        """Sparse-feature training: segment-CSR minibatches, fused device loop."""
        if not self.LOSS_KIND:
            raise NotImplementedError(
                f"{type(self).__name__} has no sparse loss kind"
            )
        from flink_ml_tpu.parallel.mesh import agree_max

        num_features = self.get_num_features()
        if jax.process_count() > 1:
            if num_features is None:
                raise ValueError(
                    "multi-process sparse training requires numFeatures "
                    "(each process would otherwise infer a different "
                    "dimension from its own file shard)"
                )
            if not batch_share or batch_share <= 0:
                raise ValueError(
                    "multi-process sparse training requires an explicit "
                    "globalBatchSize: the full-batch default would derive "
                    "the per-device minibatch from each process's LOCAL "
                    "row count, compiling mismatched block shapes when "
                    "shards are unequal"
                )
        # multi-process: the packed nnz width and step count derive from
        # LOCAL rows, but every process must compile the same block shapes.
        # A cheap pre-scan (row counts only, no stack materialized) computes
        # the local layout scalars, agree_max reconciles them, and the ONE
        # pack runs with the agreed floors.  The nnz floor is schedule-
        # neutral (pad entries carry zero weight); the steps floor only
        # differs when shards are unequal-sized, where the shorter shard's
        # trailing all-pad steps contribute zero gradient (with reg > 0
        # those steps still apply weight decay, like any zero-gradient step)
        if jax.process_count() > 1:
            from flink_ml_tpu.lib.common import (
                sparse_layout_floors,
                sparse_row_counts,
            )

            counts = sparse_row_counts(table.col(self.get_vector_col()))
            nnz_pad, steps = agree_max(
                *sparse_layout_floors(counts, n_dev, batch_share)
            )
        else:
            nnz_pad, steps = 0, 0  # pack's own natural layout
        layout_key = ("sparse", self.get_vector_col(), self.get_label_col(),
                      n_dev, batch_share, num_features, nnz_pad, steps)
        sstack = table.cached_pack(
            layout_key,
            lambda: pack_sparse_minibatches(
                table.col(self.get_vector_col()), y, n_dev,
                batch_share, dim=num_features,
                min_nnz_pad=nnz_pad, min_steps=steps,
            ),
        )
        if nnz_pad and (sstack.nnz_pad, sstack.steps) != (nnz_pad, steps):
            # the pre-scan must predict the pack's layout exactly, or the
            # processes compile mismatched shapes and the collective hangs
            raise AssertionError(
                f"sparse layout pre-scan predicted (nnz_pad={nnz_pad}, "
                f"steps={steps}) but the pack chose "
                f"({sstack.nnz_pad}, {sstack.steps})"
            )
        from flink_ml_tpu.parallel.mesh import shard_batch_prefetched
        from flink_ml_tpu.table import slab_pool

        hot_k = int(self.get_num_hot_features() or 0)
        if hot_k > 0:
            return self._fit_sparse_hotcold(table, mesh, layout_key, sstack,
                                            hot_k)
        # thunk: resolved lazily so a no-op checkpoint resume skips the hop
        sparse_cols = [self.get_vector_col(), self.get_label_col()]
        device_batch = lambda: slab_pool.get_or_place(  # noqa: E731
            table, layout_key + ("dev",), mesh,
            lambda: shard_batch_prefetched(
                mesh, (sstack.ints, sstack.floats)
            ),
            cols=sparse_cols,
        )
        w0 = jnp.zeros((sstack.dim,), dtype=jnp.float32)
        b0 = jnp.zeros((), dtype=jnp.float32)
        lr = self.get_learning_rate()
        result = fault.run_guarded(
            lambda lr_scale: train_glm_sparse(
                (w0, b0),
                sstack,
                self.LOSS_KIND,
                mesh,
                learning_rate=lr * lr_scale,
                max_iter=self.get_max_iter(),
                reg=self.get_reg(),
                tol=self.get_tol(),
                with_intercept=self.get_with_intercept(),
                checkpoint=self._checkpoint_config(),
                device_batch=device_batch,
            ),
            what=type(self).__name__,
        )
        return self._finish(result)

    def _fit_sparse_hotcold(self, table, mesh, layout_key, sstack,
                            hot_k: int) -> GlmModelBase:
        """Hot/cold sparse fit (VERDICT r3 item 1): the top-``hot_k``
        frequent features stream through a dense bf16 MXU slab, the cold
        tail stays segment-CSR.  On a ('data','model') mesh the slab
        columns and the weight vector shard over ``model`` — the hot/cold
        formulation AND the wider-than-one-chip story at once.  See
        lib/common.HotColdStack."""
        from flink_ml_tpu.lib.common import (
            hotcold_device_batch,
            split_hot_cold,
            train_glm_sparse_hotcold,
        )

        model_size = dict(mesh.shape).get("model", 1)
        counts = None
        plan = None
        min_hot_pad = min_cold_pad = 0
        if jax.process_count() > 1:
            # every process must select the same hot set and fill the same
            # shapes: agree on the GLOBAL frequency vector (sum of local
            # entry counts) and the max pad widths before splitting; the
            # model-axis weight placement rides global_put, so the 2-D
            # layout works across processes too
            from flink_ml_tpu.lib.common import (
                hotcold_entry_counts,
                hotcold_layout_floors,
            )
            from flink_ml_tpu.parallel.mesh import agree_max, agree_sum

            counts = agree_sum(hotcold_entry_counts(sstack))
            (hp, cp), plan = hotcold_layout_floors(
                sstack, hot_k, model_size=model_size, counts=counts
            )
            min_hot_pad, min_cold_pad = agree_max(hp, cp)
        # thunks: the host split AND the device slab build resolve lazily,
        # so a no-op checkpoint resume pays neither
        hstack = lambda: table.cached_pack(  # noqa: E731
            layout_key + ("hot", hot_k, model_size, min_hot_pad,
                          min_cold_pad),
            lambda: split_hot_cold(
                sstack, hot_k, model_size=model_size, counts=counts,
                min_hot_pad=min_hot_pad, min_cold_pad=min_cold_pad,
                plan=plan,
            ),
        )
        # formulation choice (VERDICT r4 #1): resident slabs are fastest
        # but their HBM footprint grows O(rows x hot_k); the streamed
        # (in-program-densify) formulation holds only the packed entries.
        # 'auto' keeps resident only while the slabs fit the budget.
        mode = self.get_hot_slab_mode()
        if mode == "auto":
            from flink_ml_tpu.lib.common import (
                hotcold_hot_k_eff,
                hotcold_slab_bytes,
            )

            budget = knobs.knob_int("FMT_HOT_SLAB_BUDGET_MB") * (1 << 20)
            # padded rows = groups x mb; slab width from the plan's own rule
            slab_bytes = hotcold_slab_bytes(
                sstack.ints.shape[0] * sstack.mb,
                hotcold_hot_k_eff(sstack.dim, hot_k, model_size),
            )
            resident = slab_bytes <= budget
            if jax.process_count() > 1:
                # local budget env vars / near-boundary slab sizes can
                # disagree across processes; divergent resident-vs-stream
                # booleans build fused programs with different collective
                # schedules — a hang.  Stream wins ties: any process voting
                # stream (its slabs don't fit) forces stream everywhere.
                from flink_ml_tpu.parallel.mesh import agree_max

                (want_stream,) = agree_max(int(not resident))
                resident = not want_stream
            obs.gauge_set("train.hot_slab_bytes", float(slab_bytes))
        else:
            resident = mode == "resident"
        # the agreed decision, visible in every RunReport: 1.0 = resident
        # slabs, 0.0 = in-program densify (stream)
        obs.gauge_set("train.hot_slab_resident", float(resident))
        from flink_ml_tpu.table import slab_pool

        hot_cols = [self.get_vector_col(), self.get_label_col()]
        if resident:
            # the pool's multi-process hit agreement matters HERE: the
            # resident builder dispatches the densify device program, which
            # every process must enter together
            device_batch = lambda: slab_pool.get_or_place(  # noqa: E731
                table, layout_key + ("hotdev", hot_k), mesh,
                lambda: hotcold_device_batch(mesh, hstack()),
                cols=hot_cols,
            )
        else:
            from flink_ml_tpu.lib.common import hotcold_entries_device_batch

            device_batch = lambda: slab_pool.get_or_place(  # noqa: E731
                table, layout_key + ("hotdev-stream", hot_k), mesh,
                lambda: hotcold_entries_device_batch(mesh, hstack()),
                cols=hot_cols,
            )
        w0 = jnp.zeros((sstack.dim,), dtype=jnp.float32)
        b0 = jnp.zeros((), dtype=jnp.float32)
        lr = self.get_learning_rate()
        result = fault.run_guarded(
            lambda lr_scale: train_glm_sparse_hotcold(
                (w0, b0),
                hstack,
                self.LOSS_KIND,
                mesh,
                learning_rate=lr * lr_scale,
                max_iter=self.get_max_iter(),
                reg=self.get_reg(),
                tol=self.get_tol(),
                with_intercept=self.get_with_intercept(),
                checkpoint=self._checkpoint_config(),
                device_batch=device_batch,
                resident_slabs=resident,
            ),
            what=type(self).__name__,
        )
        return self._finish(result)

    def _fit_out_of_core(self, table) -> GlmModelBase:
        """Streaming fit over a :class:`~flink_ml_tpu.table.sources.ChunkedTable`.

        The dataset is never materialized: chunks stream through the fused
        per-chunk program (lib/out_of_core.py) with host->device prefetch.
        Step-major packing makes the result bit-identical to the in-memory
        fit of the same rows.  Requires an explicit ``globalBatchSize``
        (full-batch SGD needs the entire dataset resident by definition).

        Configurations with a full layout pre-pass (hot/cold frequency
        scan, multi-process shape/count scans) run under a
        :func:`~flink_ml_tpu.table.sources.chunk_cache`: the scan's text
        parse records binary chunks, the pack pass replays them — ONE text
        read of the source total (VERDICT r4 #3).
        """
        from flink_ml_tpu.table.sources import chunk_cache

        hot_k = int(self.get_num_hot_features() or 0)
        with chunk_cache(
            table, enabled=jax.process_count() > 1 or hot_k > 0
        ) as table:
            return self._fit_out_of_core_impl(table)

    def _fit_out_of_core_impl(self, table) -> GlmModelBase:
        from flink_ml_tpu.lib import out_of_core as oc
        from flink_ml_tpu.parallel.mesh import (
            data_parallel_size,
            local_data_parallel_size,
        )
        from flink_ml_tpu.table.schema import DataTypes

        env = MLEnvironmentFactory.get_default()
        mesh = env.get_mesh()
        # mb (per-device rows) comes from the GLOBAL axis; block packing
        # targets this process's LOCAL share (multi-process, each process
        # streams its own file shard into the global block queue)
        n_dev = data_parallel_size(mesh)
        n_dev_pack = local_data_parallel_size(mesh)
        model_size = data_parallel_size(mesh, "model")
        gbs = self.get_global_batch_size()
        if gbs is None or gbs <= 0:
            raise ValueError(
                "out-of-core training requires an explicit globalBatchSize "
                "(full batch would need the whole dataset resident)"
            )
        hot_k = int(self.get_num_hot_features() or 0)
        mb = max(1, -(-gbs // n_dev))
        G_local = mb * n_dev_pack
        steps_per_chunk = max(1, table.chunk_rows // G_local)
        label = self.get_label_col()
        vector_col = self.get_vector_col()
        if (vector_col is None) == (self.get_feature_cols() is None):
            raise ValueError("set exactly one of vectorCol / featureCols")
        lr, reg = self.get_learning_rate(), self.get_reg()
        checkpoint = self._checkpoint_config()
        schema = table.schema
        is_sparse = (
            vector_col is not None
            and schema.type_of(vector_col) == DataTypes.SPARSE_VECTOR
        )

        if is_sparse:
            if not self.LOSS_KIND:
                raise NotImplementedError(
                    f"{type(self).__name__} has no sparse loss kind"
                )
            dim = self.get_num_features()
            if dim is None:
                # a CSR-backed column carries the global width (the
                # categorical pipeline's encoder stamps it per chunk) —
                # peek one chunk before demanding the param
                from flink_ml_tpu.ops.batch import CsrRows

                chunks = table.chunks()
                try:
                    first = next(chunks, None)
                finally:
                    close = getattr(chunks, "close", None)
                    if close is not None:
                        close()
                if first is not None:
                    col = first.col(vector_col)
                    if isinstance(col, CsrRows):
                        dim = int(col.dim)
            if dim is None:
                raise ValueError(
                    "out-of-core sparse training requires numFeatures (the "
                    "global dimension cannot be inferred from a stream of "
                    "per-row sparse vectors)"
                )
            pad_to_blocks = None
            counts = None
            if jax.process_count() > 1:
                from flink_ml_tpu.parallel.mesh import agree_max

                # every process must compile the same block shapes AND
                # dispatch the same number of collective chunk calls per
                # epoch: ONE exact scan of the local shard (the sampled
                # estimate would disagree across processes; the hot/cold
                # frequency vector rides the same pass), then agree on
                # the pad and the per-epoch block count — short shards pad
                # their epochs with gated no-op blocks
                scanned = oc.scan_sparse_stream(
                    table, vector_col, mb,
                    count_dim=dim if hot_k > 0 else None,
                )
                nnz_local, rows_local = scanned[0], scanned[1]
                counts = scanned[2] if hot_k > 0 else None
                rows_per_block = steps_per_chunk * mb * n_dev_pack
                nnz_pad, pad_to_blocks = agree_max(
                    nnz_local, -(-rows_local // rows_per_block)
                )
            elif hot_k > 0:
                # the hot/cold counting pass doubles as an EXACT pad scan:
                # one read yields both (out-of-core means every pass is a
                # full disk/network read — never pay two), and the exact
                # pad removes the sampled estimate's mid-fit failure mode
                nnz_pad, _, counts = oc.scan_sparse_stream(
                    table, vector_col, mb, count_dim=dim
                )
            else:
                nnz_pad = oc.estimate_nnz_pad(table, vector_col, mb, n_dev)

            def extract(t):
                # the column passes through as-is: CsrRows (native stream)
                # stays vectorized end-to-end, object columns stay lists
                return (
                    t.col(vector_col),
                    np.asarray(t.col(label), dtype=np.float64),
                )

            if hot_k > 0:
                return self._fit_out_of_core_hotcold(
                    table, mesh, extract, n_dev_pack, mb, steps_per_chunk,
                    dim, nnz_pad, hot_k, lr, reg, checkpoint,
                    pad_to_blocks, local_counts=counts,
                )
            blocks = oc.sparse_blocks_factory(
                table, extract, n_dev_pack, mb, steps_per_chunk, dim,
                nnz_pad, pad_to_blocks=pad_to_blocks,
            )
            if model_size > 1:
                # the north-star 2-D configuration: rows stream over 'data'
                # while the weight vector shards over 'model' — Criteo-scale
                # data AND a wider-than-one-chip model at once
                from jax.sharding import PartitionSpec as P

                from flink_ml_tpu.lib.common import (
                    make_feature_shard_placer,
                    make_sparse_mb_grad_step_2d,
                )

                place_params, trim, dim_pad = make_feature_shard_placer(
                    mesh, dim, model_size
                )
                mb_grad = make_sparse_mb_grad_step_2d(
                    self.LOSS_KIND, mb, nnz_pad, dim_pad // model_size,
                    self.get_with_intercept(),
                )
                param_spec = (P("model"), P())
                key = ("chunk-sparse2d", self.LOSS_KIND, mesh, mb, nnz_pad,
                       dim_pad, float(lr), float(reg),
                       self.get_with_intercept())
            else:
                from flink_ml_tpu.lib.common import make_sparse_mb_grad_step

                mb_grad = make_sparse_mb_grad_step(
                    self.LOSS_KIND, mb, nnz_pad, dim, self.get_with_intercept()
                )
                param_spec = None
                place_params = None
                trim = None
                key = ("chunk-sparse", self.LOSS_KIND, mesh, mb, nnz_pad, dim,
                       float(lr), float(reg), self.get_with_intercept())
        else:
            dim = self.get_num_features()
            if dim is None and self.get_feature_cols() is not None:
                dim = len(self.get_feature_cols())
            if dim is None:
                # vectorCol with unknown width: peek one chunk to pin it
                chunks = table.chunks()
                try:
                    first = next(chunks, None)
                finally:
                    close = getattr(chunks, "close", None)
                    if close is not None:
                        close()
                if first is None:
                    raise ValueError("empty source")
                _, dim = resolve_features(first, self)

            if hot_k > 0:
                raise ValueError(
                    "numHotFeatures applies only to sparse vector columns "
                    "(dense features already stream through the MXU); "
                    "unset it for dense training"
                )

            def extract(t):
                X, _ = resolve_features(t, self, dim=dim)
                return np.asarray(X), np.asarray(
                    t.col(label), dtype=np.float64
                )

            pad_to_blocks = None
            if jax.process_count() > 1:
                from flink_ml_tpu.parallel.mesh import agree_max

                # every process must dispatch the same number of collective
                # chunk calls per epoch: one row-count pass, then agree —
                # short shards pad with gated no-op blocks
                rows_per_block = steps_per_chunk * mb * n_dev_pack
                (pad_to_blocks,) = agree_max(
                    -(-oc.count_stream_rows(table) // rows_per_block)
                )
            blocks = oc.dense_blocks_factory(
                table, extract, n_dev_pack, mb, steps_per_chunk,
                pad_to_blocks=pad_to_blocks, pad_dim=dim,
            )
            grad_fn = self._grad_fn()

            def mb_grad(p, mbs):
                return grad_fn(p, mbs[..., :-2], mbs[..., -2], mbs[..., -1])

            param_spec = None
            place_params = None
            trim = None
            key = ("chunk-dense", grad_fn, mesh, float(lr), float(reg))

        w0 = jnp.zeros((dim,), dtype=jnp.float32)
        b0 = jnp.zeros((), dtype=jnp.float32)
        use_spill = getattr(table, "spill", False) and self.get_max_iter() > 1
        with oc.maybe_spill(blocks, use_spill) as blocks:
            # guarded: a rollback retries at a backed-off learning rate —
            # the scale joins the program key so the colder-step chunk
            # program compiles fresh instead of hitting the hot one
            result = fault.run_guarded(
                lambda lr_scale: oc.train_out_of_core(
                    (w0, b0),
                    blocks,
                    lambda: oc.make_chunk_step_fn(
                        key + ("lrs", lr_scale), mb_grad, mesh,
                        lr * lr_scale, reg, param_spec=param_spec,
                    ),
                    mesh,
                    max_iter=self.get_max_iter(),
                    tol=self.get_tol(),
                    checkpoint=checkpoint,
                    place_params=place_params,
                ),
                what=type(self).__name__,
            )
        if trim is not None:  # the placer's own inverse: trim 2-D padding
            w_t, b_t = trim(result.params)
            result.params = (np.asarray(w_t), b_t)
        return self._finish(result)

    def _fit_out_of_core_hotcold(self, table, mesh, extract, n_dev, mb,
                                 steps_per_chunk, dim, nnz_pad, hot_k,
                                 lr, reg, checkpoint,
                                 pad_to_blocks=None,
                                 local_counts=None) -> GlmModelBase:
        """Out-of-core hot/cold fit: the stream's frequency head rides the
        MXU slab while the data never materializes.

        The caller's ONE layout pre-pass (scan_sparse_stream with
        count_dim) yields both the exact pad and the frequency vector that
        fixes the hot set and permutation for the whole fit (a prefix
        sample would bias selection on sorted files — the KMeans
        reservoir-init reasoning); each streamed block
        then packs and splits with that one plan, and the chunk program
        densifies each minibatch's slab IN-PROGRAM (the in-memory path's
        HBM-resident slabs cannot exist here by contract — the slab
        footprint stays one (mb, hot_k) scratch).  Training runs in
        permuted feature space start to finish: the initial params are
        zeros (permutation-invariant), STREAM CHECKPOINTS HOLD THE
        PERMUTED representation (a resume re-derives the identical
        permutation from the deterministic pre-pass), and only the final
        coefficients unpermute."""
        from flink_ml_tpu.lib import out_of_core as oc
        from flink_ml_tpu.lib.common import (
            hotcold_feature_plan,
            make_hotcold_stream_mb_grad_step,
        )

        # counts always arrive from the caller's combined layout scan —
        # one stream pass yields the pad AND the frequency vector
        if local_counts is None:
            raise ValueError(
                "hot/cold out-of-core fits require the caller's "
                "scan-derived frequency vector"
            )
        counts = local_counts
        if jax.process_count() > 1:
            # the hot set must come from the GLOBAL frequency vector;
            # pads need no extra agreement (both ride the agreed nnz_pad)
            from flink_ml_tpu.parallel.mesh import agree_sum

            counts = agree_sum(counts)
        model_size = dict(mesh.shape).get("model", 1)
        fplan = hotcold_feature_plan(dim, hot_k, model_size, counts)
        dim_pad = fplan["dim_pad"]
        hot_k_eff = fplan["hot_k_eff"]
        # the SAME block layout serves 1-D and 2-D (entries carry global
        # slab columns / permuted ids; the 2-D step masks to its shard
        # ownership in-program)
        blocks = oc.hotcold_blocks_factory(
            table, extract, n_dev, mb, steps_per_chunk, dim, nnz_pad,
            hot_k, fplan, pad_to_blocks=pad_to_blocks,
        )
        if model_size > 1:
            from jax.sharding import PartitionSpec as P

            from flink_ml_tpu.lib.common import (
                make_hotcold_stream_mb_grad_step_2d,
            )
            from flink_ml_tpu.parallel.mesh import global_put

            mb_grad = make_hotcold_stream_mb_grad_step_2d(
                self.LOSS_KIND, mb, nnz_pad, hot_k_eff // model_size,
                dim_pad // model_size, self.get_with_intercept(),
            )
            param_spec = (P("model"), P())

            def place_params(params):
                # params are ALREADY in permuted space (zeros init or a
                # permuted-representation checkpoint): place, don't permute
                w0, b0 = params
                return (
                    global_put(
                        mesh, np.asarray(w0, np.float32), P("model")
                    ),
                    global_put(mesh, np.asarray(b0, np.float32), P()),
                )

            key = ("chunk-hotcold2d", self.LOSS_KIND, mesh, mb, nnz_pad,
                   hot_k_eff, dim_pad, float(lr), float(reg),
                   self.get_with_intercept())
        else:
            mb_grad = make_hotcold_stream_mb_grad_step(
                self.LOSS_KIND, mb, nnz_pad, hot_k_eff, dim_pad,
                self.get_with_intercept(),
            )
            param_spec = None
            place_params = None
            key = ("chunk-hotcold", self.LOSS_KIND, mesh, mb, nnz_pad,
                   hot_k_eff, dim_pad, float(lr), float(reg),
                   self.get_with_intercept())
        w0 = jnp.zeros((dim_pad,), dtype=jnp.float32)
        b0 = jnp.zeros((), dtype=jnp.float32)
        # checkpointed params are in PERMUTED space: stamp the layout into
        # the snapshot and refuse resumes under a different one (a changed
        # mesh model size or hot_k yields a shape-compatible but
        # differently-permuted vector — silently wrong without this)
        import zlib

        layout_sig = {
            "model_size": model_size,
            "hot_k_eff": hot_k_eff,
            "dim_pad": dim_pad,
            "perm_crc": int(zlib.crc32(fplan["perm"].tobytes())),
        }

        def validate_meta(meta):
            stored = meta.get("hotcold_layout")
            if stored is not None and stored != layout_sig:
                raise ValueError(
                    "checkpoint was written under a different hot/cold "
                    f"layout ({stored} != {layout_sig}); resume with the "
                    "original mesh/numHotFeatures or start fresh"
                )

        use_spill = getattr(table, "spill", False) and self.get_max_iter() > 1
        with oc.maybe_spill(blocks, use_spill) as blocks:
            result = fault.run_guarded(
                lambda lr_scale: oc.train_out_of_core(
                    (w0, b0),
                    blocks,
                    lambda: oc.make_chunk_step_fn(
                        key + ("lrs", lr_scale), mb_grad, mesh,
                        lr * lr_scale, reg, param_spec=param_spec,
                    ),
                    mesh,
                    max_iter=self.get_max_iter(),
                    tol=self.get_tol(),
                    checkpoint=checkpoint,
                    place_params=place_params,
                    meta_extra={"hotcold_layout": layout_sig},
                    validate_meta=validate_meta,
                ),
                what=type(self).__name__,
            )
        w_t = np.asarray(result.params[0])[fplan["perm"]]
        result.params = (w_t, result.params[1])
        return self._finish(result)

    def _finish(self, result) -> GlmModelBase:
        w, b = result.params
        if not self.get_with_intercept():
            b = 0.0
        model = self._make_model()
        model.get_params().merge(self.get_params())
        model.set_model_data(make_model_table(w, float(b)))
        model.train_epochs_ = result.epochs
        model.train_losses_ = result.losses
        model.train_metrics_ = result.metrics
        obs.fit_report(
            type(self).__name__,
            step_metrics=result.metrics,
            extra={
                "epochs": result.epochs,
                "loss": result.losses[-1] if result.losses else None,
                **fit_pool_extra(self, result),
            },
        )
        return model
