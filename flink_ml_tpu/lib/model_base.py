"""Shared model-data plumbing for table-backed models.

Every algorithm Model in this library follows the reference's model-as-table
convention (Model.java:102-122, SURVEY.md §2.3.2): model data is rows of a
table, persisted through the columnar codec, materialized into a device
mapper at transform time.  This base implements that contract once; concrete
models supply the validation predicate and the mapper.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

from flink_ml_tpu.api.core import Model
from flink_ml_tpu.common.mapper import ModelMapper
from flink_ml_tpu.table.schema import Schema
from flink_ml_tpu.table.table import Table
from flink_ml_tpu.utils import persistence
from flink_ml_tpu.utils.environment import MLEnvironmentFactory

MODEL_DATA_FILE = "model_data.jsonl"


class TableModelBase(Model):
    """Model whose data is one table (set/get/save/load implemented)."""

    # class-level defaults: Stage.load reconstructs instances bypassing __init__
    _model_table: Optional[Table] = None
    _mapper_cache: Optional[ModelMapper] = None
    _mapper_cache_key: Optional[tuple] = None

    #: name of a column the model table must contain (None skips the check)
    REQUIRED_MODEL_COL: Optional[str] = None

    def __init__(self):
        super().__init__()
        self._model_table = None
        self._mapper_cache = None
        self._mapper_cache_key = None

    def set_model_data(self, *inputs: Table) -> "TableModelBase":
        (table,) = inputs
        required = self.REQUIRED_MODEL_COL
        if required is not None and not table.schema.contains(required):
            raise ValueError(f"model table must have a {required!r} column")
        self._model_table = table
        self._mapper_cache = None  # device-side model state must reload
        return self

    def get_model_data(self) -> Tuple[Table, ...]:
        if self._model_table is None:
            raise RuntimeError("model data not set")
        return (self._model_table,)

    def save_model_data(self, path: str) -> None:
        persistence.save_table(self._model_table, os.path.join(path, MODEL_DATA_FILE))

    def load_model_data(self, path: str) -> None:
        self._model_table = persistence.load_table(os.path.join(path, MODEL_DATA_FILE))
        self._mapper_cache = None

    # -- transform -----------------------------------------------------------

    def _make_mapper(self, data_schema: Schema) -> ModelMapper:
        raise NotImplementedError

    def loaded_mapper(self, data_schema: Schema) -> ModelMapper:
        """The model's mapper for ``data_schema``, with model data already
        materialized on device.

        The loaded mapper holds the model packed on DEVICE (the
        broadcast-variable analog); reloading it per transform would
        re-transfer the whole model — for Knn that is the training set
        itself.  Cached, keyed by everything the mapper captures — the
        mesh included: load-time placement can be mesh-committed
        (shardModelData), so a mesh change must rebuild the mapper.  The
        fused pipeline planner calls this too: plan build needs each
        stage's device state without running a transform."""
        key = (
            tuple(data_schema.field_names),
            tuple(data_schema.field_types),
            self.get_params().to_json(),
            MLEnvironmentFactory.get_default().get_mesh(),
        )
        if self._mapper_cache is None or self._mapper_cache_key != key:
            mapper = self._make_mapper(data_schema)
            mapper.load_model(*self.get_model_data())
            self._mapper_cache = mapper
            self._mapper_cache_key = key
        return self._mapper_cache

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        mapper = self.loaded_mapper(table.schema)
        batch = MLEnvironmentFactory.get_default().default_batch_size
        # per-transform serve accounting: the serve.* counter delta across
        # this apply (quarantined rows, fallbacks, dispatch retries) lands
        # in a 'transform' RunReport, which `obs --check` judges for the
        # SERVE-DEGRADED flag (completed, but only via the CPU fallback)
        from flink_ml_tpu import obs as _obs
        from flink_ml_tpu.serve import serve_counter_snapshot

        serve0 = serve_counter_snapshot() if _obs.enabled() else None
        # top-level transforms root a trace (FMT_TRACE); inside an
        # already-traced region (a pipeline stage, a served batch) this
        # degrades to a child span under the caller's context.  The
        # drift scope (FMT_DRIFT, ISSUE 11) is a no-op inside a serving
        # batch or an outer pipeline the same way.
        with _obs.drift.transform_scope() as dscope:
            with _obs.trace.root_span("stage", {
                "stage": type(self).__name__, "rows": table.num_rows(),
            }):
                out = mapper.apply(table, batch_size=batch)
            if dscope is not None:
                # produced (score/prediction) columns into the live
                # window — the standalone-transform twin of the serving
                # demux tap
                dscope.observe_scores(
                    out, exclude=frozenset(table.schema.field_names)
                )
        if serve0 is not None:
            from flink_ml_tpu.obs.report import transform_report
            from flink_ml_tpu.serve import serve_counter_delta

            transform_report(
                type(self).__name__, rows=table.num_rows(),
                serve_delta=serve_counter_delta(serve0),
            )
        return (out,)
    # transform_chunks (streamed inference) is inherited from Transformer;
    # the mapper cache above keeps the model device-resident across chunks
