"""Online LogisticRegression — unbounded streaming mini-batch training
(BASELINE configs[4]).

The reference defines this topology but never implements it: the unbounded
iteration entry point returns null (Iterations.java:87-90) and the
IncrementalLearningSkeleton example (SURVEY.md §3.4) shows the intended shape —
training stream -> event-time tumbling window -> model update per window;
prediction stream connected to the freshest model.  Here that shape runs on
the :class:`flink_ml_tpu.iteration.unbounded.StreamingDriver`: each fired
window is one jitted SGD step on a padded row bucket (static shapes keep the
jit cache bounded), and the prediction path scores batches with exactly the
model that was current at each record's event time.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu import obs
from flink_ml_tpu.api.core import Estimator
from flink_ml_tpu.iteration.unbounded import StreamingDriver, StreamingResult
from flink_ml_tpu.lib.classification import LogisticRegressionModel, _log_loss_grads
from flink_ml_tpu.lib.common import bucket_rows, make_sgd_update, resolve_features
from flink_ml_tpu.lib.glm import GlmTrainParams, make_model_table
from flink_ml_tpu.lib.params import HasAllowedLateness, HasWindowMs
from flink_ml_tpu.table.sources import UnboundedSource
from flink_ml_tpu.table.table import Table


def _f64_or_nan(v) -> float:
    """Coerce one streamed cell to float64; junk (None, 'n/a', anything
    non-numeric) becomes NaN so the degenerate-row mask drops it instead
    of the coercion crashing the loop."""
    try:
        return float(v)
    except (TypeError, ValueError):
        return np.nan


class _PeekedSource(UnboundedSource):
    """Re-yields a record peeked off a single-pass source, then the remainder
    of the SAME iterator — nothing is lost to the dim probe.  One-shot:
    ``stream()`` may only be consumed once (like the source it wraps).
    Deliberately leaves ``stream_chunks`` unsupported: the peek consumed
    from the per-record view, so only that view is coherent."""

    def __init__(self, first, rest, inner: UnboundedSource):
        self._first = first
        self._rest = rest
        self._inner = inner

    def stream(self):
        yield self._first
        yield from self._rest

    def schema(self):
        return self._inner.schema()


class _PeekedChunkSource(UnboundedSource):
    """Chunk-protocol analog of :class:`_PeekedSource`: re-yields the chunk
    peeked for the dim probe ahead of the same chunk iterator, preserving
    the columnar fast path through the streaming driver.  One-shot."""

    def __init__(self, first_chunk, rest, inner: UnboundedSource):
        self._first = first_chunk
        self._rest = rest
        self._inner = inner

    def stream_chunks(self, max_rows: Optional[int] = None):
        def all_chunks():
            yield self._first
            yield from self._rest

        if max_rows is None:
            return all_chunks()

        step = int(max_rows)

        def resliced():
            # honor the caller's chunk bound by re-slicing buffered chunks
            for ts, cols in all_chunks():
                for a in range(0, len(ts), step):
                    b = a + step
                    yield ts[a:b], {k: v[a:b] for k, v in cols.items()}

        return resliced()

    def stream(self):
        from flink_ml_tpu.table.sources import chunk_row_iter

        schema = self.schema()
        for ts, cols in self.stream_chunks():
            yield from chunk_row_iter(ts, cols, schema)

    def schema(self):
        return self._inner.schema()


class OnlineLogisticRegression(Estimator, GlmTrainParams, HasWindowMs, HasAllowedLateness):
    """Streaming binary LR: one SGD step per fired event-time window.

    ``fit`` consumes a *bounded* table by replaying it as a timestamped
    stream (useful for tests); ``fit_unbounded`` is the real entry point:
    it drives training and optional concurrent prediction sources and
    returns the final model plus the full :class:`StreamingResult`
    (per-record predictions, model history, windows fired).
    """

    def __init__(self):
        super().__init__()
        self._dim: Optional[int] = None

    # -- feature packing for a window ---------------------------------------

    def _window_xyw(
        self, table: Table
    ) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Padded (X, y, w) for one fired window, or ``None`` for a window
        with no usable rows.

        A live label stream carries junk — null vectors, wrong-width
        vectors, NaN labels — and a window must never crash the loop or
        perturb the model over rows that cannot train.  Degenerate rows
        are ZEROED and weighted 0 (zeroing matters: a NaN feature times a
        0 weight is still NaN through the gradient), so the surviving
        rows' update is bit-identical to a window that never held the bad
        rows; a window with nothing usable returns ``None`` and the
        update skips it (counted, never an all-zero dispatch: with L2 on,
        a zero-weight dispatch would still decay the params toward an
        all-zero candidate).
        """
        n = table.num_rows()
        if n == 0:
            return None
        try:
            X, _ = resolve_features(table, self, dim=self._dim)
            X = np.asarray(X, dtype=np.float64)
            row_ok = np.ones(n, dtype=bool)
        except Exception:  # noqa: BLE001 - degenerate rows: rebuild row-wise
            if self.get_vector_col() is not None:
                dim = self._dim
                col = table.col(self.get_vector_col())
                X = np.zeros((n, dim), dtype=np.float64)
                row_ok = np.zeros(n, dtype=bool)
                for i, v in enumerate(col):
                    try:
                        arr = np.asarray(v.to_dense().values,
                                         dtype=np.float64)
                    except Exception:  # noqa: BLE001 - null / not a vector
                        continue
                    if arr.shape != (dim,):
                        continue
                    X[i] = arr
                    row_ok[i] = True
            else:
                # featureCols layout: junk cells coerce to NaN and the
                # finite-row mask below drops them
                X = np.column_stack([
                    [_f64_or_nan(v) for v in table.col(c)]
                    for c in self.get_feature_cols()
                ])
                row_ok = np.ones(n, dtype=bool)
        raw_y = table.col(self.get_label_col())
        if isinstance(raw_y, np.ndarray) and raw_y.dtype != object:
            y = np.asarray(raw_y, dtype=np.float64)
        else:
            y = np.array([_f64_or_nan(v) for v in raw_y], dtype=np.float64)
        mask = row_ok & np.isfinite(y) & np.all(np.isfinite(X), axis=1)
        kept = int(mask.sum())
        if kept < n:
            obs.counter_add("online.dropped_rows", n - kept)
        if kept == 0:
            return None
        X[~mask] = 0.0
        y = np.where(mask, y, 0.0)
        b = bucket_rows(n, 64)
        Xp = np.zeros((b, X.shape[1]), dtype=np.float32)
        yp = np.zeros((b,), dtype=np.float32)
        wp = np.zeros((b,), dtype=np.float32)
        Xp[:n], yp[:n], wp[:n] = X, y, mask.astype(np.float32)
        return Xp, yp, wp

    def _infer_dim(self, source: UnboundedSource) -> Tuple[int, UnboundedSource]:
        """Feature dim + the source to actually train from.

        When the dim comes from peeking the first record, the peeked record is
        buffered and re-yielded ahead of the same iterator — the UnboundedSource
        contract does not promise ``stream()`` is re-iterable, and a
        single-pass source (socket/queue-backed) must not lose its first
        training record to the probe.
        """
        if self.get_feature_cols() is not None:
            return len(self.get_feature_cols()), source
        chunks = (
            source.stream_chunks()
            if hasattr(source, "stream_chunks") else None
        )
        if chunks is not None:
            # probe from the chunk view so the driver's vectorized ingest
            # path stays available downstream
            it = iter(chunks)
            first = next(it, None)
            while first is not None and len(first[0]) == 0:
                first = next(it, None)
            if first is None:
                raise ValueError(
                    "empty training stream; cannot infer feature dim"
                )
            schema = source.schema()
            # canonical name: chunk columns are keyed by schema field names,
            # the param lookup is case-insensitive (TableUtil.findColIndex)
            name = schema.field_names[
                schema.find_col_index(self.get_vector_col())
            ]
            col = first[1][name]
            if isinstance(col, np.ndarray) and col.ndim == 2:
                dim = int(col.shape[1])
            else:
                v = col[0]
                dim = v.size() if v.size() >= 0 else v.to_dense().size()
            return dim, _PeekedChunkSource(first, it, source)
        it = iter(source.stream())
        try:
            first = next(it)
        except StopIteration:
            raise ValueError("empty training stream; cannot infer feature dim")
        i = source.schema().find_col_index(self.get_vector_col())
        v = first[1][i]
        dim = v.size() if v.size() >= 0 else v.to_dense().size()
        return dim, _PeekedSource(first, it, source)

    # -- streaming fit -------------------------------------------------------

    def fit_unbounded(
        self,
        training_source: UnboundedSource,
        prediction_source: Optional[UnboundedSource] = None,
        max_windows: Optional[int] = None,
        keep_model_history: bool = False,
        checkpoint=None,
        window_hook=None,
    ) -> Tuple[LogisticRegressionModel, StreamingResult]:
        # the streaming path compiles bare jits without building a mesh, so
        # it must finish the deferred compile-cache decision itself (the
        # mesh layer's hook never runs here)
        from flink_ml_tpu.utils.compile_cache import (
            ensure_compilation_cache_for_backend,
        )

        ensure_compilation_cache_for_backend()
        self._dim, training_source = self._infer_dim(training_source)
        lr = self.get_learning_rate()
        reg = self.get_reg()
        grad_fn = _log_loss_grads(self.get_with_intercept())

        sgd_update = make_sgd_update(lr, reg)

        @jax.jit
        def sgd_step(params, x, y, w):
            grads, _, w_sum = grad_fn(params, x, y, w)
            return sgd_update(params, grads, jnp.maximum(w_sum, 1.0))

        @jax.jit
        def score(params, x):
            w, b = params
            return x @ w + b

        def update(state, window_table: Table, epoch: int):
            xyw = self._window_xyw(window_table)
            if xyw is None:
                # nothing trainable in the window: skip, count, keep
                # streaming — the returned state is the SAME object, which
                # window hooks use to tell a skip from a real step
                obs.counter_add("online.skipped_windows")
                new_state = state
            else:
                x, y, w = xyw
                new_state = sgd_step(
                    state, jnp.asarray(x), jnp.asarray(y), jnp.asarray(w)
                )
            if window_hook is not None:
                # the continuous-learning controller's tap (ISSUE 14): a
                # non-None return REPLACES the trainer state — how a
                # poisoned run is reset to the last good candidate
                replacement = window_hook(epoch, new_state)
                if replacement is not None:
                    new_state = replacement
            return new_state

        # host mirror of the freshest reachable params for the CPU fallback:
        # the live ``state`` is a device pytree, and pulling it during an
        # outage is itself a device call — the fallback must score from
        # memory the dead accelerator cannot take down.  Refreshed on every
        # fallback while the device still answers D2H; when even that fails,
        # the last-reachable model serves (stale-model degraded semantics).
        host_params = {
            "w": np.zeros((self._dim,), dtype=np.float32),
            "b": np.float32(0.0),
        }

        def predict(state, batch_table: Table):
            from flink_ml_tpu import serve

            X, _ = resolve_features(batch_table, self, dim=self._dim)
            n = X.shape[0]
            b = bucket_rows(n, 64)
            Xp = np.zeros((b, X.shape[1]), dtype=np.float32)
            Xp[:n] = X

            def cpu_scores():
                try:
                    host_params["w"], host_params["b"] = (
                        np.asarray(state[0], np.float32),
                        np.float32(np.asarray(state[1])),
                    )
                except Exception:  # noqa: BLE001 - D2H died with the device
                    pass
                return Xp[:n] @ host_params["w"] + host_params["b"]

            scores = serve.dispatch(
                "OnlineLogisticRegression.predict",
                device=lambda: np.asarray(score(state, jnp.asarray(Xp)))[:n],
                fallback=cpu_scores,
            )
            return (scores > 0).astype(np.float64)

        params0 = (
            jnp.zeros((self._dim,), dtype=jnp.float32),
            jnp.zeros((), dtype=jnp.float32),
        )
        driver = StreamingDriver(
            window_ms=self.get_window_ms(),
            keep_model_history=keep_model_history,
            allowed_lateness_ms=self.get_allowed_lateness_ms(),
        )
        # an explicit CheckpointConfig wins over the param-derived one
        if checkpoint is None and self.get_checkpoint_dir() is not None:
            from flink_ml_tpu.iteration.checkpoint import CheckpointConfig

            checkpoint = CheckpointConfig(
                directory=self.get_checkpoint_dir(),
                every_n_epochs=self.get_checkpoint_interval(),
            )
        result = driver.run(
            params0,
            training_source,
            update,
            prediction_source=prediction_source,
            predict=predict if prediction_source is not None else None,
            max_windows=max_windows,
            checkpoint=checkpoint,
        )
        w, b = (np.asarray(a) for a in result.final_state)
        model = LogisticRegressionModel()
        model.get_params().merge(self.get_params())
        model.set_model_data(make_model_table(w, float(b)))
        model.windows_fired_ = result.windows_fired
        model.train_metrics_ = result.metrics
        obs.fit_report(
            type(self).__name__,
            step_metrics=result.metrics,
            extra={"windows_fired": result.windows_fired},
        )
        return model, result

    # -- bounded convenience (replay a table as a stream) --------------------

    def fit(self, *inputs: Table) -> LogisticRegressionModel:
        from flink_ml_tpu.table.sources import GeneratorSource

        (table,) = inputs
        rows = table.to_rows()
        # spread rows uniformly so each window holds ~globalBatchSize rows
        per_window = self.get_global_batch_size() or 32
        interval = max(1, self.get_window_ms() // per_window)
        source = GeneratorSource.linear_timestamps(rows, interval, table.schema)
        model, _ = self.fit_unbounded(source)
        return model
