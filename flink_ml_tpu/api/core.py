"""Core pipeline node hierarchy.

Parity map (flink-ml-api/.../api/core/):
  Stage.java:37-44            -> Stage (save/load contract, params holder)
  Estimator.java:31-39        -> Estimator.fit(*tables) -> Model
  AlgoOperator.java:153-161   -> AlgoOperator.transform(*tables) -> tuple[Table]
  Transformer.java:70-71      -> Transformer (1-in/1-out marker; transform1)
  Model.java:102-122          -> Model (set_model_data/get_model_data, default
                                 unsupported, exactly like the reference)

save/load layout per stage directory:
  stage.json   {"module": ..., "class": ..., "params": <Params json>}
  model data   whatever save_model_data writes (tables via utils.persistence)

Loading follows the reference's static-`load`-by-convention (Stage.java:41-43):
``load_stage(path)`` imports the recorded class and calls its ``load``
classmethod (the base implementation restores params + model data).
"""

from __future__ import annotations

import importlib
import json
import os
from typing import Tuple

from flink_ml_tpu.params import Params, WithParams
from flink_ml_tpu.table.table import Table

_STAGE_FILE = "stage.json"


class Stage(WithParams):
    """Root of the pipeline node hierarchy; serializable via save/load."""

    def save(self, path: str) -> None:
        from flink_ml_tpu.serve.integrity import atomic_json_dump

        os.makedirs(path, exist_ok=True)
        meta = {
            "module": type(self).__module__,
            "class": type(self).__qualname__,
            "params": self.get_params().to_json(),
        }
        # model data first, descriptor last-as-commit (atomic tmp+rename):
        # a crash mid-save leaves no stage.json, which load reports as
        # corruption instead of resolving a stage with half-written data
        self.save_model_data(path)
        atomic_json_dump(meta, os.path.join(path, _STAGE_FILE))

    @classmethod
    def load(cls, path: str) -> "Stage":
        from flink_ml_tpu.serve.errors import ModelIntegrityError

        descriptor = os.path.join(path, _STAGE_FILE)
        try:
            with open(descriptor) as f:
                meta = json.load(f)
            # field access inside the guard: a parseable-but-wrong
            # descriptor (partial overwrite, a JSON list) is the same
            # corruption contract as an unparseable one
            module, qualname = meta["module"], meta["class"]
            params_json = meta["params"]
        except FileNotFoundError:
            raise ModelIntegrityError(
                f"{path!r} has no {_STAGE_FILE} — not a saved stage, or a "
                "save that died before its commit descriptor was written"
            ) from None
        except (ValueError, KeyError, TypeError) as e:
            raise ModelIntegrityError(
                f"stage descriptor {descriptor!r} is unreadable ({e}); "
                "the saved stage is corrupt"
            ) from e
        klass = _resolve_class(module, qualname)
        if not issubclass(klass, Stage):
            raise TypeError(f"{klass} is not a Stage")
        # the static-load convention (Stage.java:41-43): a class owning its
        # persistence layout (Pipeline/PipelineModel nest stage dirs)
        # overrides load — delegate so Stage.load(path) works uniformly on
        # any saved stage
        if getattr(klass.load, "__func__", None) is not Stage.load.__func__:
            return klass.load(path)
        stage = klass.__new__(klass)
        Stage.__init__(stage)  # params container
        stage._params = Params.from_json(params_json)
        stage.load_model_data(path)
        return stage

    # hooks for stages that carry model data
    def save_model_data(self, path: str) -> None:
        pass

    def load_model_data(self, path: str) -> None:
        pass


class AlgoOperator(Stage):
    """Multi-in/multi-out relational compute (AlgoOperator.java:153-161)."""

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        raise NotImplementedError

    def transform1(self, table: Table) -> Table:
        """Convenience for the ubiquitous 1-in/1-out case."""
        out = self.transform(table)
        if len(out) != 1:
            raise ValueError(f"expected exactly one output table, got {len(out)}")
        return out[0]


class Transformer(AlgoOperator):
    """Marker: row-wise 1-in/1-out semantics (Transformer.java:70-71)."""

    def transform_chunks(self, chunked_table):
        """Streamed inference: score a ChunkedTable chunk by chunk, yielding
        one output Table per input chunk.

        The out-of-core counterpart of ``transform`` — works for any
        Transformer (PipelineModel included): per-chunk transforms reuse
        whatever device state the stage caches, and host residency stays
        bounded by one chunk, so files larger than RAM score end-to-end.
        Feed the iterator to
        :func:`flink_ml_tpu.utils.persistence.write_csv_chunks` to stream
        results straight to disk.
        """
        for chunk in chunked_table.chunks():
            yield self.transform1(chunk)  # asserts the 1-in/1-out contract


class Model(Transformer):
    """A Transformer with attached model data (Model.java:102-122)."""

    def set_model_data(self, *inputs: Table) -> "Model":
        raise NotImplementedError(
            f"{type(self).__name__} does not support set_model_data"
        )

    def get_model_data(self) -> Tuple[Table, ...]:
        raise NotImplementedError(
            f"{type(self).__name__} does not support get_model_data"
        )


class Estimator(Stage):
    """fit(*tables) -> Model (Estimator.java:31-39)."""

    def fit(self, *inputs: Table) -> Model:
        raise NotImplementedError


def load_stage(path: str) -> Stage:
    """Load any saved stage by the recorded class (static-load convention).

    ``Stage.load`` already resolves the recorded class and delegates to its
    override — this name remains as the discoverable module-level entry."""
    return Stage.load(path)


def _resolve_class(module: str, qualname: str):
    try:
        mod = importlib.import_module(module)
        obj = mod
        for part in qualname.split("."):
            obj = getattr(obj, part)
        return obj
    except (ImportError, AttributeError) as e:
        hint = (
            " (the stage class was defined in __main__; define stages in an "
            "importable module to reload them from another process)"
            if module == "__main__"
            else ""
        )
        raise ImportError(
            f"cannot resolve stage class {module}.{qualname}{hint}"
        ) from e
