"""Pipeline and PipelineModel.

``Pipeline.fit`` reproduces the reference's chaining algorithm exactly
(Pipeline.java:69-97): find the last Estimator; walk stages, reusing
AlgoOperators as-is and fitting Estimators; feed each produced model's
transform output forward only while an Estimator still lies ahead.
``PipelineModel.transform`` applies stages sequentially
(PipelineModel.java:53-59).

save/load — implemented (the reference throws, Pipeline.java:100-106):
a pipeline directory holds ``pipeline.json`` plus one numbered subdirectory
per stage, each saved via the Stage contract.
"""

from __future__ import annotations

import json
import os
from typing import List, Sequence, Tuple

from flink_ml_tpu.api.core import (
    AlgoOperator,
    Estimator,
    Model,
    Stage,
    Transformer,
    load_stage,
)
from flink_ml_tpu.table.table import Table

_PIPELINE_FILE = "pipeline.json"


class Pipeline(Estimator):
    """An Estimator composed of stages (Estimators / Transformers / AlgoOperators)."""

    def __init__(self, stages: Sequence[Stage] = ()):
        self.stages: List[Stage] = list(stages)

    def append_stage(self, stage: Stage) -> "Pipeline":
        self.stages.append(stage)
        return self

    def fit(self, *inputs: Table) -> "PipelineModel":
        last_estimator_idx = -1
        for i, stage in enumerate(self.stages):
            if isinstance(stage, Estimator):
                last_estimator_idx = i

        if (
            len(inputs) == 1
            and getattr(inputs[0], "is_chunked", False)
            and getattr(inputs[0], "spill", False)
            and sum(isinstance(s, Estimator) for s in self.stages) > 1
        ):
            # multi-stage chunked fit: each estimator's fit is a full
            # stream pass over the same source — share ONE binary replay
            # cache across the whole chain so the text parse runs once
            # (out-of-core rule: never pay a read twice)
            from flink_ml_tpu.table.sources import chunk_cache

            with chunk_cache(inputs[0]) as cached:
                return self._fit_stages((cached,), last_estimator_idx)
        return self._fit_stages(inputs, last_estimator_idx)

    def _fit_stages(self, inputs, last_estimator_idx: int) -> "PipelineModel":
        model_stages: List[AlgoOperator] = []
        last_inputs = inputs
        for i, stage in enumerate(self.stages):
            if isinstance(stage, Estimator):
                model_stage: AlgoOperator = stage.fit(*last_inputs)
            elif isinstance(stage, AlgoOperator):
                model_stage = stage
            else:
                raise TypeError(
                    f"stage {i} ({type(stage).__name__}) is neither Estimator nor AlgoOperator"
                )
            model_stages.append(model_stage)
            if i < last_estimator_idx:
                if len(last_inputs) == 1 and getattr(
                    last_inputs[0], "is_chunked", False
                ):
                    # out-of-core forwarding: wrap instead of materializing,
                    # so each downstream epoch streams base chunks through
                    # this stage's transform1 (host residency = one chunk)
                    from flink_ml_tpu.table.sources import TransformedChunkedTable

                    if not isinstance(model_stage, Transformer):
                        raise TypeError(
                            f"stage {i} ({type(model_stage).__name__}) cannot "
                            "forward a chunked input: only Transformers (1-in/"
                            "1-out) support streamed transform_chunks"
                        )
                    last_inputs = (
                        TransformedChunkedTable(last_inputs[0], model_stage),
                    )
                else:
                    last_inputs = model_stage.transform(*last_inputs)
        return PipelineModel(model_stages)

    # -- persistence ---------------------------------------------------------

    def save(self, path: str) -> None:
        _save_stages(self.stages, path, kind="Pipeline")

    @classmethod
    def load(cls, path: str) -> "Pipeline":
        _check_kind(path, "Pipeline")
        _, stages = _load_stages(path)
        return Pipeline(stages)


class PipelineModel(Model):
    """A Model composed of stages; sequential transform (PipelineModel.java:53-59).

    When ``FMT_FUSE_TRANSFORM`` is on (the default), transform routes
    through the fused inference planner (`common/fused.py`): maximal runs
    of kernel-capable mappers compile into ONE device dispatch per batch
    with the vector columns held device-resident across stages, and
    anything the planner cannot fuse — kernel-less mappers, AlgoOperators,
    a tripped per-plan breaker — serves through this sequential path in
    place, bit-identical on discrete outputs."""

    def __init__(self, stages: Sequence[AlgoOperator] = ()):
        self.stages: List[AlgoOperator] = list(stages)

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        from flink_ml_tpu import obs
        from flink_ml_tpu.common import fused
        from flink_ml_tpu.common.mapper import pipeline_reap_scope

        # one slab-pool reap for the WHOLE chain (stage applies inside the
        # scope skip theirs — an S-stage pipeline must not pay S reaps);
        # per-transform serve accounting wraps the chain the same way the
        # single-model transform does
        with pipeline_reap_scope():
            serve0 = None
            if obs.enabled():
                from flink_ml_tpu.serve import serve_counter_snapshot

                serve0 = serve_counter_snapshot()
            # top-level transforms root a trace (FMT_TRACE); inside a
            # served batch this degrades to a child span under the
            # dispatcher's handed-off request context(s).  Same rule for
            # the drift scope (FMT_DRIFT, ISSUE 11): the OUTERMOST
            # transform owns the tap scope, so stage transforms inside
            # this chain never double-sketch the same rows.
            with obs.drift.transform_scope() as dscope:
                with obs.trace.root_span("pipeline", {
                    "stages": len(self.stages),
                }):
                    if len(inputs) == 1 and isinstance(inputs[0], Table) \
                            and len(self.stages) > 1 \
                            and fused.fusion_enabled():
                        out = fused.transform_fused(self, inputs)
                    else:
                        out = inputs
                        for stage in self.stages:
                            out = stage.transform(*out)
                if dscope is not None and len(inputs) == 1 \
                        and isinstance(inputs[0], Table) \
                        and len(out) == 1 and isinstance(out[0], Table):
                    # produced (score/prediction) columns into the live
                    # window, input columns excluded
                    dscope.observe_scores(
                        out[0],
                        exclude=frozenset(inputs[0].schema.field_names),
                    )
            if serve0 is not None and len(inputs) == 1 \
                    and isinstance(inputs[0], Table):
                from flink_ml_tpu.obs.report import transform_report
                from flink_ml_tpu.serve import serve_counter_delta

                transform_report(
                    type(self).__name__, rows=inputs[0].num_rows(),
                    serve_delta=serve_counter_delta(serve0),
                )
        return out

    def save(self, path: str) -> None:
        _save_stages(self.stages, path, kind="PipelineModel")

    @classmethod
    def load(cls, path: str) -> "PipelineModel":
        _check_kind(path, "PipelineModel")
        _, stages = _load_stages(path)
        return PipelineModel(stages)


def _save_stages(stages: Sequence[Stage], path: str, kind: str) -> None:
    from flink_ml_tpu.serve.integrity import atomic_json_dump

    os.makedirs(path, exist_ok=True)
    # stage dirs first, descriptors last: pipeline.json is the commit
    # record of the whole save — a crash mid-save leaves stage dirs
    # without a descriptor, which load reports as corruption instead of
    # loading a partial pipeline
    for i, stage in enumerate(stages):
        stage.save(os.path.join(path, f"stage_{i:03d}"))
    # the standard stage descriptor so a pipeline nests inside another
    # pipeline and load_stage() resolves it uniformly
    container = Pipeline if kind == "Pipeline" else PipelineModel
    atomic_json_dump(
        {"module": container.__module__, "class": container.__qualname__,
         "params": "{}"},
        os.path.join(path, "stage.json"),
    )
    atomic_json_dump(
        {"kind": kind, "num_stages": len(stages)},
        os.path.join(path, _PIPELINE_FILE),
    )


def _check_kind(path: str, expected: str) -> None:
    from flink_ml_tpu.serve.errors import ModelIntegrityError

    descriptor = os.path.join(path, _PIPELINE_FILE)
    try:
        with open(descriptor) as f:
            kind = json.load(f)["kind"]
    except FileNotFoundError:
        raise ModelIntegrityError(
            f"{path!r} has no {_PIPELINE_FILE} — not a saved pipeline, or "
            "a save that died before its commit descriptor was written"
        ) from None
    except (ValueError, KeyError, TypeError) as e:
        raise ModelIntegrityError(
            f"pipeline descriptor {descriptor!r} is unreadable ({e}); "
            "the saved pipeline is corrupt"
        ) from e
    if kind != expected:
        raise ValueError(f"{path} holds a {kind}, not a {expected}")


def _load_stages(path: str) -> Tuple[str, List[Stage]]:
    from flink_ml_tpu.serve.errors import ModelIntegrityError

    descriptor = os.path.join(path, _PIPELINE_FILE)
    try:
        with open(descriptor) as f:
            meta = json.load(f)
        kind, num_stages = meta["kind"], int(meta["num_stages"])
    except (ValueError, KeyError, TypeError) as e:
        raise ModelIntegrityError(
            f"pipeline descriptor {descriptor!r} is unreadable ({e}); "
            "the saved pipeline is corrupt"
        ) from e
    stages = []
    for i in range(num_stages):
        stage_dir = os.path.join(path, f"stage_{i:03d}")
        if not os.path.isdir(stage_dir):
            raise ModelIntegrityError(
                f"saved pipeline {path!r} promises {num_stages} stages "
                f"but {stage_dir!r} is missing — partial save or deleted "
                "stage directory"
            )
        stages.append(load_stage(stage_dir))
    return kind, stages
