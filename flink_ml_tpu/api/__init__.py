"""api — the Pipeline layer (flink-ml-api core parity).

Stage/Estimator/Transformer/Model/AlgoOperator protocols, Pipeline with the
reference's exact fit-chaining algorithm (Pipeline.java:69-97), PipelineModel
sequential transform (PipelineModel.java:53-59), and *working* save/load —
the contract the reference declared (Stage.java:39-43) but left throwing
(Pipeline.java:100-106, PipelineModel.java:61-68).
"""

from flink_ml_tpu.api.core import (  # noqa: F401
    AlgoOperator,
    Estimator,
    Model,
    Stage,
    Transformer,
    load_stage,
)
from flink_ml_tpu.api.pipeline import Pipeline, PipelineModel  # noqa: F401
