// Native data-plane loaders.
//
// The reference's only native boundary is the math kernel (netlib BLAS via
// flink-ml-lib, BLAS.java:28-41); on TPU that role is played by XLA.  The
// runtime component that still deserves native code here is ingestion: CSV
// and LibSVM parsing is pure host CPU work on the training path (SURVEY.md
// §7.1 'bounded sources'), and the Python fallbacks are interpreter-bound.
//
// Exposed via a plain C ABI consumed with ctypes (no pybind11 in this
// environment):
//   fml_read_csv     -> one malloc'd buffer: rows joined by \x1e, cells by
//                       \x1f (RFC-4180 quoting handled here; Python does two
//                       C-speed splits to materialize cells)
//   fml_read_libsvm  -> CSR triplet buffers (labels / indptr / indices /
//                       values) ready to wrap as numpy arrays
//   fml_free         -> release any buffer returned by the calls above

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

// Read a whole file into a string; empty string on failure (len 0).
static bool read_file(const char* path, std::string& out) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return false;
    std::fseek(f, 0, SEEK_END);
    long size = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    if (size < 0) {
        std::fclose(f);
        return false;
    }
    out.resize(static_cast<size_t>(size));
    size_t got = size ? std::fread(&out[0], 1, static_cast<size_t>(size), f) : 0;
    std::fclose(f);
    out.resize(got);
    return true;
}

}  // namespace

extern "C" {

void fml_free(void* p) { std::free(p); }

// Parse CSV with RFC-4180 double-quote semantics.  Returns a buffer of
// rows separated by \x1e whose cells are separated by \x1f, or nullptr on
// I/O error (*out_len = 0) or when the data itself contains the separator
// control bytes 0x1E/0x1F (*out_len = -2: legal in quoted cells but not
// representable in this transport — the caller falls back to the pure
// parser).  Otherwise *out_len receives the buffer length.
char* fml_read_csv(const char* path, char delim, int skip_header,
                   int64_t* out_len) {
    *out_len = 0;
    std::string data;
    if (!read_file(path, data)) return nullptr;
    if (data.find('\x1e') != std::string::npos ||
        data.find('\x1f') != std::string::npos) {
        *out_len = -2;
        return nullptr;
    }

    std::string out;
    out.reserve(data.size() + data.size() / 8);

    size_t i = 0;
    const size_t n = data.size();
    bool row_started = false;
    bool skipping = skip_header != 0;

    while (i < n) {
        // parse one cell
        std::string cell;
        if (data[i] == '"') {
            ++i;
            while (i < n) {
                if (data[i] == '"') {
                    if (i + 1 < n && data[i + 1] == '"') {
                        cell.push_back('"');
                        i += 2;
                    } else {
                        ++i;
                        break;
                    }
                } else {
                    cell.push_back(data[i++]);
                }
            }
        } else {
            while (i < n && data[i] != delim && data[i] != '\n' && data[i] != '\r') {
                cell.push_back(data[i++]);
            }
        }
        if (!skipping) {
            if (row_started) out.push_back('\x1f');
            out += cell;
            row_started = true;
        }
        // cell terminator
        if (i < n && data[i] == delim) {
            ++i;
            continue;
        }
        // row terminator (handle \r\n and \n)
        if (i < n && data[i] == '\r') ++i;
        if (i < n && data[i] == '\n') ++i;
        if (skipping) {
            skipping = false;
        } else if (row_started) {
            out.push_back('\x1e');
            row_started = false;
        }
    }
    if (row_started) out.push_back('\x1e');

    char* buf = static_cast<char*>(std::malloc(out.size() ? out.size() : 1));
    if (!buf) return nullptr;
    std::memcpy(buf, out.data(), out.size());
    *out_len = static_cast<int64_t>(out.size());
    return buf;
}

// Parse LibSVM/SVMlight text into CSR buffers.  '#' starts a comment.
// Returns 0 on success, -1 on I/O error, -2 on parse error.
int fml_read_libsvm(const char* path, int zero_based, double** out_labels,
                    int64_t** out_indptr, int64_t** out_indices,
                    double** out_values, int64_t* out_rows, int64_t* out_nnz,
                    int64_t* out_max_idx) {
    std::string data;
    if (!read_file(path, data)) return -1;

    std::vector<double> labels;
    std::vector<int64_t> indptr(1, 0);
    std::vector<int64_t> indices;
    std::vector<double> values;
    int64_t max_idx = -1;
    const int64_t offset = zero_based ? 0 : 1;

    const char* p = data.c_str();
    const char* end = p + data.size();
    while (p < end) {
        // one line
        const char* line_end = static_cast<const char*>(
            std::memchr(p, '\n', static_cast<size_t>(end - p)));
        if (!line_end) line_end = end;
        const char* hash = static_cast<const char*>(
            std::memchr(p, '#', static_cast<size_t>(line_end - p)));
        const char* stop = hash ? hash : line_end;

        // skip leading whitespace
        while (p < stop && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
        if (p < stop) {
            char* next = nullptr;
            double label = std::strtod(p, &next);
            if (next == p) return -2;
            labels.push_back(label);
            p = next;
            // idx:val pairs
            for (;;) {
                while (p < stop && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
                if (p >= stop) break;
                char* colon = nullptr;
                long long idx = std::strtoll(p, &colon, 10);
                if (colon == p || colon >= stop || *colon != ':') return -2;
                // the value must start right after ':' within this line —
                // strtod's own whitespace-skipping would otherwise walk past
                // the newline and silently consume the next line's label
                const char* vstart = colon + 1;
                if (vstart >= stop || *vstart == ' ' || *vstart == '\t' ||
                    *vstart == '\r' || *vstart == '\n') {
                    return -2;
                }
                char* after = nullptr;
                double val = std::strtod(vstart, &after);
                if (after == vstart || after > stop) return -2;
                int64_t j = static_cast<int64_t>(idx) - offset;
                if (j < 0) return -2;
                indices.push_back(j);
                values.push_back(val);
                if (j > max_idx) max_idx = j;
                p = after;
            }
            indptr.push_back(static_cast<int64_t>(indices.size()));
        }
        p = (line_end < end) ? line_end + 1 : end;
    }

    const size_t nr = labels.size();
    const size_t nz = indices.size();
    auto* lab = static_cast<double*>(std::malloc(sizeof(double) * (nr ? nr : 1)));
    auto* ptr = static_cast<int64_t*>(std::malloc(sizeof(int64_t) * (nr + 1)));
    auto* ind = static_cast<int64_t*>(std::malloc(sizeof(int64_t) * (nz ? nz : 1)));
    auto* val = static_cast<double*>(std::malloc(sizeof(double) * (nz ? nz : 1)));
    if (!lab || !ptr || !ind || !val) {
        std::free(lab); std::free(ptr); std::free(ind); std::free(val);
        return -1;
    }
    if (nr) std::memcpy(lab, labels.data(), sizeof(double) * nr);
    std::memcpy(ptr, indptr.data(), sizeof(int64_t) * (nr + 1));
    if (nz) std::memcpy(ind, indices.data(), sizeof(int64_t) * nz);
    if (nz) std::memcpy(val, values.data(), sizeof(double) * nz);
    *out_labels = lab;
    *out_indptr = ptr;
    *out_indices = ind;
    *out_values = val;
    *out_rows = static_cast<int64_t>(nr);
    *out_nnz = static_cast<int64_t>(nz);
    *out_max_idx = max_idx;
    return 0;
}

}  // extern "C"
