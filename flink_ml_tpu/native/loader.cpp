// Native data-plane loaders.
//
// The reference's only native boundary is the math kernel (netlib BLAS via
// flink-ml-lib, BLAS.java:28-41); on TPU that role is played by XLA.  The
// runtime component that still deserves native code here is ingestion: CSV
// and LibSVM parsing is pure host CPU work on the training path (SURVEY.md
// §7.1 'bounded sources'), and the Python fallbacks are interpreter-bound.
//
// Exposed via a plain C ABI consumed with ctypes (no pybind11 in this
// environment):
//   fml_read_csv     -> one malloc'd buffer: rows joined by \x1e, cells by
//                       \x1f (RFC-4180 quoting handled here; Python does two
//                       C-speed splits to materialize cells)
//   fml_read_libsvm  -> CSR triplet buffers (labels / indptr / indices /
//                       values) ready to wrap as numpy arrays
//   fml_free         -> release any buffer returned by the calls above
//
// Streaming handles (the out-of-core path — bounded memory, one chunk of
// rows per call, files never fully materialized):
//   fml_open_libsvm_stream / fml_next_libsvm_chunk / fml_close_libsvm_stream
//       -> per-chunk CSR triplets, identical row semantics to fml_read_libsvm
//   fml_open_csv_stream / fml_next_csv_doubles / fml_close_csv_stream
//       -> per-chunk (rows x arity) double matrix for all-numeric schemas
//          (RFC-4180 quoting honored; empty/null cells parse as NaN); the
//          common dense-ML case skips per-cell Python entirely

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

// Read a whole file into a string; empty string on failure (len 0).
static bool read_file(const char* path, std::string& out) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return false;
    std::fseek(f, 0, SEEK_END);
    long size = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    if (size < 0) {
        std::fclose(f);
        return false;
    }
    out.resize(static_cast<size_t>(size));
    size_t got = size ? std::fread(&out[0], 1, static_cast<size_t>(size), f) : 0;
    std::fclose(f);
    out.resize(got);
    return true;
}

static const size_t NPOS = static_cast<size_t>(-1);

// Incremental file reader: a bounded buffer of not-yet-consumed bytes.
struct TextStream {
    FILE* f = nullptr;
    std::string buf;
    size_t pos = 0;  // consumed prefix
    bool eof = false;

    bool refill() {
        if (eof) return false;
        if (pos > (1u << 20)) {  // compact so memory stays ~one block
            buf.erase(0, pos);
            pos = 0;
        }
        char tmp[1 << 16];
        size_t got = std::fread(tmp, 1, sizeof tmp, f);
        if (got == 0) {
            eof = true;
            return false;
        }
        buf.append(tmp, got);
        return true;
    }
};

// End (exclusive) of the first COMPLETE row at `from`, honoring RFC-4180
// quoting (newlines inside quoted cells are data); `next_pos` receives the
// offset past the row terminator.  NPOS = the buffer holds no complete row
// yet (caller refills) — boundary-ambiguous cases ("" split across a block
// edge, trailing \r) are treated as incomplete until eof.
static size_t find_row_end(const std::string& s, size_t from, bool eof,
                           size_t& next_pos) {
    bool in_quotes = false;
    size_t i = from;
    const size_t n = s.size();
    while (i < n) {
        char c = s[i];
        if (in_quotes) {
            if (c == '"') {
                if (i + 1 >= n) {
                    if (!eof) return NPOS;  // could be the first of ""
                    in_quotes = false;
                    ++i;
                    continue;
                }
                if (s[i + 1] == '"') {
                    i += 2;
                    continue;
                }
                in_quotes = false;
                ++i;
                continue;
            }
            ++i;
            continue;
        }
        if (c == '"') {
            in_quotes = true;
            ++i;
            continue;
        }
        if (c == '\n') {
            next_pos = i + 1;
            return i;
        }
        if (c == '\r') {
            if (i + 1 >= n && !eof) return NPOS;  // \r\n may span blocks
            next_pos = (i + 1 < n && s[i + 1] == '\n') ? i + 2 : i + 1;
            return i;
        }
        ++i;
    }
    return NPOS;
}

// One CSV row [p, e) -> doubles.  Empty / "null" cells parse as NaN.
// Returns false on a non-numeric cell.
static bool parse_double_cells(const char* p, const char* e, char delim,
                               std::vector<double>& out, int64_t* count) {
    int64_t c = 0;
    std::string cell;
    while (true) {
        cell.clear();
        if (p < e && *p == '"') {
            ++p;
            while (p < e) {
                if (*p == '"') {
                    if (p + 1 < e && p[1] == '"') {
                        cell.push_back('"');
                        p += 2;
                    } else {
                        ++p;
                        break;
                    }
                } else {
                    cell.push_back(*p++);
                }
            }
        } else {
            while (p < e && *p != delim) cell.push_back(*p++);
        }
        size_t b = cell.find_first_not_of(" \t");
        size_t t = cell.find_last_not_of(" \t");
        std::string trimmed =
            (b == std::string::npos) ? std::string() : cell.substr(b, t - b + 1);
        double v;
        if (trimmed.empty() || trimmed == "null" || trimmed == "NULL" ||
            trimmed == "Null") {
            v = std::nan("");
        } else {
            // strtod accepts forms Python's float() rejects (hex floats,
            // nan(payload)); reject those so the stream and read() agree —
            // legitimate decimals never contain 'x'/'X'/'('
            if (trimmed.find_first_of("xX(") != std::string::npos) return false;
            char* after = nullptr;
            v = std::strtod(trimmed.c_str(), &after);
            if (after != trimmed.c_str() + trimmed.size()) return false;
        }
        out.push_back(v);
        ++c;
        if (p < e && *p == delim) {
            ++p;
            continue;
        }
        break;
    }
    *count = c;
    return true;
}

// One LibSVM line [p, stop) into the accumulators.  Returns 0 = row added,
// 1 = blank/comment-only (skip), -2 = parse error.  Shared by the whole-file
// reader and the streaming chunk reader so their row semantics cannot drift.
static int parse_libsvm_line(const char* p, const char* stop, int64_t offset,
                             std::vector<double>& labels,
                             std::vector<int64_t>& indices,
                             std::vector<double>& values, int64_t* max_idx) {
    const char* hash =
        static_cast<const char*>(std::memchr(p, '#', static_cast<size_t>(stop - p)));
    if (hash) stop = hash;
    while (p < stop && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
    if (p >= stop) return 1;
    char* next = nullptr;
    double label = std::strtod(p, &next);
    if (next == p) return -2;
    labels.push_back(label);
    p = next;
    for (;;) {
        while (p < stop && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
        if (p >= stop) break;
        char* colon = nullptr;
        long long idx = std::strtoll(p, &colon, 10);
        if (colon == p || colon >= stop || *colon != ':') return -2;
        // the value must start right after ':' within this line — strtod's
        // own whitespace-skipping would otherwise walk past the newline and
        // silently consume the next line's label
        const char* vstart = colon + 1;
        if (vstart >= stop || *vstart == ' ' || *vstart == '\t' ||
            *vstart == '\r' || *vstart == '\n') {
            return -2;
        }
        char* after = nullptr;
        double val = std::strtod(vstart, &after);
        if (after == vstart || after > stop) return -2;
        int64_t j = static_cast<int64_t>(idx) - offset;
        if (j < 0) return -2;
        indices.push_back(j);
        values.push_back(val);
        if (j > *max_idx) *max_idx = j;
        p = after;
    }
    return 0;
}

template <typename T>
static T* copy_out(const std::vector<T>& v) {
    auto* out = static_cast<T*>(std::malloc(sizeof(T) * (v.empty() ? 1 : v.size())));
    if (out && !v.empty()) std::memcpy(out, v.data(), sizeof(T) * v.size());
    return out;
}

struct CsvStream {
    TextStream ts;
    char delim;
    bool skip_pending;
};

struct LibsvmStream {
    TextStream ts;
    int64_t offset;
};

}  // namespace

extern "C" {

void fml_free(void* p) { std::free(p); }

// Parse CSV with RFC-4180 double-quote semantics.  Returns a buffer of
// rows separated by \x1e whose cells are separated by \x1f, or nullptr on
// I/O error (*out_len = 0) or when the data itself contains the separator
// control bytes 0x1E/0x1F (*out_len = -2: legal in quoted cells but not
// representable in this transport — the caller falls back to the pure
// parser).  Otherwise *out_len receives the buffer length.
char* fml_read_csv(const char* path, char delim, int skip_header,
                   int64_t* out_len) {
    *out_len = 0;
    std::string data;
    if (!read_file(path, data)) return nullptr;
    if (data.find('\x1e') != std::string::npos ||
        data.find('\x1f') != std::string::npos) {
        *out_len = -2;
        return nullptr;
    }

    std::string out;
    out.reserve(data.size() + data.size() / 8);

    size_t i = 0;
    const size_t n = data.size();
    bool row_started = false;
    bool skipping = skip_header != 0;

    while (i < n) {
        // parse one cell
        std::string cell;
        if (data[i] == '"') {
            ++i;
            while (i < n) {
                if (data[i] == '"') {
                    if (i + 1 < n && data[i + 1] == '"') {
                        cell.push_back('"');
                        i += 2;
                    } else {
                        ++i;
                        break;
                    }
                } else {
                    cell.push_back(data[i++]);
                }
            }
        } else {
            while (i < n && data[i] != delim && data[i] != '\n' && data[i] != '\r') {
                cell.push_back(data[i++]);
            }
        }
        if (!skipping) {
            if (row_started) out.push_back('\x1f');
            out += cell;
            row_started = true;
        }
        // cell terminator
        if (i < n && data[i] == delim) {
            ++i;
            continue;
        }
        // row terminator (handle \r\n and \n)
        if (i < n && data[i] == '\r') ++i;
        if (i < n && data[i] == '\n') ++i;
        if (skipping) {
            skipping = false;
        } else if (row_started) {
            out.push_back('\x1e');
            row_started = false;
        }
    }
    if (row_started) out.push_back('\x1e');

    char* buf = static_cast<char*>(std::malloc(out.size() ? out.size() : 1));
    if (!buf) return nullptr;
    std::memcpy(buf, out.data(), out.size());
    *out_len = static_cast<int64_t>(out.size());
    return buf;
}

// Parse LibSVM/SVMlight text into CSR buffers.  '#' starts a comment.
// Returns 0 on success, -1 on I/O error, -2 on parse error.
int fml_read_libsvm(const char* path, int zero_based, double** out_labels,
                    int64_t** out_indptr, int64_t** out_indices,
                    double** out_values, int64_t* out_rows, int64_t* out_nnz,
                    int64_t* out_max_idx) {
    std::string data;
    if (!read_file(path, data)) return -1;

    std::vector<double> labels;
    std::vector<int64_t> indptr(1, 0);
    std::vector<int64_t> indices;
    std::vector<double> values;
    int64_t max_idx = -1;
    const int64_t offset = zero_based ? 0 : 1;

    const char* p = data.c_str();
    const char* end = p + data.size();
    while (p < end) {
        const char* line_end = static_cast<const char*>(
            std::memchr(p, '\n', static_cast<size_t>(end - p)));
        if (!line_end) line_end = end;
        int rc = parse_libsvm_line(p, line_end, offset, labels, indices,
                                   values, &max_idx);
        if (rc == -2) return -2;
        if (rc == 0) indptr.push_back(static_cast<int64_t>(indices.size()));
        p = (line_end < end) ? line_end + 1 : end;
    }

    const size_t nr = labels.size();
    const size_t nz = indices.size();
    auto* lab = static_cast<double*>(std::malloc(sizeof(double) * (nr ? nr : 1)));
    auto* ptr = static_cast<int64_t*>(std::malloc(sizeof(int64_t) * (nr + 1)));
    auto* ind = static_cast<int64_t*>(std::malloc(sizeof(int64_t) * (nz ? nz : 1)));
    auto* val = static_cast<double*>(std::malloc(sizeof(double) * (nz ? nz : 1)));
    if (!lab || !ptr || !ind || !val) {
        std::free(lab); std::free(ptr); std::free(ind); std::free(val);
        return -1;
    }
    if (nr) std::memcpy(lab, labels.data(), sizeof(double) * nr);
    std::memcpy(ptr, indptr.data(), sizeof(int64_t) * (nr + 1));
    if (nz) std::memcpy(ind, indices.data(), sizeof(int64_t) * nz);
    if (nz) std::memcpy(val, values.data(), sizeof(double) * nz);
    *out_labels = lab;
    *out_indptr = ptr;
    *out_indices = ind;
    *out_values = val;
    *out_rows = static_cast<int64_t>(nr);
    *out_nnz = static_cast<int64_t>(nz);
    *out_max_idx = max_idx;
    return 0;
}

// -- streaming (out-of-core) handles -----------------------------------------

void* fml_open_libsvm_stream(const char* path, int zero_based) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return nullptr;
    auto* s = new LibsvmStream;
    s->ts.f = f;
    s->offset = zero_based ? 0 : 1;
    return s;
}

// Up to max_rows rows as CSR triplets (caller frees all four buffers with
// fml_free).  Returns rows read (0 = end of file), -1 = alloc failure,
// -2 = parse error.
int64_t fml_next_libsvm_chunk(void* handle, int64_t max_rows,
                              double** out_labels, int64_t** out_indptr,
                              int64_t** out_indices, double** out_values,
                              int64_t* out_nnz, int64_t* out_max_idx) {
    auto* s = static_cast<LibsvmStream*>(handle);
    std::vector<double> labels;
    std::vector<int64_t> indptr(1, 0);
    std::vector<int64_t> indices;
    std::vector<double> values;
    int64_t max_idx = -1;

    while (static_cast<int64_t>(labels.size()) < max_rows) {
        const std::string& b = s->ts.buf;
        const char* base = b.c_str();
        const void* nl = (s->ts.pos < b.size())
            ? std::memchr(base + s->ts.pos, '\n', b.size() - s->ts.pos)
            : nullptr;
        size_t line_end, next_pos;
        if (nl != nullptr) {
            line_end = static_cast<const char*>(nl) - base;
            next_pos = line_end + 1;
        } else if (!s->ts.eof) {
            if (!s->ts.refill() && s->ts.pos >= s->ts.buf.size()) break;
            continue;
        } else if (s->ts.pos < b.size()) {
            line_end = b.size();  // final unterminated line
            next_pos = line_end;
        } else {
            break;  // fully consumed
        }
        int rc = parse_libsvm_line(base + s->ts.pos, base + line_end,
                                   s->offset, labels, indices, values,
                                   &max_idx);
        if (rc == -2) return -2;
        if (rc == 0) indptr.push_back(static_cast<int64_t>(indices.size()));
        s->ts.pos = next_pos;
    }

    *out_labels = copy_out(labels);
    *out_indptr = copy_out(indptr);
    *out_indices = copy_out(indices);
    *out_values = copy_out(values);
    if (!*out_labels || !*out_indptr || !*out_indices || !*out_values) {
        std::free(*out_labels);
        std::free(*out_indptr);
        std::free(*out_indices);
        std::free(*out_values);
        return -1;
    }
    *out_nnz = static_cast<int64_t>(values.size());
    *out_max_idx = max_idx;
    return static_cast<int64_t>(labels.size());
}

void fml_close_libsvm_stream(void* handle) {
    auto* s = static_cast<LibsvmStream*>(handle);
    if (s) {
        if (s->ts.f) std::fclose(s->ts.f);
        delete s;
    }
}

void* fml_open_csv_stream(const char* path, char delim, int skip_header) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return nullptr;
    auto* s = new CsvStream;
    s->ts.f = f;
    s->delim = delim;
    s->skip_pending = skip_header != 0;
    return s;
}

// Up to max_rows rows of an all-numeric CSV as one (rows x arity) row-major
// double buffer (caller frees with fml_free).  Returns rows read (0 = end
// of file), -1 = alloc failure, -2 = non-numeric cell or arity mismatch
// (the Python caller falls back to the pure parser, skipping the rows this
// handle already delivered).
int64_t fml_next_csv_doubles(void* handle, int64_t max_rows, int64_t arity,
                             double** out) {
    auto* s = static_cast<CsvStream*>(handle);
    std::vector<double> vals;
    vals.reserve(static_cast<size_t>(max_rows * arity));
    int64_t rows = 0;

    while (rows < max_rows) {
        size_t next_pos = 0;
        size_t row_end = find_row_end(s->ts.buf, s->ts.pos, s->ts.eof, next_pos);
        if (row_end == NPOS) {
            if (s->ts.refill()) continue;
            if (s->ts.pos >= s->ts.buf.size()) break;
            row_end = s->ts.buf.size();  // final unterminated row
            next_pos = row_end;
        }
        const char* b = s->ts.buf.c_str() + s->ts.pos;
        const char* e = s->ts.buf.c_str() + row_end;
        // the header skip consumes physical row 0 even when blank (the pure
        // parser enumerates csv.reader rows, so a blank first line IS the
        // skipped header) — check before the blank-line skip
        if (s->skip_pending) {
            s->skip_pending = false;
            s->ts.pos = next_pos;
            continue;
        }
        if (b == e) {  // blank line: skipped, like csv.reader's empty row
            s->ts.pos = next_pos;
            continue;
        }
        int64_t count = 0;
        if (!parse_double_cells(b, e, s->delim, vals, &count)) return -2;
        if (count != arity) return -2;
        ++rows;
        s->ts.pos = next_pos;
    }

    *out = copy_out(vals);
    if (!*out) return -1;
    return rows;
}

void fml_close_csv_stream(void* handle) {
    auto* s = static_cast<CsvStream*>(handle);
    if (s) {
        if (s->ts.f) std::fclose(s->ts.f);
        delete s;
    }
}

}  // extern "C"
