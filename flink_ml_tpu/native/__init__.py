"""ctypes bindings for the native ingestion library (loader.cpp).

The shared library is built lazily on first use (``make`` in this directory)
and the table sources fall back to pure Python when it is unavailable —
``FLINK_ML_TPU_NO_NATIVE=1`` forces the fallback.  API consumed by
``flink_ml_tpu.table.sources._native_lib``:

  available() -> bool
  read_csv(path, delimiter, skip_header, arity) -> list[list[str]] | None
      (None = input not representable in the native transport — control
      bytes inside quoted cells — caller must fall back to the pure parser)
  read_libsvm(path, n_features, zero_based) -> (labels ndarray, CsrRows)

Streaming (bounded memory — the out-of-core path):

  iter_csv_doubles(path, delimiter, skip_header, arity, max_rows)
      -> yields (rows, arity) float64 ndarrays; raises NativeFallback on the
      first non-numeric cell with .rows_delivered so the caller can resume
      the pure parser from that row
  iter_libsvm_chunks(path, n_features, zero_based, max_rows)
      -> yields raw CSR chunks (labels, indptr, indices, values)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libflinkmltpu.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("FLINK_ML_TPU_NO_NATIVE"):
            return None
        # rebuild only when the .so is missing or older than its sources — a
        # cheap mtime stat instead of forking make in every process (which
        # would also race concurrent builders and always fail in read-only
        # installs)
        sources = (os.path.join(_DIR, "loader.cpp"), os.path.join(_DIR, "Makefile"))
        try:
            stale = not os.path.exists(_SO) or os.path.getmtime(_SO) < max(
                os.path.getmtime(p) for p in sources
            )
        except OSError:
            stale = not os.path.exists(_SO)
        if stale:
            try:
                subprocess.run(
                    ["make", "-C", _DIR],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            except Exception:
                if not os.path.exists(_SO):
                    return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.fml_read_csv.restype = ctypes.POINTER(ctypes.c_char)
        lib.fml_read_csv.argtypes = [
            ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.fml_read_libsvm.restype = ctypes.c_int
        lib.fml_read_libsvm.argtypes = [
            ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.fml_free.restype = None
        lib.fml_free.argtypes = [ctypes.c_void_p]
        # the streaming symbols arrived later: a stale prebuilt .so (no
        # compiler to rebuild) must keep the whole-file fast paths working
        # and only lose streaming, not all native acceleration
        try:
            lib.fml_open_libsvm_stream.restype = ctypes.c_void_p
            lib.fml_open_libsvm_stream.argtypes = [ctypes.c_char_p, ctypes.c_int]
            lib.fml_next_libsvm_chunk.restype = ctypes.c_int64
            lib.fml_next_libsvm_chunk.argtypes = [
                ctypes.c_void_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
            ]
            lib.fml_close_libsvm_stream.restype = None
            lib.fml_close_libsvm_stream.argtypes = [ctypes.c_void_p]
            lib.fml_open_csv_stream.restype = ctypes.c_void_p
            lib.fml_open_csv_stream.argtypes = [
                ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
            ]
            lib.fml_next_csv_doubles.restype = ctypes.c_int64
            lib.fml_next_csv_doubles.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
            ]
            lib.fml_close_csv_stream.restype = None
            lib.fml_close_csv_stream.argtypes = [ctypes.c_void_p]
            lib._fml_streaming = True
        except AttributeError:
            lib._fml_streaming = False
        _lib = lib
        return _lib


def streaming_available() -> bool:
    lib = _load()
    return lib is not None and getattr(lib, "_fml_streaming", False)


class NativeFallback(Exception):
    """The native numeric-CSV stream hit a non-numeric cell; the caller must
    continue with the pure parser, skipping ``rows_delivered`` rows."""

    def __init__(self, rows_delivered: int):
        super().__init__(f"non-numeric cell after {rows_delivered} rows")
        self.rows_delivered = rows_delivered


def available() -> bool:
    return _load() is not None


def read_csv(path: str, delimiter: str, skip_header: bool, arity: int):
    """Parse via the native loader; None means 'fall back to pure Python'
    (the file contains the transport's control bytes in quoted cells)."""
    lib = _load()
    out_len = ctypes.c_int64(0)
    buf = lib.fml_read_csv(
        path.encode(), delimiter.encode()[:1], 1 if skip_header else 0,
        ctypes.byref(out_len),
    )
    if not buf:
        if out_len.value == -2:
            return None
        raise IOError(f"cannot read {path}")
    try:
        text = ctypes.string_at(buf, out_len.value).decode("utf-8", "replace")
    finally:
        lib.fml_free(buf)
    rows = []
    for i, line in enumerate(text.split("\x1e")):
        if line == "" and i > 0:
            continue  # trailing terminator
        cells = line.split("\x1f")
        if cells == [""]:
            continue  # blank line in the file
        if len(cells) != arity:
            raise ValueError(
                f"{path}: row {i} has {len(cells)} fields, schema expects {arity}"
            )
        rows.append(cells)
    return rows


def read_libsvm(path: str, n_features: Optional[int], zero_based: bool):
    """Whole-file LibSVM parse -> (labels, CsrRows column).

    The CSR column IS the fast representation (lazy SparseVector row views
    for row-level consumers, contiguous arrays for the vectorized packer) —
    no per-row object construction on load.
    """
    from flink_ml_tpu.ops.batch import CsrRows

    lib = _load()
    labels_p = ctypes.POINTER(ctypes.c_double)()
    indptr_p = ctypes.POINTER(ctypes.c_int64)()
    indices_p = ctypes.POINTER(ctypes.c_int64)()
    values_p = ctypes.POINTER(ctypes.c_double)()
    n_rows = ctypes.c_int64(0)
    nnz = ctypes.c_int64(0)
    max_idx = ctypes.c_int64(0)
    rc = lib.fml_read_libsvm(
        path.encode(), 1 if zero_based else 0,
        ctypes.byref(labels_p), ctypes.byref(indptr_p),
        ctypes.byref(indices_p), ctypes.byref(values_p),
        ctypes.byref(n_rows), ctypes.byref(nnz), ctypes.byref(max_idx),
    )
    if rc == -1:
        raise IOError(f"cannot read {path}")
    if rc != 0:
        raise ValueError(f"{path}: malformed libsvm input")
    try:
        nr, nz = n_rows.value, nnz.value
        labels = np.ctypeslib.as_array(labels_p, shape=(max(nr, 1),))[:nr].copy()
        indptr = np.ctypeslib.as_array(indptr_p, shape=(nr + 1,)).copy()
        indices = np.ctypeslib.as_array(indices_p, shape=(max(nz, 1),))[:nz].copy()
        values = np.ctypeslib.as_array(values_p, shape=(max(nz, 1),))[:nz].copy()
    finally:
        lib.fml_free(labels_p)
        lib.fml_free(indptr_p)
        lib.fml_free(indices_p)
        lib.fml_free(values_p)

    dim = n_features if n_features is not None else int(max_idx.value) + 1
    if n_features is not None and nz and int(indices.max()) >= dim:
        raise ValueError(
            f"{path}: feature index {int(indices.max())} out of range for "
            f"declared size {dim}"
        )
    return labels, CsrRows(dim, indptr, indices, values)


def iter_csv_doubles(path: str, delimiter: str, skip_header: bool,
                     arity: int, max_rows: int):
    """Stream an all-numeric CSV as ``(rows, arity)`` float64 chunks.

    On the first non-numeric cell, raises :class:`NativeFallback` carrying
    how many rows were already yielded — the caller resumes the pure parser
    from there (rows consumed by the failed native call re-parse cleanly
    because the fallback re-reads the file).
    """
    lib = _load()
    handle = lib.fml_open_csv_stream(
        path.encode(), delimiter.encode()[:1], 1 if skip_header else 0
    )
    if not handle:
        raise IOError(f"cannot read {path}")
    delivered = 0
    try:
        while True:
            out = ctypes.POINTER(ctypes.c_double)()
            n = lib.fml_next_csv_doubles(handle, max_rows, arity,
                                         ctypes.byref(out))
            if n == -2:
                raise NativeFallback(delivered)
            if n == -1:
                raise MemoryError(f"native CSV chunk alloc failed for {path}")
            if n == 0:
                lib.fml_free(out)  # the EOF call still allocated its buffer
                return
            try:
                chunk = np.ctypeslib.as_array(
                    out, shape=(int(n), arity)
                ).copy()
            finally:
                lib.fml_free(out)
            delivered += int(n)
            yield chunk
    finally:
        lib.fml_close_csv_stream(handle)


def iter_libsvm_chunks(path: str, n_features: int, zero_based: bool,
                       max_rows: int):
    """Stream a LibSVM file as raw CSR chunks
    ``(labels, indptr, indices, values)`` — callers wrap them (CsrRows)
    without any per-row Python."""
    lib = _load()
    handle = lib.fml_open_libsvm_stream(path.encode(), 1 if zero_based else 0)
    if not handle:
        raise IOError(f"cannot read {path}")
    try:
        while True:
            labels_p = ctypes.POINTER(ctypes.c_double)()
            indptr_p = ctypes.POINTER(ctypes.c_int64)()
            indices_p = ctypes.POINTER(ctypes.c_int64)()
            values_p = ctypes.POINTER(ctypes.c_double)()
            nnz = ctypes.c_int64(0)
            max_idx = ctypes.c_int64(0)
            n = lib.fml_next_libsvm_chunk(
                handle, max_rows,
                ctypes.byref(labels_p), ctypes.byref(indptr_p),
                ctypes.byref(indices_p), ctypes.byref(values_p),
                ctypes.byref(nnz), ctypes.byref(max_idx),
            )
            if n == -2:
                raise ValueError(f"{path}: malformed libsvm input")
            if n == -1:
                raise MemoryError(f"native libsvm chunk alloc failed for {path}")
            if n == 0:
                # the EOF call still allocated its (empty) buffers
                for p in (labels_p, indptr_p, indices_p, values_p):
                    lib.fml_free(p)
                return
            try:
                nr, nz = int(n), int(nnz.value)
                labels = np.ctypeslib.as_array(labels_p, shape=(nr,)).copy()
                indptr = np.ctypeslib.as_array(indptr_p, shape=(nr + 1,)).copy()
                indices = np.ctypeslib.as_array(
                    indices_p, shape=(max(nz, 1),)
                )[:nz].copy()
                values = np.ctypeslib.as_array(
                    values_p, shape=(max(nz, 1),)
                )[:nz].copy()
            finally:
                lib.fml_free(labels_p)
                lib.fml_free(indptr_p)
                lib.fml_free(indices_p)
                lib.fml_free(values_p)
            yield labels, indptr, indices, values
    finally:
        lib.fml_close_libsvm_stream(handle)
