"""Shared parameter vocabulary — column-selection conventions every algorithm honors.

Capability parity with ``flink-ml-lib/.../params/shared`` (HasMLEnvironmentId.java
plus the colname family): each mixin contributes one ParamInfo class attribute and
typed getter/setter, so algorithms compose their vocabulary by inheritance exactly
as the reference composes interfaces (exemplar: HasSelectedCol.java:33-47).

The convention these encode (select -> compute -> merge, cf. SURVEY.md §2.3.5):
an op reads `selected_col(s)`, writes `output_col(s)`/`prediction_col`, and the
result schema keeps `reserved_cols` from the input (OutputColsHelper rules).
"""

from __future__ import annotations

from flink_ml_tpu.params.params import ParamInfo, WithParams, param_info


class HasMLEnvironmentId(WithParams):
    """Which MLEnvironment a stage runs in (HasMLEnvironmentId.java:28-42)."""

    ML_ENVIRONMENT_ID = param_info(
        "MLEnvironmentId",
        "ID of ML environment.",
        default=0,
        value_type=int,
    )

    def get_ml_environment_id(self) -> int:
        return self.get(self.ML_ENVIRONMENT_ID)

    def set_ml_environment_id(self, value: int):
        return self.set(self.ML_ENVIRONMENT_ID, value)


class HasSelectedCol(WithParams):
    SELECTED_COL: ParamInfo = param_info(
        "selectedCol", "Name of the selected column used for processing",
        optional=False, value_type=str,
    )

    def get_selected_col(self) -> str:
        return self.get(self.SELECTED_COL)

    def set_selected_col(self, value: str):
        return self.set(self.SELECTED_COL, value)


class HasSelectedColDefaultAsNull(WithParams):
    SELECTED_COL: ParamInfo = param_info(
        "selectedCol", "Name of the selected column used for processing",
        default=None, value_type=str,
    )

    def get_selected_col(self):
        return self.get(self.SELECTED_COL)

    def set_selected_col(self, value: str):
        return self.set(self.SELECTED_COL, value)


class HasSelectedCols(WithParams):
    SELECTED_COLS: ParamInfo = param_info(
        "selectedCols", "Names of the columns used for processing",
        optional=False, value_type=list,
    )

    def get_selected_cols(self):
        return self.get(self.SELECTED_COLS)

    def set_selected_cols(self, value):
        return self.set(self.SELECTED_COLS, list(value))


class HasSelectedColsDefaultAsNull(WithParams):
    SELECTED_COLS: ParamInfo = param_info(
        "selectedCols", "Names of the columns used for processing",
        default=None, value_type=list,
    )

    def get_selected_cols(self):
        return self.get(self.SELECTED_COLS)

    def set_selected_cols(self, value):
        return self.set(self.SELECTED_COLS, list(value) if value is not None else None)


class HasOutputCol(WithParams):
    OUTPUT_COL: ParamInfo = param_info(
        "outputCol", "Name of the output column", optional=False, value_type=str,
    )

    def get_output_col(self) -> str:
        return self.get(self.OUTPUT_COL)

    def set_output_col(self, value: str):
        return self.set(self.OUTPUT_COL, value)


class HasOutputColDefaultAsNull(WithParams):
    OUTPUT_COL: ParamInfo = param_info(
        "outputCol", "Name of the output column", default=None, value_type=str,
    )

    def get_output_col(self):
        return self.get(self.OUTPUT_COL)

    def set_output_col(self, value: str):
        return self.set(self.OUTPUT_COL, value)


class HasOutputCols(WithParams):
    OUTPUT_COLS: ParamInfo = param_info(
        "outputCols", "Names of the output columns", optional=False, value_type=list,
    )

    def get_output_cols(self):
        return self.get(self.OUTPUT_COLS)

    def set_output_cols(self, value):
        return self.set(self.OUTPUT_COLS, list(value))


class HasOutputColsDefaultAsNull(WithParams):
    OUTPUT_COLS: ParamInfo = param_info(
        "outputCols", "Names of the output columns", default=None, value_type=list,
    )

    def get_output_cols(self):
        return self.get(self.OUTPUT_COLS)

    def set_output_cols(self, value):
        return self.set(self.OUTPUT_COLS, list(value) if value is not None else None)


class HasPredictionCol(WithParams):
    """Column name of the prediction output (HasPredictionCol.java:27-41)."""

    PREDICTION_COL: ParamInfo = param_info(
        "predictionCol", "Column name of prediction.", optional=False, value_type=str,
    )

    def get_prediction_col(self) -> str:
        return self.get(self.PREDICTION_COL)

    def set_prediction_col(self, value: str):
        return self.set(self.PREDICTION_COL, value)


class HasPredictionDetailCol(WithParams):
    PREDICTION_DETAIL_COL: ParamInfo = param_info(
        "predictionDetailCol",
        "Column name of prediction detail (e.g. per-class probabilities).",
        default=None, value_type=str,
    )

    def get_prediction_detail_col(self):
        return self.get(self.PREDICTION_DETAIL_COL)

    def set_prediction_detail_col(self, value: str):
        return self.set(self.PREDICTION_DETAIL_COL, value)


class HasReservedCols(WithParams):
    RESERVED_COLS: ParamInfo = param_info(
        "reservedCols",
        "Names of the input columns to keep in the output; None keeps all.",
        default=None, value_type=list,
    )

    def get_reserved_cols(self):
        return self.get(self.RESERVED_COLS)

    def set_reserved_cols(self, value):
        return self.set(self.RESERVED_COLS, list(value) if value is not None else None)
