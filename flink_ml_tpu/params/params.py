"""Params / ParamInfo / WithParams — the framework's typed config system.

Semantics match the reference's JSON-string-valued param map
(``flink-ml-api/.../api/misc/param/Params.java``):

* values are stored JSON-encoded, keyed by the param *name*;
* ``get`` resolves name **or** any alias, raising on duplicate name/alias hits
  (Params.java:95-125), falling back to the default value and raising when a
  non-optional param is unset or an optional one has no default;
* ``set`` runs the validator hook (Params.java:138-145);
* ``to_json``/``from_json`` round-trip the whole map (Params.java:177-214);
* ``merge``/``clone`` (Params.java:222-239).

``ParamInfo`` carries name/alias/description/optional/default/validator
(ParamInfo.java:46-53); ``param_info`` is the builder
(ParamInfoFactory.java:41-122).  ``extract_param_infos`` walks a class and its
bases collecting ``ParamInfo`` class attributes for persistence
(util/param/ExtractParamInfosUtil.java:42-70).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, Generic, Iterable, List, Optional, Sequence, TypeVar

V = TypeVar("V")

# Sentinel distinguishing "no default value" from "default value is None":
# the reference tracks this with an explicit hasDefaultValue flag
# (ParamInfo.java:49, ParamInfoFactory.java:75-83).
_NO_DEFAULT = object()


class ParamValidator(Generic[V]):
    """Validation hook for a param value (ParamValidator.java:31-39).

    Any callable ``value -> bool`` is also accepted wherever a validator is
    expected; this class exists for subclass-style validators with state.
    """

    def validate(self, value: V) -> bool:  # pragma: no cover - interface default
        return True

    def __call__(self, value: V) -> bool:
        return self.validate(value)


class ParamInfo(Generic[V]):
    """Definition of a parameter: metadata + default (ParamInfo.java)."""

    __slots__ = ("name", "alias", "description", "optional", "_default", "validator", "value_type")

    def __init__(
        self,
        name: str,
        description: str = "",
        *,
        alias: Sequence[str] = (),
        optional: bool = True,
        default: Any = _NO_DEFAULT,
        validator: Optional[Callable[[V], bool]] = None,
        value_type: Optional[type] = None,
    ):
        if not name:
            raise ValueError("param name must be non-empty")
        for a in alias:
            if not a:
                raise ValueError("param alias must be non-empty")
        self.name = name
        self.alias = tuple(alias)
        self.description = description
        self.optional = optional
        self._default = default
        self.validator = validator
        self.value_type = value_type

    @property
    def has_default(self) -> bool:
        return self._default is not _NO_DEFAULT

    @property
    def default(self) -> V:
        if not self.has_default:
            raise ValueError(f"param {self.name!r} has no default value")
        return self._default

    def names(self) -> List[str]:
        """Name followed by aliases — resolution order used by Params.get."""
        return [self.name, *self.alias]

    def __repr__(self) -> str:
        return f"ParamInfo({self.name!r})"

    # ParamInfos are identity-hashed: two infos with the same name are still
    # distinct definitions, mirroring the reference's object semantics.


def param_info(
    name: str,
    description: str = "",
    *,
    alias: Sequence[str] = (),
    optional: bool = True,
    default: Any = _NO_DEFAULT,
    validator: Optional[Callable[[Any], bool]] = None,
    value_type: Optional[type] = None,
) -> ParamInfo:
    """Builder for ParamInfo (ParamInfoFactory.createParamInfo + builder chain)."""
    return ParamInfo(
        name,
        description,
        alias=alias,
        optional=optional,
        default=default,
        validator=validator,
        value_type=value_type,
    )


class Params:
    """Map-like container of params; values stored as JSON strings."""

    __slots__ = ("_params",)

    def __init__(self) -> None:
        self._params: Dict[str, str] = {}

    # -- size / emptiness ---------------------------------------------------

    def size(self) -> int:
        return len(self._params)

    def __len__(self) -> int:
        return len(self._params)

    def is_empty(self) -> bool:
        return not self._params

    def clear(self) -> None:
        self._params.clear()

    # -- typed access -------------------------------------------------------

    def get(self, info: ParamInfo[V]) -> V:
        """Value for ``info`` or its default (Params.java:95-125).

        Raises ValueError when the same param is set under both its name and
        an alias, when a non-optional param is unset, or when an optional
        unset param has no default.
        """
        used_name = None
        value_json = None
        for name_or_alias in info.names():
            if name_or_alias in self._params:
                if used_name is not None:
                    raise ValueError(
                        f"Duplicate parameters of {used_name} and {name_or_alias}"
                    )
                used_name = name_or_alias
                value_json = self._params[name_or_alias]
        if used_name is not None:
            return self._decode(value_json)
        if not info.optional:
            raise ValueError(f"Missing non-optional parameter {info.name}")
        if not info.has_default:
            raise ValueError(f"Cannot find default value for optional parameter {info.name}")
        return info.default

    def set(self, info: ParamInfo[V], value: V) -> "Params":
        """Set a value, checking declared type then the validator hook (Params.java:138-145)."""
        if info.value_type is not None and value is not None:
            vt = info.value_type
            is_bool = isinstance(value, bool)
            ok = (
                (isinstance(value, vt) and not (is_bool and vt is not bool))
                # ints are acceptable where floats are declared (but bools are not)
                or (vt is float and isinstance(value, int) and not is_bool)
                # tuples are acceptable where lists are declared (JSON makes them lists)
                or (vt is list and isinstance(value, tuple))
            )
            if not ok:
                raise TypeError(
                    f"Setting {info.name}: expected {vt.__name__}, got {type(value).__name__}"
                )
        if info.validator is not None and not info.validator(value):
            raise ValueError(f"Setting {info.name} as a invalid value:{value}")
        self._params[info.name] = self._encode(value)
        return self

    def remove(self, info: ParamInfo[V]) -> None:
        """Remove under name and every alias (Params.java:154-160)."""
        self._params.pop(info.name, None)
        for a in info.alias:
            self._params.pop(a, None)

    def contains(self, info: ParamInfo[V]) -> bool:
        return any(n in self._params for n in info.names())

    def __contains__(self, info: ParamInfo) -> bool:
        return self.contains(info)

    # -- raw access (used by json round-trip and save/load) -----------------

    def set_raw(self, name: str, value: Any) -> "Params":
        """Set by bare name with no ParamInfo (used to exercise alias logic)."""
        self._params[name] = self._encode(value)
        return self

    def keys(self) -> Iterable[str]:
        return self._params.keys()

    # -- json persistence ---------------------------------------------------

    def to_json(self) -> str:
        """One JSON object mapping name -> JSON-encoded value (Params.java:177-184)."""
        return json.dumps(self._params, sort_keys=True)

    def load_json(self, payload: str) -> None:
        self._params.update(json.loads(payload))

    @staticmethod
    def from_json(payload: str) -> "Params":
        p = Params()
        p.load_json(payload)
        return p

    # -- merge / clone ------------------------------------------------------

    def merge(self, other: Optional["Params"]) -> "Params":
        if other is not None:
            self._params.update(other._params)
        return self

    def clone(self) -> "Params":
        p = Params()
        p._params.update(self._params)
        return p

    # -- codec --------------------------------------------------------------

    @staticmethod
    def _encode(value: Any) -> str:
        return json.dumps(value)

    @staticmethod
    def _decode(value_json: Optional[str]) -> Any:
        if value_json is None:
            return None
        return json.loads(value_json)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Params) and self._params == other._params

    def __repr__(self) -> str:
        return f"Params({self._params})"


class WithParams:
    """Mixin giving typed get/set that delegates to get_params() (WithParams.java:44-59).

    Subclasses (stages, mappers, operators) expose their ParamInfos as class
    attributes; ``extract_param_infos`` finds them for persistence.
    """

    def get_params(self) -> Params:
        p = getattr(self, "_params", None)
        if p is None:
            p = Params()
            self._params = p
        return p

    def set(self, info: ParamInfo[V], value: V) -> "WithParams":
        self.get_params().set(info, value)
        return self

    def get(self, info: ParamInfo[V]) -> V:
        return self.get_params().get(info)


def extract_param_infos(obj: Any) -> Dict[str, ParamInfo]:
    """Collect every ParamInfo reachable as a class attribute of ``obj``'s type.

    Walks the full MRO (class, superclasses, mixin interfaces), mirroring the
    reflection walk in ExtractParamInfosUtil.java:42-70.  Subclass definitions
    shadow superclass definitions of the same name.
    """
    infos: Dict[str, ParamInfo] = {}
    cls = obj if isinstance(obj, type) else type(obj)
    for klass in reversed(cls.__mro__):
        for attr in vars(klass).values():
            if isinstance(attr, ParamInfo):
                infos[attr.name] = attr
    return infos
