"""Typed, JSON-persistable parameter system.

Capability parity with the reference's ``flink-ml-api`` param package
(``org.apache.flink.ml.api.misc.param``): ``Params`` (Params.java),
``ParamInfo`` (ParamInfo.java), builder (ParamInfoFactory.java),
``WithParams`` (WithParams.java), ``ParamValidator`` (ParamValidator.java),
and ``extract_param_infos`` (util/param/ExtractParamInfosUtil.java).
"""

from flink_ml_tpu.params.params import (  # noqa: F401
    ParamInfo,
    ParamValidator,
    Params,
    WithParams,
    extract_param_infos,
    param_info,
)
from flink_ml_tpu.params import shared  # noqa: F401
