"""Tenant-keyed model registry — many models behind one ModelServer.

ISSUE 20 (ROADMAP item 3, the last unserved scale axis): the reference's
Pipeline/Model abstraction was built to host MANY small models per
deployment, but this repo served exactly one model per fleet.  This
module is the control-plane half of multi-tenant serving:

* **registry** — ``register(tenant, source)`` binds a tenant key to a
  model artifact (a saved-model directory, reloaded with the standard
  integrity-verified loaders) or an in-memory model object.  Tenant keys
  are validated at the admission door (``[A-Za-z0-9._-]``, length-capped)
  so a malformed key fails loudly instead of minting a garbage tenant;
* **LRU residency over the slab pool** — resolved models live in the
  process-wide :mod:`~flink_ml_tpu.table.slab_pool` under
  ``("tenant_model", tenant, ...)`` keys, so tenant models share one
  budget (``FMT_SLAB_POOL_BUDGET_MB``) with every other cached placement
  and honor the pool's pin invariant: the dispatcher pins a tenant's
  model for the duration of its batch, and neither budget pressure nor
  the registry's own residency cap (``FMT_TENANT_MAX_RESIDENT``) can
  drop it mid-dispatch;
* **evict-under-pressure, reason-coded** — the registry listens on the
  pool's eviction events and stamps each tenant fault-out into the
  flight recorder (``serving.tenant.evicted`` with the pool's reason:
  ``budget`` / ``pressure`` / ``resident_cap``) and the
  ``serving.tenant.evictions`` counter;
* **millisecond fault-in** — a cold load re-reads the artifact (ms) but
  pays no compile: same-family tenants share executables through the
  family cache (``common/fused._FAMILY_FNS``) and PR 18's warm-artifact
  store, whose entry keys were already family-structural;
* **per-tenant accounting** — requests/rows/sheds/cold-loads/evictions
  per tenant, a top-N-by-traffic table for ``/statusz``, and the
  ``FMT_TENANT_QUOTA_ROWS`` quota the server's admission door enforces.

Knobs (BASELINE.md round-23 table): ``FMT_TENANT_MAX_RESIDENT``,
``FMT_TENANT_QUOTA_ROWS``, ``FMT_TENANT_MUX``.
"""

from __future__ import annotations

import os
import re
import threading
import time
from collections import Counter, OrderedDict
from typing import Dict, List, Optional

from flink_ml_tpu import obs
from flink_ml_tpu.utils import knobs

__all__ = [
    "DEFAULT_TENANT",
    "TENANT_KEY_MAX",
    "TenantRegistry",
    "validate_tenant_key",
]

#: the wire-compatible tenant old callers land on: a ``submit()`` with no
#: tenant key serves the VersionManager's active version exactly as before
DEFAULT_TENANT = "default"

TENANT_KEY_MAX = 64
_TENANT_KEY_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")

#: floor estimate for an in-memory model object whose footprint the
#: registry cannot cheaply walk (path artifacts use their on-disk size)
_MODEL_NBYTES_FLOOR = 1 << 20


def validate_tenant_key(tenant: str) -> str:
    """The admission-door key check: non-empty, ``[A-Za-z0-9._-]`` with a
    leading alphanumeric, at most ``TENANT_KEY_MAX`` chars.  Raises
    ``ValueError`` — a malformed tenant key is a caller bug (like an
    empty request table), never a shed."""
    if not isinstance(tenant, str) or not tenant:
        raise ValueError("tenant key must be a non-empty string")
    if len(tenant) > TENANT_KEY_MAX:
        raise ValueError(
            f"tenant key exceeds {TENANT_KEY_MAX} chars: {tenant[:80]!r}"
        )
    if not _TENANT_KEY_RE.match(tenant):
        raise ValueError(
            f"malformed tenant key {tenant!r}: use [A-Za-z0-9._-] with a "
            "leading letter or digit"
        )
    return tenant


def _artifact_nbytes(path: str) -> int:
    """On-disk artifact size as the resident-footprint estimate for a
    path-registered tenant (the placed params are within a small factor
    of the serialized form, and the estimate only steers LRU order)."""
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


class _TenantState:
    __slots__ = ("tenant", "source", "version", "model_obj", "counts",
                 "last_request_s", "family_token")

    def __init__(self, tenant: str, source, version: str):
        self.tenant = tenant
        #: a saved-model directory path (str) or an in-memory model object
        self.source = source
        self.version = version
        #: strong ref kept ONLY when the slab pool is disabled (without a
        #: pool there is nowhere to be resident — reloading per request
        #: would be absurd) or the source IS the object
        self.model_obj = None
        self.counts: Counter = Counter()
        self.last_request_s = 0.0
        #: structural plan token of this tenant's model (None until its
        #: first serve computes one) — the dispatcher's same-family check
        self.family_token: Optional[str] = None


class TenantRegistry:
    """Tenant -> model map, LRU-resident over the slab pool."""

    def __init__(self, tally=None):
        self._lock = threading.RLock()
        self._tenants: Dict[str, _TenantState] = {}
        #: tenants with a pool-resident model, LRU order (synced from the
        #: pool's eviction events; approximate is fine — the pool is the
        #: source of truth and a stale entry just re-faults)
        self._resident: "OrderedDict[str, tuple]" = OrderedDict()
        #: per-server tally hook (ModelServer._tally) so tenant events
        #: land in the server's own stats alongside the global counters
        self._tally = tally if tally is not None else (lambda *_: None)
        from flink_ml_tpu.table import slab_pool

        self._pool = slab_pool.pool()
        self._pool.add_eviction_listener(self._on_pool_evict)

    def close(self) -> None:
        self._pool.remove_eviction_listener(self._on_pool_evict)

    # -- registration ---------------------------------------------------------

    def register(self, tenant: str, source, version: str = "v1") -> None:
        """Bind ``tenant`` to a saved-model path or model object.  Lazy:
        the model loads (faults in) on the tenant's first request."""
        validate_tenant_key(tenant)
        if tenant == DEFAULT_TENANT:
            raise ValueError(
                "the default tenant is the server's deployed model — "
                "use deploy(), not register_tenant()"
            )
        if not isinstance(source, (str, os.PathLike)) and source is None:
            raise ValueError("tenant source must be a path or a model")
        with self._lock:
            self._tenants[tenant] = _TenantState(
                tenant, str(source) if isinstance(source, os.PathLike)
                else source, version,
            )

    def known(self, tenant: str) -> bool:
        with self._lock:
            return tenant in self._tenants

    def tenants(self) -> List[str]:
        with self._lock:
            return sorted(self._tenants)

    def quota_rows(self) -> int:
        """Per-tenant queued-row quota (0 = unenforced)."""
        return knobs.knob_int("FMT_TENANT_QUOTA_ROWS")

    # -- residency / fault-in -------------------------------------------------

    def _state(self, tenant: str) -> _TenantState:
        with self._lock:
            state = self._tenants.get(tenant)
        if state is None:
            raise KeyError(f"unknown tenant {tenant!r}")
        return state

    def _pool_key(self, state: _TenantState) -> tuple:
        src = (state.source if isinstance(state.source, str)
               else f"obj:{id(state.source)}")
        return ("tenant_model", state.tenant, src, state.version)

    def _load(self, state: _TenantState):
        """One cold load: the integrity-verified standard loaders, timed
        and flight-recorded.  Compiles do NOT ride here — the family
        executable cache and the warm-artifact store make the faulted-in
        tenant's first dispatch a cache hit."""
        t0 = time.perf_counter()
        if isinstance(state.source, str):
            from flink_ml_tpu.serving.versioning import _load_model

            model = _load_model(state.source)
        else:
            model = state.source
        ms = (time.perf_counter() - t0) * 1e3
        state.counts["cold_loads"] += 1
        obs.counter_add("serving.tenant.cold_loads")
        self._tally("serving.tenant.cold_loads")
        obs.flight.record("serving.tenant.cold_load", tenant=state.tenant,
                          ms=round(ms, 3))
        return model

    def resolve(self, tenant: str):
        """The tenant's (model, version label), faulting the model in when
        it is not resident.  The model is pool-owned — callers pin it
        (``pool().pinned(model)``) for the duration of their dispatch."""
        from flink_ml_tpu.table import slab_pool

        state = self._state(tenant)
        version = f"{tenant}:{state.version}"
        if not isinstance(state.source, str):
            # object-registered tenant: the object IS the resident model
            if state.model_obj is None:
                state.model_obj = self._load(state)
            return state.model_obj, version
        if not slab_pool.enabled():
            if state.model_obj is None:
                state.model_obj = self._load(state)
            return state.model_obj, version
        key = self._pool_key(state)
        nbytes = max(_artifact_nbytes(state.source), _MODEL_NBYTES_FLOOR)
        model = self._pool.get_or_build(
            key, lambda: self._load(state), refs=(), nbytes=nbytes,
            agreed=False,  # inference is collective-free by contract
        )
        with self._lock:
            self._resident[tenant] = key
            self._resident.move_to_end(tenant)
            over = len(self._resident) - max(
                1, knobs.knob_int("FMT_TENANT_MAX_RESIDENT")
            )
            victims = []
            if over > 0:
                for t, k in self._resident.items():
                    if t != tenant:
                        victims.append((t, k))
                        over -= 1
                        if over <= 0:
                            break
        for _t, k in victims:
            # discard honors the pin invariant: a tenant mid-dispatch
            # stays resident and retries at the next resolve
            self._pool.discard(k, reason="resident_cap")
        return model, version

    def note_family(self, tenant: str, token: Optional[str]) -> None:
        """Record the structural plan token of a tenant's model (computed
        at its first serve; None pins "not mux-eligible") — the
        dispatcher's same-family batch-cut check reads it lock-free."""
        with self._lock:
            state = self._tenants.get(tenant)
            if state is None and tenant == DEFAULT_TENANT:
                state = self._tenants[tenant] = _TenantState(
                    tenant, None, "active")
        if state is not None:
            state.family_token = token

    def family_token(self, tenant: str) -> Optional[str]:
        with self._lock:
            state = self._tenants.get(tenant)
        return state.family_token if state is not None else None

    def _on_pool_evict(self, key, reason: str, nbytes: int) -> None:
        """Pool eviction listener: reason-coded tenant fault-out events
        (the registry's keys only — everything else in the pool is not
        ours to narrate)."""
        if not (isinstance(key, tuple) and key and key[0] == "tenant_model"):
            return
        tenant = key[1]
        with self._lock:
            self._resident.pop(tenant, None)
            state = self._tenants.get(tenant)
        if state is not None:
            state.counts["evictions"] += 1
        obs.counter_add("serving.tenant.evictions")
        self._tally("serving.tenant.evictions")
        obs.flight.record("serving.tenant.evicted", tenant=tenant,
                          reason=reason, nbytes=int(nbytes))

    # -- traffic accounting ---------------------------------------------------

    def note_request(self, tenant: str, rows: int) -> None:
        with self._lock:
            state = self._tenants.get(tenant)
            if state is None and tenant == DEFAULT_TENANT:
                # the default tenant is implicit — minted on first use so
                # its traffic shows in the same table
                state = self._tenants[tenant] = _TenantState(
                    tenant, None, "active")
        if state is None:
            return
        state.counts["requests"] += 1
        state.counts["rows"] += rows
        state.last_request_s = time.monotonic()
        obs.counter_add("serving.tenant.requests")
        self._tally("serving.tenant.requests")

    def note_shed(self, tenant: str) -> None:
        with self._lock:
            state = self._tenants.get(tenant)
        if state is not None:
            state.counts["sheds"] += 1
        obs.counter_add("serving.tenant.sheds")
        self._tally("serving.tenant.sheds")

    def top(self, n: int = 10) -> List[dict]:
        """Top-N tenants by request count — the ``/statusz`` table."""
        with self._lock:
            states = list(self._tenants.values())
            resident = set(self._resident)
        states.sort(key=lambda s: s.counts["requests"], reverse=True)
        return [
            {
                "tenant": s.tenant,
                "requests": int(s.counts["requests"]),
                "rows": int(s.counts["rows"]),
                "sheds": int(s.counts["sheds"]),
                "cold_loads": int(s.counts["cold_loads"]),
                "evictions": int(s.counts["evictions"]),
                "resident": (s.tenant in resident
                             or s.model_obj is not None
                             or s.tenant == DEFAULT_TENANT),
            }
            for s in states[:max(0, n)]
        ]

    def status(self) -> dict:
        with self._lock:
            n_tenants = len(self._tenants)
            n_resident = len(self._resident)
        return {
            "tenants": n_tenants,
            "resident": n_resident,
            "max_resident": knobs.knob_int("FMT_TENANT_MAX_RESIDENT"),
            "quota_rows": self.quota_rows(),
            "top": self.top(10),
        }
