"""Replica router: the horizontal scale-out front-end over the telemetry
plane (ISSUE 13, ROADMAP item 2).

Every request so far terminated in ONE ``ModelServer`` process — a hard
ceiling no matter how fast the chip path gets.  The reference
architecture splits the framework from an execution substrate that
scales it out (PAPER.md layer map: Pipeline/Estimator API above, Flink's
distributed runtime below); :class:`ReplicaRouter` is that substrate's
first rung: the same ``submit() -> Future`` contract as ``ModelServer``,
fanned across N replica subprocesses, each running its own
``ModelServer`` (micro-batching, breakers, pressure recovery, telemetry —
the whole single-process stack) behind the wire layer in
:mod:`flink_ml_tpu.serving.replica`.

**Health-aware balancing.**  A background poll loop scrapes every
replica's ``/readyz`` + ``/metrics`` (PR 10 built exactly the probes an
orchestrator needs — now we are the orchestrator): a replica reporting
503 — ``breaker_open``, ``memory_pressure``, ``slo_burning``, ``drift``,
``deploy_in_progress``, ``queue_saturated`` — is routed around.  Among
ready replicas, dispatch picks by power-of-two-choices on observed load
(scraped queue depth + the router's own in-flight count): two random
candidates, the less-loaded one wins — near-optimal balance without a
global scan per request.

**Shed classification, not string matching.**  A replica's reason-coded
shed is classified by :func:`~flink_ml_tpu.serving.errors.shed_policy`:
``queue_full`` / ``memory_pressure`` / ``deadline_expired`` retry on
another replica (one replica's transient load), ``shutdown`` /
``breaker_open`` route away (eject the replica from rotation AND retry
elsewhere), anything unknown sheds to the caller unchanged.  Retries are
budgeted by ``FMT_ROUTER_RETRIES`` and counted in ``router.retries``.

**Rolling deploys.**  ``deploy(path, version)`` reuses the round-10 swap
contract per replica, one replica at a time: stop routing to it (drain),
wait for its in-flight requests, drive its ``/deploy`` (load -> verify ->
pre-warm -> atomic swap inside the replica), wait for ``/readyz`` 200,
re-admit — the rest of the fleet serves throughout, so a deploy sheds
nothing.  A failed deploy (corrupt artifact, broken warmup) leaves THAT
replica on its old version (the versioning.py contract is the rollback),
stops the roll, and raises :class:`RollingDeployError` carrying the
partial per-replica status (also readable at :attr:`deploy_status`).

**Supervision.**  A crashed or killed replica is detected two ways —
the poll loop's ``waitpid`` check and the dead socket its in-flight
dispatches hit — its requests retry on surviving replicas, and a
replacement is respawned on the router's current (path, version), with
bounded spawn retries before a slot is abandoned.

Telemetry: ``router.replicas_ready`` / ``router.queue_depth`` gauges;
``router.requests`` / ``router.retries`` / ``router.shed(.reason)`` /
``router.replica_deaths`` / ``router.respawns`` /
``router.rolling_deploys`` counters; a ``serving`` RunReport at
shutdown.  Chaos levers: injection points ``router.dispatch`` (before
each forward) and ``router.spawn`` (replica boot).

**Elastic membership (round 22).**  The fleet is no longer fixed at
boot: :meth:`add_replica` grows it through the standard spawn path (the
child inherits the sealed warmstart manifest, so its first request
stays warm) and :meth:`remove_replica` shrinks it drain-aware by
reusing the rolling-deploy drain contract via :meth:`_drain_replica` —
stop routing, wait out in-flight work, terminate, tombstone the slot so
every index stays stable.  Membership changes serialize with rolling
deploys.  :class:`~flink_ml_tpu.serving.autoscaler.FleetAutoscaler`
closes the observe→decide→act loop over :meth:`fleet_health`.  Two
supervision refinements ride along: a live replica only leaves rotation
after ``FMT_ROUTER_SCRAPE_STRIKES`` consecutive failed scrapes (with
jittered re-probes between strikes — one blackholed scrape must not
read like a dead socket; waitpid-confirmed death stays immediate), and
a slot whose replica dies ``FMT_ROUTER_CRASHLOOP_MAX`` times inside
``FMT_ROUTER_CRASHLOOP_WINDOW_S`` is quarantined with exponential
backoff (a ``router.crashloop`` flight dump names the slot and exit
status) instead of hot-loop respawning.

Knobs (BASELINE.md round-16 table): ``FMT_ROUTER_REPLICAS``,
``FMT_ROUTER_POLL_MS``, ``FMT_ROUTER_QUEUE_CAP``,
``FMT_ROUTER_DISPATCH_THREADS``, ``FMT_ROUTER_RETRIES``,
``FMT_ROUTER_SPAWN_TIMEOUT_S``, ``FMT_ROUTER_DRAIN_TIMEOUT_S``; the
round-22 table adds ``FMT_ROUTER_SCRAPE_STRIKES``,
``FMT_ROUTER_CRASHLOOP_MAX`` and ``FMT_ROUTER_CRASHLOOP_WINDOW_S``.
"""

from __future__ import annotations

import random
import threading
import time
from collections import Counter, deque
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional, Tuple

from flink_ml_tpu import obs
from flink_ml_tpu.serving.admission import now_s
from flink_ml_tpu.serving.batcher import ServeResult
from flink_ml_tpu.serving.errors import (
    POLICY_FAIL,
    POLICY_ROUTE_AWAY,
    SHED_DEADLINE,
    SHED_NO_REPLICA,
    SHED_QUEUE_FULL,
    SHED_SHUTDOWN,
    ServerClosedError,
    ServerOverloadedError,
    shed_policy,
)
from flink_ml_tpu.serving.replica import (
    ReplicaClient,
    ReplicaProcess,
    ReplicaRemoteError,
    ReplicaUnreachableError,
)
from flink_ml_tpu.utils import knobs

__all__ = ["ReplicaRouter", "RollingDeployError", "RouterConfig"]

#: consecutive failed probe rounds before a process-less (injected)
#: replica backend is treated as dead; process-backed replicas are
#: declared dead by ``waitpid``, which needs no debounce
_PROBE_FAILURE_DEBOUNCE = 3

#: poll beats between /metrics queue-depth scrapes (readiness is checked
#: every beat; the full-registry exposition is the expensive half of a
#: probe and the router's own in-flight counts stay current in between)
_DEPTH_SCRAPE_EVERY = 4

#: spawn attempts per replacement before a slot is abandoned (the fleet
#: keeps serving on the survivors; abandoning beats a respawn hot-loop)
_MAX_SPAWN_ATTEMPTS = 3

#: per-forward wire timeout — generous: the replica's own admission
#: deadline is the real latency contract, this only bounds a wedged peer
_DISPATCH_TIMEOUT_S = 120.0

#: first crash-loop quarantine parks the slot this long, doubling per
#: consecutive episode up to the cap — long enough to break a hot loop,
#: short enough that a recovered dependency re-admits the slot soon
_CRASHLOOP_BACKOFF_S = 2.0
_CRASHLOOP_BACKOFF_CAP_S = 60.0


@dataclass(frozen=True)
class RouterConfig:
    """Resolved router knobs (environment defaults, overrides win)."""

    replicas: int = 2
    poll_ms: float = 50.0
    queue_cap: int = 4096
    dispatch_threads: int = 8
    retries: int = 2
    spawn_timeout_s: float = 120.0
    drain_timeout_s: float = 30.0
    scrape_strikes: int = 3
    crashloop_max: int = 3
    crashloop_window_s: float = 30.0

    @classmethod
    def from_env(cls, replicas: Optional[int] = None,
                 poll_ms: Optional[float] = None,
                 queue_cap: Optional[int] = None,
                 dispatch_threads: Optional[int] = None,
                 retries: Optional[int] = None,
                 spawn_timeout_s: Optional[float] = None,
                 drain_timeout_s: Optional[float] = None,
                 scrape_strikes: Optional[int] = None,
                 crashloop_max: Optional[int] = None,
                 crashloop_window_s: Optional[float] = None
                 ) -> "RouterConfig":
        cfg = cls(
            replicas=int(replicas if replicas is not None
                         else knobs.knob_int("FMT_ROUTER_REPLICAS")),
            poll_ms=float(poll_ms if poll_ms is not None
                          else knobs.knob_float("FMT_ROUTER_POLL_MS")),
            queue_cap=int(queue_cap if queue_cap is not None
                          else knobs.knob_int("FMT_ROUTER_QUEUE_CAP")),
            dispatch_threads=int(
                dispatch_threads if dispatch_threads is not None
                else knobs.knob_int("FMT_ROUTER_DISPATCH_THREADS")),
            retries=int(retries if retries is not None
                        else knobs.knob_int("FMT_ROUTER_RETRIES")),
            spawn_timeout_s=float(
                spawn_timeout_s if spawn_timeout_s is not None
                else knobs.knob_float("FMT_ROUTER_SPAWN_TIMEOUT_S")),
            drain_timeout_s=float(
                drain_timeout_s if drain_timeout_s is not None
                else knobs.knob_float("FMT_ROUTER_DRAIN_TIMEOUT_S")),
            scrape_strikes=max(int(
                scrape_strikes if scrape_strikes is not None
                else knobs.knob_int("FMT_ROUTER_SCRAPE_STRIKES")), 1),
            crashloop_max=int(
                crashloop_max if crashloop_max is not None
                else knobs.knob_int("FMT_ROUTER_CRASHLOOP_MAX")),
            crashloop_window_s=float(
                crashloop_window_s if crashloop_window_s is not None
                else knobs.knob_float("FMT_ROUTER_CRASHLOOP_WINDOW_S")),
        )
        if cfg.replicas < 1 or cfg.dispatch_threads < 1 or cfg.queue_cap < 1:
            raise ValueError(
                f"replicas, dispatch_threads and queue_cap must be >= 1 "
                f"(got {cfg.replicas}, {cfg.dispatch_threads}, "
                f"{cfg.queue_cap})"
            )
        return cfg


class RollingDeployError(RuntimeError):
    """A rolling deploy stopped mid-fleet.  ``status`` holds the partial
    per-replica outcome (which replicas swapped, which failed and rolled
    back, which were skipped) — the failing replica itself kept serving
    its OLD version, per the versioning.py contract."""

    def __init__(self, status: dict):
        failed = [r["replica"] for r in status.get("replicas", [])
                  if r.get("outcome") == "failed"]
        super().__init__(
            f"rolling deploy of {status.get('version')!r} stopped: "
            f"{', '.join(failed) or 'drain timeout'} — fleet left "
            f"partially on {status.get('previous')!r} (see .status)"
        )
        self.status = status


@dataclass
class _RouterRequest:
    table: object
    future: Future
    enqueued_at: float
    deadline_at: Optional[float]
    n_rows: int
    attempts: int = 0
    #: the routed request's ROOT trace (obs.trace.RequestTrace, None when
    #: tracing is off/sampled out) — every dispatch attempt parents under
    #: it, and its context ships to the replica over the wire
    trace: Optional[object] = None
    #: multi-tenant routing key (ISSUE 20); None = the default tenant,
    #: kept off the wire so pre-tenant replicas still parse the payload
    tenant: Optional[str] = None

    def expired(self, now: float) -> bool:
        return self.deadline_at is not None and now > self.deadline_at

    def remaining_ms(self, now: float) -> Optional[float]:
        if self.deadline_at is None:
            return None
        return max((self.deadline_at - now) * 1e3, 1.0)


class _Replica:
    """The router's view of one replica slot: wire client + health and
    load state, all transitions under the replica's own lock (probe
    thread, N dispatch threads, and the deploy thread all touch it)."""

    def __init__(self, name: str, client: ReplicaClient,
                 process: Optional[ReplicaProcess] = None,
                 version: str = "", scrape_strikes: int = 1):
        self.name = name
        self.client = client
        self.process = process
        self._lock = threading.Condition()
        self._ready = False
        self._reasons: List[str] = ["booting"]
        self._queue_depth = 0.0
        self._burn_rates: Dict[str, float] = {}
        self._in_flight = 0
        self._draining = False
        self._dead = False
        self._probe_failures = 0
        self._probe_inflight = False
        self._scrape_strikes = max(int(scrape_strikes), 1)
        self._version = version

    # -- health (poll loop) --------------------------------------------------

    def mark_probe(self, probe: dict) -> None:
        with self._lock:
            self._ready = bool(probe.get("ready"))
            self._reasons = list(probe.get("reasons", []))
            if "queue_depth" in probe:
                # readiness refreshes every beat; depth only on scrape
                # beats (absent key = keep the last observation)
                self._queue_depth = float(probe["queue_depth"])
            if "burn_rates" in probe:
                self._burn_rates = dict(probe["burn_rates"])
            self._probe_failures = 0

    def note_probe_failure(self) -> int:
        """One unreachable probe; returns the consecutive-failure count.
        Transient-vs-dead discrimination: a live replica only leaves
        rotation after ``scrape_strikes`` consecutive failures — one
        blackholed scrape must not read like a dead socket (a dead
        socket is waitpid's verdict, which needs no debounce)."""
        with self._lock:
            self._probe_failures += 1
            if self._probe_failures >= self._scrape_strikes:
                self._ready = False
                self._reasons = ["unreachable"]
            return self._probe_failures

    def try_begin_probe(self) -> bool:
        """Claim this replica's probe slot (False = a probe is still in
        flight — a wedged peer's 2 s timeout must stall only its OWN
        refresh, never the fleet's)."""
        with self._lock:
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def end_probe(self) -> None:
        with self._lock:
            self._probe_inflight = False

    def mark_unready(self, reason: str) -> None:
        """A dispatch-path verdict (a route-away shed): stop routing here
        until the next probe says otherwise."""
        with self._lock:
            self._ready = False
            self._reasons = [reason]

    def mark_dead(self, why: str) -> None:
        with self._lock:
            self._dead = True
            self._ready = False
            self._reasons = [why]
            self._lock.notify_all()  # a drain waiter must not outwait a corpse

    def is_dead(self) -> bool:
        with self._lock:
            return self._dead

    # -- routing (dispatch threads) ------------------------------------------

    def routable(self) -> bool:
        with self._lock:
            return self._ready and not self._draining and not self._dead

    def load(self) -> float:
        """The power-of-two-choices comparand: the replica's scraped
        queue depth plus the router's own not-yet-acknowledged forwards
        (the scrape lags; in-flight is current)."""
        with self._lock:
            return self._queue_depth + float(self._in_flight)

    def begin_dispatch(self) -> None:
        with self._lock:
            self._in_flight += 1

    def end_dispatch(self) -> None:
        with self._lock:
            self._in_flight = max(self._in_flight - 1, 0)
            if self._in_flight == 0:
                self._lock.notify_all()

    # -- rolling deploy (deploy thread) --------------------------------------

    def set_draining(self, draining: bool) -> None:
        with self._lock:
            self._draining = bool(draining)

    def wait_drained(self, timeout_s: float) -> bool:
        """Block until no router-originated request is in flight on this
        replica (or it dies); False on timeout."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            while self._in_flight > 0 and not self._dead:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._lock.wait(timeout=remaining)
            return True

    def set_version(self, version: str) -> None:
        with self._lock:
            self._version = version

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            snap = {
                "name": self.name,
                "ready": self._ready,
                "reasons": list(self._reasons),
                "queue_depth": self._queue_depth,
                "burn_rates": dict(self._burn_rates),
                "in_flight": self._in_flight,
                "draining": self._draining,
                "dead": self._dead,
                "version": self._version,
            }
        if self.process is not None:
            snap["pid"] = self.process.pid
            snap["serve_address"] = self.process.serve_address
            snap["telemetry_address"] = self.process.telemetry_address
        return snap


class ReplicaRouter:
    """Scale-out front-end over N ``ModelServer`` replica processes.

    ``ReplicaRouter(path, replicas=3)`` spawns three replicas serving the
    saved pipeline at ``path`` and starts balancing; use as a context
    manager or call :meth:`shutdown`.  ``submit``/``predict`` mirror
    ``ModelServer`` — a caller's :class:`ServeResult` is bit-identical to
    a solo in-process transform of its rows.

    ``replica_factory`` (tests, embeddings) replaces subprocess spawning:
    a callable ``(slot_name, path, version) -> (client, process_or_None)``
    returning anything speaking the :class:`ReplicaClient` protocol.
    """

    def __init__(self, path: str, *, version: str = "v1",
                 replicas: Optional[int] = None,
                 queue_cap: Optional[int] = None,
                 poll_ms: Optional[float] = None,
                 dispatch_threads: Optional[int] = None,
                 retries: Optional[int] = None,
                 spawn_timeout_s: Optional[float] = None,
                 drain_timeout_s: Optional[float] = None,
                 scrape_strikes: Optional[int] = None,
                 crashloop_max: Optional[int] = None,
                 crashloop_window_s: Optional[float] = None,
                 replica_env: Optional[Dict[str, str]] = None,
                 replica_factory=None,
                 start: bool = True):
        self.config = RouterConfig.from_env(
            replicas=replicas, poll_ms=poll_ms, queue_cap=queue_cap,
            dispatch_threads=dispatch_threads, retries=retries,
            spawn_timeout_s=spawn_timeout_s,
            drain_timeout_s=drain_timeout_s,
            scrape_strikes=scrape_strikes,
            crashloop_max=crashloop_max,
            crashloop_window_s=crashloop_window_s,
        )
        self._replica_env = dict(replica_env or {})
        self._factory = replica_factory or self._spawn_backend
        self._cond = threading.Condition()
        self._queue: Deque[_RouterRequest] = deque()
        self._queued_rows = 0
        self._stopping = False
        self._closed = False
        self._rep_lock = threading.Lock()
        self._slots: List[Optional[_Replica]] = []
        self._generation = 0
        self._respawning: set = set()
        #: per-slot recent death stamps + quarantine episodes (crash-loop
        #: detection, round 22) — both under ``_rep_lock``
        self._death_times: Dict[int, Deque[float]] = {}
        self._quarantine: Dict[int, dict] = {}
        self._source_path = str(path)
        self._source_version = str(version)
        self._deploy_status: Optional[dict] = None
        self._deploy_lock = threading.Lock()
        self._counts: Counter = Counter()
        self._counts_lock = threading.Lock()
        self._latencies: Deque[float] = deque(maxlen=512)
        self._threads: List[threading.Thread] = []
        self._poll_stop = threading.Event()
        self._boot_replicas()
        if start:
            self.start()

    # -- lifecycle -----------------------------------------------------------

    def _spawn_backend(self, name: str, path: str, version: str
                       ) -> Tuple[ReplicaClient, Optional[ReplicaProcess]]:
        process = ReplicaProcess.spawn(
            path, version, extra_env=self._replica_env,
            boot_timeout_s=self.config.spawn_timeout_s,
        )
        return (ReplicaClient(process.serve_address,
                              process.telemetry_address), process)

    def _boot_replicas(self) -> None:
        """Spawn the initial fleet in parallel (replica boot is seconds
        of jax import + model load each; serial boot would multiply it).
        Any boot failure stops the already-started children and raises —
        a router that opens must open whole."""
        results: List[Optional[_Replica]] = [None] * self.config.replicas
        errors: List[BaseException] = []

        def boot(i: int) -> None:
            try:
                results[i] = self._make_replica(i)
            except BaseException as exc:  # noqa: BLE001 - re-raised below
                errors.append(exc)

        threads = [threading.Thread(target=boot, args=(i,), daemon=True)
                   for i in range(self.config.replicas)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            for replica in results:
                if replica is not None:
                    self._stop_backend(replica)
            raise errors[0]
        with self._rep_lock:
            self._slots = results
        obs.gauge_set("router.replicas", float(self.config.replicas))

    def _make_replica(self, index: int) -> _Replica:
        with self._rep_lock:
            self._generation += 1
            generation = self._generation
            path, version = self._source_path, self._source_version
        name = f"replica-{index}-g{generation}"
        client, process = self._factory(name, path, version)
        replica = _Replica(name, client, process, version=version,
                           scrape_strikes=self.config.scrape_strikes)
        # first health sample inline: a fresh replica is routable the
        # moment it answers, not one poll interval later
        try:
            replica.mark_probe(client.probe())
        except ReplicaUnreachableError:
            replica.note_probe_failure()
        if obs.trace.enabled() and process is not None:
            # one NTP-style clock sample per (re)spawn: enough for the
            # fleet stitcher to land this child's spans on our timeline
            try:
                obs.trace.note_clock_offset(**client.clock_probe())
            except (ReplicaUnreachableError, AttributeError, TypeError):
                pass
        return replica

    def start(self) -> "ReplicaRouter":
        with self._cond:
            if self._closed:
                raise ServerClosedError("router already shut down")
            if self._threads:
                return self
        threads = [
            threading.Thread(target=self._dispatch_loop,
                             name=f"fmt-router-dispatch-{i}", daemon=True)
            for i in range(self.config.dispatch_threads)
        ]
        threads.append(threading.Thread(
            target=self._poll_loop, name="fmt-router-poll", daemon=True))
        for t in threads:
            t.start()
        self._threads = threads
        return self

    def __enter__(self) -> "ReplicaRouter":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.shutdown()
        return False

    def shutdown(self, drain: bool = True, timeout: float = 60.0) -> None:
        """Stop routing.  ``drain=True`` serves the queue first;
        ``drain=False`` sheds it with the ``shutdown`` reason.  Replicas
        get SIGTERM (they drain their own queues and exit 0).
        Idempotent."""
        dropped: List[_RouterRequest] = []
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._stopping = True
            if not drain:
                dropped = list(self._queue)
                self._queue.clear()
                self._queued_rows = 0
            self._cond.notify_all()
        for request in dropped:
            self._fail(request, self._shed_error(
                SHED_SHUTDOWN, "router shut down without draining"))
        self._poll_stop.set()
        started = bool(self._threads)
        for t in self._threads:
            t.join(timeout=timeout)
        self._threads = []
        if not started and drain:
            # never started: drain inline so queued futures still resolve
            while True:
                request = self._next_request(block=False)
                if request is None:
                    break
                self._route(request)
        # wait out in-flight respawns (they abort on the stopping flag,
        # stopping their own replacement) so the fresh snapshot below
        # covers every child that could have been installed
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            with self._rep_lock:
                respawning = bool(self._respawning)
            if not respawning:
                break
            time.sleep(0.05)
        stoppers = [threading.Thread(target=self._stop_backend, args=(r,),
                                     daemon=True)
                    for r in self._replicas_snapshot() if r is not None]
        for t in stoppers:
            t.start()
        for t in stoppers:
            t.join(timeout=30.0)
        obs.gauge_set("router.replicas_ready", 0.0)
        self._write_report()

    @staticmethod
    def _stop_backend(replica: _Replica) -> None:
        if replica.process is not None:
            replica.process.stop()

    # -- the request path ----------------------------------------------------

    def submit(self, table, deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None) -> Future:
        """Enqueue one request for the fleet; returns a Future resolving
        to a :class:`ServeResult`.  Sheds reason-coded at the door when
        the router queue is at ``FMT_ROUTER_QUEUE_CAP`` rows.

        ``tenant`` (ISSUE 20) names the registered model that serves the
        rows; None routes to each replica's default deployed model, and
        the key is validated/resolved at the REPLICA door (the router
        holds no model state)."""
        n = table.num_rows()
        if n == 0:
            raise ValueError("empty request: submit at least one row")
        now = now_s()
        deadline_at = (now + float(deadline_ms) / 1e3
                       if deadline_ms and deadline_ms > 0 else None)
        trace_attrs = {"rows": n}
        if tenant is not None:
            trace_attrs["tenant"] = tenant
        req_trace = obs.trace.start_request("router.request", trace_attrs)
        t_submit = time.perf_counter()
        request = _RouterRequest(table=table, future=Future(),
                                 enqueued_at=now, deadline_at=deadline_at,
                                 n_rows=n, trace=req_trace, tenant=tenant)
        rejected = None
        with self._cond:
            if self._closed or self._stopping:
                if req_trace is not None:
                    req_trace.end(status="shed",
                                  attrs={"shed_reason": SHED_SHUTDOWN})
                raise ServerClosedError("router is shut down")
            if self._queued_rows + n > self.config.queue_cap:
                rejected = (
                    f"{self._queued_rows} rows queued against a cap of "
                    f"{self.config.queue_cap} (request adds {n})"
                )
            else:
                self._queue.append(request)
                self._queued_rows += n
                obs.gauge_set("router.queue_depth", self._queued_rows)
                self._cond.notify()
        if rejected is not None:
            if req_trace is not None:
                req_trace.end(status="shed",
                              attrs={"shed_reason": SHED_QUEUE_FULL})
            raise self._shed_error(
                SHED_QUEUE_FULL, rejected,
                trace_id=req_trace.trace_id if req_trace else None)
        if req_trace is not None:
            obs.trace.record_span((req_trace.ctx,), "submit",
                                  time.perf_counter() - t_submit,
                                  {"rows": n})
        self._tally("router.requests")
        self._tally("router.request_rows", n)
        obs.counter_add("router.requests")
        obs.counter_add("router.request_rows", n)
        return request.future

    def predict(self, table, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None,
                tenant: Optional[str] = None) -> ServeResult:
        """Blocking convenience: ``submit(...).result(timeout)``."""
        return self.submit(table, deadline_ms=deadline_ms,
                           tenant=tenant).result(timeout)

    # -- dispatch ------------------------------------------------------------

    def _dispatch_loop(self) -> None:
        while True:
            request = self._next_request()
            if request is None:
                return
            try:
                self._route(request)
            except BaseException as exc:  # noqa: BLE001 - lane must survive
                # _route resolves every expected failure into the future
                # itself; anything that still escapes must not kill the
                # dispatch lane (a dead lane strands queued futures)
                self._fail(request, exc)

    def _next_request(self, block: bool = True
                      ) -> Optional[_RouterRequest]:
        """Pop one request (FIFO), shedding expired entries on the way.
        Returns None when the router is stopping and the queue is empty.
        Sheds complete OUTSIDE the lock (done-callbacks may re-enter)."""
        while True:
            expired: Optional[_RouterRequest] = None
            with self._cond:
                while not self._queue:
                    if self._stopping or not block:
                        return None
                    self._cond.wait()
                request = self._queue.popleft()
                self._queued_rows -= request.n_rows
                obs.gauge_set("router.queue_depth", self._queued_rows)
                if request.expired(now_s()):
                    expired = request
            if expired is not None:
                self._fail(expired, self._shed_error(
                    SHED_DEADLINE, "deadline passed in the router queue"))
                continue
            if not request.future.set_running_or_notify_cancel():
                continue  # caller cancelled while queued
            return request

    def _route(self, request: _RouterRequest) -> None:
        """Forward one request, retrying across replicas per the shed
        classification, until it serves, its budget runs out, or no
        replica can take it."""
        from flink_ml_tpu.fault.injection import InjectedFault, maybe_fail

        req_trace = request.trace
        if req_trace is not None:
            obs.trace.record_span(
                (req_trace.ctx,), "queue_wait",
                max(now_s() - request.enqueued_at, 0.0))
        # install the request's context on THIS dispatch lane: each
        # attempt below records a router.dispatch span under the root —
        # retries render as SIBLINGS, and the winning attempt's span is
        # the parent the replica's adopted subtree nests under
        with obs.trace.use((req_trace.ctx,) if req_trace is not None
                           else ()):
            excluded: set = set()
            last_exc: Optional[BaseException] = None
            while True:
                now = now_s()
                if request.expired(now):
                    self._fail(request, self._shed_error(
                        SHED_DEADLINE, "deadline passed while routing"))
                    return
                replica = self._pick(excluded)
                if replica is None and excluded:
                    # every routable replica already failed this request
                    # once; budget permitting, give the fleet a second
                    # pass (their transient load — a full queue — may
                    # have drained)
                    excluded.clear()
                    replica = self._pick(excluded)
                if replica is None:
                    replica = self._wait_routable(request)
                    if replica is None:
                        self._fail(request, last_exc or self._shed_error(
                            SHED_NO_REPLICA,
                            "no ready replica (all dead, draining, or "
                            "reason-coded unready)"))
                        return
                try:
                    with obs.trace.span("router.dispatch", {
                        "replica": replica.name,
                        "attempt": request.attempts + 1,
                        "rows": request.n_rows,
                    }):
                        maybe_fail("router.dispatch")
                        replica.begin_dispatch()
                        try:
                            ctx = obs.trace.current()
                            result = replica.client.submit(
                                request.table,
                                # remaining time re-read NOW:
                                # _wait_routable may have blocked for
                                # seconds since the iteration's deadline
                                # check, and a stale clock would hand the
                                # replica budget the caller no longer has
                                deadline_ms=request.remaining_ms(now_s()),
                                timeout_s=_DISPATCH_TIMEOUT_S,
                                # kwarg only when keyed: default-tenant
                                # traffic must reach clients (and fakes)
                                # that predate the tenant parameter
                                **({"tenant": request.tenant}
                                   if request.tenant is not None else {}),
                                **({"trace_ctx": (ctx[0].trace_id,
                                                  ctx[0].span_id)}
                                   if ctx else {}),
                            )
                        finally:
                            replica.end_dispatch()
                except ServerOverloadedError as exc:
                    policy = shed_policy(exc.reason)
                    if policy == POLICY_ROUTE_AWAY:
                        # the replica said "I am degraded", not "I am
                        # busy": out of rotation until a probe clears it
                        replica.mark_unready(exc.reason)
                    if policy == POLICY_FAIL or not self._budget(request):
                        self._tally(f"router.shed.{exc.reason}")
                        self._tally("router.shed")
                        obs.counter_add("router.shed")
                        obs.counter_add(f"router.shed.{exc.reason}")
                        self._fail(request, exc)
                        return
                    excluded.add(replica.name)
                    last_exc = exc
                    self._note_retry(replica.name, exc.reason)
                    continue
                except (ReplicaUnreachableError, InjectedFault) as exc:
                    if isinstance(exc, ReplicaUnreachableError):
                        self._note_unreachable(replica)
                    if not self._budget(request):
                        self._fail(request, exc)
                        return
                    excluded.add(replica.name)
                    last_exc = exc
                    self._note_retry(replica.name, type(exc).__name__)
                    continue
                except ReplicaRemoteError as exc:
                    # a real failure inside the replica's transform is
                    # deterministic for this request — no cross-replica
                    # retry
                    self._tally("router.failed_requests")
                    obs.counter_add("router.failed_requests")
                    self._fail(request, exc)
                    return
                except BaseException as exc:  # noqa: BLE001 - futures carry it
                    self._fail(request, exc)
                    return
                latency_ms = (now_s() - request.enqueued_at) * 1e3
                with self._counts_lock:
                    # under the tally lock: stats() sorts this deque from
                    # other threads, and a concurrent append would raise
                    # "deque mutated during iteration"
                    self._latencies.append(latency_ms)
                obs.observe("router.request_latency_ms", latency_ms)
                self._tally("router.served_requests")
                self._tally("router.served_rows", result.num_rows)
                obs.counter_add("router.served_requests")
                if req_trace is not None:
                    # end the root BEFORE resolving the future (the
                    # server-side discipline) and backfill the trace id
                    # onto the result so callers can correlate without
                    # tailing span files
                    req_trace.end(status="ok", attrs={
                        "replica": replica.name, "version": result.version,
                    })
                    if getattr(result, "trace_id", None) is None:
                        result.trace_id = req_trace.trace_id
                if not request.future.cancelled():
                    request.future.set_result(result)
                return

    def _budget(self, request: _RouterRequest) -> bool:
        """Consume one retry; False when the request is out of budget
        (``FMT_ROUTER_RETRIES`` cross-replica retries per request)."""
        request.attempts += 1
        return request.attempts <= self.config.retries

    def _note_retry(self, replica_name: str, why: str) -> None:
        self._tally("router.retries")
        obs.counter_add("router.retries")
        obs.flight.record("router.retry", replica=replica_name, why=why)

    def _fail(self, request: _RouterRequest,
              exc: BaseException) -> None:
        req_trace = getattr(request, "trace", None)
        if req_trace is not None:
            if isinstance(exc, ServerOverloadedError):
                req_trace.end(status="shed", attrs={
                    "shed_reason": getattr(exc, "reason", "")})
            else:
                req_trace.end(status="error",
                              attrs={"error": type(exc).__name__})
        if not request.future.done():
            request.future.set_exception(exc)

    def _pick(self, excluded: set) -> Optional[_Replica]:
        """Power-of-two-choices among routable replicas: two random
        candidates, the lower observed load wins — near-optimal balance
        with O(1) work and no global scan under a lock.

        Liveness is re-checked HERE, not just on the poll loop: a
        replica's last probe may be stale (on a starved box the scrape
        loop can fall seconds behind), but ``waitpid`` is a microsecond
        syscall — a killed replica must never be picked on stale health,
        and noticing its corpse here starts the respawn immediately."""
        candidates = []
        for replica in self._replicas_snapshot():
            if replica is None or replica.is_dead():
                continue
            # liveness outranks health: a corpse must enter the respawn
            # path even when a stale probe already marked it unready
            if (replica.process is not None
                    and replica.process.poll_dead() is not None):
                self._kick_death(replica)
                continue
            if replica.routable() and replica.name not in excluded:
                candidates.append(replica)
        if not candidates:
            return None
        if len(candidates) == 1:
            return candidates[0]
        a, b = random.sample(candidates, 2)
        return a if a.load() <= b.load() else b

    def _kick_death(self, replica: _Replica) -> None:
        """Route a corpse discovered outside the poll loop into the
        death/respawn path (idempotent under the claim guard)."""
        index = self._index_of(replica)
        if index is not None:
            self._on_replica_death(
                replica=replica, index=index,
                why=f"exit {replica.process.poll_dead()}")

    def _wait_routable(self, request: _RouterRequest,
                       timeout_s: float = 5.0) -> Optional[_Replica]:
        """Brief grace for a transiently empty rotation (a respawn or a
        breaker cooldown mid-flight), bounded by the request deadline."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if request.expired(now_s()):
                return None
            self._sweep_liveness()
            replica = self._pick(set())
            if replica is not None:
                return replica
            time.sleep(0.01)
        return None

    def _shed_error(self, reason: str, detail: str,
                    trace_id: Optional[str] = None) -> ServerOverloadedError:
        self._tally("router.shed")
        self._tally(f"router.shed.{reason}")
        obs.counter_add("router.shed")
        obs.counter_add(f"router.shed.{reason}")
        obs.flight.record("router.shed", reason=reason, detail=detail)
        return ServerOverloadedError(reason, detail, trace_id=trace_id)

    # -- supervision (poll loop) ---------------------------------------------

    def _poll_loop(self) -> None:
        interval = max(self.config.poll_ms, 1.0) / 1e3
        beat = 0
        while not self._poll_stop.wait(timeout=interval):
            beat += 1
            # liveness first, health second: the waitpid sweep costs
            # microseconds and must never queue behind HTTP probes (on a
            # starved box one slow /metrics scrape is seconds)
            self._sweep_liveness()
            # readiness every beat; the queue-depth /metrics scrape —
            # rendering the child's whole registry, the expensive half —
            # on a slower cadence (the in-flight counter keeps the
            # balancer current between scrapes)
            depth = beat % _DEPTH_SCRAPE_EVERY == 0
            for index, replica in enumerate(self._replicas_snapshot()):
                if replica is None or replica.is_dead():
                    continue
                if not replica.try_begin_probe():
                    continue  # its previous probe is still in flight
                # one short-lived thread per probe: a wedged replica's
                # probe timeout stalls only itself — the survivors'
                # health keeps refreshing at the polled cadence
                threading.Thread(
                    target=self._probe_replica,
                    args=(index, replica, depth),
                    name=f"fmt-router-probe-{index}", daemon=True,
                ).start()
            ready = sum(1 for r in self._replicas_snapshot()
                        if r is not None and r.routable())
            obs.gauge_set("router.replicas_ready", float(ready))

    def _probe_replica(self, index: int, replica: _Replica,
                       depth: bool) -> None:
        try:
            while True:
                try:
                    replica.mark_probe(replica.client.probe(depth=depth))
                    return
                except Exception:  # noqa: BLE001 - the probe must not escape
                    # ANY probe failure (unreachable, torn response, a
                    # future probe bug) reads as a strike, never as a
                    # dead probe thread — a silent supervisor is the one
                    # failure mode a supervisor must not have
                    failures = replica.note_probe_failure()
                    if (replica.process is None
                            and failures >= _PROBE_FAILURE_DEBOUNCE):
                        self._on_replica_death(index, replica,
                                               "probe unreachable")
                        return
                    if (failures >= self.config.scrape_strikes
                            or replica.is_dead()):
                        return  # struck out: out of rotation until a
                        # probe succeeds again
                    # below the strike count the replica KEPT its slot
                    # in rotation — re-probe after a short jittered
                    # delay instead of spending a full poll interval
                    # per strike (a blackholed scrape should cost
                    # milliseconds of uncertainty, not seconds)
                    delay = min(max(self.config.poll_ms, 1.0) / 1e3, 0.25)
                    if self._poll_stop.wait(
                            timeout=delay * random.uniform(0.5, 1.5)):
                        return
        finally:
            replica.end_probe()

    def _sweep_liveness(self) -> None:
        """``waitpid`` every process-backed replica; corpses go straight
        to the death/respawn path.  Called from the poll loop and from
        request paths that would otherwise wait on stale health."""
        for index, replica in enumerate(self._replicas_snapshot()):
            if (replica is not None and not replica.is_dead()
                    and replica.process is not None
                    and replica.process.poll_dead() is not None):
                self._on_replica_death(
                    index, replica,
                    f"exit {replica.process.poll_dead()}")

    def _note_unreachable(self, replica: _Replica) -> None:
        """A dispatch hit a dead socket: the fastest death signal there
        is.  Mark and let the poll loop confirm + respawn."""
        self._tally("router.dispatch_unreachable")
        obs.counter_add("router.dispatch_unreachable")
        replica.mark_unready("unreachable")
        if replica.process is not None and not replica.process.alive():
            index = self._index_of(replica)
            if index is not None:
                self._on_replica_death(index, replica, "dead pipe")

    def _index_of(self, replica: _Replica) -> Optional[int]:
        with self._rep_lock:
            for i, r in enumerate(self._slots):
                if r is replica:
                    return i
        return None

    def _on_replica_death(self, index: int, replica: _Replica,
                          why: str) -> None:
        """A replica is gone: eject it, count it, respawn a replacement
        on a supervisor thread (boot takes seconds — the poll loop must
        keep probing the survivors meanwhile)."""
        with self._cond:
            stopping = self._stopping
        if stopping:
            # a corpse noticed DURING shutdown is the shutdown's own
            # SIGTERM, not a death: no counter, no flight event, no
            # respawn — a clean stop must not read as a crash
            replica.mark_dead(why)
            return
        with self._rep_lock:
            if index in self._respawning or self._slots[index] is not replica:
                return  # another thread already claimed this death
            self._respawning.add(index)
            self._death_times.setdefault(
                index, deque(maxlen=32)).append(time.monotonic())
        replica.mark_dead(why)
        exit_status = (replica.process.poll_dead()
                       if replica.process is not None else None)
        self._tally("router.replica_deaths")
        obs.counter_add("router.replica_deaths")
        obs.flight.record("router.replica_death", replica=replica.name,
                          why=why)
        if replica.process is not None:
            replica.process.stop(grace_s=0.1)  # reap the zombie
        threading.Thread(target=self._respawn, args=(index, exit_status),
                         name=f"fmt-router-respawn-{index}",
                         daemon=True).start()

    def _crashloop_backoff(self, index: int,
                           exit_status) -> Optional[float]:
        """Crash-loop gate for one slot's respawn: ``None`` = spawn
        immediately; a float = the slot just entered quarantine — the
        respawn must sit out that many seconds first.  A slot whose
        replica died ``FMT_ROUTER_CRASHLOOP_MAX`` times inside the
        window is looping on something a hot respawn cannot fix (bad
        artifact, dead dependency, OOM killer) — parking it with
        exponential backoff keeps the survivors' poll loop and the
        spawn path from burning on a doomed slot.  Quarantines are
        observable: ``router.crashloops`` counter, quarantine state in
        :meth:`stats`, and a ``router.crashloop`` flight dump naming
        the slot and exit status."""
        window = self.config.crashloop_window_s
        limit = self.config.crashloop_max
        now = time.monotonic()
        with self._rep_lock:
            deaths = self._death_times.setdefault(index, deque(maxlen=32))
            while deaths and now - deaths[0] > window:
                deaths.popleft()
            if limit < 1 or len(deaths) < limit:
                # below the threshold (or detection disabled): a prior
                # quarantine episode ended in a replica that outlived
                # the window, so the slot's slate is clean again
                self._quarantine.pop(index, None)
                return None
            episodes = self._quarantine.get(index, {}).get("episodes", 0) + 1
            backoff = min(_CRASHLOOP_BACKOFF_S * (2 ** (episodes - 1)),
                          _CRASHLOOP_BACKOFF_CAP_S)
            self._quarantine[index] = {
                "episodes": episodes,
                "backoff_s": backoff,
                "until": now + backoff,
            }
            deaths_in_window = len(deaths)
        self._tally("router.crashloops")
        obs.counter_add("router.crashloops")
        obs.flight.record("router.crashloop", slot=index,
                          exit_status=exit_status,
                          deaths_in_window=deaths_in_window,
                          backoff_s=backoff)
        obs.flight.dump("router_crashloop", extra={
            "slot": index, "exit_status": exit_status,
            "deaths_in_window": deaths_in_window, "backoff_s": backoff,
        })
        return backoff

    def _respawn(self, index: int, exit_status=None) -> None:
        import warnings

        try:
            backoff = self._crashloop_backoff(index, exit_status)
            if backoff is not None and self._poll_stop.wait(timeout=backoff):
                return  # shutdown interrupted the quarantine sleep
            for attempt in range(1, _MAX_SPAWN_ATTEMPTS + 1):
                try:
                    replacement = self._make_replica(index)
                except BaseException as exc:  # noqa: BLE001 - bounded retry
                    self._tally("router.spawn_failures")
                    obs.counter_add("router.spawn_failures")
                    if attempt == _MAX_SPAWN_ATTEMPTS:
                        warnings.warn(
                            f"replica slot {index} abandoned after "
                            f"{attempt} spawn failures "
                            f"({type(exc).__name__}: {exc}); the fleet "
                            "continues on the survivors",
                            RuntimeWarning, stacklevel=2,
                        )
                        obs.flight.record("router.slot_abandoned",
                                          slot=index,
                                          error=type(exc).__name__)
                        return
                    time.sleep(0.5 * attempt)
                    continue
                with self._cond:
                    stopping = self._stopping
                if stopping:
                    # the router shut down while this replacement was
                    # booting: installing it would orphan a live child
                    # nobody supervises — stop it instead
                    self._stop_backend(replacement)
                    return
                with self._rep_lock:
                    self._slots[index] = replacement
                self._tally("router.respawns")
                obs.counter_add("router.respawns")
                # cold-start resilience (ISSUE 18): stamp how much of the
                # warm-artifact ladder the replacement inherits — a 0 here
                # on a fleet that should be warm is the first thing an
                # operator chasing a post-crash latency spike needs to see
                from flink_ml_tpu.serving import warmstart

                with self._rep_lock:
                    source_path = self._source_path
                warm = warmstart.inherited_manifest_entries(source_path)
                if warm:
                    self._tally("router.respawns_warm")
                    obs.counter_add("router.respawns_warm")
                obs.flight.record("router.respawn", slot=index,
                                  replica=replacement.name,
                                  warm_entries=warm)
                return
        finally:
            with self._rep_lock:
                self._respawning.discard(index)

    # -- elastic membership (round 22) ---------------------------------------

    def _drain_replica(self, replica: _Replica) -> bool:
        """The drain contract a rolling deploy and a scale-down share:
        stop routing to the replica, then wait out its router-originated
        in-flight work, bounded by ``FMT_ROUTER_DRAIN_TIMEOUT_S``.
        False on timeout — the replica is LEFT DRAINING; the caller
        either re-admits it (``set_draining(False)``) or terminates it."""
        replica.set_draining(True)
        return replica.wait_drained(self.config.drain_timeout_s)

    def add_replica(self) -> Optional[str]:
        """Grow the fleet by one replica through the standard spawn path
        (the child inherits the sealed warmstart manifest, so its first
        request stays warm).  Returns the new replica's name, or None
        when membership can't change right now (router stopping, or a
        rolling deploy holds the fleet — a roll iterates a fleet
        snapshot and must not race a slot appearing mid-roll).  Raises
        on spawn failure; the fleet is unchanged either way (the
        reserved slot stays a tombstone every iterator already skips)."""
        if not self._deploy_lock.acquire(blocking=False):
            return None
        try:
            with self._cond:
                if self._closed or self._stopping:
                    return None
            with self._rep_lock:
                index = len(self._slots)
                self._slots.append(None)     # reserve the slot index...
                self._respawning.add(index)  # ...and claim it (shutdown
                # waits out every claimed slot before its final sweep)
            try:
                replica = self._make_replica(index)
            except BaseException:
                with self._rep_lock:
                    self._respawning.discard(index)
                self._tally("router.spawn_failures")
                obs.counter_add("router.spawn_failures")
                raise
            with self._cond:
                stopping = self._stopping
            with self._rep_lock:
                self._respawning.discard(index)
                if not stopping:
                    self._slots[index] = replica
            if stopping:
                # shut down while the child booted: installing it would
                # orphan a live process nobody supervises
                self._stop_backend(replica)
                return None
            self._tally("router.replicas_added")
            obs.counter_add("router.replicas_added")
            obs.gauge_set("router.replicas", float(self.fleet_size()))
            obs.flight.record("router.replica_added", slot=index,
                              replica=replica.name)
            return replica.name
        finally:
            self._deploy_lock.release()

    def remove_replica(self) -> Optional[str]:
        """Shrink the fleet by one replica, drain-aware: the least
        loaded routable replica stops taking new traffic, its in-flight
        requests finish (the same :meth:`_drain_replica` contract a
        rolling deploy uses — zero caller-visible failures), then it is
        terminated (SIGTERM: the replica drains its own queue and exits
        0) and its slot tombstoned so every index stays stable.
        Returns the removed replica's name; None when nothing is
        removable — a lone routable replica is never removed, a busy
        replica whose drain timed out is re-admitted, and a rolling
        deploy holds the fleet."""
        if not self._deploy_lock.acquire(blocking=False):
            return None
        try:
            with self._cond:
                if self._closed or self._stopping:
                    return None
            candidates = [r for r in self._replicas_snapshot()
                          if r is not None and r.routable()]
            if len(candidates) <= 1:
                return None
            victim = min(candidates, key=lambda r: r.load())
            if not self._drain_replica(victim):
                victim.set_draining(False)  # busy is not removable
                self._tally("router.remove_drain_timeouts")
                obs.counter_add("router.remove_drain_timeouts")
                return None
            index = self._index_of(victim)
            with self._rep_lock:
                removable = (index is not None
                             and index not in self._respawning
                             and self._slots[index] is victim)
                if removable:
                    self._slots[index] = None
            if not removable:
                # the victim died mid-drain and its death was claimed,
                # or the fleet changed under us: re-admit and report
                # nothing removed (the supervisor owns the slot now)
                victim.set_draining(False)
                return None
            victim.mark_dead("removed")
            self._stop_backend(victim)
            self._tally("router.replicas_removed")
            obs.counter_add("router.replicas_removed")
            obs.gauge_set("router.replicas", float(self.fleet_size()))
            obs.flight.record("router.replica_removed", slot=index,
                              replica=victim.name)
            return victim.name
        finally:
            self._deploy_lock.release()

    def fleet_size(self) -> int:
        """Occupied slots (live, booting, or awaiting respawn) — the
        membership count scale decisions measure against; tombstoned
        (removed/abandoned) slots don't count."""
        with self._rep_lock:
            return sum(1 for r in self._slots if r is not None)

    def quarantined_count(self) -> int:
        """Slots currently parked by the crash-loop quarantine — the
        autoscaler reads these as capacity loss and compensates."""
        now = time.monotonic()
        with self._rep_lock:
            return sum(1 for q in self._quarantine.values()
                       if q.get("until", 0.0) > now)

    def fleet_health(self) -> dict:
        """One autoscaler observation off state the router already
        maintains — the poll loop's probes and the door tallies, no
        extra scrape.  ``burn_seen`` distinguishes "no SLO burning"
        from "no burn data at all" (a replica with no judged SLO window
        reports an empty ``burn_rates``), and ``probe_suspect`` counts
        replicas whose unreadiness is a failed/unreachable probe rather
        than a reason-coded verdict — fail-closed inputs a scale-down
        decision must treat as vetoes, never as idleness."""
        self._sweep_liveness()
        snaps = [r.snapshot() for r in self._replicas_snapshot()
                 if r is not None]
        with self._cond:
            queued = self._queued_rows
        with self._counts_lock:
            requests = float(self._counts.get("router.requests", 0))
            shed = float(self._counts.get("router.shed", 0))
        ready = live = probe_suspect = 0
        max_burn, burn_seen = 0.0, False
        for snap in snaps:
            if not snap["dead"]:
                live += 1
            if snap["ready"] and not snap["draining"] and not snap["dead"]:
                ready += 1
            rates = snap.get("burn_rates") or {}
            if rates:
                burn_seen = True
                max_burn = max(max_burn, max(rates.values()))
            if not snap["ready"] and any(
                    r in ("unreachable", "probe_error")
                    for r in snap["reasons"]):
                probe_suspect += 1
        return {
            "size": len(snaps),
            "live": live,
            "ready": ready,
            "quarantined": self.quarantined_count(),
            "queued_rows": int(queued),
            "requests": requests,
            "shed": shed,
            "max_burn_rate": max_burn,
            "burn_seen": burn_seen,
            "probe_suspect": probe_suspect,
        }

    # -- rolling deploy ------------------------------------------------------

    def deploy(self, path: str, version: str) -> dict:
        """Zero-downtime rolling deploy: one replica at a time — drain,
        swap (the replica-side versioning.py contract), await readiness,
        re-admit — while the rest of the fleet serves.  Returns the
        per-replica status dict; raises :class:`RollingDeployError` on
        the first *deploy* failure (that replica kept its old version —
        the swap contract IS the rollback — and the rest of the fleet
        stays on the known-good version; the partial status is preserved
        at :attr:`deploy_status`).  A replica that turns out to be DEAD
        when the roll reaches it is not a deploy failure: it enters the
        respawn path (which boots the roll's target version) and the
        roll continues over the survivors."""
        with self._deploy_lock:
            self._tally("router.rolling_deploys")
            obs.counter_add("router.rolling_deploys")
            with self._rep_lock:
                previous_path = self._source_path
                previous = self._source_version
                # respawns mid-roll must boot the roll's TARGET: a slot
                # that dies while the fleet converges on `version` would
                # otherwise come back on the old one and stay there.
                # Reverted below if the roll fails.  Updated BEFORE the
                # liveness sweep — the sweep itself can start a respawn,
                # which must already see the target.
                self._source_path = str(path)
                self._source_version = str(version)
            self._sweep_liveness()  # roll over the LIVE fleet, not corpses
            status: dict = {"version": str(version), "previous": previous,
                            "ok": False, "replicas": []}
            obs.flight.record("router.rolling_deploy", version=str(version),
                              previous=previous)
            try:
                for replica in self._replicas_snapshot():
                    if replica is None or replica.is_dead():
                        status["replicas"].append({
                            "replica": getattr(replica, "name",
                                               "<empty slot>"),
                            "outcome": "skipped_dead",
                        })
                        continue
                    entry = {"replica": replica.name}
                    try:
                        if not self._drain_replica(replica):
                            entry["outcome"] = "drain_timeout"
                            status["replicas"].append(entry)
                            raise RollingDeployError(status)
                        try:
                            active = replica.client.deploy(
                                str(path), str(version))
                            if not self._await_ready(replica):
                                raise ReplicaUnreachableError(
                                    f"{replica.name} died awaiting "
                                    "post-deploy readiness")
                        except ReplicaUnreachableError as exc:
                            # the replica is GONE, not refusing the
                            # artifact: hand it to the supervisor (the
                            # respawn boots the target version) and keep
                            # rolling the survivors
                            entry["outcome"] = "died"
                            entry["detail"] = str(exc)
                            status["replicas"].append(entry)
                            self._sweep_liveness()
                            continue
                        except BaseException as exc:
                            # a real deploy refusal (corrupt artifact,
                            # broken warmup): this replica already
                            # rolled back to its old version — stop the
                            # roll so the fleet stays known-good.  A
                            # wire-wrapped refusal names the REPLICA-side
                            # exception (ModelIntegrityError), not the
                            # envelope.
                            entry["outcome"] = "failed"
                            entry["error"] = (
                                exc.remote_type
                                if isinstance(exc, ReplicaRemoteError)
                                else type(exc).__name__)
                            entry["detail"] = str(exc)
                            status["replicas"].append(entry)
                            raise RollingDeployError(status) from exc
                    finally:
                        replica.set_draining(False)
                    replica.set_version(active)
                    entry["outcome"] = "deployed"
                    entry["active_version"] = active
                    status["replicas"].append(entry)
            except RollingDeployError:
                with self._rep_lock:
                    self._source_path = previous_path
                    self._source_version = previous
                self._finish_deploy(status, ok=False)
                raise
            self._finish_deploy(status, ok=True)
            return status

    def _finish_deploy(self, status: dict, ok: bool) -> None:
        status["ok"] = ok
        with self._rep_lock:
            self._deploy_status = status
        obs.flight.record("router.rolling_deploy_done",
                          version=status["version"], ok=ok)
        if not ok:
            self._tally("router.deploy_failures")
            obs.counter_add("router.deploy_failures")
            obs.flight.dump("router_partial_deploy")

    def _await_ready(self, replica: _Replica,
                     timeout_s: float = 60.0) -> bool:
        """Post-swap re-admission gate: the replica must answer
        ``/readyz`` 200 (its warmup compiled, its deploy flag cleared)
        before it takes fresh traffic again.  Returns False when the
        replica DIED while waiting (the caller hands it to the
        supervisor); raises on a live replica that stays unready."""
        deadline = time.monotonic() + timeout_s
        last: dict = {}
        while time.monotonic() < deadline:
            if (replica.process is not None
                    and replica.process.poll_dead() is not None):
                return False
            try:
                last = replica.client.probe()
            except ReplicaUnreachableError:
                last = {"ready": False, "reasons": ["unreachable"]}
            if last.get("ready"):
                replica.mark_probe(last)
                return True
            time.sleep(0.02)
        raise RuntimeError(
            f"{replica.name} never returned to ready after deploy "
            f"(last reasons: {last.get('reasons')})"
        )

    # -- introspection -------------------------------------------------------

    @property
    def active_version(self) -> str:
        with self._rep_lock:
            return self._source_version

    @property
    def deploy_status(self) -> Optional[dict]:
        """The last rolling deploy's per-replica outcome (partial on
        failure) — what an operator reads after a RollingDeployError."""
        with self._rep_lock:
            return self._deploy_status

    def _replicas_snapshot(self) -> List[Optional[_Replica]]:
        with self._rep_lock:
            return list(self._slots)

    @property
    def replicas(self) -> List[dict]:
        """Point-in-time fleet view: per-replica readiness, reasons,
        load, pid/addresses — the /statusz analog."""
        return [r.snapshot() for r in self._replicas_snapshot()
                if r is not None]

    def ready_count(self) -> int:
        self._sweep_liveness()  # stale health must not count a corpse
        return sum(1 for r in self._replicas_snapshot()
                   if r is not None and r.routable())

    def _tally(self, name: str, n: float = 1) -> None:
        with self._counts_lock:
            self._counts[name] += n

    def stats(self) -> dict:
        """THIS router's tallies plus request-latency quantiles — the
        shutdown report's payload, readable live (per-router by
        construction, like ``ModelServer.stats``)."""
        from flink_ml_tpu.obs.registry import sample_quantile

        with self._counts_lock:
            delta = {k: v for k, v in sorted(self._counts.items()) if v}
            samples = sorted(self._latencies)
        if samples:
            delta["latency_p50_ms"] = round(
                sample_quantile(samples, 0.50), 3)
            delta["latency_p99_ms"] = round(
                sample_quantile(samples, 0.99), 3)
        delta["active_version"] = self.active_version
        delta["replicas_ready"] = self.ready_count()
        delta["replicas"] = self.replicas
        quarantined = self.quarantined_count()
        if quarantined:
            now = time.monotonic()
            with self._rep_lock:
                delta["quarantined_slots"] = {
                    str(i): {"episodes": q["episodes"],
                             "backoff_s": q["backoff_s"],
                             "remaining_s": round(q["until"] - now, 3)}
                    for i, q in self._quarantine.items()
                    if q.get("until", 0.0) > now
                }
        return delta

    def _write_report(self) -> None:
        if not obs.enabled():
            return
        from flink_ml_tpu.obs.report import serving_report

        serving_report("ReplicaRouter", extra=self.stats())
