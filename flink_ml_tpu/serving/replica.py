"""Replica subprocess lifecycle + wire protocol for the replica router.

One process is a scaling ceiling no matter how fast the chip path gets
(ROADMAP item 2): this module is the *execution substrate* half of the
scale-out split — everything needed to run one ``ModelServer`` as a
supervised child process and talk to it from a routing parent:

* **the child** (``python -m flink_ml_tpu.serving.replica``) loads a
  saved pipeline, brings up a ``ModelServer`` with telemetry on an
  ephemeral port (``telemetry_port=0``), and serves a tiny loopback
  data-plane HTTP endpoint in front of it: ``POST /submit`` forwards a
  request table into ``ModelServer.submit`` (the replica's dispatcher
  coalesces concurrent forwards into fused batches exactly as it does
  in-process callers), ``POST /deploy`` drives the round-10 zero-downtime
  swap contract (``versioning.py``: load -> verify -> pre-warm -> atomic
  swap; a corrupt artifact raises and the old version keeps serving),
  ``GET /healthz`` answers liveness.  Both bound ports are published for
  the parent: the data address through ``--address-file`` and the
  telemetry address through ``FMT_TELEMETRY_PORT_FILE`` (ISSUE 13's
  ephemeral-port discovery fix) — each written atomically
  (:func:`~flink_ml_tpu.obs.telemetry.write_port_file`);

* **the parent-side handles**: :class:`ReplicaProcess` spawns, boots,
  supervises, and stops one child (handshake with a boot deadline, log
  capture to the replica workdir, ``alive()``/``poll_dead()`` for the
  router's crash detection, SIGTERM-then-SIGKILL stop);
  :class:`ReplicaClient` is the matching wire client — ``submit`` returns
  a :class:`~flink_ml_tpu.serving.batcher.ServeResult` or re-raises the
  replica's reason-coded :class:`~flink_ml_tpu.serving.errors.
  ServerOverloadedError` exactly as an in-process caller would see it,
  ``probe`` scrapes ``/readyz`` + ``/metrics`` (through the STRICT
  :func:`~flink_ml_tpu.obs.telemetry.parse_openmetrics`, never a trusting
  regex) into the health view the router balances on.

Wire format: pickled numpy column payloads over loopback HTTP.  This is
*trusted same-user subprocess IPC* — both ends are this package, spawned
by this package, bound to 127.0.0.1 — not a public protocol; the framing
exists to cross a process boundary bit-exactly (results must be
bit-identical to a solo in-process ``transform``), not to be spoken by
strangers.  Tables travel as ``(field_names, field_types, column
buffers)`` so the per-table pack cache (which may pin device buffers)
never crosses the boundary.
"""

from __future__ import annotations

import io
import json
import os
import pickle
import signal
import subprocess
import sys
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from flink_ml_tpu.obs import trace
from flink_ml_tpu.serving.batcher import ServeResult
from flink_ml_tpu.serving.errors import (
    SHED_SHUTDOWN,
    ServerClosedError,
    ServerOverloadedError,
)

__all__ = [
    "ReplicaClient",
    "ReplicaProcess",
    "ReplicaRemoteError",
    "ReplicaUnreachableError",
    "decode_table",
    "encode_table",
    "main",
]


class ReplicaUnreachableError(RuntimeError):
    """The replica's endpoint did not answer (connection refused/reset,
    timeout, dead socket): the process is gone or wedged.  The router
    treats this as a replica failure — retry the request elsewhere, eject
    and respawn the replica — never as a request failure."""


class ReplicaRemoteError(RuntimeError):
    """The replica answered with a real (non-shed) failure: the transform
    raised, a deploy was refused.  ``remote_type`` names the exception
    class inside the replica (``ModelIntegrityError``, ``ValueError``,
    ...) so supervisors can classify without parsing prose."""

    def __init__(self, remote_type: str, detail: str):
        super().__init__(f"{remote_type}: {detail}")
        self.remote_type = remote_type
        self.detail = detail


# -- wire encoding ------------------------------------------------------------


def encode_table(table) -> tuple:
    """One table as ``(names, types, {name: column buffer})`` — schema and
    raw columns only, so the pickle never drags the table's device-layout
    pack cache (or anything else process-local) across the boundary."""
    names = list(table.schema.field_names)
    return (
        names,
        list(table.schema.field_types),
        {n: table.col(n) for n in names},
    )


def decode_table(wire: tuple):
    """Rebuild a :class:`~flink_ml_tpu.table.table.Table` from
    :func:`encode_table` output, buffer-exact (no re-coercion: the
    columns were valid buffers on the sending side and must stay
    bit-identical for the router's parity contract)."""
    from flink_ml_tpu.table.schema import Schema
    from flink_ml_tpu.table.table import Table

    names, types, cols = wire
    return Table(Schema(list(names), list(types)), dict(cols))


def _dumps(obj: dict) -> bytes:
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def _loads(data: bytes) -> dict:
    return pickle.loads(data)


# -- the in-child data-plane endpoint -----------------------------------------


class _DataHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer carrying the wrapped ``ModelServer`` for its
    handler threads (http.server hands handlers only the server object)."""

    daemon_threads = True
    model_server = None  # set by ReplicaDataServer before serving


class _DataHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"  # keep-alive: one socket per router lane

    def _reply(self, code: int, payload: dict) -> None:
        body = _dumps(payload)
        self.send_response(code)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length", "0"))
        return _loads(self.rfile.read(length))

    def do_GET(self) -> None:  # noqa: N802 - http.server contract
        if self.path.split("?", 1)[0] == "/healthz":
            # ``ts`` feeds the router's NTP-style clock probe: the fleet
            # stitcher corrects each replica's spans onto one timeline
            body = json.dumps({"ok": True, "pid": os.getpid(),
                               "ts": time.time()}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._reply(404, {"type": "NotFound", "detail": self.path})

    def do_POST(self) -> None:  # noqa: N802 - http.server contract
        path = self.path.split("?", 1)[0]
        try:
            if path == "/submit":
                self._submit(self._read_body())
            elif path == "/deploy":
                self._deploy(self._read_body())
            else:
                self._reply(404, {"type": "NotFound", "detail": path})
        except BrokenPipeError:  # caller hung up mid-response
            pass
        except Exception as exc:  # noqa: BLE001 - the wire carries it
            try:
                self._reply(500, {"type": type(exc).__name__,
                                  "detail": str(exc)})
            except Exception:  # noqa: BLE001 - socket already gone
                pass

    def _submit(self, payload: dict) -> None:
        server = self.server.model_server
        table = decode_table(payload["table"])
        remote = payload.get("trace") or {}
        try:
            # adopt the router's trace context for this handler thread:
            # the server's request root then JOINS the routed trace,
            # parented under the router's dispatch span
            with trace.adopt(remote.get("trace_id"),
                             remote.get("parent_span_id", "")):
                result = server.predict(
                    table,
                    deadline_ms=payload.get("deadline_ms"),
                    timeout=payload.get("timeout_s", 120.0),
                    tenant=payload.get("tenant"),
                )
        except ServerOverloadedError as exc:
            # the shed travels as DATA, reason code intact: the router's
            # retry classification consumes the code, not the prose
            self._reply(503, {"shed": exc.reason, "detail": str(exc),
                              "trace_id": exc.trace_id})
            return
        except ServerClosedError as exc:
            self._reply(503, {"shed": SHED_SHUTDOWN, "detail": str(exc),
                              "trace_id": None})
            return
        self._reply(200, {
            "table": encode_table(result.table),
            "quarantine": {name: encode_table(t)
                           for name, t in result.quarantine.items()},
            "version": result.version,
            # SUCCESSES carry the trace id too (sheds always did): a
            # caller can correlate any response with its waterfall
            "trace_id": result.trace_id,
        })

    def _deploy(self, payload: dict) -> None:
        server = self.server.model_server
        # the round-10 swap contract does the heavy lifting: a failure
        # here (corrupt artifact, broken warmup) left the old version
        # serving, and the 500 carries the loader's diagnostic type
        server.deploy(payload["path"], payload["version"])
        self._reply(200, {"version": server.active_version})

    def log_message(self, *args) -> None:  # silence per-request stderr
        pass


class ReplicaDataServer:
    """The replica-side data-plane endpoint: bind loopback-ephemeral,
    serve on daemon threads, stop cleanly.  Separate from the telemetry
    endpoint on purpose — probes must keep answering while the data plane
    is saturated, and GET-only telemetry never grows a POST surface."""

    def __init__(self, model_server, host: str = "127.0.0.1",
                 port: int = 0):
        self._host = host
        self._httpd = _DataHTTPServer((host, port), _DataHandler)
        self._httpd.model_server = model_server
        self._thread: Optional[threading.Thread] = None

    @property
    def host(self) -> str:
        return self._host

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def address(self) -> str:
        return f"{self._host}:{self.port}"

    def start(self) -> "ReplicaDataServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, name="fmt-replica-data",
                daemon=True, kwargs={"poll_interval": 0.1},
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        thread, self._thread = self._thread, None
        if thread is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            thread.join(timeout=timeout)


# -- the parent-side wire client ----------------------------------------------


class ReplicaClient:
    """HTTP client for one replica's data + telemetry endpoints.

    Data-plane POSTs ride a PERSISTENT per-thread connection (the
    router's dispatch lanes each keep one socket open to each replica):
    per-request TCP handshakes — and the handler thread the replica's
    ThreadingHTTPServer would spawn per connection — are paid once per
    lane, not once per request.  A keep-alive socket the replica closed
    between requests (restart, idle timeout) retries ONCE on a fresh
    connection before the failure is declared a dead replica."""

    def __init__(self, serve_address: str,
                 telemetry_address: Optional[str] = None):
        self.serve_address = serve_address
        self.telemetry_address = telemetry_address
        self._local = threading.local()

    def _connection(self, timeout_s: float):
        """This thread's persistent connection (fresh one on first use
        or after :meth:`_drop_connection`); returns ``(conn, reused)``."""
        import http.client

        conn = getattr(self._local, "conn", None)
        reused = conn is not None
        if conn is None:
            host, _, port = self.serve_address.rpartition(":")
            conn = http.client.HTTPConnection(host, int(port),
                                              timeout=timeout_s)
            self._local.conn = conn
        if conn.sock is not None:
            conn.sock.settimeout(timeout_s)
        else:
            conn.timeout = timeout_s
        return conn, reused

    def _drop_connection(self) -> None:
        conn = getattr(self._local, "conn", None)
        self._local.conn = None
        if conn is not None:
            try:
                conn.close()
            except Exception:  # noqa: BLE001 - already broken
                pass

    def _post(self, path: str, payload: dict, timeout_s: float) -> dict:
        import http.client

        body = _dumps(payload)
        last_exc: Optional[BaseException] = None
        for attempt in (1, 2):
            conn, reused = self._connection(timeout_s)
            try:
                conn.request("POST", path, body, {
                    "Content-Type": "application/octet-stream",
                })
                resp = conn.getresponse()
                data = resp.read()
                status = resp.status
            except (ConnectionError, TimeoutError,
                    http.client.HTTPException, OSError) as exc:
                # a half-written response (the replica died mid-reply)
                # parses as an HTTPException — same verdict as a refused
                # connection.  A REUSED socket failing cleanly is the
                # keep-alive race (the peer closed it between requests):
                # one retry on a fresh connection, then it's a dead peer.
                self._drop_connection()
                last_exc = exc
                if reused and attempt == 1 and not isinstance(
                        exc, TimeoutError):
                    continue
                break
            if status == 200:
                return _loads(data)
            try:
                answer = _loads(data)
            except Exception:  # noqa: BLE001 - a mangled error is a dead peer
                self._drop_connection()
                raise ReplicaUnreachableError(
                    f"replica {self.serve_address} returned undecodable "
                    f"error body (HTTP {status})"
                ) from None
            if "shed" in answer:
                raise ServerOverloadedError(
                    answer["shed"], answer.get("detail", ""),
                    trace_id=answer.get("trace_id"),
                ) from None
            raise ReplicaRemoteError(
                answer.get("type", "Unknown"), answer.get("detail", "")
            ) from None
        raise ReplicaUnreachableError(
            f"replica {self.serve_address} unreachable: {last_exc}"
        ) from last_exc

    def submit(self, table, deadline_ms: Optional[float] = None,
               timeout_s: float = 120.0,
               trace_ctx: Optional[tuple] = None,
               tenant: Optional[str] = None) -> ServeResult:
        """Forward one request; returns the replica's
        :class:`ServeResult` (tables bit-identical to an in-process
        serve) or raises the replica's reason-coded shed /
        :class:`ReplicaRemoteError` / :class:`ReplicaUnreachableError`.

        ``trace_ctx`` is an optional ``(trace_id, parent_span_id)`` pair
        shipped in the payload so the replica records its spans inside
        the ROUTER's trace (``trace.adopt`` on the far side).
        ``tenant`` is the multi-tenant routing key (ISSUE 20) — omitted
        from the payload when None, so the wire format stays readable by
        pre-tenant replicas."""
        payload = {
            "table": encode_table(table), "deadline_ms": deadline_ms,
            "timeout_s": timeout_s,
        }
        if tenant is not None:
            payload["tenant"] = tenant
        if trace_ctx:
            payload["trace"] = {"trace_id": trace_ctx[0],
                                "parent_span_id": trace_ctx[1]}
        answer = self._post("/submit", payload, timeout_s=timeout_s + 10.0)
        return ServeResult(
            table=decode_table(answer["table"]),
            quarantine={name: decode_table(wire)
                        for name, wire in answer["quarantine"].items()},
            version=answer["version"],
            trace_id=answer.get("trace_id"),
        )

    def deploy(self, path: str, version: str,
               timeout_s: float = 600.0) -> str:
        """Drive the replica's zero-downtime swap; returns the active
        version after the swap.  A failed deploy surfaces as
        :class:`ReplicaRemoteError` naming the replica-side exception
        (``ModelIntegrityError`` for a corrupt artifact) — the replica
        keeps serving its old version (the versioning.py contract)."""
        answer = self._post("/deploy", {"path": path, "version": version},
                            timeout_s=timeout_s)
        return answer["version"]

    def healthz(self, timeout_s: float = 2.0) -> dict:
        import urllib.request

        try:
            with urllib.request.urlopen(
                f"http://{self.serve_address}/healthz", timeout=timeout_s
            ) as resp:
                return json.loads(resp.read().decode())
        except Exception as exc:  # noqa: BLE001 - any failure = unreachable
            raise ReplicaUnreachableError(
                f"replica {self.serve_address} healthz failed: {exc}"
            ) from exc

    def clock_probe(self, timeout_s: float = 2.0) -> dict:
        """NTP-style clock-offset estimate for this replica's process:
        ``{"pid", "offset_s", "rtt_s"}``, where ``offset_s`` is the
        replica wall clock minus ours, measured against the probe RTT's
        midpoint (the error bound is the RTT asymmetry — loopback
        microseconds, far below span widths).  The router feeds this to
        :func:`flink_ml_tpu.obs.trace.note_clock_offset` so the fleet
        stitcher lands every process's spans on ONE timeline."""
        t0 = time.time()
        body = self.healthz(timeout_s=timeout_s)
        rtt = time.time() - t0
        server_ts = float(body.get("ts") or 0.0)
        offset = server_ts - (t0 + rtt / 2.0) if server_ts else 0.0
        return {"pid": int(body.get("pid") or 0), "offset_s": offset,
                "rtt_s": rtt}

    def probe(self, timeout_s: float = 2.0, depth: bool = True) -> dict:
        """One health-poll sample off the replica's telemetry plane:
        ``{"ready": bool, "reasons": [str, ...], "queue_depth": float,
        "burn_rates": {slo_name: rate}}``.

        ``/readyz`` gives the reason-coded verdict (``breaker_open``,
        ``memory_pressure``, ``slo_burning``, ``drift``,
        ``deploy_in_progress``, ``queue_saturated``, ...); ``/metrics``
        — validated through the STRICT OpenMetrics parser, so a
        half-written scrape can never feed the balancer garbage — gives
        the queue depth power-of-two-choices compares.  ``depth=False``
        skips the metrics scrape (rendering a full registry exposition
        is the expensive half of a probe; the router refreshes depth on
        a slower cadence than readiness) — the sample then carries no
        ``queue_depth`` key so the caller keeps its last value."""
        import http.client
        import urllib.error
        import urllib.request

        from flink_ml_tpu.obs import telemetry

        if not self.telemetry_address:
            raise ReplicaUnreachableError(
                f"replica {self.serve_address} has no telemetry address"
            )
        base = f"http://{self.telemetry_address}"
        try:
            try:
                with urllib.request.urlopen(f"{base}/readyz",
                                            timeout=timeout_s) as resp:
                    ready_body = resp.read().decode()
            except urllib.error.HTTPError as exc:
                if exc.code != 503:
                    raise
                ready_body = exc.read().decode()  # unready IS an answer
            metrics_text = None
            if depth:
                with urllib.request.urlopen(f"{base}/metrics",
                                            timeout=timeout_s) as resp:
                    metrics_text = resp.read().decode()
        except (urllib.error.URLError, ConnectionError, TimeoutError,
                http.client.HTTPException, OSError) as exc:
            # HTTPException covers a peer killed MID-RESPONSE (empty
            # status line): same verdict as a refused connection
            raise ReplicaUnreachableError(
                f"replica telemetry {self.telemetry_address} "
                f"unreachable: {exc}"
            ) from exc
        try:
            verdict = json.loads(ready_body)
            samples = (telemetry.parse_openmetrics(metrics_text)
                       if metrics_text is not None else None)
        except ValueError as exc:
            # a torn scrape (process dying mid-write) must read as
            # unreachable, never crash the poll loop
            raise ReplicaUnreachableError(
                f"replica telemetry {self.telemetry_address} returned "
                f"an unparseable scrape: {exc}"
            ) from exc
        out = {
            "ready": bool(verdict.get("ready")),
            "reasons": sorted({r.get("reason", "unknown")
                               for r in verdict.get("reasons", [])}),
        }
        if samples is not None:
            out["queue_depth"] = float(
                samples.get("fmt_serving_queue_depth", 0.0))
            # per-SLO burn rates off the same strict scrape: the gauge
            # family ``slo.burn_rate.<name>`` renders as
            # ``fmt_slo_burn_rate_<name>`` — the autoscaler's scale-up
            # signal rides the probe the router already pays for
            prefix = "fmt_slo_burn_rate_"
            out["burn_rates"] = {
                k[len(prefix):]: float(v)
                for k, v in samples.items() if k.startswith(prefix)
            }
        return out


# -- the parent-side process handle -------------------------------------------


def _package_root() -> str:
    """Directory containing the ``flink_ml_tpu`` package — prepended to
    the child's ``PYTHONPATH`` so a repo-checkout parent (sys.path
    manipulation, no install) spawns importable children."""
    import flink_ml_tpu

    return os.path.dirname(os.path.dirname(
        os.path.abspath(flink_ml_tpu.__file__)))


def _cache_env(env: Dict[str, str]) -> None:
    """Hand the parent's RESOLVED compile-cache and warm-artifact dirs to
    a child replica, the way the trace sink dirs ride (the runtime may
    have picked a directory that is in neither os.environ nor the child's
    defaults) — otherwise a kill -9 -> respawn replica silently points at
    a different ``~/.cache`` and recompiles the whole ladder."""
    from flink_ml_tpu.serving import warmstart
    from flink_ml_tpu.utils import compile_cache

    d = compile_cache.cache_dir()
    if d:
        env["FMT_COMPILE_CACHE"] = d
    store = warmstart.active()
    if store is not None:
        env.setdefault("FMT_WARM_DIR", store.root)


class ReplicaProcess:
    """One supervised replica child: spawn, handshake, watch, stop.

    ``spawn`` blocks until the child publishes BOTH addresses (data plane
    via ``--address-file``, telemetry via ``FMT_TELEMETRY_PORT_FILE``) or
    the boot deadline passes — an early exit surfaces the child's log
    tail, not a bare timeout.  The child's stdout/stderr land in
    ``<workdir>/replica.log``; its RunReports are isolated to the workdir
    so a fleet of children never races the parent's reports directory.
    """

    def __init__(self, proc: subprocess.Popen, workdir: str,
                 serve_address: str, telemetry_address: str,
                 model_path: str, version: str):
        self._proc = proc
        self.workdir = workdir
        self.serve_address = serve_address
        self.telemetry_address = telemetry_address
        self.model_path = model_path
        self.version = version

    @classmethod
    def spawn(cls, model_path: str, version: str, *,
              host: str = "127.0.0.1",
              extra_env: Optional[Dict[str, str]] = None,
              boot_timeout_s: Optional[float] = None) -> "ReplicaProcess":
        from flink_ml_tpu.fault.injection import maybe_fail
        from flink_ml_tpu.obs import telemetry
        from flink_ml_tpu.utils import knobs

        maybe_fail("router.spawn")
        if boot_timeout_s is None:
            boot_timeout_s = knobs.knob_float("FMT_ROUTER_SPAWN_TIMEOUT_S")
        workdir = tempfile.mkdtemp(prefix="fmt_replica_")
        serve_file = os.path.join(workdir, "serve.addr")
        telemetry_file = os.path.join(workdir, "telemetry.addr")
        env = dict(os.environ)
        env["FMT_TELEMETRY_PORT_FILE"] = telemetry_file
        # the child's registry must record (queue-depth balancing and
        # /metrics scrapes read it) and its reports must not race the
        # parent's committed reports dir
        env["FMT_OBS"] = "1"
        env["FMT_OBS_REPORTS"] = workdir
        # a parent-side chaos schedule is the PARENT's experiment: the
        # child starts fault-free unless the caller injects explicitly
        env.pop("FMT_FAULT_INJECT", None)
        if trace.enabled():
            # a traced fleet traces its replicas too, into the SAME
            # directory (per-pid filenames keep the writers apart) —
            # the runtime enable() may postdate the parent's env
            env["FMT_TRACE"] = "1"
            env["FMT_TRACE_DIR"] = trace.trace_dir()
            env.setdefault("FMT_TRACE_SAMPLE", str(trace.sample_rate()))
            env.setdefault("FMT_TRACE_TAIL", ",".join(trace.tail_modes()))
        _cache_env(env)
        env["PYTHONPATH"] = _package_root() + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        if extra_env:
            env.update(extra_env)
        log_path = os.path.join(workdir, "replica.log")
        with open(log_path, "wb") as log:
            proc = subprocess.Popen(
                [sys.executable, "-m", "flink_ml_tpu.serving.replica",
                 "--model", str(model_path), "--version", str(version),
                 "--address-file", serve_file, "--host", host],
                stdout=log, stderr=subprocess.STDOUT, env=env,
            )
        deadline = time.monotonic() + boot_timeout_s
        while True:
            addresses = []
            for path in (serve_file, telemetry_file):
                try:
                    h, p = telemetry.read_port_file(path)
                    addresses.append(f"{h}:{p}")
                except (OSError, ValueError):
                    break
            if len(addresses) == 2:
                return cls(proc, workdir, addresses[0], addresses[1],
                           str(model_path), str(version))
            code = proc.poll()
            if code is not None:
                raise RuntimeError(
                    f"replica exited {code} during boot; log tail:\n"
                    + cls._tail(log_path)
                )
            if time.monotonic() > deadline:
                proc.kill()
                raise RuntimeError(
                    f"replica did not publish its endpoints within "
                    f"{boot_timeout_s:.0f}s; log tail:\n"
                    + cls._tail(log_path)
                )
            time.sleep(0.02)

    @staticmethod
    def _tail(log_path: str, n_bytes: int = 4000) -> str:
        try:
            with open(log_path, "rb") as f:
                f.seek(0, io.SEEK_END)
                f.seek(max(0, f.tell() - n_bytes))
                return f.read().decode(errors="replace")
        except OSError:
            return "<no log>"

    @property
    def pid(self) -> int:
        return self._proc.pid

    def alive(self) -> bool:
        return self._proc.poll() is None

    def poll_dead(self) -> Optional[int]:
        """The child's exit code, or None while it runs — the router's
        cheap per-poll liveness check (no syscall beyond waitpid)."""
        return self._proc.poll()

    def log_tail(self, n_bytes: int = 4000) -> str:
        return self._tail(os.path.join(self.workdir, "replica.log"),
                          n_bytes)

    def stop(self, grace_s: float = 10.0) -> None:
        """SIGTERM, wait up to ``grace_s`` (the child drains and exits
        0), then SIGKILL.  Idempotent on an already-dead child."""
        if self._proc.poll() is None:
            try:
                self._proc.send_signal(signal.SIGTERM)
            except ProcessLookupError:
                pass
            try:
                self._proc.wait(timeout=grace_s)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait(timeout=5.0)

    def kill(self) -> None:
        """SIGKILL — the chaos lever (a crashed replica, simulated)."""
        if self._proc.poll() is None:
            try:
                self._proc.kill()
            except ProcessLookupError:
                pass
        self._proc.wait(timeout=5.0)


# -- the child entry point ----------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m flink_ml_tpu.serving.replica`` — one serving replica:
    load the model, bring up ModelServer + telemetry (ephemeral ports),
    publish both addresses, serve until SIGTERM/SIGINT, drain, exit 0."""
    import argparse

    parser = argparse.ArgumentParser(
        description="flink_ml_tpu serving replica (one ModelServer child)"
    )
    parser.add_argument("--model", required=True,
                        help="saved pipeline/stage directory to serve")
    parser.add_argument("--version", default="v1")
    parser.add_argument("--address-file", required=True,
                        help="file that receives the data-plane host:port")
    parser.add_argument("--host", default="127.0.0.1")
    args = parser.parse_args(argv)

    from flink_ml_tpu import obs
    from flink_ml_tpu.obs import telemetry
    from flink_ml_tpu.serving.server import ModelServer

    obs.enable()  # a replica's registry IS its control surface
    server = ModelServer(path=args.model, version=args.version,
                         telemetry_port=0)
    data = ReplicaDataServer(server, host=args.host).start()
    telemetry.write_port_file(args.address_file, args.host, data.port)

    stop_event = threading.Event()

    def _stop(signum, frame):  # noqa: ARG001 - signal contract
        stop_event.set()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    print(f"replica pid={os.getpid()} serving {args.model!r} "
          f"version={args.version} data={data.address} "
          f"telemetry={server.telemetry_address}", flush=True)
    stop_event.wait()
    data.stop()
    server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
