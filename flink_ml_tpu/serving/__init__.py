"""Online serving runtime: dynamic micro-batching model server.

The request-level layer over the inference stack (PR 7): a
:class:`~flink_ml_tpu.serving.server.ModelServer` hosts loaded
``PipelineModel``s and turns streams of single-row/small-batch requests
into full fused dispatches —

* **micro-batching** — ``submit`` returns a future; a dispatcher thread
  coalesces queued requests into one ``transform`` per batch (flush on
  ``FMT_SERVING_MAX_BATCH`` rows or ``FMT_SERVING_MAX_WAIT_MS``), padded
  to the shared batch-shape ladder so the compile cache is reused across
  request sizes, then demultiplexes outputs — and quarantine side-tables
  — back to callers with request-local row offsets;
* **admission control** — bounded queue, per-request deadlines,
  shed-oldest-past-deadline-first, reason-coded
  :class:`~flink_ml_tpu.serving.errors.ServerOverloadedError` rejection,
  breaker-open shedding: overload degrades predictably instead of
  queueing unboundedly;
* **hot swap** — ``deploy(path, version)`` loads + integrity-verifies +
  pre-warms off the hot path, then swaps atomically between batches;
  in-flight requests finish on the old version and a corrupt deploy
  leaves the old version serving.

Horizontal scale-out (ISSUE 13): :class:`~flink_ml_tpu.serving.router.
ReplicaRouter` fans the same ``submit() -> Future`` contract across N
``ModelServer`` replica subprocesses — health-aware power-of-two-choices
balancing off each replica's ``/readyz`` + ``/metrics``, reason-code
retry classification (:func:`~flink_ml_tpu.serving.errors.shed_policy`),
drain-aware zero-downtime rolling deploys, and crash supervision with
respawn (:mod:`flink_ml_tpu.serving.replica` owns the subprocess
lifecycle and wire protocol).

Continuous learning (ISSUE 14): :class:`~flink_ml_tpu.serving.lifecycle.
ContinuousLearningController` closes the reference's second topology —
an online fitter consumes a label stream beside the live server,
periodically cuts a candidate, pushes it through a hard validation gate
(numeric health, holdout no-regression, score quarantine/PSI sanity),
auto-deploys passing candidates through the swap contract, and watches a
post-swap probation window that rolls back automatically on an SLO or
drift burn (``ModelServer.rollback`` / ``VersionManager.rollback``).

Elastic fleet (ISSUE 19): :class:`~flink_ml_tpu.serving.autoscaler.
FleetAutoscaler` closes the observe→decide→act loop over the router —
SLO-burn/queue-growth/shed scale-up before the p99 burns, sustained-idle
drain-safe scale-down through the rolling-deploy drain contract,
hysteresis + cooldown flap protection, and a preemption-aware
warm-spares mode (``FMT_SCALE_WARM_SPARES``) so SIGTERM storms never
drop serving capacity below target.

Entry points: ``bench_all.py serving`` (the >=3x dynamic-batching gate),
``bench_all.py router`` (the <=1.25x router-overhead gate),
``bench_all.py autoscale`` (the <=1.05x idle-controller gate),
``python scripts/chaos_smoke.py --serving`` / ``--router`` /
``--autoscale`` (shed / hot-swap / corrupt-deploy / replica-kill /
elastic-ramp legs), ``examples/online_serving.py``,
``examples/router_serving.py``.
"""

from flink_ml_tpu.serving.admission import ServingConfig  # noqa: F401
from flink_ml_tpu.serving.autoscaler import (  # noqa: F401
    FleetAutoscaler,
    ScalerConfig,
)
from flink_ml_tpu.serving.batcher import (  # noqa: F401
    ServeRequest,
    ServeResult,
)
from flink_ml_tpu.serving.errors import (  # noqa: F401
    ServerClosedError,
    ServerOverloadedError,
    shed_policy,
)
from flink_ml_tpu.serving.lifecycle import (  # noqa: F401
    ContinuousLearningController,
)
from flink_ml_tpu.serving.replica import (  # noqa: F401
    ReplicaClient,
    ReplicaProcess,
    ReplicaRemoteError,
    ReplicaUnreachableError,
)
from flink_ml_tpu.serving.router import (  # noqa: F401
    ReplicaRouter,
    RollingDeployError,
    RouterConfig,
)
from flink_ml_tpu.serving.server import ModelServer  # noqa: F401
from flink_ml_tpu.serving.versioning import (  # noqa: F401
    ModelVersion,
    VersionManager,
)

__all__ = [
    "ContinuousLearningController",
    "FleetAutoscaler",
    "ModelServer",
    "ModelVersion",
    "ReplicaClient",
    "ReplicaProcess",
    "ReplicaRemoteError",
    "ReplicaRouter",
    "ReplicaUnreachableError",
    "RollingDeployError",
    "RouterConfig",
    "ScalerConfig",
    "ServeRequest",
    "ServeResult",
    "ServerClosedError",
    "ServerOverloadedError",
    "ServingConfig",
    "VersionManager",
    "shed_policy",
]
