"""Cross-tenant dispatch multiplexing — N tenants, ONE fused dispatch.

The perf core of ISSUE 20.  Residency (serving/tenants.py) makes a
thousand registered models *storable*; this module makes them *servable*
at single-model dispatch cost.  Most tenants of a real fleet are the
same model FAMILY — the same stage chain (scaler -> GLM), the same
feature schema, different fitted parameters — so serving them as N
separate fused dispatches pays N times the dispatch latency for math
that differs only in its per-row constants.  The mux folds them:

* **eligibility** is structural, decided once per (model, schema): the
  model's FULL stage chain must assemble into one fused run
  (``_build_run(min_stages=1)`` — even a single-stage family amortizes)
  whose device chain carries a declared ``pallas_op`` per stage
  (``affine_sub_mul`` / ``affine_mul_add`` / ``glm_score``: exactly the
  ``(pa, pb)``-shaped per-stage params the mux can stack), one dense
  data desc, and no host stages.  :func:`family_token` digests that
  structure plus the input/exit schemas — two tenants coalesce iff
  their tokens match, so "same family, same schema" is a hash compare
  at batch-cut time, not a plan walk;
* **the stacked-param program**: per stage, every batch-mate tenant's
  ``(pa, pb)`` stacks into ``(T, d)`` operands (T padded to a power-of-
  two tenant rung so the executable is reused across batch mixes), each
  row carries an ``int32`` tenant index, and the jitted program computes
  ``(x - A[tid]) * B[tid]`` (and friends) — one gather per stage turns
  per-tenant math into batch-aligned math.  Under a multi-device mesh
  the program shard_maps rows (``P('data')`` on x and tid) with the
  stacked params replicated, exactly as the single-tenant plan does;
* **one coordinate space**: validation runs host-side over the FULL
  coalesced table (the family's validator is structural — same dim,
  same columns — so the verdict is bit-identical to each tenant's own)
  and emits ONE side-table per validator with coalesced-table offsets;
  the server's existing demux walks it unchanged and hands every caller
  the same request-local quarantine rows solo serving would;
* **parity** is the fused-plan contract verbatim (common/fused.py):
  affine stages are elementwise — bit-identical to solo; the score
  stage's gathered form ``sum(x * A[tid]) + b`` reassociates the
  reduction vs solo's ``x @ w + b``, so discrete predictions are
  bit-identical and float scores agree to accumulation tolerance.
  The mux always serves f32 (the strictest parity point);
* **compile economics**: the executable is keyed on (family, bucket,
  mesh, tenant rung, f32) — never on a tenant — through the shared
  family cache AND the warm-artifact store, so the compile ledger stays
  flat as tenants multiply, and a restarted replica replays the mux
  executable the same way PR 18 replays single-model ones.

Telemetry: ``serving.mux.dispatches`` / ``serving.mux.rows`` /
``serving.mux.tenants_coalesced`` (sum of batch-mates per dispatch —
divide by dispatches for the coalescing factor), plus the standard
``pipeline.fused_dispatches`` / ``pipeline.fused_rows`` so existing
dashboards count mux batches as what they are: one fused dispatch.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import List, Optional, Tuple

import numpy as np

from flink_ml_tpu import obs
from flink_ml_tpu.common.fused import (
    FusedRun,
    _active_store,
    _build_run,
    _dev_f32,
    _family_fn_get,
    _family_fn_put,
    _mark_dispatch_warm,
    _note_first_dispatch,
    _padded_rows,
    _try_place,
)
from flink_ml_tpu.common.mapper import ColumnSink
from flink_ml_tpu.fault import pressure
from flink_ml_tpu.table.table import Table

__all__ = [
    "MuxSpan",
    "family_token",
    "mux_enabled",
    "mux_run_for",
    "serve_mux",
]

#: the stage ops the stacked-param program knows how to gather-index —
#: deliberately the Pallas serve-chain vocabulary: those are exactly the
#: stages declaring ``(pa, pb)`` params of knowable shape
_MUX_OPS = ("affine_sub_mul", "affine_mul_add", "glm_score")

_MUX_RUN_CACHE = "_mux_run_cache"
_MUX_RUN_CAPACITY = 4

#: memoized warm-store executables, process-wide (a mux executable
#: belongs to a FAMILY, not to any one tenant's run object)
_WARM_MUX: "OrderedDict[str, object]" = OrderedDict()
_WARM_MUX_LOCK = threading.Lock()
_WARM_MUX_CAPACITY = 64

#: memoized stacked-and-placed param operands per exact span composition
#: — steady-state traffic repeats tenant mixes, and restacking plus
#: re-placing 2*stages (T, d) operands was the dominant mux overhead
_STACKED: "OrderedDict[tuple, tuple]" = OrderedDict()
_STACKED_LOCK = threading.Lock()
_STACKED_CAPACITY = 32


def mux_enabled() -> bool:
    from flink_ml_tpu.utils import knobs

    return knobs.knob_bool("FMT_TENANT_MUX")


def mux_run_for(model, schema, batch_size) -> Optional[FusedRun]:
    """The model's whole-chain fused run when it is mux-eligible, else
    None.  Cached on the model (an evicted tenant takes its plans with
    it).  Eligibility: EVERY stage fuses (no host stages, no staged
    tail — a partial plan would leave per-tenant host work the mux
    cannot coalesce) and the device chain lowers to the ``(pa, pb)``
    op vocabulary (``run.pallas_chain``), which also pins a single
    dense/matrix data desc and at most one entry validator."""
    stages = list(getattr(model, "stages", None) or (model,))
    key = (tuple(schema.field_names), tuple(schema.field_types),
           batch_size)
    cache = model.__dict__.setdefault(_MUX_RUN_CACHE, OrderedDict())
    if key in cache:
        cache.move_to_end(key)
        return cache[key]
    run: Optional[FusedRun] = None
    try:
        built, _bkey = _build_run(stages, 0, schema, batch_size,
                                  min_stages=1)
        if (built is not None and not built.host_stages
                and built.n_stages == len(stages)
                and built.pallas_chain is not None
                and built.device_stages[-1].fetch
                and len(built.validators) <= 1):
            run = built
    except Exception:
        run = None  # an unplannable model simply is not mux-eligible
    cache[key] = run
    while len(cache) > _MUX_RUN_CAPACITY:
        cache.popitem(last=False)
    return run


def family_token(run: FusedRun) -> str:
    """The coalescing key: the plan's structural digest (stage classes,
    ops, wiring, data descs, kernel cache tokens) plus the input and
    exit schema signatures.  Two runs with equal tokens accept each
    other's rows in one dispatch — params are the ONLY difference."""
    sig = (
        run._plan_cache_token(),
        tuple(run.run_input_schema.field_names),
        tuple(str(t) for t in run.run_input_schema.field_types),
        tuple(run.exit_schema.field_names),
        tuple(str(t) for t in run.exit_schema.field_types),
    )
    return hashlib.sha1(repr(sig).encode()).hexdigest()[:16]


class MuxSpan:
    """One tenant's contiguous row span inside a coalesced mux batch."""

    __slots__ = ("tenant", "run", "lo", "hi")

    def __init__(self, tenant: str, run: FusedRun, lo: int, hi: int):
        self.tenant = tenant
        self.run = run
        self.lo = lo
        self.hi = hi


def _tenant_rung(t: int) -> int:
    """Tenant-count bucket: the next power of two, so a fleet mixing
    17-tenant and 23-tenant batches reuses ONE 32-rung executable
    instead of tracing per mix."""
    return 1 << max(0, (t - 1).bit_length())


def _mux_fused_fn(kinds: Tuple[str, ...], fetch: Tuple[bool, ...]):
    """The traced program: per stage, gather the row's tenant params and
    apply the stage op.  Row-aligned by construction (a gather is
    elementwise over rows) — pad rows carry tid 0 and zero features,
    contribute nothing, and are sliced off host-side like every fused
    plan's pad."""

    def fused(x, tid, *stacked):
        x = _dev_f32(x)
        outs = []
        for si, kind in enumerate(kinds):
            pa = _dev_f32(stacked[2 * si])[tid]
            pb = _dev_f32(stacked[2 * si + 1])[tid]
            if kind == "glm_score":
                outs.append((x * pa).sum(axis=-1) + pb[:, 0])
            else:
                x = x * pa + pb if kind == "affine_mul_add" \
                    else (x - pa) * pb
                if fetch[si]:
                    outs.append(x)
        return tuple(outs)

    return fused


def _mux_apply_fn(run0: FusedRun, token: str, mesh, width: int):
    """The jitted mux program for (family, mesh) — family-cached like
    any other structural executable (two sibling servers in one process
    share it)."""
    kinds, _d = run0.pallas_chain
    fetch = tuple(ds.fetch for ds in run0.device_stages)
    key = ("mux", token, kinds, fetch, mesh, width > 1)
    fn = _family_fn_get(key)
    if fn is not None:
        return fn
    import jax

    fused = _mux_fused_fn(kinds, fetch)
    if width == 1:
        fn = jax.jit(fused)
    else:
        from jax.sharding import PartitionSpec as P

        from flink_ml_tpu.parallel.collectives import shard_map

        n_out = sum(
            1 for si, k in enumerate(kinds)
            if k == "glm_score" or fetch[si]
        )
        n_margs = 2 * len(kinds)
        fn = jax.jit(shard_map(
            fused, mesh=mesh,
            in_specs=tuple([P("data")] * 2 + [P()] * n_margs),
            out_specs=tuple([P("data")] * n_out),
            check_vma=False,
        ))
    _family_fn_put(key, fn)
    return fn


def _mux_dispatch_fn(run0: FusedRun, token: str, mesh, width: int,
                     placed, b: int, t_pad: int):
    """The callable for one mux dispatch plus its warm-store provenance —
    the :meth:`FusedRun._dispatch_fn` contract transplanted to a
    family-owned executable: the entry key carries the family token and
    the tenant rung, never a tenant, so every same-family replica in
    the fleet replays one artifact."""
    store = _active_store()
    if store is None:
        return _mux_apply_fn(run0, token, mesh, width), False
    try:
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(list(placed))
        sig = ",".join(
            f"{tuple(getattr(x, 'shape', ()))}/"
            f"{getattr(x, 'dtype', type(x).__name__)}"
            for x in leaves
        ) + f"|{treedef}"
        key = store.entry_key(
            "mux:" + run0.serve_name, b, width, "float32",
            extra=(f"t{t_pad}-" + token + "-"
                   + hashlib.sha1(sig.encode()).hexdigest()[:16]),
        )
        with _WARM_MUX_LOCK:
            memo = _WARM_MUX.get(key)
            if memo is not None:
                _WARM_MUX.move_to_end(key)
        if memo is not None:
            return memo, False
        loaded = store.load(key)
        if loaded is not None:
            fn = loaded
        else:
            fn = _mux_apply_fn(run0, token, mesh, width).lower(
                *placed
            ).compile()
            store.save(key, fn)
        with _WARM_MUX_LOCK:
            _WARM_MUX[key] = fn
            while len(_WARM_MUX) > _WARM_MUX_CAPACITY:
                _WARM_MUX.popitem(last=False)
        return fn, loaded is not None
    except Exception:
        # the warm layer can slow a dispatch down, never break it
        return _mux_apply_fn(run0, token, mesh, width), False


def _stack_params(spans: List[MuxSpan], d: int) -> Tuple[list, int]:
    """Per stage, every span tenant's ``(pa, pb)`` stacked to the tenant
    rung — rung pads repeat span 0's params (real params, so tracing
    never meets a degenerate operand; no pad row indexes them)."""
    t_pad = _tenant_rung(len(spans))
    kinds, _ = spans[0].run.pallas_chain
    stacked: list = []
    for si, kind in enumerate(kinds):
        pas, pbs = [], []
        for span in spans:
            ds = span.run.device_stages[si]
            pa, pb = span.run.model_args[ds.marg_lo:ds.marg_hi]
            pas.append(np.asarray(pa, dtype=np.float32).reshape(d))
            want_b = 1 if kind == "glm_score" else d
            pbs.append(np.asarray(pb, dtype=np.float32).reshape(want_b))
        while len(pas) < t_pad:
            pas.append(pas[0])
            pbs.append(pbs[0])
        stacked.append(np.stack(pas))
        stacked.append(np.stack(pbs))
    return stacked, t_pad


def _stacked_placed(spans: List[MuxSpan], d: int) -> Tuple[list, int]:
    """:func:`_stack_params` memoized by the exact run composition, with
    the stacks already device-placed (replicated) — a repeated tenant mix
    pays neither the numpy restack nor the host->device copies.  The
    cache value holds the runs themselves, so an entry's ``id()`` keys
    cannot be recycled while the entry lives."""
    key = (tuple(id(s.run) for s in spans), d)
    with _STACKED_LOCK:
        hit = _STACKED.get(key)
        if hit is not None:
            _STACKED.move_to_end(key)
            return hit[0], hit[1]
    import jax.numpy as jnp

    stacked, t_pad = _stack_params(spans, d)
    placed = [jnp.asarray(a) for a in stacked]
    with _STACKED_LOCK:
        _STACKED[key] = (placed, t_pad, tuple(s.run for s in spans))
        while len(_STACKED) > _STACKED_CAPACITY:
            _STACKED.popitem(last=False)
    return placed, t_pad


def serve_mux(table: Table, spans: List[MuxSpan], mesh) -> Table:
    """Serve one coalesced multi-tenant batch as ONE fused dispatch.

    ``table`` is the spans' tables concatenated in span order (the
    server coalesces per-tenant-contiguous, so each span is one row
    range).  Returns the combined exit table — validation survivors in
    input order — which the server's existing demux splits per request
    exactly as a single-tenant batch.  Quarantine emissions (if any)
    carry coalesced-table offsets in ONE side-table per validator.

    Raises on any dispatch failure: the server discards this attempt's
    quarantine capture and re-serves the spans solo (counters double-
    bump on that rare path; futures and side-tables never do)."""
    from flink_ml_tpu.serve import quarantine

    run0 = spans[0].run
    kinds, d = run0.pallas_chain
    n_total = table.num_rows()

    # -- validation: one structural verdict over the whole batch ---------
    good_all: Optional[np.ndarray] = None
    t = table
    if quarantine.enabled() and run0.validators:
        mapper = run0.validators[0]
        verdict = mapper.validate_batch(table)
        if verdict is not None:
            good, reasons = verdict
            good = np.asarray(good, dtype=bool)
            quarantine.emit(mapper.serve_name(), table, good, reasons,
                            row_offset=0)
            if not good.all():
                t = table.filter_rows(good)
                good_all = good
    if run0.validators:
        obs.drift.observe_input(run0.validators[0], t)
    n = t.num_rows()

    # survivor-space span bounds (quarantined rows drop out of the
    # dispatch; demux re-aligns callers through the emitted side-table)
    kept: List[int] = []
    for span in spans:
        kept.append(
            int(good_all[span.lo:span.hi].sum()) if good_all is not None
            else span.hi - span.lo
        )

    field_order = run0.exit_schema.field_names
    out_names = sorted(run0.device_cols, key=field_order.index)
    out_types = [run0.exit_schema.type_of(nm) for nm in out_names]
    if n == 0:
        cols = ColumnSink(out_names, out_types, 0).columns()
    else:
        row_multiple = run0._mesh_width(mesh)
        b = run0._bucket(n, row_multiple)
        pressure.maybe_oom(n)
        with obs.trace.span("mux_dispatch", {
            "rows": n, "tenants": len(spans),
            "plan": run0.serve_name, "bucket": b,
        }):
            args = run0._extract(t, b, mesh, row_multiple, mode=None)
            b = _padded_rows(args) or b
            tid = np.zeros(b, dtype=np.int32)
            lo = 0
            for k, span in enumerate(spans):
                tid[lo:lo + kept[k]] = k
                lo += kept[k]
            placed = [args[0], _try_place(tid, mesh, row_multiple)]
            stacked, t_pad = _stacked_placed(spans, d)
            placed.extend(stacked)
            import jax
            import jax.numpy as jnp

            from flink_ml_tpu.lib.common import fetch_flat

            placed = [
                a if isinstance(a, jax.Array)
                or not isinstance(a, np.ndarray) else jnp.asarray(a)
                for a in placed
            ]
            width = run0._mesh_width(mesh)
            token = family_token(run0)
            t_disp = time.perf_counter()
            fn, warm_hit = _mux_dispatch_fn(
                run0, token, mesh, width, placed, b, t_pad
            )
            res = fn(*placed)
            plan = f"mux:{run0.serve_name}@t{t_pad}"
            if warm_hit:
                _mark_dispatch_warm(plan, b, width, dtype="float32",
                                    pallas=False)
            else:
                _note_first_dispatch(
                    plan, b, width, time.perf_counter() - t_disp,
                    dtype="float32", pallas=False,
                )
            with obs.trace.span("device_sync"):
                fetched = fetch_flat(*res)
        if width > 1:
            obs.counter_add("fused.shard_map_dispatches")
        obs.counter_add("serving.mux.dispatches")
        obs.counter_add("serving.mux.rows", n)
        obs.counter_add("serving.mux.tenants_coalesced", len(spans))
        obs.counter_add("pipeline.fused_dispatches")
        obs.counter_add("pipeline.fused_rows", n)

        # -- per-span finalize: each tenant's own host tail --------------
        trimmed = [np.asarray(v)[:n] for v in fetched]
        sink = ColumnSink(out_names, out_types, n)
        lo = 0
        for k, span in enumerate(spans):
            n_k = kept[k]
            out_k: dict = {}
            for fi, (ds0, key) in enumerate(run0.fetch_layout):
                ds = span.run.device_stages[ds0.index]
                vals = {key: trimmed[fi][lo:lo + n_k]}
                cols_k = ds.kernel.finalize(vals, n_k)
                for c, v in cols_k.items():
                    if span.run.exit_schema.contains(c):
                        canon = span.run.exit_schema.resolve(c)
                        if span.run.exit_src.get(canon) == ds.index:
                            out_k[canon] = v
            sink.append(out_k, n_k)
            lo += n_k
        cols = sink.columns()

    passthrough = [
        nm for nm in run0.exit_schema.field_names
        if run0.exit_src[nm] == "input"
    ]
    if passthrough:
        src = t.select(passthrough)
        for nm in passthrough:
            cols[nm] = src.col(nm)
    return Table.from_columns(run0.exit_schema, cols)
