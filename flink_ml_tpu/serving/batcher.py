"""Request coalescing and result demultiplexing.

The data-plane half of dynamic micro-batching: many small requests become
ONE table for the fused plan (``coalesce``), and the plan's outputs — the
served table plus any quarantine side-tables — route back to the right
callers with request-local row offsets (``demux``).

Offset contract: the coalesced table concatenates requests in queue
order, so request ``r`` owns the half-open global row span
``[lo_r, hi_r)``.  Quarantine emissions during the transform do NOT all
share one coordinate space: a fused run validates every stage at plan
entry and stamps run-input offsets for all of them, but a STAGED chain
(``FMT_FUSE_TRANSFORM=0``, or a split around a kernel-less stage)
quarantines per stage, and a later stage's offsets are relative to the
table ALREADY REDUCED by earlier quarantines.  Each captured emission
therefore carries the row count of the batch its emitter validated
(``quarantine.capture``), and ``demux`` tracks the space as it walks the
emissions in order: an emission whose batch row count matches the
current space maps through it directly (the fused entry-validator case —
several validators against the same entry table); one whose batch is
smaller first advances the space by dropping every row already
quarantined (the staged case).  After the remap every offset is a global
coalesced index, rewritten to each request's LOCAL row index — a caller
who sent 3 rows and got ``nan_inf@1`` reads exactly what a solo
``transform`` of those 3 rows would have said.  Served rows demux by the
same mask: the output table drops quarantined rows in order, so request
``r``'s slice is the kept-row prefix sums over its span.
"""

from __future__ import annotations

from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from flink_ml_tpu.serve.quarantine import (
    QUARANTINE_ROW_COL,
    QUARANTINE_TRACE_COL,
)
from flink_ml_tpu.table.schema import DataTypes
from flink_ml_tpu.table.table import Table

__all__ = ["ServeRequest", "ServeResult", "coalesce", "demux"]


@dataclass
class ServeRequest:
    """One caller's rows plus the future that will carry them back.

    ``trace`` is the request's root span
    (:class:`~flink_ml_tpu.obs.trace.RequestTrace`, minted at submit,
    None when tracing is off or the request was sampled out) — the
    explicit handoff object the dispatcher thread parents its batch
    spans under."""

    table: Table
    future: Future
    enqueued_at: float
    deadline_at: Optional[float] = None  # absolute monotonic; None = none
    trace: Optional[object] = None
    #: the routing key of multi-tenant serving (ISSUE 20): which
    #: registered model serves these rows.  The default tenant is the
    #: server's deployed model — old callers never set this and observe
    #: the exact single-model behavior
    tenant: str = "default"
    n_rows: int = field(init=False)

    def __post_init__(self):
        self.n_rows = self.table.num_rows()
        self._n_bytes: Optional[int] = None

    @property
    def n_bytes(self) -> int:
        """Estimated resident bytes (ISSUE 9) — computed lazily and
        memoized, so requests only pay the schema walk when a server
        actually enforces ``FMT_SERVING_QUEUE_CAP_MB``."""
        if self._n_bytes is None:
            from flink_ml_tpu.serving.admission import table_nbytes

            self._n_bytes = table_nbytes(self.table)
        return self._n_bytes

    def expired(self, now: float) -> bool:
        return self.deadline_at is not None and now > self.deadline_at


@dataclass
class ServeResult:
    """What a request's future resolves to.

    ``table``      the served output rows (quarantined rows dropped),
                   bit-identical to a solo ``transform`` of the request;
    ``quarantine`` per-mapper side-tables for THIS request's bad rows,
                   ``_quarantine_row`` rewritten to request-local indices;
    ``version``    the model version that served the batch;
    ``trace_id``   the request's trace id (None when untraced) — returned
                   on SUCCESS as well as on sheds, so a caller can
                   correlate any response with its fleet waterfall
                   without tailing span files.
    """

    table: Table
    quarantine: Dict[str, Table]
    version: str
    trace_id: Optional[str] = None

    @property
    def num_rows(self) -> int:
        return self.table.num_rows()

    @property
    def num_quarantined(self) -> int:
        return sum(t.num_rows() for t in self.quarantine.values())


def coalesce(requests: Sequence[ServeRequest]) -> Tuple[Table, List[Tuple[int, int]]]:
    """One batch table from many requests, plus each request's global row
    span ``[lo, hi)`` in queue order."""
    spans: List[Tuple[int, int]] = []
    offset = 0
    for r in requests:
        spans.append((offset, offset + r.n_rows))
        offset += r.n_rows
    tables = [r.table for r in requests]
    return (Table.concat(tables) if len(tables) > 1 else tables[0]), spans


def demux(
    out: Table,
    captured: Sequence[Tuple[str, Table, int]],
    spans: Sequence[Tuple[int, int]],
    version: str,
    trace_ids: Optional[Sequence[Optional[str]]] = None,
) -> List[ServeResult]:
    """Split a coalesced transform's outputs back per request.

    ``captured`` is the quarantine capture sink from the transform —
    ``(mapper name, side-table, emitting batch rows)`` triples, walked in
    emission order with the space-tracking remap documented on the
    module, so staged and fused emission coordinates both resolve to
    global coalesced offsets.  Raises ``RuntimeError`` on row
    misalignment (served + quarantined must account for every input row —
    a demux that guessed would hand callers other callers' rows).

    ``trace_ids`` (span-aligned, entries None for untraced requests)
    re-stamps each request's quarantine rows with that REQUEST's own
    trace id: the emitter stamped the batch-scope trace(s), but once the
    rows are attributed to a caller the precise id is known.
    """
    total = spans[-1][1] if spans else 0
    kept = np.ones(total, dtype=bool)
    side_rows: List[Tuple[str, Table, np.ndarray]] = []
    # the current coordinate space: global index of each row the NEXT
    # same-sized emission's offsets refer to
    space = np.arange(total, dtype=np.int64)
    for name, side, batch_rows in captured:
        if batch_rows != len(space):
            # the emitter validated an already-reduced table (a staged
            # stage downstream of earlier quarantines, or a later fused
            # run): advance the space past everything quarantined so far
            space = space[kept[space]]
            if batch_rows != len(space):
                raise RuntimeError(
                    f"quarantine emission for {name!r} validated "
                    f"{batch_rows} rows but the surviving space holds "
                    f"{len(space)} — demux cannot attribute its offsets"
                )
        rows = np.asarray(side.col(QUARANTINE_ROW_COL), dtype=np.int64)
        if rows.size and (rows.min() < 0 or rows.max() >= len(space)):
            raise RuntimeError(
                f"quarantine offsets for {name!r} fall outside its "
                f"emission space (rows {rows.min()}..{rows.max()} of "
                f"{len(space)}) — demux cannot attribute them to a request"
            )
        rows = space[rows]  # -> global coalesced offsets
        kept[rows] = False
        side_rows.append((name, side, rows))
    n_kept = int(kept.sum())
    if out.num_rows() != n_kept:
        raise RuntimeError(
            f"served batch returned {out.num_rows()} rows but "
            f"{n_kept} of {total} coalesced rows survived quarantine — "
            "output is misaligned with the request spans"
        )
    # output position of each kept input row: exclusive prefix sum
    out_pos = np.cumsum(kept) - kept.astype(np.int64)
    results: List[ServeResult] = []
    for i, (lo, hi) in enumerate(spans):
        span_kept = int(kept[lo:hi].sum())
        start = int(out_pos[lo]) if hi > lo else 0
        table = out.slice_rows(start, start + span_kept)
        trace_id = trace_ids[i] if trace_ids is not None else None
        quarantine: Dict[str, Table] = {}
        for name, side, rows in side_rows:
            mask = (rows >= lo) & (rows < hi)
            if not mask.any():
                continue
            part = side.filter_rows(mask).with_column(
                QUARANTINE_ROW_COL, DataTypes.LONG, rows[mask] - lo
            )
            if side.schema.contains(QUARANTINE_TRACE_COL):
                # the emitter stamped the batch-scope trace(s); the rows
                # are now attributed to ONE caller, so stamp its exact id
                part = part.with_column(
                    QUARANTINE_TRACE_COL, DataTypes.STRING,
                    [trace_id or ""] * part.num_rows(),
                )
            if name in quarantine:
                part = Table.concat([quarantine[name], part])
            quarantine[name] = part
        results.append(ServeResult(table=table, quarantine=quarantine,
                                   version=version, trace_id=trace_id))
    return results
