"""SLO-guarded fleet autoscaler: the elastic control loop over
:class:`~flink_ml_tpu.serving.router.ReplicaRouter` (ISSUE 19, ROADMAP
item 4 — the last control-plane gap).

Every signal an autoscaler needs already exists — ``slo.burn_rate.*``
gauges off the replicas' own monitors, scraped queue depth and
reason-coded ``/readyz`` off the router's poll loop, and a warmstart
store that makes spawning a replica cheap — yet fleet size was a static
``FMT_ROUTER_REPLICAS`` fixed at boot.  :class:`FleetAutoscaler` closes
the observe→decide→act cycle:

**Observe.**  One :meth:`ReplicaRouter.fleet_health` sample per tick —
state the router already maintains (ready/live/slot counts, crash-loop
quarantine, door queue depth, cumulative request/shed tallies) plus the
fleet-max ``slo.burn_rate.*`` the replicas expose through the STRICT
OpenMetrics scrape path their probes already ride.  No new scrape loop.

**Decide.**  Scale up *before* the p99 SLO burns: any replica's burn
rate at ``FMT_SCALE_UP_BURN``, sustained queue growth over
``FMT_SCALE_WINDOW_S``, or sheds inside the window each add one replica
to the target.  Scale down only on *sustained idle* — every sample
across ``FMT_SCALE_IDLE_WINDOWS`` windows must show an empty queue and
zero sheds, and the decision is fail-closed: a replica whose
unreadiness is a broken probe, a quarantined slot, or live traffic with
no judged burn data (the thin-SLO-window case — ``burning()`` under
``FMT_SLO_MIN_EVENTS`` arrivals says nothing, not "all clear") each
VETO the shrink.  Hysteresis is structural: the up threshold
(``FMT_SCALE_UP_BURN``) and down threshold (``FMT_SCALE_DOWN_BURN``)
are separate knobs, a post-action cooldown (``FMT_SCALE_COOLDOWN_S``)
rate-limits actions, and the idle horizon is several windows long — a
square wave at the threshold produces at most one scale event per
period (tested as such).

**Act.**  Growth goes through :meth:`ReplicaRouter.add_replica` (the
standard spawn path — the child inherits the sealed warmstart manifest,
so its first request stays warm); shrink through
:meth:`ReplicaRouter.remove_replica` (the rolling-deploy drain
contract: stop routing → wait in-flight → terminate — zero
caller-visible failures).  ``FMT_SCALE_WARM_SPARES`` keeps N spares
*above* target so a SIGTERM storm never drops serving capacity below
target while the router respawns; quarantined slots read as capacity
loss and are compensated the same way.

Every decision is observable: ``autoscaler.scale_ups`` /
``autoscaler.scale_downs`` / ``autoscaler.blocked.<reason>`` counters,
``autoscaler.target`` / ``autoscaler.actual`` gauges, flight events
carrying the triggering signal snapshot, an ``autoscaler`` section on
``/statusz``, and a decision span on the fleet trace timeline per
scale action.

Knobs (BASELINE.md round-22 table): ``FMT_SCALE_MIN``,
``FMT_SCALE_MAX``, ``FMT_SCALE_UP_BURN``, ``FMT_SCALE_DOWN_BURN``,
``FMT_SCALE_WINDOW_S``, ``FMT_SCALE_IDLE_WINDOWS``,
``FMT_SCALE_COOLDOWN_S``, ``FMT_SCALE_WARM_SPARES``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Optional

from flink_ml_tpu import obs
from flink_ml_tpu.utils import knobs

__all__ = ["FleetAutoscaler", "ScalerConfig"]


@dataclass(frozen=True)
class ScalerConfig:
    """Resolved autoscaler knobs (environment defaults, overrides win)."""

    min_replicas: int = 1
    max_replicas: int = 8
    up_burn: float = 1.0
    down_burn: float = 0.5
    window_s: float = 30.0
    idle_windows: int = 3
    cooldown_s: float = 60.0
    warm_spares: int = 0

    @classmethod
    def from_env(cls, min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 up_burn: Optional[float] = None,
                 down_burn: Optional[float] = None,
                 window_s: Optional[float] = None,
                 idle_windows: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 warm_spares: Optional[int] = None) -> "ScalerConfig":
        cfg = cls(
            min_replicas=int(min_replicas if min_replicas is not None
                             else knobs.knob_int("FMT_SCALE_MIN")),
            max_replicas=int(max_replicas if max_replicas is not None
                             else knobs.knob_int("FMT_SCALE_MAX")),
            up_burn=float(up_burn if up_burn is not None
                          else knobs.knob_float("FMT_SCALE_UP_BURN")),
            down_burn=float(down_burn if down_burn is not None
                            else knobs.knob_float("FMT_SCALE_DOWN_BURN")),
            window_s=float(window_s if window_s is not None
                           else knobs.knob_float("FMT_SCALE_WINDOW_S")),
            idle_windows=int(idle_windows if idle_windows is not None
                             else knobs.knob_int("FMT_SCALE_IDLE_WINDOWS")),
            cooldown_s=float(cooldown_s if cooldown_s is not None
                             else knobs.knob_float("FMT_SCALE_COOLDOWN_S")),
            warm_spares=int(warm_spares if warm_spares is not None
                            else knobs.knob_int("FMT_SCALE_WARM_SPARES")),
        )
        if cfg.min_replicas < 1 or cfg.max_replicas < cfg.min_replicas:
            raise ValueError(
                f"fleet bounds must satisfy 1 <= min <= max "
                f"(got {cfg.min_replicas}..{cfg.max_replicas})"
            )
        if cfg.window_s <= 0 or cfg.idle_windows < 1:
            raise ValueError(
                f"window_s must be > 0 and idle_windows >= 1 "
                f"(got {cfg.window_s}, {cfg.idle_windows})"
            )
        if cfg.warm_spares < 0:
            raise ValueError(f"warm_spares must be >= 0 "
                             f"(got {cfg.warm_spares})")
        return cfg


class FleetAutoscaler:
    """Elastic control loop over one :class:`ReplicaRouter`.

    ``FleetAutoscaler(router).start()`` samples the fleet every tick and
    converges occupied slots on ``target + warm_spares``, where
    ``target`` moves one step per decision inside
    ``[FMT_SCALE_MIN, FMT_SCALE_MAX]``.  Use as a context manager or
    call :meth:`stop`.  Tests drive :meth:`step` directly with an
    injected ``now_fn`` — every decision is a pure function of the
    sample history and the clock, so hysteresis is provable without
    sleeping.
    """

    def __init__(self, router, *,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 up_burn: Optional[float] = None,
                 down_burn: Optional[float] = None,
                 window_s: Optional[float] = None,
                 idle_windows: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 warm_spares: Optional[int] = None,
                 tick_s: Optional[float] = None,
                 now_fn=time.monotonic):
        self._router = router
        self._cfg = ScalerConfig.from_env(
            min_replicas=min_replicas, max_replicas=max_replicas,
            up_burn=up_burn, down_burn=down_burn, window_s=window_s,
            idle_windows=idle_windows, cooldown_s=cooldown_s,
            warm_spares=warm_spares,
        )
        self._now = now_fn
        #: sample cadence: several observations per window (a trend
        #: needs points), bounded away from a busy-loop
        self._tick_s = float(tick_s if tick_s is not None
                             else max(min(self._cfg.window_s / 4.0, 2.0),
                                      0.05))
        self._mu = threading.Lock()
        self._samples: Deque[dict] = deque()
        cfg = self._cfg
        initial = getattr(router, "fleet_size", lambda: cfg.min_replicas)()
        self._target = min(max(int(initial) - cfg.warm_spares,
                               cfg.min_replicas), cfg.max_replicas)
        self._last_action_t: Optional[float] = None
        self._ups = 0
        self._downs = 0
        self._events: Deque[dict] = deque(maxlen=64)
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._status_key = None

    @property
    def config(self) -> ScalerConfig:
        return self._cfg

    @property
    def target(self) -> int:
        """Desired serving capacity (spares ride on top of this)."""
        with self._mu:
            return self._target

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FleetAutoscaler":
        with self._mu:
            if self._thread is not None:
                return self
            self._stop_evt.clear()
            thread = threading.Thread(target=self._loop,
                                      name="fmt-autoscaler", daemon=True)
            self._thread = thread
        if self._status_key is None:
            from flink_ml_tpu.obs import telemetry
            self._status_key = telemetry.register_status(
                "autoscaler", self._status_section)
        thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        with self._mu:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=30.0)
        if self._status_key is not None:
            from flink_ml_tpu.obs import telemetry
            telemetry.unregister_status(self._status_key)
            self._status_key = None

    def __enter__(self) -> "FleetAutoscaler":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.stop()
        return False

    def _loop(self) -> None:
        while not self._stop_evt.wait(timeout=self._tick_s):
            try:
                self.step()
            except Exception:  # noqa: BLE001 - the control loop must survive
                # a failed observation or a racing shutdown is a skipped
                # beat, never a dead supervisor
                obs.counter_add("autoscaler.errors")

    # -- observe → decide → act ----------------------------------------------

    def step(self) -> dict:
        """One control cycle; returns the decision record (also the
        flight-event payload when the cycle acted or was blocked)."""
        now = self._now()
        health = self._router.fleet_health()
        with self._mu:
            sample = self._observe(now, health)
            actual = int(health["size"]) - int(health["quarantined"])
            decision = {"t": now, "action": "hold", "reason": "",
                        "target": self._target, "actual": actual,
                        "signal": sample}
            up_reason = self._up_signal(now)
            down_ok, down_block = self._down_signal(now)
            # target moves one step per decision, cooldown-gated like
            # the act itself: a brief burst must not ratchet the target
            # to max and keep the fleet growing after traffic subsides
            if up_reason and self._target >= self._cfg.max_replicas:
                self._note_blocked_locked(decision, "at_max", up_reason)
            elif up_reason and self._in_cooldown_locked(now):
                self._note_blocked_locked(decision, "cooldown", up_reason)
            elif up_reason:
                self._target += 1
                decision["reason"] = up_reason
            elif down_ok and self._target > self._cfg.min_replicas:
                if self._in_cooldown_locked(now):
                    self._note_blocked_locked(decision, "cooldown",
                                              "scale_down")
                else:
                    self._target -= 1
                    decision["reason"] = "sustained_idle"
            elif down_block is not None:
                # idleness was plausible but a fail-closed input vetoed
                # the shrink: a broken probe, a quarantined slot, or
                # traffic with no judged burn window must never read as
                # "safe to remove capacity"
                self._note_blocked_locked(decision, down_block, "scale_down")
            desired = self._target + self._cfg.warm_spares
            decision["target"] = self._target
        if actual < desired:
            self._try_scale(decision, "up", now,
                            decision["reason"] or "capacity_loss")
        elif actual > desired and down_ok:
            self._try_scale(decision, "down", now,
                            decision["reason"] or "sustained_idle")
        obs.gauge_set("autoscaler.target", float(decision["target"]))
        obs.gauge_set("autoscaler.actual", float(actual))
        if decision["action"] != "hold" or decision.get("blocked"):
            with self._mu:
                self._events.append(decision)
        return decision

    def _observe(self, now: float, health: dict) -> dict:
        sample = {
            "t": now,
            "queued": int(health.get("queued_rows", 0)),
            "ready": int(health.get("ready", 0)),
            "size": int(health.get("size", 0)),
            "quarantined": int(health.get("quarantined", 0)),
            "requests": float(health.get("requests", 0.0)),
            "shed": float(health.get("shed", 0.0)),
            "burn": float(health.get("max_burn_rate", 0.0)),
            "burn_seen": bool(health.get("burn_seen", False)),
            "probe_suspect": int(health.get("probe_suspect", 0)),
        }
        self._samples.append(sample)
        # retain one window beyond the idle horizon so coverage checks
        # ("do my samples actually span the window?") stay answerable
        horizon = self._cfg.window_s * (self._cfg.idle_windows + 1)
        while (len(self._samples) > 2
               and self._samples[0]["t"] < now - horizon):
            self._samples.popleft()
        return sample

    def _up_signal(self, now: float) -> Optional[str]:
        """The scale-up triggers, checked most-urgent first.  Burn rate
        acts on the LATEST sample (an SLO already burning pays for every
        tick of delay); queue growth and sheds must sustain across
        ``window_s`` (one bursty sample must not flap the fleet)."""
        cfg = self._cfg
        latest = self._samples[-1]
        if latest["burn_seen"] and latest["burn"] >= cfg.up_burn:
            return "slo_burn"
        if self._samples[0]["t"] > now - cfg.window_s:
            return None  # history doesn't span the window yet
        window = [s for s in self._samples if s["t"] >= now - cfg.window_s]
        if not window:
            return None
        if (all(s["queued"] > 0 for s in window)
                and window[-1]["queued"] >= window[0]["queued"]):
            return "queue_growth"
        if window[-1]["shed"] > window[0]["shed"]:
            return "shed"
        return None

    def _down_signal(self, now: float):
        """``(ok, block_reason)``: ``ok`` means sustained idle held for
        the full horizon with every fail-closed veto clear.  A non-None
        ``block_reason`` means idleness was otherwise plausible but a
        veto stopped the shrink — that's a counted, observable decision;
        plain traffic is neither (an active fleet isn't "blocked from
        scaling down", it's just busy)."""
        cfg = self._cfg
        horizon = cfg.window_s * cfg.idle_windows
        if self._samples[0]["t"] > now - horizon:
            return False, None  # not enough history: patience, not a veto
        win = [s for s in self._samples if s["t"] >= now - horizon]
        if not win:
            return False, None
        if any(s["queued"] > 0 for s in win):
            return False, None
        if win[-1]["shed"] > win[0]["shed"]:
            return False, None
        if any(s["quarantined"] > 0 for s in win):
            return False, "quarantine"
        if any(s["probe_suspect"] > 0 for s in win):
            return False, "probe_error"
        if win[-1]["requests"] > win[0]["requests"]:
            # requests flowed this horizon (empty queue = fleet keeping
            # up): shrinking needs positive evidence the SLO sits well
            # below the DOWN threshold — and a thin SLO window that
            # judged nothing provides none
            if not all(s["burn_seen"] for s in win):
                return False, "no_burn_signal"
            if max(s["burn"] for s in win) >= cfg.down_burn:
                return False, None  # hysteresis: burn not low enough
        return True, None

    def _in_cooldown_locked(self, now: float) -> bool:
        return (self._last_action_t is not None
                and now - self._last_action_t < self._cfg.cooldown_s)

    def _note_blocked_locked(self, decision: dict, reason: str,
                             wanted: str) -> None:
        decision.setdefault("blocked", []).append(reason)
        obs.counter_add(f"autoscaler.blocked.{reason}")
        obs.flight.record("autoscaler.blocked", reason=reason,
                          wanted=wanted, target=self._target,
                          signal=decision["signal"])

    def _try_scale(self, decision: dict, direction: str, now: float,
                   reason: str) -> None:
        """One act attempt toward ``target + spares`` — cooldown-gated,
        traced as a decision span on the fleet timeline, and recorded
        with the triggering signal snapshot whichever way it goes."""
        with self._mu:
            if self._in_cooldown_locked(now):
                self._note_blocked_locked(decision, "cooldown", reason)
                return
        req = obs.trace.start_request("autoscaler.scale", {
            "direction": direction, "reason": reason,
            "target": decision["target"],
        })
        name = None
        try:
            if direction == "up":
                name = self._router.add_replica()
            else:
                name = self._router.remove_replica()
        except BaseException:
            with self._mu:
                self._note_blocked_locked(decision, "spawn_failed",
                                          reason)
            if req is not None:
                req.end(status="error", attrs={"reason": reason})
            return
        if req is not None:
            req.end(status="ok" if name else "blocked",
                    attrs={"replica": name or ""})
        with self._mu:
            if name is None:
                # the router declined (deploy in progress, lone replica,
                # drain timeout): counted, retried after the next tick
                self._note_blocked_locked(decision, "router_busy", reason)
                return
            decision["action"] = direction
            decision["reason"] = reason
            decision["replica"] = name
            self._last_action_t = now
            if direction == "up":
                self._ups += 1
            else:
                self._downs += 1
        counter = ("autoscaler.scale_ups" if direction == "up"
                   else "autoscaler.scale_downs")
        obs.counter_add(counter)
        obs.flight.record("autoscaler.scale", direction=direction,
                          reason=reason, replica=name,
                          target=decision["target"],
                          signal=decision["signal"])

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        with self._mu:
            return {
                "target": self._target,
                "scale_ups": self._ups,
                "scale_downs": self._downs,
                "last_action_t": self._last_action_t,
            }

    def _status_section(self) -> dict:
        """The ``/statusz`` ``autoscaler`` section: configuration,
        position, and the recent decision tail — what an operator needs
        to answer "why is the fleet this size?" without log archaeology."""
        cfg = self._cfg
        now = self._now()
        with self._mu:
            return {
                "target": self._target,
                "bounds": [cfg.min_replicas, cfg.max_replicas],
                "warm_spares": cfg.warm_spares,
                "up_burn": cfg.up_burn,
                "down_burn": cfg.down_burn,
                "window_s": cfg.window_s,
                "idle_windows": cfg.idle_windows,
                "cooldown_s": cfg.cooldown_s,
                "in_cooldown": self._in_cooldown_locked(now),
                "scale_ups": self._ups,
                "scale_downs": self._downs,
                "recent": [dict(e, signal=None) for e in
                           list(self._events)[-8:]],
            }
