"""Admission control and load shedding for the model server.

The policy half of the request path, separated from the queue mechanics
(``server.py``) so it reads as policy:

* **knobs** — :class:`ServingConfig` resolves the four environment knobs
  (``FMT_SERVING_MAX_BATCH`` / ``FMT_SERVING_MAX_WAIT_MS`` /
  ``FMT_SERVING_QUEUE_CAP`` / ``FMT_SERVING_DEADLINE_MS``) with
  constructor overrides winning;
* **deadlines** — every request carries an absolute deadline (per-request
  override, else the config default, else none); a request past its
  deadline is undeliverable by definition and is shed, never served late;
* **shedding order** — when the queue is at its row cap, the
  oldest-past-deadline queued requests are shed FIRST to make room (they
  were dead weight anyway); only if the queue is still full does the new
  request get a ``queue_full`` rejection.  Overload therefore degrades in
  the order an operator wants: expired work first, then new arrivals,
  while everything already admitted and deliverable keeps its slot.

Every shed lands in ``serving.shed`` plus ``serving.shed.<reason>`` so
backpressure is visible before it becomes an outage.
"""

from __future__ import annotations

import time
from concurrent.futures import InvalidStateError
from dataclasses import dataclass
from typing import Optional

from flink_ml_tpu import obs
from flink_ml_tpu.serving.errors import (
    SHED_BREAKER_OPEN,
    SHED_MEMORY_PRESSURE,
    ServerOverloadedError,
)
from flink_ml_tpu.utils import knobs

__all__ = [
    "ServingConfig",
    "now_s",
    "overloaded",
    "shed",
    "table_nbytes",
]


@dataclass(frozen=True)
class ServingConfig:
    """Resolved serving knobs (environment defaults, overrides win).

    ``max_batch``   rows per coalesced dispatch (flush trigger 1)
    ``max_wait_ms`` oldest-request age that forces a flush (trigger 2)
    ``queue_cap``   max queued rows before admission sheds
    ``queue_cap_mb`` max estimated queued MEGABYTES before admission
                    sheds with the ``memory_pressure`` reason (0 = off):
                    a row cap cannot see that one caller's rows are 100x
                    wider than another's, so an HBM budget needs a
                    bytes-denominated door too (ISSUE 9)
    ``deadline_ms`` default per-request deadline (0 = none)
    ``shed_on_breaker`` refuse at the door while a circuit breaker is
                    open instead of queueing onto a dead device
    """

    max_batch: int = 512
    max_wait_ms: float = 2.0
    queue_cap: int = 4096
    queue_cap_mb: float = 0.0
    deadline_ms: float = 0.0
    shed_on_breaker: bool = True

    @classmethod
    def from_env(
        cls,
        max_batch: Optional[int] = None,
        max_wait_ms: Optional[float] = None,
        queue_cap: Optional[int] = None,
        queue_cap_mb: Optional[float] = None,
        deadline_ms: Optional[float] = None,
        shed_on_breaker: Optional[bool] = None,
    ) -> "ServingConfig":
        if shed_on_breaker is None:
            shed_on_breaker = knobs.knob_bool("FMT_SERVING_SHED_ON_BREAKER")
        cfg = cls(
            max_batch=int(
                max_batch if max_batch is not None
                else knobs.knob_int("FMT_SERVING_MAX_BATCH")
            ),
            max_wait_ms=float(
                max_wait_ms if max_wait_ms is not None
                else knobs.knob_float("FMT_SERVING_MAX_WAIT_MS")
            ),
            queue_cap=int(
                queue_cap if queue_cap is not None
                else knobs.knob_int("FMT_SERVING_QUEUE_CAP")
            ),
            queue_cap_mb=float(
                queue_cap_mb if queue_cap_mb is not None
                else knobs.knob_float("FMT_SERVING_QUEUE_CAP_MB")
            ),
            deadline_ms=float(
                deadline_ms if deadline_ms is not None
                else knobs.knob_float("FMT_SERVING_DEADLINE_MS")
            ),
            shed_on_breaker=bool(shed_on_breaker),
        )
        if cfg.max_batch < 1 or cfg.queue_cap < 1:
            raise ValueError(
                f"max_batch and queue_cap must be >= 1 "
                f"(got {cfg.max_batch}, {cfg.queue_cap})"
            )
        if cfg.queue_cap_mb < 0:
            raise ValueError(
                f"queue_cap_mb must be >= 0 (got {cfg.queue_cap_mb})"
            )
        return cfg

    @property
    def queue_cap_bytes(self) -> int:
        """The bytes-denominated admission cap (0 = disabled)."""
        return int(self.queue_cap_mb * (1 << 20))

    @property
    def max_wait_s(self) -> float:
        return self.max_wait_ms / 1e3

    def deadline_at(self, enqueued_at: float,
                    deadline_ms: Optional[float]) -> Optional[float]:
        """Absolute (monotonic) deadline for a request enqueued now:
        per-request override first, config default second, None for no
        deadline (0 or negative disables)."""
        ms = self.deadline_ms if deadline_ms is None else float(deadline_ms)
        if ms <= 0:
            return None
        return enqueued_at + ms / 1e3


#: fallback bytes/row for columns whose width the schema cannot bound
#: (object columns: strings, sparse vectors) — deliberately conservative
_OBJECT_ROW_BYTES = 64


def table_nbytes(table) -> int:
    """Estimated resident bytes of one request's rows — the unit of the
    ``FMT_SERVING_QUEUE_CAP_MB`` admission budget.  Numeric/vector
    columns report their backing buffers' true ``nbytes`` (the schema row
    width times rows, exactly); object columns estimate a conservative
    per-row width."""
    total = 0
    n = table.num_rows()
    for name in table.schema.field_names:
        col = table.col(name)
        nbytes = getattr(col, "nbytes", None)
        if nbytes is not None and getattr(col, "dtype", None) is not None \
                and col.dtype != object:
            total += int(nbytes)
        else:
            total += _OBJECT_ROW_BYTES * n
    return total


def overloaded(reason: str, detail: str = "",
               trace_id=None) -> ServerOverloadedError:
    """Count one shed and build its reason-coded error.  EVERY shed —
    synchronous rejection at submit, queued-future sheds, no-drain
    shutdown — goes through here or :func:`shed`, so the
    ``serving.shed.<reason>`` counters can never drift from the errors
    callers actually see.  Each shed also lands in the flight-recorder
    ring (with the shed request's ``trace_id`` when it has one), so a
    black box dumped moments later shows WHO was turned away and why."""
    obs.counter_add("serving.shed")
    obs.counter_add(f"serving.shed.{reason}")
    obs.flight.record("serving.shed", reason=reason, detail=detail,
                      trace_id=trace_id)
    if reason == SHED_BREAKER_OPEN:
        # turning traffic away because the dispatch path is DEGRADED is a
        # black-box moment (a queue_full shed is just load): the dump now
        # holds the closed->open breaker walk AND the shed it caused, in
        # ring order.  Rate-limited like every dump reason.
        obs.flight.dump("breaker_open_shed")
    elif reason == SHED_MEMORY_PRESSURE:
        # shedding for MEMORY is a degradation signal too (ISSUE 9): the
        # dump holds the pressure walk — OOMs, evictions, bisections —
        # that led to turning this request away
        obs.flight.dump("memory_pressure_shed")
    return ServerOverloadedError(reason, detail, trace_id=trace_id)


def shed(request, reason: str, detail: str = "") -> None:
    """Fail one queued request's future with a counted, reason-coded
    rejection.  A future the caller already cancelled is left alone
    (``set_exception`` on a cancelled future raises, and a dead
    dispatcher is the one failure mode a server must never have).

    Callers must NOT hold the server's queue lock: completing a future
    runs its done-callbacks synchronously, and a callback that touches
    the server (a shed-retry ``submit``) would re-enter under the lock
    mid-queue-iteration."""
    req_trace = getattr(request, "trace", None)
    exc = overloaded(
        reason, detail,
        trace_id=req_trace.trace_id if req_trace is not None else None,
    )
    if req_trace is not None:
        req_trace.end(status="shed", attrs={"shed_reason": reason})
    try:
        request.future.set_exception(exc)
    except InvalidStateError:
        pass  # caller cancelled while queued: nothing left to deliver


def now_s() -> float:
    """The serving clock (monotonic seconds) — one place to stub in tests."""
    return time.monotonic()
