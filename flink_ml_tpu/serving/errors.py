"""Serving-runtime error vocabulary.

Dependency-free like :mod:`flink_ml_tpu.serve.errors`: these types cross
thread boundaries inside futures, so they must be importable anywhere
without dragging the server (or jax) along.
"""

from __future__ import annotations

__all__ = [
    "SHED_BREAKER_OPEN",
    "SHED_DEADLINE",
    "SHED_MEMORY_PRESSURE",
    "SHED_QUEUE_FULL",
    "SHED_SHUTDOWN",
    "ServerClosedError",
    "ServerOverloadedError",
]

#: reason codes (the shed vocabulary — mirrored in ``serving.shed.<reason>``
#: counters so dashboards and errors speak the same words)
SHED_QUEUE_FULL = "queue_full"
SHED_DEADLINE = "deadline_expired"
SHED_BREAKER_OPEN = "breaker_open"
SHED_SHUTDOWN = "shutdown"
#: the queue's estimated bytes would exceed ``FMT_SERVING_QUEUE_CAP_MB``
#: (ISSUE 9): admission refuses work the device memory budget cannot hold
#: rather than queueing it onto an allocator already under pressure
SHED_MEMORY_PRESSURE = "memory_pressure"


class ServerOverloadedError(RuntimeError):
    """A request was shed instead of served, with a reason code.

    Load shedding is the contract, not a failure mode: when the server
    cannot answer in time it says so immediately — a bounded queue plus a
    reason-coded rejection degrades predictably where unbounded queueing
    melts down.  ``reason`` is one of the ``SHED_*`` codes
    (``queue_full`` / ``deadline_expired`` / ``breaker_open`` /
    ``memory_pressure`` / ``shutdown``); the matching
    ``serving.shed.<reason>`` counter moved by
    one.  ``trace_id`` carries the shed request's trace (None when
    tracing is off or the request was sampled out) — the handle that
    finds the request in the span sink and the flight-recorder ring.
    """

    trace_id = None

    def __init__(self, reason: str, detail: str = "", trace_id=None):
        super().__init__(
            f"request shed ({reason})" + (f": {detail}" if detail else "")
        )
        self.reason = reason
        self.trace_id = trace_id


class ServerClosedError(RuntimeError):
    """submit() on a server that is not running (never started, shutting
    down, or already shut down)."""
