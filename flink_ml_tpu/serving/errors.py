"""Serving-runtime error vocabulary.

Dependency-free like :mod:`flink_ml_tpu.serve.errors`: these types cross
thread boundaries inside futures, so they must be importable anywhere
without dragging the server (or jax) along.
"""

from __future__ import annotations

__all__ = [
    "POLICY_FAIL",
    "POLICY_RETRY",
    "POLICY_ROUTE_AWAY",
    "SHED_BREAKER_OPEN",
    "SHED_DEADLINE",
    "SHED_MEMORY_PRESSURE",
    "SHED_NO_REPLICA",
    "SHED_QUEUE_FULL",
    "SHED_SHUTDOWN",
    "SHED_TENANT_QUOTA",
    "ServerClosedError",
    "ServerOverloadedError",
    "shed_policy",
]

#: reason codes (the shed vocabulary — mirrored in ``serving.shed.<reason>``
#: counters so dashboards and errors speak the same words)
SHED_QUEUE_FULL = "queue_full"
SHED_DEADLINE = "deadline_expired"
SHED_BREAKER_OPEN = "breaker_open"
SHED_SHUTDOWN = "shutdown"
#: the queue's estimated bytes would exceed ``FMT_SERVING_QUEUE_CAP_MB``
#: (ISSUE 9): admission refuses work the device memory budget cannot hold
#: rather than queueing it onto an allocator already under pressure
SHED_MEMORY_PRESSURE = "memory_pressure"
#: the replica router found no routable replica for a request (ISSUE 13):
#: every replica is dead, draining, or reason-coded unready — the
#: scale-out analog of ``shutdown``, and like it, terminal for the caller
SHED_NO_REPLICA = "no_replica"
#: one tenant's queued rows hit ``FMT_TENANT_QUOTA_ROWS`` (ISSUE 20): the
#: multi-tenant admission door sheds THAT tenant's overflow so a single
#: hot tenant cannot starve its batch-mates out of the shared queue
SHED_TENANT_QUOTA = "tenant_quota"


# -- shed-reason retryability (ISSUE 13) --------------------------------------
#
# The replica router classifies a shed RESPONSE by its reason code instead
# of string-matching messages: a shed request was, by contract, never
# served, so the question is only whether ANOTHER replica could plausibly
# serve it — and whether the shedding replica should keep taking traffic.

#: another replica can plausibly serve this request right now: the reason
#: describes ONE replica's transient load (its queue, its memory budget,
#: its backlog aging requests past deadline), not the request itself
POLICY_RETRY = "retry_elsewhere"
#: the shedding replica is degraded as a WHOLE (shutting down, breaker
#: open): stop routing to it, and retry the request on another replica
POLICY_ROUTE_AWAY = "route_away"
#: unknown or terminal reason: hand the shed to the caller unchanged —
#: retrying what we do not understand turns one error into N
POLICY_FAIL = "fail"

_SHED_POLICIES = {
    SHED_QUEUE_FULL: POLICY_RETRY,
    SHED_MEMORY_PRESSURE: POLICY_RETRY,
    SHED_DEADLINE: POLICY_RETRY,
    SHED_SHUTDOWN: POLICY_ROUTE_AWAY,
    SHED_BREAKER_OPEN: POLICY_ROUTE_AWAY,
    # a tenant over its own quota is over it on EVERY replica (the quota
    # follows the tenant, not the server) — retrying elsewhere turns one
    # rejection into N, so hand the shed to the caller
    SHED_TENANT_QUOTA: POLICY_FAIL,
}


def shed_policy(reason: str) -> str:
    """The router-facing classification of one shed reason code:
    ``POLICY_RETRY`` (retry on another replica), ``POLICY_ROUTE_AWAY``
    (eject the replica from rotation AND retry elsewhere), or
    ``POLICY_FAIL`` (shed to the caller).  Unknown reasons fail —
    the conservative default for a vocabulary that may grow."""
    return _SHED_POLICIES.get(reason, POLICY_FAIL)


class ServerOverloadedError(RuntimeError):
    """A request was shed instead of served, with a reason code.

    Load shedding is the contract, not a failure mode: when the server
    cannot answer in time it says so immediately — a bounded queue plus a
    reason-coded rejection degrades predictably where unbounded queueing
    melts down.  ``reason`` is one of the ``SHED_*`` codes
    (``queue_full`` / ``deadline_expired`` / ``breaker_open`` /
    ``memory_pressure`` / ``shutdown``); the matching
    ``serving.shed.<reason>`` counter moved by
    one.  ``trace_id`` carries the shed request's trace (None when
    tracing is off or the request was sampled out) — the handle that
    finds the request in the span sink and the flight-recorder ring.
    """

    trace_id = None

    def __init__(self, reason: str, detail: str = "", trace_id=None):
        super().__init__(
            f"request shed ({reason})" + (f": {detail}" if detail else "")
        )
        self.reason = reason
        self.trace_id = trace_id

    @property
    def retryable(self) -> bool:
        """Could ANOTHER server plausibly serve this request (ISSUE 13)?
        True for every reason :func:`shed_policy` maps to retry or
        route-away — a shed request was never served, so retrying it
        elsewhere is safe whenever the reason is understood."""
        return shed_policy(self.reason) != POLICY_FAIL


class ServerClosedError(RuntimeError):
    """submit() on a server that is not running (never started, shutting
    down, or already shut down)."""
