"""Guarded continuous learning: online train -> validated candidate ->
auto-deploy, with poisoned-data rollback (ISSUE 14).

The reference designs exactly two topologies and this closes the second:
the unbounded connected train/predict stream (the
``IncrementalLearningSkeleton`` shape, PAPER.md §0.4) wired into the
serving runtime as a self-healing model lifecycle.  A
:class:`ContinuousLearningController` runs an online fitter
(:meth:`~flink_ml_tpu.lib.online.OnlineLogisticRegression.fit_unbounded`
over :mod:`flink_ml_tpu.iteration.unbounded`) on a label stream beside a
live :class:`~flink_ml_tpu.serving.server.ModelServer`, and every
``FMT_LIFECYCLE_EVERY_WINDOWS`` effective training windows it cuts a
**candidate** and pushes it through a hard validation gate before the
candidate is allowed anywhere near traffic:

1. **numeric health** — :func:`~flink_ml_tpu.fault.guard.check_health`
   on the candidate's parameters (a poisoned label burst that drove the
   online SGD to NaN/Inf dies HERE, reason-coded ``numeric_health``);
2. **score quarantine** — the candidate's holdout scores must be finite
   (``score_quarantine``: finite params can still overflow a dot
   product);
3. **holdout no-regression** — the candidate's holdout AUC may trail the
   incumbent's by at most ``FMT_LIFECYCLE_REGRESSION_TOL``
   (``holdout_regression``);
4. **score-drift sanity** — PSI between the candidate's and the
   incumbent's STANDARDIZED holdout score distributions must stay under
   ``FMT_LIFECYCLE_SCORE_PSI`` (``score_drift``: a candidate whose AUC
   survived but whose score distribution changed shape — a sign flip, a
   collapse to a point mass, a bimodal split — scores a different
   function than the ranking metric can see; near-constant candidate
   scores are degenerate and block outright, which is also what keeps an
   all-zero candidate away from traffic).

A **passing** candidate is committed to disk through the sidecar-commit
scheme (``Stage.save`` integrity sidecars + a ``lifecycle.json``
descriptor written last-as-commit) and auto-deploys through the round-10
swap contract (:meth:`ModelServer.deploy`: integrity-verified load ->
pre-warm off the hot path -> atomic swap; the server's drift reference
resets so the new version's population is the new normal).  A
**failing** candidate is reason-coded (``lifecycle.blocked.<reason>``),
flight-recorded with a black-box dump, and the old model keeps serving;
when the failure says the TRAINER state itself is poisoned
(``numeric_health`` / ``score_quarantine``), the controller resets the
online fitter to the last validated candidate's parameters
(``lifecycle.trainer_resets``) so one poisoned burst cannot wedge the
loop forever.

After every swap a **probation window** (``FMT_LIFECYCLE_PROBATION_S``)
watches the live burn-rate signals (``slo.burning.*`` — serving p99,
shed/error ratio, drift PSI) through the server's
:class:`~flink_ml_tpu.obs.slo.SLOMonitor`; a breach rolls the server
back to the previous version through the SAME integrity-verified swap
path (:meth:`ModelServer.rollback`), restores the incumbent baseline,
and counts ``lifecycle.rollbacks``.

Preemption (the satellite contract): the streaming driver polls SIGTERM
at record/span boundaries and commits an emergency stream snapshot; the
controller then commits an **emergency candidate** before the clean
exit, and a restarted loop resumes from the committed state
bit-identically (subprocess-tested).

Counters: ``lifecycle.candidates`` / ``lifecycle.swaps`` /
``lifecycle.blocked`` (+ ``.{reason}``) / ``lifecycle.rollbacks`` /
``lifecycle.trainer_resets`` / ``lifecycle.emergency_candidates``.
Knobs (BASELINE.md round-17): ``FMT_LIFECYCLE_EVERY_WINDOWS``,
``FMT_LIFECYCLE_REGRESSION_TOL``, ``FMT_LIFECYCLE_SCORE_PSI``,
``FMT_LIFECYCLE_PROBATION_S``, ``FMT_LIFECYCLE_HISTORY``,
``FMT_LIFECYCLE_DIR``.

Entry points: ``scripts/chaos_smoke.py --online`` (poisoned burst /
drift-burn rollback / multi-swap loop legs), ``bench_all.py
online_loop`` (the <= 1.05 controller-attached overhead gate),
``tests/test_lifecycle.py``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from flink_ml_tpu import obs
from flink_ml_tpu.table.table import Table

__all__ = [
    "BLOCK_DEPLOY_FAILED",
    "BLOCK_HOLDOUT_REGRESSION",
    "BLOCK_NUMERIC_HEALTH",
    "BLOCK_SCORE_DRIFT",
    "BLOCK_SCORE_QUARANTINE",
    "ContinuousLearningController",
    "latest_candidate",
]

#: gate reason codes (the ``lifecycle.blocked.<reason>`` vocabulary)
BLOCK_NUMERIC_HEALTH = "numeric_health"
BLOCK_SCORE_QUARANTINE = "score_quarantine"
BLOCK_HOLDOUT_REGRESSION = "holdout_regression"
BLOCK_SCORE_DRIFT = "score_drift"
BLOCK_DEPLOY_FAILED = "deploy_failed"

#: gate failures that mean the TRAINER state itself is poisoned — the
#: controller resets the online fitter to the last good candidate
_POISON_REASONS = frozenset({BLOCK_NUMERIC_HEALTH, BLOCK_SCORE_QUARANTINE})

#: the candidate commit descriptor, written last-as-commit: a candidate
#: directory without one is an aborted save, never a resume point
_CANDIDATE_FILE = "lifecycle.json"
_CANDIDATE_PREFIX = "candidate-"

#: probation poll cadence — cheap (one dict read off the SLO monitor)
_PROBE_INTERVAL_S = 0.25

#: candidate-outcome records kept in the controller's history window —
#: the loop runs forever, so even bookkeeping must stay bounded (the
#: counters keep the true totals)
_HISTORY_RECORDS = 256


def _auc(y: np.ndarray, scores: np.ndarray) -> float:
    """Rank-based AUC (Mann-Whitney) — the holdout no-regression metric."""
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty(len(scores))
    ranks[order] = np.arange(1, len(scores) + 1)
    pos = y == 1
    n1 = int(pos.sum())
    n0 = len(y) - n1
    if n1 == 0 or n0 == 0:
        return 0.5
    return float((ranks[pos].sum() - n1 * (n1 + 1) / 2) / (n1 * n0))


def _score_psi(reference: np.ndarray, live: np.ndarray) -> Optional[float]:
    """Shape-PSI between two holdout score vectors via the obs quantile
    sketches (the same statistic the data-plane drift monitor judges).

    Both vectors are STANDARDIZED first: continued online training
    legitimately grows score magnitude window over window, so raw-score
    PSI would block every healthy candidate — what the sanity gate hunts
    is a SHAPE change (sign flip, collapse to a point mass, bimodal
    split) that says the candidate scores a different function, not a
    sharper one.  Returns None for a degenerate (near-constant) live
    distribution — the caller blocks those outright, which is also what
    keeps an all-zero candidate away from traffic."""
    from flink_ml_tpu.obs.sketch import QuantileSketch, psi

    live_std = float(np.std(live))
    if live_std < 1e-12:
        return None
    ref_std = float(np.std(reference)) or 1.0
    ref = QuantileSketch()
    ref.update((reference - np.mean(reference)) / ref_std)
    cur = QuantileSketch()
    cur.update((live - np.mean(live)) / live_std)
    return psi(ref, cur)


def latest_candidate(candidate_dir: str) -> Optional[Tuple[str, dict]]:
    """``(path, descriptor)`` of the newest COMMITTED candidate under
    ``candidate_dir``, or None.  Commit = a parseable ``lifecycle.json``
    (written last); aborted saves are invisible, exactly like the spill
    blocks and checkpoints this scheme is borrowed from."""
    if not os.path.isdir(candidate_dir):
        return None
    best = None
    for name in sorted(os.listdir(candidate_dir)):
        if not name.startswith(_CANDIDATE_PREFIX):
            continue
        descriptor = os.path.join(candidate_dir, name, _CANDIDATE_FILE)
        try:
            with open(descriptor) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            continue  # uncommitted / torn candidate: not a resume point
        best = (os.path.join(candidate_dir, name), meta)
    return best


class ContinuousLearningController:
    """Online training -> validated candidate -> auto-deploy, guarded.

    ``estimator`` is an
    :class:`~flink_ml_tpu.lib.online.OnlineLogisticRegression` (feature/
    label cols configured); ``training_source`` its label stream;
    ``holdout`` a labeled validation table the gate judges every
    candidate on.  ``server`` is the live :class:`ModelServer` passing
    candidates deploy onto — ``None`` runs the loop in publish-only mode
    (candidates validate and commit to disk, nothing deploys), the
    trainer-box half of a split deployment.

    ``run()`` drives the loop on the calling thread (the preemption-
    scope entry point — use this from a process's main thread);
    ``start()`` runs it on a background thread beside the caller.  The
    probation watcher runs on its own daemon thread either way.
    """

    def __init__(self, estimator, training_source, holdout: Table, *,
                 server=None, candidate_dir: Optional[str] = None,
                 candidate_every: Optional[int] = None,
                 regression_tol: Optional[float] = None,
                 score_psi: Optional[float] = None,
                 probation_s: Optional[float] = None,
                 max_windows: Optional[int] = None):
        from flink_ml_tpu.lib.common import resolve_features
        from flink_ml_tpu.utils import knobs

        self.estimator = estimator
        self._training_source = training_source
        self._server = server
        self._max_windows = max_windows
        self.candidate_every = int(
            candidate_every if candidate_every is not None
            else knobs.knob_int("FMT_LIFECYCLE_EVERY_WINDOWS")
        )
        if self.candidate_every < 1:
            raise ValueError("candidate_every must be >= 1")
        self.regression_tol = float(
            regression_tol if regression_tol is not None
            else knobs.knob_float("FMT_LIFECYCLE_REGRESSION_TOL")
        )
        self.score_psi = float(
            score_psi if score_psi is not None
            else knobs.knob_float("FMT_LIFECYCLE_SCORE_PSI")
        )
        self.probation_s = float(
            probation_s if probation_s is not None
            else knobs.knob_float("FMT_LIFECYCLE_PROBATION_S")
        )
        if candidate_dir is None:
            candidate_dir = knobs.knob_str("FMT_LIFECYCLE_DIR")
        if not candidate_dir:
            import tempfile

            candidate_dir = tempfile.mkdtemp(prefix="fmt_lifecycle_")
        self.candidate_dir = candidate_dir
        os.makedirs(self.candidate_dir, exist_ok=True)
        #: the streaming driver's snapshot directory — its cadence is
        #: pinned to the candidate cadence so a committed candidate and
        #: the stream snapshot describe the same window boundary
        self.stream_dir = os.path.join(self.candidate_dir, "stream")

        Xh, _ = resolve_features(holdout, estimator)
        self._holdout_x = np.asarray(Xh, dtype=np.float64)
        self._holdout_y = np.asarray(
            holdout.col(estimator.get_label_col()), dtype=np.float64
        )
        if not np.all(np.isfinite(self._holdout_x)) or not np.all(
                np.isfinite(self._holdout_y)):
            raise ValueError(
                "holdout table carries non-finite features/labels — the "
                "gate's yardstick must itself be clean"
            )

        # mutable shared state: the trainer thread and the probation
        # watcher both touch it, so every access goes through _lock
        self._lock = threading.Lock()
        # serializes the trainer's candidate deploy against the prober's
        # rollback: interleaving them would leave the serving pointer,
        # the retained-version ordering, and the incumbent bookkeeping
        # telling three different stories
        self._deploy_mutex = threading.Lock()
        self._state = None          # latest device pytree from the hook
        self._windows = 0           # windows fired (incl. skipped)
        self._effective_since = 0   # effective windows since last candidate
        self._seq = 0               # candidate sequence number
        self._incumbent: Optional[dict] = None   # {version,path,w,b,auc,scores}
        self._prev_incumbent: Optional[dict] = None
        from collections import deque

        self._probation_until = 0.0
        self._counts: Dict[str, int] = {}
        self._history: "deque[dict]" = deque(maxlen=_HISTORY_RECORDS)
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._trainer: Optional[threading.Thread] = None
        self._prober: Optional[threading.Thread] = None

        self._bootstrap_incumbent()

    # -- bootstrap / resume ---------------------------------------------------

    def _bootstrap_incumbent(self) -> None:
        """The gate's baseline: the server's live model when it is
        score-capable, else the newest committed candidate on disk (the
        restart path), else None — the first candidate then deploys
        gated by health/finiteness alone, and BECOMES the baseline."""
        latest = latest_candidate(self.candidate_dir)
        if latest is not None:
            path, meta = latest
            with self._lock:
                self._seq = int(meta.get("seq", 0))
        record = None
        if self._server is not None:
            record = self._eval_model(
                self._server.active_model,
                version=self._server.active_version, path=None)
        if record is None and latest is not None:
            path, meta = latest
            try:
                from flink_ml_tpu.api.core import load_stage

                record = self._eval_model(
                    load_stage(path), version=meta.get("version"),
                    path=path)
            except Exception:  # noqa: BLE001 - a rotted candidate is not
                record = None  # a baseline; the loop re-learns one
        with self._lock:
            self._incumbent = record

    def _eval_model(self, model, version, path) -> Optional[dict]:
        """Holdout evaluation of a score-capable (GLM-family) model, or
        None for stages with no linear scores to compare against."""
        try:
            w = np.asarray(model.coefficients(), dtype=np.float64)
            b = float(model.intercept())
        except Exception:  # noqa: BLE001 - not a GLM-family stage
            return None
        if w.shape != (self._holdout_x.shape[1],):
            return None
        scores = self._holdout_x @ w + b
        if not np.all(np.isfinite(scores)):
            return None
        return {
            "version": version, "path": path, "w": w, "b": b,
            "auc": _auc(self._holdout_y, scores), "scores": scores,
        }

    # -- lifecycle ------------------------------------------------------------

    def run(self):
        """Drive the training loop to stream end on the CALLING thread
        (blocking).  From a main thread this is the preemption-scope
        entry: a SIGTERM commits the driver's emergency stream snapshot
        AND an emergency candidate, then exits cleanly via
        :class:`~flink_ml_tpu.fault.guard.Preempted`.  Returns the final
        fitted model."""
        from flink_ml_tpu.fault.guard import Preempted
        from flink_ml_tpu.iteration.checkpoint import CheckpointConfig

        self._start_prober()
        checkpoint = CheckpointConfig(
            directory=self.stream_dir,
            every_n_epochs=self.candidate_every,
            min_interval_s=0.0,
        )
        try:
            model, _ = self.estimator.fit_unbounded(
                self._training_source,
                max_windows=self._max_windows,
                checkpoint=checkpoint,
                window_hook=self._on_window,
            )
        except Preempted:
            self._emergency_candidate()
            raise
        # stream end: the final state is the last candidate opportunity
        with self._lock:
            state = self._state
            due = self._effective_since > 0
        if due and state is not None:
            self._candidate(state)
        return model

    def start(self) -> "ContinuousLearningController":
        """Run the loop on a background thread beside the caller (the
        in-process serving topology).  A SIGTERM still reaches worker-
        thread boundary polls when the process's main thread holds a
        preemption scope; the emergency-candidate epilogue runs either
        way."""
        from flink_ml_tpu.fault.guard import Preempted

        def body():
            try:
                self.run()
            except Preempted:
                pass  # clean preemption exit recorded by the epilogue
            except BaseException as exc:  # noqa: BLE001 - surfaced via .error
                with self._lock:
                    self._error = exc

        self._trainer = threading.Thread(
            target=body, name="fmt-lifecycle-trainer", daemon=True,
        )
        self._trainer.start()
        return self

    def join(self, timeout: Optional[float] = None) -> None:
        """Wait for a :meth:`start`-ed loop to reach stream end; re-raise
        the trainer's failure if it died."""
        if self._trainer is not None:
            self._trainer.join(timeout=timeout)
        err = self.error
        if err is not None:
            raise err

    def stop(self, timeout: float = 5.0) -> None:
        """Stop the probation watcher (the trainer stops when its source
        ends — close/drain the source to stop it early).  Idempotent."""
        self._stop.set()
        prober, self._prober = self._prober, None
        if prober is not None:
            prober.join(timeout=timeout)

    @property
    def error(self) -> Optional[BaseException]:
        with self._lock:
            return self._error

    @property
    def windows(self) -> int:
        """Windows the trainer has fired (skipped ones included)."""
        with self._lock:
            return self._windows

    @property
    def incumbent_version(self) -> Optional[str]:
        with self._lock:
            return (self._incumbent or {}).get("version")

    def stats(self) -> dict:
        """Counts + candidate history, the controller's report payload."""
        with self._lock:
            return {
                **dict(sorted(self._counts.items())),
                "windows": self._windows,
                "incumbent": (self._incumbent or {}).get("version"),
                "history": [dict(h) for h in self._history],
            }

    def _count_locked(self, name: str, n: int = 1) -> None:
        self._counts[name] = self._counts.get(name, 0) + n

    # -- the window hook ------------------------------------------------------

    def _on_window(self, epoch: int, state):
        """Called by the online fitter after EVERY fired window (on the
        trainer thread).  Tracks effective windows (a skipped window
        returns the identical state object), cuts a candidate every
        ``candidate_every`` effective windows, and returns a replacement
        state when the gate says the trainer itself is poisoned."""
        with self._lock:
            skipped = state is self._state and self._windows > 0
            self._state = state
            self._windows = epoch + 1
            if not skipped:
                self._effective_since += 1
            due = self._effective_since >= self.candidate_every
        if not due:
            return None
        return self._candidate(state)

    # -- candidate pipeline ---------------------------------------------------

    def _candidate(self, state):
        """Cut one candidate from the live trainer state: fetch, gate,
        commit, deploy.  Returns a replacement trainer state (the
        poisoned-trainer reset) or None."""
        w = np.asarray(state[0], dtype=np.float64)
        b = float(np.asarray(state[1]))
        with self._lock:
            self._effective_since = 0
            self._seq += 1
            seq = self._seq
        version = f"cl-{seq}"
        obs.counter_add("lifecycle.candidates")
        with self._lock:
            self._count_locked("lifecycle.candidates")
        verdict = self._gate(w, b)
        if verdict["reason"] is not None:
            return self._blocked(version, verdict)
        path = self._commit_candidate(seq, version, w, b, verdict["auc"])
        if self._server is not None:
            try:
                with self._deploy_mutex:
                    self._server.deploy(path, version)
            except BaseException as exc:  # noqa: BLE001 - old model serves
                return self._blocked(version, {
                    "reason": BLOCK_DEPLOY_FAILED,
                    "detail": f"{type(exc).__name__}: {exc}",
                    "auc": verdict["auc"], "scores": None,
                })
            obs.counter_add("lifecycle.swaps")
            obs.flight.record("lifecycle.swap", version=version,
                              auc=round(verdict["auc"], 6), path=path)
        else:
            obs.counter_add("lifecycle.published")
        with self._lock:
            self._count_locked("lifecycle.swaps"
                               if self._server is not None
                               else "lifecycle.published")
            self._prev_incumbent = self._incumbent
            self._incumbent = {
                "version": version, "path": path, "w": w, "b": b,
                "auc": verdict["auc"], "scores": verdict["scores"],
            }
            self._history.append({
                "version": version, "outcome": "swapped"
                if self._server is not None else "published",
                "auc": round(verdict["auc"], 6), "windows": self._windows,
            })
            # probation arms only when there is a live server whose SLOs
            # can breach — and a previous version to roll back onto
            if self._server is not None:
                self._probation_until = time.monotonic() + self.probation_s
        return None

    def _blocked(self, version: str, verdict: dict):
        """Reason-code, count, flight-record a blocked candidate; the old
        model keeps serving.  Returns the trainer-reset state when the
        reason marks the trainer itself as poisoned."""
        reason, detail = verdict["reason"], verdict["detail"]
        obs.counter_add("lifecycle.blocked")
        obs.counter_add(f"lifecycle.blocked.{reason}")
        obs.flight.record("lifecycle.candidate_blocked", version=version,
                          reason=reason, detail=detail)
        obs.flight.dump("lifecycle_blocked")
        with self._lock:
            self._count_locked("lifecycle.blocked")
            self._count_locked(f"lifecycle.blocked.{reason}")
            self._history.append({
                "version": version, "outcome": "blocked", "reason": reason,
                "detail": detail, "windows": self._windows,
            })
            incumbent = self._incumbent
        if reason not in _POISON_REASONS:
            return None
        # the trainer state itself is poisoned: continuing to fold clean
        # windows into NaN params can never recover — reset the online
        # fitter to the last validated candidate (or a cold start)
        import jax.numpy as jnp

        dim = self._holdout_x.shape[1]
        if incumbent is not None:
            w0, b0 = incumbent["w"], incumbent["b"]
            target = incumbent["version"]
        else:
            w0, b0 = np.zeros((dim,)), 0.0
            target = "initial"
        obs.counter_add("lifecycle.trainer_resets")
        obs.flight.record("lifecycle.trainer_reset", to=target,
                          reason=reason)
        with self._lock:
            self._count_locked("lifecycle.trainer_resets")
        return (
            jnp.asarray(np.asarray(w0, dtype=np.float32)),
            jnp.asarray(np.float32(b0)),
        )

    def _gate(self, w: np.ndarray, b: float) -> dict:
        """The hard validation gate.  Returns ``{reason, detail, auc,
        scores}`` — ``reason`` None means the candidate may deploy."""
        from flink_ml_tpu.fault.guard import NumericHealthError, check_health

        out = {"reason": None, "detail": "", "auc": 0.0, "scores": None}
        try:
            check_health(leaves=(w, np.float64(b)),
                         where="lifecycle.candidate")
        except NumericHealthError as exc:
            out.update(reason=BLOCK_NUMERIC_HEALTH, detail=str(exc))
            return out
        if not (np.all(np.isfinite(w)) and np.isfinite(b)):
            # FMT_GUARD=0 turns check_health into a no-op, but a swap
            # gate has no business deploying NaN params regardless
            out.update(reason=BLOCK_NUMERIC_HEALTH,
                       detail="non-finite candidate parameters")
            return out
        scores = self._holdout_x @ w + b
        if not np.all(np.isfinite(scores)):
            bad = int(np.size(scores) - np.isfinite(scores).sum())
            out.update(reason=BLOCK_SCORE_QUARANTINE,
                       detail=f"{bad} non-finite holdout scores")
            return out
        out["scores"] = scores
        out["auc"] = _auc(self._holdout_y, scores)
        with self._lock:
            incumbent = self._incumbent
        if incumbent is not None:
            floor = incumbent["auc"] - self.regression_tol
            if out["auc"] < floor:
                out.update(
                    reason=BLOCK_HOLDOUT_REGRESSION,
                    detail=(f"holdout AUC {out['auc']:.4f} under the "
                            f"incumbent's {incumbent['auc']:.4f} - "
                            f"{self.regression_tol:g} tolerance"),
                )
                return out
            psi_value = _score_psi(incumbent["scores"], scores)
            if psi_value is None:
                out.update(
                    reason=BLOCK_SCORE_DRIFT,
                    detail="degenerate candidate scores (near-constant "
                           "holdout score distribution)",
                )
                return out
            if psi_value > self.score_psi:
                out.update(
                    reason=BLOCK_SCORE_DRIFT,
                    detail=(f"candidate-vs-incumbent standardized holdout "
                            f"score PSI {psi_value:.4f} > "
                            f"{self.score_psi:g}"),
                )
                return out
        return out

    def _commit_candidate(self, seq: int, version: str, w: np.ndarray,
                          b: float, auc: float,
                          emergency: bool = False) -> str:
        """Persist one candidate through the sidecar-commit scheme: the
        model saves first (its own integrity sidecars), the
        ``lifecycle.json`` descriptor lands last as the commit record."""
        from flink_ml_tpu.lib.classification import LogisticRegressionModel
        from flink_ml_tpu.lib.glm import make_model_table
        from flink_ml_tpu.serve.integrity import atomic_json_dump

        model = LogisticRegressionModel()
        model.get_params().merge(self.estimator.get_params())
        model.set_model_data(make_model_table(w, float(b)))
        path = os.path.join(self.candidate_dir,
                            f"{_CANDIDATE_PREFIX}{seq:06d}")
        model.save(path)
        with self._lock:
            windows = self._windows
        atomic_json_dump({
            "seq": seq, "version": version, "windows": windows,
            "auc": round(float(auc), 6), "emergency": bool(emergency),
        }, os.path.join(path, _CANDIDATE_FILE))
        return path

    def _emergency_candidate(self) -> None:
        """The preemption epilogue: commit the current trainer state as a
        candidate (no gate, no deploy — it is a checkpoint, not a swap)
        unless that state is non-finite, which would poison the restart's
        incumbent bootstrap."""
        with self._lock:
            state = self._state
            self._seq += 1
            seq = self._seq
        if state is None:
            return
        w = np.asarray(state[0], dtype=np.float64)
        b = float(np.asarray(state[1]))
        if not (np.all(np.isfinite(w)) and np.isfinite(b)):
            return
        scores = self._holdout_x @ w + b
        auc = _auc(self._holdout_y, scores) if np.all(
            np.isfinite(scores)) else 0.5
        self._commit_candidate(seq, f"cl-{seq}", w, b, auc,
                               emergency=True)
        obs.counter_add("lifecycle.emergency_candidates")
        obs.flight.record("lifecycle.emergency_candidate", seq=seq)

    # -- probation ------------------------------------------------------------

    def _start_prober(self) -> None:
        if self._prober is not None and self._prober.is_alive():
            return
        self._stop.clear()
        self._prober = threading.Thread(
            target=self._probe_loop, name="fmt-lifecycle-probation",
            daemon=True,
        )
        self._prober.start()

    def _probe_loop(self) -> None:
        while not self._stop.wait(_PROBE_INTERVAL_S):
            try:
                self._probe_once()
            except Exception:  # noqa: BLE001 - the watcher must outlive
                pass           # one bad sample; rollback failure is logged

    def _burning_now(self) -> Dict[str, float]:
        """The live burn signal: every SLO the server's monitor says is
        burning right now (empty when no monitor is armed)."""
        if self._server is None:
            return {}
        monitor = self._server.slo_monitor
        if monitor is None:
            return {}
        return dict(monitor.burning())

    def _probe_once(self) -> None:
        with self._lock:
            armed = (self._probation_until > 0.0
                     and time.monotonic() < self._probation_until)
        if not armed:
            return
        burning = self._burning_now()
        if not burning:
            return
        with self._lock:
            # disarm BEFORE rolling back: one breach, one rollback — the
            # prober must not machine-gun the version history while the
            # burn gauge takes a window to clear
            if not (self._probation_until > 0.0
                    and time.monotonic() < self._probation_until):
                return
            self._probation_until = 0.0
        self._rollback(burning)

    def _rollback(self, burning: Dict[str, float]) -> None:
        slos = ",".join(sorted(burning))
        try:
            with self._deploy_mutex:
                deployed = self._server.rollback()
        except Exception as exc:  # noqa: BLE001 - nothing to roll back to /
            # a rotted artifact: the breach stands, loudly, and the
            # current version keeps serving
            obs.flight.record("lifecycle.rollback_failed", slos=slos,
                              error=type(exc).__name__, detail=str(exc))
            return
        obs.counter_add("lifecycle.rollbacks")
        obs.flight.record("lifecycle.rollback", version=deployed.version,
                          slos=slos,
                          burn=round(max(burning.values()), 4))
        obs.flight.dump("lifecycle_rollback")
        with self._lock:
            self._count_locked("lifecycle.rollbacks")
            rolled_from = (self._incumbent or {}).get("version")
            # the incumbent baseline follows the serving pointer: the
            # next candidate must beat the RESTORED version, and the
            # poisoned-trainer reset targets it too
            if self._prev_incumbent is not None:
                self._incumbent = self._prev_incumbent
                self._prev_incumbent = None
            self._history.append({
                "version": rolled_from, "outcome": "rolled_back",
                "slos": slos, "restored": deployed.version,
            })
