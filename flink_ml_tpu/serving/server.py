"""ModelServer — the dynamic micro-batching request runtime.

Every inference path below this layer is table-at-a-time: PR 6 made one
fused dispatch per batch nearly optimal, but a production feed is not a
batch — it is thousands of concurrent single-row requests, each of which
would pay its own dispatch through ``transform``.  This server is the
layer that FILLS those fused batches from small requests (the Clipper-
style adaptive-batching frontend, specialized to our fused plans,
circuit breakers, and integrity-checked model files):

* ``submit(table)`` returns a ``concurrent.futures.Future`` immediately;
  requests land in a bounded queue and a dispatcher thread coalesces them
  into ONE ``PipelineModel.transform`` call — flushed when
  ``FMT_SERVING_MAX_BATCH`` rows are queued or the oldest request has
  waited ``FMT_SERVING_MAX_WAIT_MS``, whichever first.  The transform
  pads to the shared batch-shape ladder
  (``utils/compile_cache.bucket_batch_rows``), so mixed request sizes
  reuse a handful of compiled programs instead of compiling per size;
* outputs — and quarantine side-tables — demultiplex back to each caller
  with request-local row offsets (``batcher.demux``): a caller's result
  is bit-identical to a solo ``transform`` of its rows;
* admission control sheds instead of melting: queue at its row cap ->
  expired requests shed first, then ``queue_full`` rejection; a request
  past its deadline is shed, never served late; an OPEN circuit breaker
  sheds at the door (``breaker_open``) rather than queueing onto a dead
  device (``serve.open_breaker_names``);
* ``deploy(path, version)`` hot-swaps the model with zero downtime
  (``versioning.VersionManager``): integrity-verified load, pre-warm off
  the hot path, atomic pointer swap — in-flight batches finish on the old
  version, and a corrupt deploy leaves the old version serving;
* ``shutdown(drain=True)`` serves everything already queued, then joins
  the dispatcher; ``drain=False`` fails queued futures with a
  ``shutdown`` shed code.

Telemetry: ``serving.requests`` / ``request_rows`` / ``batches`` /
``served_rows`` / ``shed`` (+ per-reason) / ``failed_requests`` /
``swaps`` / ``deploy_failures`` counters, ``serving.queue_depth`` and
``serving.batch_occupancy`` gauges, and the ``serving.request_latency_ms``
histogram (p50/p99 via the registry's timing quantiles) — all landing in
a ``serving`` RunReport at shutdown.

Tracing (ISSUE 8, ``FMT_TRACE``): every submit mints a per-request
``trace_id`` (head-sampled via ``FMT_TRACE_SAMPLE``); the dispatcher
hands the context across its thread explicitly, so one request renders
as one ``submit -> queue_wait -> coalesce -> transform -> demux``
waterfall (``python -m flink_ml_tpu.obs trace``), sheds stamp the
``trace_id`` into ``ServerOverloadedError`` and the flight-recorder
ring, and quarantined rows carry it in their side-table.

Memory pressure (ISSUE 9, round 12): admission also enforces a
bytes-denominated budget — ``FMT_SERVING_QUEUE_CAP_MB`` (estimated from
each request's schema row width) sheds with the ``memory_pressure``
reason before the queue's memory footprint can grow past what the
device budget could ever serve — and the dispatcher recovers from
allocator OOM by splitting the coalesced batch at request boundaries
(bit-identical per-caller results), with the ``serving.batch`` pressure
state capping subsequent coalescing until the AIMD probe restores full
batches.

Live telemetry (ISSUE 10, ``FMT_TELEMETRY_PORT`` / the
``telemetry_port`` argument): the server brings up an embedded
OpenMetrics endpoint (``/metrics`` / ``/healthz`` / ``/readyz`` /
``/statusz``) and the SLO burn-rate monitor with its lifecycle —
``/readyz`` degrades reason-coded on open breakers, pressure caps,
deploys in progress, a saturating queue, and burning SLOs
(:mod:`flink_ml_tpu.obs.telemetry` / :mod:`flink_ml_tpu.obs.slo`).

Data drift (ISSUE 11, ``FMT_DRIFT`` / the ``drift`` argument): the
server arms a :class:`~flink_ml_tpu.obs.drift.DriftMonitor` whose
reference distribution snapshots at deploy (persisted next to a
path-deployed model, reset by redeploys), taps input features at the
quarantine boundary and output scores at demux, and feeds the third
(``drift``) SLO — ``slo.burning.drift``, a reason-coded ``drift``
``/readyz`` entry, per-column ``/statusz``, and ``drift_breach``
black boxes.

Knobs (BASELINE.md round-10/12/13/14 tables): ``FMT_SERVING_MAX_BATCH``,
``FMT_SERVING_MAX_WAIT_MS``, ``FMT_SERVING_QUEUE_CAP``,
``FMT_SERVING_QUEUE_CAP_MB``, ``FMT_SERVING_DEADLINE_MS``,
``FMT_SERVING_SHED_ON_BREAKER``, ``FMT_TELEMETRY_PORT``,
``FMT_SLO_WINDOW_S``, ``FMT_SLO_P99_MS``, ``FMT_SLO_ERR_RATIO``,
``FMT_DRIFT``, ``FMT_DRIFT_REF_ROWS``, ``FMT_DRIFT_PSI``,
``FMT_DRIFT_WINDOW_S``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import Counter, deque
from concurrent.futures import Future
from typing import Deque, List, Optional

from flink_ml_tpu import obs
from flink_ml_tpu.fault import pressure
from flink_ml_tpu.serving.admission import (
    ServingConfig,
    now_s,
    overloaded,
    shed,
)
from flink_ml_tpu.serving.batcher import (
    ServeRequest,
    ServeResult,
    coalesce,
    demux,
)
from flink_ml_tpu.serving.errors import (
    SHED_BREAKER_OPEN,
    SHED_DEADLINE,
    SHED_MEMORY_PRESSURE,
    SHED_QUEUE_FULL,
    SHED_SHUTDOWN,
    SHED_TENANT_QUOTA,
    ServerClosedError,
)
from flink_ml_tpu.serving.tenants import (
    DEFAULT_TENANT,
    TenantRegistry,
    validate_tenant_key,
)
from flink_ml_tpu.serving.versioning import VersionManager
from flink_ml_tpu.table.table import Table

__all__ = ["ModelServer"]

#: rows retained from the newest coalesced batch as the default warmup
#: sample for the next deploy (enough to exercise the plan, cheap to hold)
_WARMUP_SAMPLE_ROWS = 8

#: the dispatcher's memory-pressure surface (ISSUE 9): an allocator OOM
#: from a coalesced transform splits the batch at a request boundary and
#: caps subsequent coalescing here until the AIMD probe recovers
_SERVING_SURFACE = "serving.batch"


def _breaker_scope_names(model) -> frozenset:
    """The breaker names this model's transforms can dispatch through:
    its stages' serving telemetry keys (mapper ``serve_name`` defaults to
    the model stage's class name).  Scopes the shed-on-breaker admission
    check so an unrelated pipeline's open breaker — another server in the
    same process, a batch job's mapper — never sheds THIS server's
    traffic.  A custom mapper overriding ``serve_name`` beyond its class
    name falls outside the scope and simply never sheds at admission
    (fail-open: the transform path's own breaker/fallback still applies).
    """
    stages = getattr(model, "stages", None)
    if stages is None:
        stages = [model]
    return frozenset(type(s).__name__ for s in stages)


def _breaker_in_scope(name: str, scope: frozenset) -> bool:
    """Does an open breaker belong to one of this server's dispatch
    surfaces?  Per-mapper breakers match by name; per-plan breakers
    (``FusedPlan[A+B+...]``) match when every fused member is one of the
    server's stages."""
    if name in scope:
        return True
    if name.startswith("FusedPlan[") and name.endswith("]"):
        members = name[len("FusedPlan["):-1].split("+")
        return all(m in scope for m in members)
    return False


def _transform_one(model, table: Table) -> Table:
    """One model's 1-in/1-out serving transform (the ``ModelVersion.
    transform`` tuple-unwrap, for tenant models that carry no version
    wrapper)."""
    out = model.transform(table)
    (result,) = out if isinstance(out, tuple) else (out,)
    return result


def _warmstart_status() -> dict:
    """The /statusz warmstart section: the active warm-artifact store (or
    None when the layer is inert) and its sealed-manifest coverage."""
    from flink_ml_tpu.serving import warmstart

    store = warmstart.active()
    if store is None:
        return {"store": None}
    return {
        "store": store.root,
        "fingerprint": store.fingerprint,
        "manifest_entries": len(store.manifest().get("entries", {})),
    }


class ModelServer:
    """Request-level model server over a deployed pipeline.

    ``ModelServer(model)`` (or ``ModelServer(path=...)``) deploys version
    ``v1`` and starts the dispatcher; use as a context manager or call
    :meth:`shutdown` explicitly.  ``start=False`` builds the server
    paused — submissions queue (admission rules apply) until
    :meth:`start`, which tests and pre-loading setups use.
    """

    def __init__(self, model=None, *, path: Optional[str] = None,
                 version: str = "v1", warmup: Optional[Table] = None,
                 max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 queue_cap: Optional[int] = None,
                 queue_cap_mb: Optional[float] = None,
                 deadline_ms: Optional[float] = None,
                 shed_on_breaker: Optional[bool] = None,
                 telemetry_port: Optional[int] = None,
                 drift: Optional[bool] = None,
                 tenants: Optional[str] = None,
                 start: bool = True):
        if (model is None) == (path is None):
            raise ValueError("pass exactly one of model / path")
        self.config = ServingConfig.from_env(
            max_batch=max_batch, max_wait_ms=max_wait_ms,
            queue_cap=queue_cap, queue_cap_mb=queue_cap_mb,
            deadline_ms=deadline_ms,
            shed_on_breaker=shed_on_breaker,
        )
        # mesh-aware coalescing (ISSUE 15): the transform below shards
        # every fused dispatch over the mesh's data axis, so a full flush
        # should feed EVERY device — the knob-default coalescing target
        # scales to mesh_size x FMT_SERVING_MAX_BATCH.  An explicit
        # max_batch argument is the caller's number and stays verbatim.
        self._mesh_devices = self._serving_mesh_width()
        if max_batch is None and self._mesh_devices > 1:
            import dataclasses

            self.config = dataclasses.replace(
                self.config,
                max_batch=self.config.max_batch * self._mesh_devices,
            )
        # a coalesced dispatch must stay a SINGLE internal transform batch:
        # past the environment batch size the fused path switches to its
        # prefetch-producer thread, which the dispatcher's thread-local
        # quarantine capture cannot see — demux would lose side-tables.
        # Clamp rather than fail: the operator asked for bigger batches
        # than the pipeline will form anyway.
        limit = self._single_batch_rows()
        if limit and self.config.max_batch > limit:
            import dataclasses
            import warnings

            warnings.warn(
                f"FMT_SERVING_MAX_BATCH={self.config.max_batch} exceeds "
                f"the environment batch size ({limit}); clamping — a "
                "coalesced dispatch must stay one internal transform "
                "batch for quarantine demux to see its side-tables",
                stacklevel=2,
            )
            self.config = dataclasses.replace(self.config, max_batch=limit)
        self._versions = VersionManager()
        deployed = self._versions.deploy(
            model if model is not None else path, version, warmup=warmup
        )
        self._breaker_scope = _breaker_scope_names(deployed.model)
        self._warmup_sample: Optional[Table] = warmup
        self._cond = threading.Condition()
        self._queue: Deque[ServeRequest] = deque()
        self._queued_rows = 0
        self._queued_bytes = 0
        self._stopping = False
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        # per-server accounting: stats()/the shutdown report must describe
        # THIS server's traffic — the process-global serving.* counters
        # and latency histogram aggregate across every server (and test)
        # in the process, so each server tallies its own events alongside
        self._counts: Counter = Counter()
        self._counts_lock = threading.Lock()
        self._latencies: Deque[float] = deque(maxlen=512)
        # multi-tenant serving (ISSUE 20): the tenant-keyed model
        # registry (LRU-resident over the slab pool) plus per-tenant
        # queued-row accounting for the FMT_TENANT_QUOTA_ROWS admission
        # quota (guarded by self._cond like every other queue stat).  A
        # path deploy auto-registers every subdirectory of
        # <path>/tenants/ (or the explicit ``tenants`` directory) — the
        # replica convention: lay models out next to the default one.
        self._tenants = TenantRegistry(tally=self._tally)
        self._tenant_queued: Counter = Counter()
        tenant_dir = tenants if tenants is not None else (
            os.path.join(path, "tenants") if path is not None else None
        )
        if tenant_dir is not None and os.path.isdir(tenant_dir):
            for name in sorted(os.listdir(tenant_dir)):
                p = os.path.join(tenant_dir, name)
                if os.path.isdir(p):
                    self._tenants.register(name, p)
        # open-breaker admission memo (the scan locks every breaker in
        # the process): revalidated on any breaker state TRANSITION (the
        # generation counter — an opening breaker sheds immediately) or
        # after ~50 ms (a cooldown EXPIRING fires no transition)
        self._breaker_memo = (float("-inf"), -1, [])
        # data-plane drift monitor (ISSUE 11, FMT_DRIFT / the drift
        # argument): reference snapshotted at deploy — reloaded from the
        # model dir's persisted baseline when one exists — live window
        # tapped per coalesced batch; feeds the third SLO below
        self._drift = None
        self._drift_status_key: Optional[str] = None
        from flink_ml_tpu.obs import drift as _drift_mod

        drift_on = _drift_mod.enabled() if drift is None else bool(drift)
        if drift_on:
            self._drift = self._make_drift_monitor(deployed)
        # live telemetry plane (ISSUE 10): the endpoint + SLO monitor
        # come up with the server — even a paused (start=False) server
        # is scrapeable, and its saturated queue shows in /readyz
        self._telemetry = None
        self._slo = None
        self._status_key: Optional[str] = None
        self._mesh_status_key: Optional[str] = None
        from flink_ml_tpu.obs import telemetry as _telemetry_mod

        port = (telemetry_port if telemetry_port is not None
                else _telemetry_mod.env_port())
        if port is not None:
            self._start_telemetry(port)
        elif self._drift is not None:
            # no endpoint, but drift is armed: the SLO monitor still
            # samples so slo.burning.drift flips and /readyz (from some
            # other process surface) can consume it
            from flink_ml_tpu.obs import slo as slo_mod

            self._slo = slo_mod.SLOMonitor(drift=self._drift).start()
        if start:
            self.start()

    def _tally(self, name: str, n: float = 1) -> None:
        """Per-server tally only — the matching global counter is bumped
        where the event happens (obs.counter_add here, or the admission
        shed helpers), so neither side double-counts.  Own lock: submit
        threads and the dispatcher tally concurrently, and a lost
        increment would fail the exact-count assertions reports rely on."""
        with self._counts_lock:
            self._counts[name] += n

    def _shed(self, request: ServeRequest, reason: str,
              detail: str = "") -> None:
        """Shed one queued request: per-server tally + the counted,
        reason-coded future rejection (admission.shed).  Never call while
        holding ``self._cond``."""
        self._tally("serving.shed")
        self._tally(f"serving.shed.{reason}")
        shed(request, reason, detail)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ModelServer":
        with self._cond:
            if self._closed:
                raise ServerClosedError("server already shut down")
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, name="fmt-serving-dispatcher",
                    daemon=True,
                )
                self._thread.start()
        return self

    @property
    def running(self) -> bool:
        with self._cond:  # reentrant: _cond wraps an RLock
            thread = self._thread
        return thread is not None and thread.is_alive()

    def __enter__(self) -> "ModelServer":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.shutdown()
        return False

    def shutdown(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the server.  ``drain=True`` serves every queued request
        first (their futures resolve normally); ``drain=False`` sheds the
        queue with the ``shutdown`` reason code.  Idempotent."""
        dropped: List[ServeRequest] = []
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._stopping = True
            if not drain:
                dropped = list(self._queue)
                self._queue.clear()
                self._queued_rows = 0
                self._queued_bytes = 0
                self._tenant_queued.clear()
            thread = self._thread  # join OUTSIDE the lock, on a stable ref
            self._cond.notify_all()
        for r in dropped:  # complete futures outside the lock
            self._shed(r, SHED_SHUTDOWN, "server shut down without draining")
        self._tenants.close()  # detach the pool eviction listener
        if thread is not None:
            thread.join(timeout=timeout)
        elif drain:
            # never started: drain inline on the calling thread so queued
            # futures still resolve (submit-before-start is supported)
            while True:
                batch = self._next_batch()
                if batch is None:
                    break
                self._serve_batch(batch)
        self._stop_telemetry()
        self._write_report()

    # -- data-plane drift (ISSUE 11) -----------------------------------------

    @property
    def drift_monitor(self):
        """This server's :class:`~flink_ml_tpu.obs.drift.DriftMonitor`
        (None when drift is off)."""
        return self._drift

    def _make_drift_monitor(self, deployed):
        """The deploy-time reference contract: a path deploy whose model
        dir holds a persisted ``drift_reference.json`` restarts with its
        committed baseline; anything else starts snapshotting a fresh
        one from the pre-warm sample + the first ``FMT_DRIFT_REF_ROWS``
        live rows (persisted back to the model dir once frozen, so the
        NEXT restart keeps it).  A corrupt persisted baseline warns and
        re-learns — drift is advisory telemetry, and refusing to serve
        over it would invert the severity."""
        import warnings

        from flink_ml_tpu.obs import drift as drift_mod

        source = deployed.source_path
        monitor = drift_mod.DriftMonitor(name="serving",
                                         persist_path=source)
        if source:
            try:
                monitor.load_reference(source)
            except Exception as exc:  # noqa: BLE001 - advisory, see above
                warnings.warn(
                    f"persisted drift reference under {source!r} is "
                    f"unusable ({type(exc).__name__}: {exc}); re-learning "
                    "a baseline from live traffic",
                    RuntimeWarning, stacklevel=3,
                )
                obs.flight.record("drift.reference_corrupt",
                                  source=source,
                                  error=type(exc).__name__)
        if not monitor.reference_complete and self._warmup_sample is not None:
            monitor.bootstrap(self._warmup_sample)
        return monitor

    def _reset_drift_for(self, deployed, warmup: Optional[Table]) -> None:
        """Redeploy semantics: the new version serves a (possibly
        intentionally different) population, so the baseline resets —
        unless the NEW model dir already carries its own persisted
        reference, which is the restart/rollback case and wins."""
        import warnings

        monitor = self._drift
        if monitor is None:
            return
        source = deployed.source_path
        if source:
            try:
                if monitor.load_reference(source):
                    return
            except Exception as exc:  # noqa: BLE001
                warnings.warn(
                    f"persisted drift reference under {source!r} is "
                    f"unusable ({type(exc).__name__}); re-learning",
                    RuntimeWarning, stacklevel=3,
                )
        monitor.reset_reference(persist_path=source, warmup=warmup)

    # -- live telemetry plane (ISSUE 10) -------------------------------------

    @property
    def telemetry(self):
        """This server's :class:`~flink_ml_tpu.obs.telemetry.
        TelemetryServer` (None when telemetry is off or failed to bind)."""
        return self._telemetry

    @property
    def telemetry_address(self) -> Optional[str]:
        """The BOUND ``host:port`` of this server's telemetry endpoint
        (None when telemetry is off or failed to bind) — with an
        ephemeral ``telemetry_port=0`` this is where the listener
        actually landed, the address ``FMT_TELEMETRY_PORT_FILE``
        publishes for out-of-process discovery (ISSUE 13)."""
        t = self._telemetry
        if t is None or t.port is None:
            return None
        return f"{t.host}:{t.port}"

    def _start_telemetry(self, port: int) -> None:
        """Bring up the /metrics endpoint + SLO monitor and plug this
        server's readiness/status into them.  A bind failure warns and
        leaves the server serving — telemetry must never take down the
        traffic it observes."""
        import warnings

        from flink_ml_tpu.obs import slo as slo_mod
        from flink_ml_tpu.obs import telemetry as telemetry_mod

        try:
            self._telemetry = telemetry_mod.TelemetryServer(
                port=port).start()
        except OSError as exc:
            warnings.warn(
                f"telemetry endpoint failed to bind port {port}: {exc}; "
                "serving continues without /metrics",
                RuntimeWarning, stacklevel=3,
            )
            self._telemetry = None
            return
        telemetry_mod.register_readiness(self._readiness_reasons)
        self._status_key = telemetry_mod.register_status(
            "server", self._telemetry_status)
        if self._drift is not None:
            # /statusz gains the per-column drift section
            self._drift_status_key = telemetry_mod.register_status(
                "drift", self._drift.status)
        if self._mesh_devices > 1:
            # /statusz gains the per-device row-share breakdown of the
            # SPMD fused dispatches this server's transforms run
            from flink_ml_tpu.common import fused as fused_mod

            self._mesh_status_key = telemetry_mod.register_status(
                "mesh", fused_mod.mesh_status)
        self._slo = slo_mod.SLOMonitor(drift=self._drift).start()

    def _stop_telemetry(self) -> None:
        if self._slo is not None:
            self._slo.stop()
            self._slo = None
        if self._telemetry is not None:
            from flink_ml_tpu.obs import telemetry as telemetry_mod

            telemetry_mod.unregister_readiness(self._readiness_reasons)
            if self._status_key is not None:
                telemetry_mod.unregister_status(self._status_key)
                self._status_key = None
            if self._drift_status_key is not None:
                telemetry_mod.unregister_status(self._drift_status_key)
                self._drift_status_key = None
            if self._mesh_status_key is not None:
                telemetry_mod.unregister_status(self._mesh_status_key)
                self._mesh_status_key = None
            self._telemetry.stop()
            self._telemetry = None
        if self._drift is not None:
            self._drift.close()

    def _readiness_reasons(self) -> List[dict]:
        """This server's /readyz feed: a deploy mid-flight and a
        saturating queue both mean "stop routing here" BEFORE admission
        starts shedding.  Plain int reads — no lock: readiness is a
        heuristic probe, and a stale-by-one row count cannot matter."""
        from flink_ml_tpu.obs import telemetry as telemetry_mod

        reasons: List[dict] = []
        if self._versions.deploy_in_progress:
            reasons.append({
                "reason": "deploy_in_progress",
                "detail": f"deploying over {self.active_version!r}",
            })
        cap = self.config.queue_cap
        saturated_at = max(1, int(cap * telemetry_mod.
                                  queue_saturation_frac()))
        if self._queued_rows >= saturated_at:
            reasons.append({
                "reason": "queue_saturated",
                "detail": (f"{self._queued_rows} of {cap} queue-cap rows "
                           f"queued (saturation at {saturated_at})"),
            })
        return reasons

    def _telemetry_status(self) -> dict:
        """This server's /statusz contribution."""
        from flink_ml_tpu.common.fused import (
            serve_pallas_enabled, serve_precision,
        )

        with self._cond:
            queued_rows = self._queued_rows
        return {
            "active_version": self.active_version,
            "versions": self.versions,
            "running": self.running,
            "deploy_in_progress": self._versions.deploy_in_progress,
            "queued_rows": queued_rows,
            "queue_cap": self.config.queue_cap,
            "max_batch": self.config.max_batch,
            # the data plane's numeric contract (ISSUE 17): the router
            # surfaces each replica's serving precision and whether the
            # Pallas hot path is requested — an operator diffing replica
            # scores needs to see a precision split before anything else
            "precision": serve_precision(),
            "pallas": serve_pallas_enabled(),
            # cold-start resilience (ISSUE 18): which warm-artifact store
            # this replica serves from, and how much of the ladder its
            # manifest says is already warm — the router's rollup makes a
            # cold respawn visible before its first slow request would
            "warmstart": _warmstart_status(),
            # multi-tenant plane (ISSUE 20): registered/resident tenant
            # counts, the residency cap and quota, and the top-N-by-
            # traffic tenant table (requests/rows/sheds/cold-loads/
            # evictions per tenant)
            "tenants": self._tenants.status(),
            "stats": self.stats(),
        }

    # -- the request path ----------------------------------------------------

    def submit(self, table: Table,
               deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None) -> Future:
        """Enqueue one request; returns a Future resolving to a
        :class:`~flink_ml_tpu.serving.batcher.ServeResult`.

        ``tenant`` routes the rows to a registered tenant model (ISSUE
        20); None — the wire-compatible default — serves the deployed
        version exactly as before.  A malformed or unregistered tenant
        key raises ``ValueError`` at the door (a caller bug, never a
        shed); a tenant past its ``FMT_TENANT_QUOTA_ROWS`` queued-row
        quota sheds reason-coded ``tenant_quota``.

        Raises :class:`ServerClosedError` when the server is shut down and
        :class:`ServerOverloadedError` (reason-coded) when the request is
        shed at admission: the queue is at ``queue_cap`` rows even after
        shedding expired entries, or a circuit breaker is open and
        ``shed_on_breaker`` is on.
        """
        n = table.num_rows()
        if n == 0:
            raise ValueError("empty request: submit at least one row")
        if tenant is None:
            tenant = DEFAULT_TENANT
        else:
            validate_tenant_key(tenant)
            if tenant != DEFAULT_TENANT and not self._tenants.known(tenant):
                raise ValueError(
                    f"unknown tenant {tenant!r}: register_tenant() it "
                    "before submitting its traffic"
                )
        limit = self._single_batch_rows()
        if limit and n > limit:
            raise ValueError(
                f"request of {n} rows exceeds the environment batch size "
                f"({limit}); a request that large is a table, not a "
                "request — call transform directly"
            )
        # the request's trace root (None when tracing is off / sampled
        # out): minted HERE so even a synchronous admission shed carries
        # a trace_id, and every downstream hop parents under one context
        t_submit = time.perf_counter()
        req_trace = obs.trace.start_request(
            "serving.request", {"rows": n, "tenant": tenant}
        )
        trace_id = req_trace.trace_id if req_trace is not None else None
        # breaker admission reads no queue state: check it OUTSIDE the
        # condition lock so every submit doesn't serialize a scan of all
        # breakers against the dispatcher's wakeups.  Only breakers on
        # THIS server's dispatch surfaces count — another pipeline's dead
        # device must not shed a healthy server's traffic.
        if self.config.shed_on_breaker:
            open_names = self._open_scoped_breakers()
            if open_names:
                self._tally("serving.shed")
                self._tally(f"serving.shed.{SHED_BREAKER_OPEN}")
                self._tenants.note_shed(tenant)
                if req_trace is not None:
                    req_trace.end(status="shed", attrs={
                        "shed_reason": SHED_BREAKER_OPEN,
                        "breaker": open_names[0],
                    })
                raise overloaded(
                    SHED_BREAKER_OPEN,
                    f"circuit breaker open for {open_names[0]!r} — "
                    "refusing to queue onto a degraded dispatch path",
                    trace_id=trace_id,
                )
        now = now_s()
        request = ServeRequest(
            table=table, future=Future(), enqueued_at=now,
            deadline_at=self.config.deadline_at(now, deadline_ms),
            trace=req_trace, tenant=tenant,
        )
        quota = self._tenants.quota_rows()
        cap_bytes = self.config.queue_cap_bytes
        expired: List[ServeRequest] = []
        rejected = None
        try:
            with self._cond:
                if self._closed or self._stopping:
                    if req_trace is not None:
                        req_trace.end(status="error",
                                      attrs={"error": "ServerClosedError"})
                    raise ServerClosedError("server is shut down")
                if self._queued_rows + n > self.config.queue_cap or (
                    cap_bytes
                    and self._queued_bytes + request.n_bytes > cap_bytes
                ):
                    # make room by shedding what can no longer be served
                    # in time — oldest first (FIFO order IS age order)
                    expired = self._collect_expired_locked(now)
                if self._queued_rows + n > self.config.queue_cap:
                    rejected = (SHED_QUEUE_FULL, (
                        f"{self._queued_rows} rows queued against a cap "
                        f"of {self.config.queue_cap} (request adds {n})"
                    ))
                elif (cap_bytes
                      and self._queued_bytes + request.n_bytes > cap_bytes):
                    # the rows fit but the BYTES don't: the queue's
                    # estimated memory footprint would exceed the HBM
                    # admission budget (FMT_SERVING_QUEUE_CAP_MB)
                    rejected = (SHED_MEMORY_PRESSURE, (
                        f"{self._queued_bytes} estimated bytes queued "
                        f"against a cap of {cap_bytes} (request adds "
                        f"{request.n_bytes})"
                    ))
                elif quota and self._tenant_queued[tenant] + n > quota:
                    # per-tenant fair-share door (ISSUE 20): ONE hot
                    # tenant's backlog sheds against its own quota, not
                    # against its batch-mates' shared queue cap
                    rejected = (SHED_TENANT_QUOTA, (
                        f"tenant {tenant!r} has "
                        f"{self._tenant_queued[tenant]} rows queued "
                        f"against a quota of {quota} (request adds {n})"
                    ))
                else:
                    self._queue.append(request)
                    self._queued_rows += n
                    self._tenant_queued[tenant] += n
                    obs.gauge_set("serving.queue_depth", self._queued_rows)
                    if cap_bytes:
                        self._queued_bytes += request.n_bytes
                        obs.gauge_set("serving.queue_bytes",
                                      self._queued_bytes)
                    self._cond.notify()
        finally:
            # futures complete OUTSIDE the lock: done-callbacks may touch
            # the server (shed-retry submits) and must not re-enter
            for r in expired:
                self._shed(r, SHED_DEADLINE, "deadline passed while queued")
        if rejected is not None:
            reason, detail = rejected
            self._tally("serving.shed")
            self._tally(f"serving.shed.{reason}")
            self._tenants.note_shed(tenant)
            if req_trace is not None:
                req_trace.end(status="shed",
                              attrs={"shed_reason": reason,
                                     "tenant": tenant})
            raise overloaded(reason, detail, trace_id=trace_id)
        if req_trace is not None:
            # the admission + enqueue window, on the caller thread
            obs.trace.record_span(
                (req_trace.ctx,), "submit",
                time.perf_counter() - t_submit, {"rows": n},
            )
        self._tally("serving.requests")
        self._tally("serving.request_rows", n)
        obs.counter_add("serving.requests")
        obs.counter_add("serving.request_rows", n)
        self._tenants.note_request(tenant, n)
        return request.future

    def predict(self, table: Table, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None,
                tenant: Optional[str] = None) -> ServeResult:
        """Blocking convenience: ``submit(...).result(timeout)``."""
        return self.submit(
            table, deadline_ms=deadline_ms, tenant=tenant
        ).result(timeout)

    def register_tenant(self, tenant: str, source,
                        version: str = "v1") -> None:
        """Bind ``tenant`` to a saved-model directory (or an in-memory
        model).  Registration is metadata-only — the model faults in on
        the tenant's first request (LRU-resident over the slab pool,
        evicted under pressure, re-faulted in milliseconds off the
        warm-artifact store).  See :mod:`flink_ml_tpu.serving.tenants`."""
        self._tenants.register(tenant, source, version=version)

    @property
    def tenants(self) -> List[str]:
        """Registered tenant keys (the default tenant not included)."""
        return [t for t in self._tenants.tenants() if t != DEFAULT_TENANT]

    def _open_scoped_breakers(self) -> List[str]:
        """Open breakers on THIS server's dispatch surfaces, memoized:
        the registry scan locks every breaker in the process, so the
        admission hot path reuses the last answer until a breaker state
        TRANSITION bumps the generation counter (a breaker opening sheds
        the very next submit) or ~50 ms pass (a cooldown expiring fires
        no transition, so traffic resumes within the window)."""
        from flink_ml_tpu.serve import open_breaker_names
        from flink_ml_tpu.serve.breaker import state_generation

        now = now_s()
        gen = state_generation()
        stamp, memo_gen, names = self._breaker_memo
        if gen == memo_gen and now - stamp < 0.05:
            return names
        names = [
            b for b in open_breaker_names()
            if _breaker_in_scope(b, self._breaker_scope)
        ]
        self._breaker_memo = (now, gen, names)
        return names

    # -- hot swap ------------------------------------------------------------

    def deploy(self, model_or_path, version: str,
               warmup: Optional[Table] = None):
        """Hot-swap to a new model version with zero downtime.

        Runs on the CALLING thread: load + integrity verification + plan
        pre-warm happen while the dispatcher keeps serving the old
        version; only the final pointer swap is shared state.  ``warmup``
        defaults to a sample retained from live traffic (the last batch's
        head) so mid-traffic deploys warm the exact request schema.
        Raises on a failed deploy (corrupt artifact, broken transform) —
        the old version never stops serving.
        """
        if warmup is None:
            warmup = self._warmup_sample
        try:
            deployed = self._versions.deploy(model_or_path, version,
                                             warmup=warmup)
        except BaseException:
            self._tally("serving.deploy_failures")
            raise
        self._tally("serving.swaps")
        self._breaker_scope = _breaker_scope_names(deployed.model)
        # drift reference reset (ISSUE 11): the new version's population
        # is the new normal — unless its model dir carries a persisted
        # baseline (restart/rollback), which is reloaded instead
        self._reset_drift_for(deployed, warmup)
        return deployed

    def rollback(self, warmup: Optional[Table] = None):
        """Redeploy the previous retained version through the same
        integrity-verified swap path as :meth:`deploy` (ISSUE 14) — the
        continuous-learning controller's answer to a post-swap SLO/drift
        breach, and an operator's big red button.  The drift baseline
        follows the rollback: the restored version's model dir usually
        carries its persisted reference, which wins over re-learning."""
        if warmup is None:
            warmup = self._warmup_sample
        deployed = self._versions.rollback(warmup=warmup)
        self._tally("serving.rollbacks")
        self._breaker_scope = _breaker_scope_names(deployed.model)
        self._reset_drift_for(deployed, warmup)
        return deployed

    @property
    def active_version(self) -> Optional[str]:
        return self._versions.active_version

    @property
    def active_model(self):
        """The model object currently serving (the active version's)."""
        return self._versions.active().model

    @property
    def previous_version(self) -> Optional[str]:
        """Label a :meth:`rollback` would reactivate (None when no
        previous version is retained)."""
        return self._versions.previous_version

    @property
    def versions(self) -> List[str]:
        return self._versions.history

    @property
    def slo_monitor(self):
        """This server's :class:`~flink_ml_tpu.obs.slo.SLOMonitor` (None
        when neither telemetry nor drift armed one) — the burn-rate
        signal the continuous-learning probation window watches."""
        return self._slo

    # -- dispatcher ----------------------------------------------------------

    def _loop(self) -> None:
        while True:
            batch = self._next_batch()
            if batch is None:
                return
            self._serve_batch(batch)

    def _next_batch(self) -> Optional[List[ServeRequest]]:
        """Block until a flush condition holds, then take one batch.

        Flush when: queued rows >= ``max_batch``; OR the oldest request
        has waited ``max_wait_ms``; OR the server is draining.  Expired
        requests shed here too — a request that died waiting must not
        consume device time.  Their futures complete OUTSIDE the lock
        (the ``try``'s ``finally`` runs after the ``with`` releases it):
        a caller's done-callback may touch the server and must not
        re-enter under the lock mid-queue-iteration."""
        cfg = self.config
        while True:
            expired: List[ServeRequest] = []
            cancelled: List = []  # RequestTraces of drops, ended unlocked
            try:
                with self._cond:
                    while True:
                        now = now_s()
                        expired.extend(self._collect_expired_locked(now))
                        if self._queue:
                            flush_at = (
                                self._queue[0].enqueued_at + cfg.max_wait_s
                            )
                            if (
                                self._queued_rows >= cfg.max_batch
                                or now >= flush_at
                                or self._stopping
                            ):
                                return self._take_locked(cancelled)
                            if expired:
                                break  # shed first, then come back
                            self._cond.wait(timeout=flush_at - now)
                        elif self._stopping:
                            return None
                        else:
                            if expired:
                                break
                            self._cond.wait()
            finally:
                # cancellation is a terminal outcome too: a sampled
                # cancelled request's root span must still land (outside
                # the lock — ending a root flushes the span sink)
                for tr in cancelled:
                    tr.end(status="cancelled")
                for r in expired:
                    self._shed(r, SHED_DEADLINE,
                               "deadline passed while waiting in queue")

    def _take_locked(self, cancelled: Optional[List] = None,
                     ) -> List[ServeRequest]:
        """Pop whole requests up to ``max_batch`` rows (an oversized
        request serves alone; a schema change cuts the batch so coalesce
        never mixes schemas).  Each taken request transitions its future
        to RUNNING — a request whose caller cancelled it while queued is
        dropped here (its trace appended to ``cancelled`` for the CALLER
        to end once the lock is released), and a RUNNING future can no
        longer be cancelled, so result delivery cannot race a
        cancellation."""
        taken: List[ServeRequest] = []
        rows = 0
        bytes_out = 0
        dropped = 0
        schema = None
        # under memory pressure the coalescing target shrinks to the last
        # working batch size (and AIMD-probes back toward max_batch) —
        # one OOM must not re-split every subsequent coalesced dispatch.
        # The cap is per-device-denominated (ISSUE 15): an OOM on an
        # 8-device mesh shrinks the per-device share, not the whole
        # mesh's batch to a 1-device floor.  The width is read LIVE (not
        # the construction-time cache) so a mid-flight FMT_SERVE_MESH
        # flip keeps the pressure accounting on the actual dispatch width
        max_rows = pressure.state(_SERVING_SURFACE).admit(
            self.config.max_batch, n_dev=self._serving_mesh_width()
        )
        track_bytes = bool(self.config.queue_cap_bytes)
        while self._queue:
            r = self._queue[0]
            if taken and (
                rows + r.n_rows > max_rows
                or r.table.schema != schema
                or not self._tenant_compat(taken[0].tenant, r.tenant)
            ):
                break
            self._queue.popleft()
            self._tenant_queued[r.tenant] = max(
                self._tenant_queued[r.tenant] - r.n_rows, 0
            )
            if track_bytes:
                bytes_out += r.n_bytes
            if not r.future.set_running_or_notify_cancel():
                dropped += r.n_rows  # cancelled while queued
                if r.trace is not None and cancelled is not None:
                    cancelled.append(r.trace)
                continue
            schema = r.table.schema
            taken.append(r)
            rows += r.n_rows
        self._queued_rows -= rows + dropped
        obs.gauge_set("serving.queue_depth", self._queued_rows)
        if track_bytes:
            self._queued_bytes = max(self._queued_bytes - bytes_out, 0)
            obs.gauge_set("serving.queue_bytes", self._queued_bytes)
        if dropped:
            self._tally("serving.cancelled_rows", dropped)
            obs.counter_add("serving.cancelled_rows", dropped)
        return taken

    def _tenant_compat(self, a: str, b: str) -> bool:
        """May requests of tenants ``a`` and ``b`` share one coalesced
        batch?  Same tenant always; different tenants only when the mux
        is on and BOTH tenants' models are known same-family (their
        structural plan tokens, recorded at each tenant's first serve,
        compare equal) — so the first-ever request of a tenant serves
        solo once and coalesces ever after."""
        if a == b:
            return True
        from flink_ml_tpu.serving.mux import mux_enabled

        if not mux_enabled():
            return False
        ta = self._tenants.family_token(a)
        return ta is not None and ta == self._tenants.family_token(b)

    def _resolve_tenant(self, tenant: str, version):
        """One tenant's (model, version label) for a dispatch: the
        default tenant is the snapshotted active version; a registered
        tenant faults in through the registry (slab-pool resident)."""
        if tenant == DEFAULT_TENANT:
            return version.model, version.version
        return self._tenants.resolve(tenant)

    def _note_tenant_family(self, tenant: str, model, schema) -> None:
        """Record (once per tenant) the family token under which this
        tenant's model is mux-eligible — the compat check
        :meth:`_take_locked` runs at every batch cut.  A model whose
        chain cannot mux records nothing: its tenant simply keeps
        serving solo batches."""
        if self._tenants.family_token(tenant) is not None:
            return
        from flink_ml_tpu.serving import mux as mux_mod

        run = mux_mod.mux_run_for(
            model, schema, self._single_batch_rows() or None
        )
        if run is not None:
            self._tenants.note_family(tenant, mux_mod.family_token(run))

    def _collect_expired_locked(self, now: float) -> List[ServeRequest]:
        """Remove every expired request from the queue and return them
        for the CALLER to shed once the lock is released (completing a
        future under the lock would run caller callbacks re-entrantly)."""
        if not any(r.expired(now) for r in self._queue):
            return []
        expired: List[ServeRequest] = []
        kept: Deque[ServeRequest] = deque()
        track_bytes = bool(self.config.queue_cap_bytes)
        for r in self._queue:
            if r.expired(now):
                self._queued_rows -= r.n_rows
                self._tenant_queued[r.tenant] = max(
                    self._tenant_queued[r.tenant] - r.n_rows, 0
                )
                if track_bytes:
                    self._queued_bytes = max(
                        self._queued_bytes - r.n_bytes, 0
                    )
                expired.append(r)
            else:
                kept.append(r)
        self._queue = kept
        obs.gauge_set("serving.queue_depth", self._queued_rows)
        if track_bytes:
            obs.gauge_set("serving.queue_bytes", self._queued_bytes)
        return expired

    def _serve_batch(self, requests: List[ServeRequest]) -> None:
        """One coalesced dispatch, with memory-pressure recovery (ISSUE
        9): an allocator OOM from the transform splits the batch at a
        REQUEST boundary and serves each half on its own dispatch.
        Request-local demux offsets never depended on batchmates, so
        every caller's result — outputs and quarantine side-tables —
        stays bit-identical to the unsplit (and the solo) path.  The
        ``serving.batch`` pressure state caps subsequent coalescing at
        the working size, and the AIMD probe restores full batches once
        pressure clears."""
        if not requests:
            return
        from flink_ml_tpu.obs import drift as drift_mod

        try:
            # the drift tap scope (ISSUE 11): deep taps (quarantine
            # boundary, fused plan entry) inside this batch's transform
            # feed THIS server's monitor; exit rolls it (reference
            # freeze/persist + window rotation).  None = no-op context.
            with drift_mod.active(self._drift):
                self._serve_batch_once(requests)
        except BaseException as exc:  # noqa: BLE001 - OOM-only, see below
            # _serve_batch_once resolves every other failure into the
            # futures itself; only a splittable OOM escapes it
            if not (pressure.enabled() and pressure.is_oom(exc)
                    and len(requests) > 1):
                raise
            n_rows = sum(r.n_rows for r in requests)
            pressure.note_oom(_SERVING_SURFACE, n_rows, exc,
                              n_dev=self._serving_mesh_width())
            obs.counter_add("pressure.bisections")
            obs.counter_add(f"pressure.bisections.{_SERVING_SURFACE}")
            obs.counter_add("serving.pressure_splits")
            self._tally("serving.pressure_splits")
            obs.flight.record("serving.pressure_split", rows=n_rows,
                              requests=len(requests))
            mid = len(requests) // 2
            self._serve_batch(requests[:mid])
            self._serve_batch(requests[mid:])

    def _serve_batch_once(self, requests: List[ServeRequest]) -> None:
        """One coalesced dispatch: snapshot the active version, transform
        under quarantine capture, demux, resolve futures.

        Trace handoff: the dispatcher installs EVERY sampled request's
        context at once (``trace.use``), so the batch-scope spans —
        coalesce, the transform (and the fused plan's place/dispatch/sync
        spans under it), demux — fan out to each participating trace with
        shared timestamps: every caller's waterfall is complete on its
        own, and a racing sibling's spans can never cross over."""
        from flink_ml_tpu.obs import trace
        from flink_ml_tpu.serve.quarantine import QUARANTINE_REASON_COL

        if not requests:
            return  # every taken request was cancelled while queued
        if any(r.tenant != requests[0].tenant for r in requests):
            # multi-tenant batch (ISSUE 20): per-tenant-contiguous span
            # order — the mux stacks params per contiguous tenant span
            # and finalize runs per tenant slice.  The sort is stable,
            # so FIFO order holds WITHIN each tenant, and demux/futures
            # walk this same reordered list end to end.
            requests = sorted(requests, key=lambda r: r.tenant)
        version = self._versions.active()  # in-flight pins the old version
        traced = [r.trace for r in requests if r.trace is not None]
        now0 = now_s()
        for r in requests:
            # once per request: a memory-pressure split re-enters here
            # for each half, and a duplicate queue_wait would double-
            # count the wait in the request's waterfall
            if r.trace is not None and not getattr(
                    r, "_queue_wait_recorded", False):
                r._queue_wait_recorded = True
                trace.record_span((r.trace.ctx,), "queue_wait",
                                  now0 - r.enqueued_at)
        with trace.use(tuple(t.ctx for t in traced)):
            with trace.span("coalesce", {"requests": len(requests)}):
                table, spans = coalesce(requests)
            n_rows = table.num_rows()
            try:
                with obs.phase("serving.batch"):
                    results, scored = self._serve_spans(
                        requests, table, spans, version
                    )
                if self._drift is not None and scored is not None:
                    # the demux-side drift tap (ISSUE 11): produced
                    # score/prediction columns of the whole coalesced
                    # batch into the live (or still-filling reference)
                    # window, request input columns excluded.  Only
                    # default-tenant batches feed it: the reference
                    # belongs to the ACTIVE VERSION, and tenant outputs
                    # would drift it by construction
                    self._drift.observe_scores(
                        scored, exclude=frozenset(table.schema.field_names)
                    )
            except BaseException as exc:  # noqa: BLE001 - futures carry it
                if (pressure.enabled() and pressure.is_oom(exc)
                        and len(requests) > 1):
                    # allocator exhaustion on a splittable batch: let the
                    # caller split at a request boundary — the futures
                    # stay pending and every request still serves
                    raise
                self._tally("serving.failed_batches")
                self._tally("serving.failed_requests", len(requests))
                obs.counter_add("serving.failed_batches")
                obs.counter_add("serving.failed_requests", len(requests))
                for r in requests:
                    if r.trace is not None:  # before the future resolves
                        r.trace.end(status="error", attrs={
                            "error": type(exc).__name__,
                        })
                    if not r.future.done():
                        r.future.set_exception(exc)
                return
        now = now_s()
        for r, res in zip(requests, results):
            if r.trace is not None:
                # end the trace BEFORE resolving the future: once the
                # caller observes completion the whole trace must already
                # be recorded (a caller that disables tracing right after
                # result() must never race a trailing root-span write)
                attrs = {"version": res.version,
                         "quarantined": res.num_quarantined}
                if res.num_quarantined:
                    attrs["quarantine_reasons"] = ",".join(sorted({
                        str(x) for t in res.quarantine.values()
                        for x in t.col(QUARANTINE_REASON_COL)
                    }))
                r.trace.end(status="ok", attrs=attrs)
            r.future.set_result(res)
            latency_ms = (now - r.enqueued_at) * 1e3
            self._latencies.append(latency_ms)
            obs.observe("serving.request_latency_ms", latency_ms)
        self._tally("serving.batches")
        self._tally("serving.served_rows", n_rows)
        self._tally("serving.coalesced_requests", len(requests))
        obs.counter_add("serving.batches")
        obs.counter_add("serving.served_rows", n_rows)
        obs.counter_add("serving.coalesced_requests", len(requests))
        obs.gauge_set("serving.batch_occupancy",
                      min(n_rows / self.config.max_batch, 1.0))
        # retain a live-schema head as the default warmup for hot swaps
        self._warmup_sample = table.slice_rows(
            0, min(n_rows, _WARMUP_SAMPLE_ROWS)
        )

    def _serve_spans(self, requests: List[ServeRequest], table: Table,
                     spans, version):
        """Transform + demux for one taken batch, tenant-aware.

        Returns ``(results, scored)``: per-request results in span
        order, plus the combined output table when the whole batch was
        the default tenant (the drift monitor's feed; None otherwise).

        An all-default batch runs the historical single-model body
        verbatim.  A multi-tenant batch — only formed when every
        member's family token matched at the cut — serves as ONE
        multiplexed dispatch (:mod:`flink_ml_tpu.serving.mux`); mux
        ineligibility or failure falls back to per-tenant groups, each
        its own transform under a fresh quarantine capture, so every
        caller's outputs and side-tables stay bit-identical to solo
        serving either way."""
        from flink_ml_tpu.obs import trace
        from flink_ml_tpu.serve import quarantine

        trace_ids = [
            r.trace.trace_id if r.trace is not None else None
            for r in requests
        ]
        tenants = [r.tenant for r in requests]
        if all(t == DEFAULT_TENANT for t in tenants):
            with trace.span("transform", {
                "rows": table.num_rows(), "version": version.version,
            }):
                with quarantine.capture() as captured:
                    out = version.transform(table)
            with trace.span("demux"):
                results = demux(out, captured, spans, version.version,
                                trace_ids=trace_ids)
            self._note_tenant_family(DEFAULT_TENANT, version.model,
                                     table.schema)
            return results, out
        # contiguous per-tenant request groups (take order = span order)
        groups: List[tuple] = []  # (tenant, first request idx, last+1)
        for i, t in enumerate(tenants):
            if groups and groups[-1][0] == t:
                groups[-1] = (t, groups[-1][1], i + 1)
            else:
                groups.append((t, i, i + 1))
        if len(groups) > 1:
            results = self._serve_mux(requests, table, spans, groups,
                                      version, trace_ids)
            if results is not None:
                return results, None
        # per-tenant fallback: each group is exactly the single-tenant
        # body on its slice of the batch — own capture, own demux, so
        # offsets never need cross-group surgery
        from flink_ml_tpu.table import slab_pool

        results = []
        for tenant, i0, i1 in groups:
            lo, hi = spans[i0][0], spans[i1 - 1][1]
            g_table = (table if lo == 0 and hi == table.num_rows()
                       else table.slice_rows(lo, hi))
            g_spans = [(a - lo, b - lo) for a, b in spans[i0:i1]]
            model, label = self._resolve_tenant(tenant, version)
            with slab_pool.pool().pinned(model):
                with trace.span("transform", {
                    "rows": g_table.num_rows(), "version": label,
                    "tenant": tenant,
                }):
                    with quarantine.capture() as captured:
                        out = _transform_one(model, g_table)
                with trace.span("demux"):
                    results.extend(demux(
                        out, captured, g_spans, label,
                        trace_ids=trace_ids[i0:i1],
                    ))
            self._note_tenant_family(tenant, model, g_table.schema)
        return results, None

    def _serve_mux(self, requests, table: Table, spans, groups,
                   version, trace_ids):
        """One multiplexed dispatch for a multi-tenant batch, or None
        when a member's plan turns out mux-ineligible (the caller falls
        back to per-tenant groups).  Every tenant's model is pinned
        (slab-pool pin invariant) for the duration of the dispatch, so
        neither budget pressure nor the residency cap can fault a
        batch-mate out mid-flight.  A dispatch failure degrades to the
        fallback too — except an allocator OOM, which propagates so the
        request-boundary pressure split can halve the batch."""
        import contextlib

        from flink_ml_tpu.obs import trace
        from flink_ml_tpu.parallel.mesh import inference_mesh
        from flink_ml_tpu.serve import quarantine
        from flink_ml_tpu.serving import mux as mux_mod
        from flink_ml_tpu.table import slab_pool
        from flink_ml_tpu.utils.environment import MLEnvironmentFactory

        if not mux_mod.mux_enabled():
            return None
        batch_size = self._single_batch_rows() or None
        mux_spans: List = []
        models: List = []
        labels = {}
        token = None
        for tenant, i0, i1 in groups:
            model, label = self._resolve_tenant(tenant, version)
            run = mux_mod.mux_run_for(model, table.schema, batch_size)
            if run is None:
                return None
            tok = mux_mod.family_token(run)
            if token is None:
                token = tok
            elif tok != token:
                return None
            lo, hi = spans[i0][0], spans[i1 - 1][1]
            mux_spans.append(mux_mod.MuxSpan(tenant, run, lo, hi))
            models.append(model)
            labels[tenant] = label
        try:
            with contextlib.ExitStack() as stack:
                pool = slab_pool.pool()
                for m in models:
                    stack.enter_context(pool.pinned(m))
                mesh = inference_mesh(
                    MLEnvironmentFactory.get_default().get_mesh()
                )
                with trace.span("transform", {
                    "rows": table.num_rows(), "mux_tenants": len(groups),
                }):
                    with quarantine.capture() as captured:
                        out = mux_mod.serve_mux(table, mux_spans, mesh)
                with trace.span("demux"):
                    results = demux(out, captured, spans, version.version,
                                    trace_ids=trace_ids)
        except BaseException as exc:  # noqa: BLE001 - OOM re-raised below
            if (pressure.enabled() and pressure.is_oom(exc)
                    and len(requests) > 1):
                raise
            obs.counter_add("serving.mux_fallbacks")
            self._tally("serving.mux_fallbacks")
            obs.flight.record("serving.mux_fallback",
                              error=type(exc).__name__,
                              tenants=len(groups))
            return None
        # each caller reads ITS tenant's version label on the result
        for r, res in zip(requests, results):
            res.version = labels.get(r.tenant, res.version)
        return results

    # -- accounting ----------------------------------------------------------

    @staticmethod
    def _serving_mesh_width() -> int:
        """The data-axis width the transforms below this server dispatch
        over — 1 when ``FMT_SERVE_MESH`` pins serving to one device."""
        from flink_ml_tpu.common.fused import serve_mesh_enabled
        from flink_ml_tpu.parallel.mesh import (
            data_parallel_size,
            inference_mesh,
        )
        from flink_ml_tpu.utils.environment import MLEnvironmentFactory

        if not serve_mesh_enabled():
            return 1
        return data_parallel_size(
            inference_mesh(MLEnvironmentFactory.get_default().get_mesh())
        )

    @staticmethod
    def _single_batch_rows() -> int:
        """The environment's internal transform batch size — the row bound
        under which a coalesced dispatch is guaranteed to run as ONE batch
        on the dispatcher thread (0 = unbounded)."""
        from flink_ml_tpu.utils.environment import MLEnvironmentFactory

        return int(
            MLEnvironmentFactory.get_default().default_batch_size or 0
        )

    def stats(self) -> dict:
        """THIS server's own tallies (requests, batches, shed per reason,
        swaps, ...) plus latency quantiles over its own requests — the
        shutdown report's payload, readable live.  Per-server by
        construction: the process-global ``serving.*`` counters and the
        ``serving.request_latency_ms`` histogram aggregate across every
        server in the process, so reports read the local ledger instead."""
        from flink_ml_tpu.obs.registry import sample_quantile

        delta = {k: v for k, v in sorted(self._counts.items()) if v}
        samples = sorted(self._latencies)
        if samples:
            delta["latency_p50_ms"] = round(
                sample_quantile(samples, 0.50), 3)
            delta["latency_p99_ms"] = round(
                sample_quantile(samples, 0.99), 3)
            delta["latency_mean_ms"] = round(
                sum(samples) / len(samples), 3)
        delta["active_version"] = self.active_version
        return delta

    def _write_report(self) -> None:
        if not obs.enabled():
            return
        from flink_ml_tpu.obs.report import serving_report

        extra = self.stats()
        if self._drift is not None:
            # the drift section `obs --check` flags and the
            # `obs drift` CLI renders
            section = self._drift.report_section()
            if section is not None:
                extra["drift"] = section
        serving_report("ModelServer", extra=extra)
