"""Warm-artifact store: persisted AOT executables for cold-start resilience.

The persistent XLA compilation cache (``utils/compile_cache``) replays
*compiles* across processes, but a respawned replica still pays tracing,
lowering, and cache lookup per fused program — and on CPU the cache is
deliberately deferred.  This layer goes one level higher: after a fused
program compiles, the finished executable is serialized via JAX's AOT
path (``jax.experimental.serialize_executable``) and persisted next to
the model artifact; a kill -9 → respawn replica (or a second process in a
rolling deploy) deserializes the executable in milliseconds instead of
recompiling for seconds.

Entries are keyed by ``(kernel id, bucket rung, mesh shape, dtype)``
under a per-``fingerprint()`` directory — the fingerprint pins the jax /
jaxlib versions, backend, device kind and device count, so an upgraded
wheel or a different topology can never replay a stale executable.  Every
entry is written with the model-artifact sidecar-commit CRC scheme
(``serve/integrity``) using per-writer tmp names: N replicas warming the
same ladder concurrently coordinate by write-to-tmp + atomic rename,
last writer wins.  A torn write, corrupt entry, fingerprint mismatch, or
deserialization failure is *detected* and degrades to a plain recompile —
a reason-coded ``warmstart.degraded.<reason>`` counter plus a flight
event, never a wrong answer and never a crash.

Observability: ``warmstart.hits`` / ``misses`` / ``saves`` /
``save_failures`` / ``degraded`` (+ per-reason) / ``compile_skips`` /
``gc_evictions`` counters; fault points ``warmstart.load`` and
``warmstart.save`` (``fault/injection``) exercise both degrade paths in
chaos runs.  ``deploy()`` seals a ``manifest.json`` after pre-warming the
bucket ladder so an inheriting replica can see what is already warm.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import shutil
import threading
from typing import Optional

from flink_ml_tpu.utils import knobs

__all__ = [
    "ENTRY_FORMAT",
    "WarmstartStore",
    "active",
    "activate_for",
    "configure",
    "enabled",
    "fingerprint",
    "store_dir_for",
]

#: bump when the pickled entry layout changes — old entries degrade to
#: recompile instead of unpickling garbage
ENTRY_FORMAT = 1

_LOCK = threading.Lock()
_STORE: Optional["WarmstartStore"] = None
_FINGERPRINT: Optional[str] = None


def enabled() -> bool:
    """Whether the warm-artifact layer may activate at all."""
    return knobs.knob_bool("FMT_WARMSTART")


def fingerprint() -> str:
    """Digest pinning everything an executable is only valid under:
    jax/jaxlib versions, backend name, device kind, and device count.
    A mismatch on any axis means the entry must not be replayed."""
    global _FINGERPRINT
    if _FINGERPRINT is not None:
        return _FINGERPRINT
    import jax

    try:
        import jaxlib

        jaxlib_ver = getattr(jaxlib, "__version__", "")
    except ImportError:
        jaxlib_ver = ""
    try:
        devs = jax.devices()
        parts = (
            jax.__version__,
            jaxlib_ver,
            jax.default_backend(),
            devs[0].device_kind if devs else "",
            str(len(devs)),
        )
    except Exception:  # backend init failure: never break the caller
        parts = (jax.__version__, jaxlib_ver, "unknown", "", "0")
    _FINGERPRINT = hashlib.sha256("|".join(parts).encode()).hexdigest()[:12]
    return _FINGERPRINT


def store_dir_for(model_path: str) -> str:
    """The default warm-artifact directory for a model artifact: a
    ``warm_aot/`` directory right beside the model's own files, so the
    artifact and its executables travel (and get cleaned up) together."""
    return os.path.join(model_path, "warm_aot")


def configure(root: Optional[str]) -> Optional["WarmstartStore"]:
    """(De)activate the process-wide store.  ``None`` deactivates."""
    global _STORE
    with _LOCK:
        if root is None:
            _STORE = None
        elif _STORE is None or _STORE.root != root:
            _STORE = WarmstartStore(root)
        return _STORE


def activate_for(model_path: str) -> Optional["WarmstartStore"]:
    """Activate the store a deploy of ``model_path`` should use:
    ``FMT_WARM_DIR`` when set (a fleet-shared store), else ``warm_aot/``
    beside the artifact.  No-op (returns None) when the layer is off."""
    if not enabled():
        return None
    return configure(knobs.knob_str("FMT_WARM_DIR")
                     or store_dir_for(model_path))


def inherited_manifest_entries(model_path: str) -> int:
    """How many warm artifacts a replica booting from ``model_path`` will
    inherit (0 = a cold boot): the sealed manifest's entry count at the
    store that replica will activate.  Never raises — this is a status
    annotation, not a gate."""
    if not enabled():
        return 0
    try:
        root = knobs.knob_str("FMT_WARM_DIR") or store_dir_for(model_path)
        return len(WarmstartStore(root).manifest().get("entries", {}))
    except Exception:
        return 0


def active() -> Optional["WarmstartStore"]:
    """The currently configured store, or None (layer fully inert)."""
    global _STORE
    if not enabled():
        return None
    with _LOCK:
        if _STORE is None:
            env_dir = knobs.knob_str("FMT_WARM_DIR")
            if env_dir:
                # a spawned replica inherits the incumbent's store via env
                _STORE = WarmstartStore(env_dir)
        return _STORE


def _degrade(reason: str, key: str, path: str, err: object) -> None:
    """Reason-coded degrade: counter + flight event, caller recompiles."""
    from flink_ml_tpu import obs

    obs.counter_add("warmstart.degraded")
    obs.counter_add(f"warmstart.degraded.{reason}")
    obs.flight.record(
        "warmstart.degraded", reason=reason, key=key, path=path,
        error=str(err)[:200],
    )


class WarmstartStore:
    """One warm-artifact directory: ``<root>/<fingerprint>/<digest>.aot``
    entries with CRC commit sidecars, plus a sealed ``manifest.json``."""

    def __init__(self, root: str):
        self.root = root
        self.fingerprint = fingerprint()
        self._dir = os.path.join(root, self.fingerprint)
        self._lock = threading.Lock()
        self._manifest_keys: dict = {}

    # -- keys and paths -------------------------------------------------

    @staticmethod
    def entry_key(kernel: str, bucket: int, mesh: int, dtype: str,
                  extra: str = "") -> str:
        """The logical identity of one executable: which fused plan
        (``kernel`` — serve name + structural token), which ladder rung,
        which mesh width, which precision; ``extra`` carries the
        argument shape/treedef digest that pins feature dims."""
        return f"{kernel}|b{int(bucket)}|m{int(mesh)}|{dtype}|{extra}"

    def entry_path(self, key: str) -> str:
        digest = hashlib.sha256(key.encode()).hexdigest()[:20]
        return os.path.join(self._dir, digest + ".aot")

    # -- load / save ----------------------------------------------------

    def load(self, key: str):
        """The deserialized executable for ``key``, or None (miss or
        detected-degrade — the caller compiles as if the store were
        absent; this function never raises and never returns a wrong
        executable)."""
        from flink_ml_tpu import obs
        from flink_ml_tpu.fault import injection
        from flink_ml_tpu.serve.errors import ModelIntegrityError
        from flink_ml_tpu.serve.integrity import verify_commit_record

        path = self.entry_path(key)
        try:
            injection.maybe_fail("warmstart.load")
            if not os.path.exists(path):
                obs.counter_add("warmstart.misses")
                return None
            if not os.path.exists(path + ".commit.json"):
                # a torn write: the entry renamed in but the writer died
                # before committing the sidecar (or a last-writer race
                # left them out of step — the CRC path below covers that)
                raise _Torn(f"{path!r} has no commit record")
            verify_commit_record(path, required=True)
            with open(path, "rb") as f:
                blob = pickle.loads(f.read())
            if (not isinstance(blob, dict)
                    or blob.get("fmt") != ENTRY_FORMAT
                    or blob.get("key") != key):
                raise _Format(f"entry {path!r} has an unexpected layout")
            if blob.get("fingerprint") != self.fingerprint:
                raise _Fingerprint(
                    f"entry {path!r} was built under fingerprint "
                    f"{blob.get('fingerprint')!r}, this process is "
                    f"{self.fingerprint!r}"
                )
            from jax.experimental import serialize_executable as se

            loaded = se.deserialize_and_load(
                blob["payload"], blob["in_tree"], blob["out_tree"]
            )
        except injection.InjectedFault as e:
            _degrade("injected", key, path, e)
            return None
        except _Torn as e:
            _degrade("torn", key, path, e)
            return None
        except _Fingerprint as e:
            _degrade("fingerprint", key, path, e)
            return None
        except ModelIntegrityError as e:
            _degrade("corrupt", key, path, e)
            return None
        except _Format as e:
            _degrade("format", key, path, e)
            return None
        except Exception as e:  # unpickle/deserialize failure, I/O, ...
            _degrade("deserialize", key, path, e)
            return None
        obs.counter_add("warmstart.hits")
        return loaded

    def save(self, key: str, compiled) -> bool:
        """Persist ``compiled`` (a ``jax.stages.Compiled``) under ``key``.
        Returns False on any failure (counter + flight event) — a replica
        that cannot persist its executable still serves; the next process
        just compiles again."""
        from flink_ml_tpu import obs
        from flink_ml_tpu.fault import injection
        from flink_ml_tpu.serve.integrity import AtomicFile

        path = self.entry_path(key)
        try:
            injection.maybe_fail("warmstart.save")
            from jax.experimental import serialize_executable as se

            payload, in_tree, out_tree = se.serialize(compiled)
            blob = pickle.dumps({
                "fmt": ENTRY_FORMAT,
                "fingerprint": self.fingerprint,
                "key": key,
                "payload": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
            })
            with AtomicFile(path, unique_tmp=True) as f:
                f.write(blob)
        except injection.InjectedFault as e:
            obs.counter_add("warmstart.save_failures")
            obs.flight.record("warmstart.save_failed", key=key, path=path,
                              error=str(e)[:200])
            return False
        except Exception as e:
            obs.counter_add("warmstart.save_failures")
            obs.flight.record("warmstart.save_failed", key=key, path=path,
                              error=str(e)[:200])
            return False
        obs.counter_add("warmstart.saves")
        with self._lock:
            self._manifest_keys[key] = os.path.basename(path)
        self.gc()
        return True

    # -- manifest -------------------------------------------------------

    def manifest_path(self) -> str:
        return os.path.join(self._dir, "manifest.json")

    def seal_manifest(self) -> Optional[str]:
        """Atomically write the manifest of everything this process has
        warmed (deploy calls this after walking the ladder).  Entries
        observed on disk from other writers are folded in — the manifest
        describes the store, not one process's contribution."""
        from flink_ml_tpu.serve.integrity import atomic_json_dump

        try:
            entries = dict(self._read_manifest().get("entries", {}))
        except Exception:
            entries = {}
        with self._lock:
            entries.update(self._manifest_keys)
        try:
            os.makedirs(self._dir, exist_ok=True)
            mp = self.manifest_path()
            atomic_json_dump({
                "fingerprint": self.fingerprint,
                "format": ENTRY_FORMAT,
                "entries": entries,
            }, mp)
        except OSError:
            return None
        return mp

    def _read_manifest(self) -> dict:
        try:
            with open(self.manifest_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def manifest(self) -> dict:
        """The sealed manifest (empty dict when none is on disk)."""
        return self._read_manifest()

    # -- bounded-size GC ------------------------------------------------

    def gc(self, max_bytes: Optional[int] = None) -> int:
        """Bound the store's on-disk size.  Stale-fingerprint directories
        (an upgraded jax wheel left them unreadable forever) are evicted
        first, then oldest-mtime entries under the live fingerprint.
        Returns the number of evicted files/directories; never raises."""
        from flink_ml_tpu import obs

        if max_bytes is None:
            max_bytes = knobs.knob_int("FMT_WARM_CACHE_MB") * (1 << 20)
        evicted = 0
        try:
            total = 0
            stale_dirs, live_files = [], []
            for name in sorted(os.listdir(self.root)):
                p = os.path.join(self.root, name)
                if not os.path.isdir(p):
                    continue
                size = sum(
                    os.path.getsize(os.path.join(p, f))
                    for f in os.listdir(p)
                    if os.path.isfile(os.path.join(p, f))
                )
                total += size
                if name != self.fingerprint:
                    stale_dirs.append((p, size))
                else:
                    live_files = sorted(
                        (os.path.getmtime(os.path.join(p, f)),
                         os.path.join(p, f),
                         os.path.getsize(os.path.join(p, f)))
                        for f in os.listdir(p)
                        if f.endswith(".aot")
                    )
            for p, size in stale_dirs:
                if total <= max_bytes:
                    break
                shutil.rmtree(p, ignore_errors=True)
                total -= size
                evicted += 1
            for _, f, size in live_files:
                if total <= max_bytes:
                    break
                for victim in (f, f + ".commit.json"):
                    try:
                        os.remove(victim)
                    except OSError:
                        pass
                total -= size
                evicted += 1
        except OSError:
            return evicted
        if evicted:
            obs.counter_add("warmstart.gc_evictions", evicted)
        return evicted


class _Torn(RuntimeError):
    """Entry present without its commit sidecar — a torn write."""


class _Fingerprint(RuntimeError):
    """Entry built under a different jax/backend fingerprint."""


class _Format(RuntimeError):
    """Entry blob has an unexpected pickled layout."""
