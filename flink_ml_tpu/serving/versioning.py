"""Zero-downtime model versioning: load -> verify -> pre-warm -> swap.

A serving process must be able to take a new model without dropping a
request.  The sequence here makes a deploy boring:

1. **load + verify** — a path deploy goes through the standard loaders
   (``PipelineModel.load`` / ``load_stage``), which verify the
   length+CRC32 commit sidecars and parse-level checks from
   ``serve/integrity``: a truncated or bit-rotted artifact raises
   :class:`~flink_ml_tpu.serve.errors.ModelIntegrityError` here, on the
   deploy thread, never as garbage predictions on the hot path;
2. **pre-warm** — the new version transforms a small warmup batch OFF the
   hot path, so its mappers load model data onto the device and its fused
   plan compiles at a ladder bucket before any caller's rows touch it
   (the shared bucket ladder means the warmed program is the same one
   live batches will hit);
3. **atomic swap** — the active-version pointer flips under a lock; the
   dispatcher snapshots it once per batch, so in-flight batches finish on
   the version they started with and the next batch serves the new one.

A deploy that fails at ANY step (integrity, warmup compile, a broken
transform) leaves the previous version serving, counted in
``serving.deploy_failures``; a successful swap counts in
``serving.swaps``.

Rollback (ISSUE 14): the manager retains the last ``FMT_LIFECYCLE_
HISTORY`` deployed versions, and :meth:`VersionManager.rollback`
redeploys the previous one THROUGH :meth:`deploy` — a path-sourced
version is re-loaded and integrity-re-verified (the artifact may have
rotted since its first deploy), the warmup batch pre-warms it again,
``deploy_in_progress`` (and so ``/readyz``) degrades for the duration,
and only then does the pointer swap.  A bare pointer flip would skip
every one of those guarantees.  Counted in ``serving.rollbacks``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from flink_ml_tpu import obs
from flink_ml_tpu.serving import warmstart
from flink_ml_tpu.table.table import Table
from flink_ml_tpu.utils import knobs
from flink_ml_tpu.utils.compile_cache import bucket_batch_rows

__all__ = ["ModelVersion", "VersionManager"]


class ModelVersion:
    """One deployed model: the stage (anything with ``transform``), its
    version label, and where it came from."""

    def __init__(self, version: str, model, source_path: Optional[str] = None):
        self.version = str(version)
        self.model = model
        self.source_path = source_path
        self.deployed_at = time.time()

    def transform(self, table: Table) -> Table:
        out = self.model.transform(table)
        # Stage.transform returns a tuple of tables; serving is 1-in/1-out
        (result,) = out if isinstance(out, tuple) else (out,)
        return result


def _load_model(path: str):
    """Load a saved pipeline (or a single saved stage) with integrity
    verification — the standard loaders already check commit sidecars."""
    from flink_ml_tpu.api.core import load_stage
    from flink_ml_tpu.api.pipeline import PipelineModel

    if os.path.exists(os.path.join(path, "pipeline.json")):
        return PipelineModel.load(path)
    return load_stage(path)


class VersionManager:
    """The server's model registry: one active version, swap under lock."""

    #: version LABELS kept for history/statusz — a continuous-learning
    #: loop deploys forever, so even the label trail must stay bounded
    #: (the total-deploys gauge keeps the true count)
    HISTORY_LABELS = 1024

    def __init__(self, keep: Optional[int] = None):
        from collections import deque

        from flink_ml_tpu.utils import knobs

        self._lock = threading.Lock()
        self._active: Optional[ModelVersion] = None
        # version labels in deploy order, newest last (bounded window)
        self._history: "deque[str]" = deque(maxlen=self.HISTORY_LABELS)
        self._deploys = 0  # total successful deploys (gauge source)
        self._deploying = 0  # deploys currently loading/warming
        # retained ModelVersion objects, newest last (the rollback
        # targets); bounded so a long-lived continuous-learning loop
        # cannot pin every model it ever deployed in memory
        self._retained: List[ModelVersion] = []
        self._keep = max(
            2, keep if keep is not None
            else knobs.knob_int("FMT_LIFECYCLE_HISTORY")
        )

    @property
    def deploy_in_progress(self) -> bool:
        """Is a deploy mid-flight (loading, verifying, pre-warming)?
        The telemetry plane's ``/readyz`` degrades on this: a replica
        compiling a new version's plans is about to swap and should not
        take fresh traffic it would serve with cold-warmup latency."""
        with self._lock:
            return self._deploying > 0

    def active(self) -> ModelVersion:
        with self._lock:
            if self._active is None:
                raise RuntimeError("no model deployed")
            return self._active

    @property
    def active_version(self) -> Optional[str]:
        with self._lock:
            return self._active.version if self._active else None

    @property
    def history(self) -> List[str]:
        with self._lock:
            return list(self._history)

    def deploy(self, model_or_path, version: str,
               warmup: Optional[Table] = None) -> ModelVersion:
        """Load, verify, pre-warm, and atomically activate a version.

        ``model_or_path`` is a directory produced by ``save`` (integrity-
        verified at load) or an already-constructed model object.
        ``warmup`` is a small input-schema batch transformed BEFORE the
        swap so compiles and device model loads happen off the hot path;
        without one the first live batch pays them (logged as a counter,
        not an error).  Any failure leaves the previous version active.
        """
        with self._lock:
            self._deploying += 1
        try:
            model = (
                _load_model(model_or_path)
                if isinstance(model_or_path, (str, os.PathLike))
                else model_or_path
            )
            source = (
                str(model_or_path)
                if isinstance(model_or_path, (str, os.PathLike)) else None
            )
            candidate = ModelVersion(version, model, source)
            if source is not None:
                # path-deploys get the warm-artifact store beside the
                # artifact (or FMT_WARM_DIR): executables this warmup
                # compiles persist for respawned/rolling replicas
                warmstart.activate_for(source)
            if warmup is not None and warmup.num_rows() > 0:
                with obs.phase("serving.warmup"):
                    candidate.transform(warmup)
                    self._warm_ladder(candidate, warmup)
            else:
                obs.counter_add("serving.cold_deploys")
        except BaseException as exc:
            # the old version never stopped serving; the operator gets the
            # loader's diagnostic (ModelIntegrityError names the artifact)
            obs.counter_add("serving.deploy_failures")
            # a failed deploy is a black-box moment: the ring shows what
            # the system was doing when the bad artifact arrived
            obs.flight.record(
                "serving.deploy_failure", version=str(version),
                error=type(exc).__name__, detail=str(exc),
                source=str(model_or_path)
                if isinstance(model_or_path, (str, os.PathLike)) else None,
            )
            obs.flight.dump("deploy_failure")
            raise
        finally:
            with self._lock:
                self._deploying -= 1
        with self._lock:
            swapped = self._active is not None
            prev = self._history[-1] if self._history else None
            self._active = candidate
            self._history.append(candidate.version)
            self._deploys += 1
            deploys = self._deploys
            self._retained.append(candidate)
            while len(self._retained) > self._keep:
                self._retained.pop(0)
        obs.flight.record("serving.swap", version=candidate.version,
                          previous=prev, warmed=warmup is not None)
        if swapped:
            obs.counter_add("serving.swaps")
        obs.gauge_set("serving.versions_deployed", deploys)
        store = warmstart.active()
        if store is not None:
            # seal what this deploy warmed so an inheriting replica (kill
            # -9 respawn, rolling deploy) can see the ladder is covered
            store.seal_manifest()
        return candidate

    @staticmethod
    def _warm_ladder(candidate: ModelVersion, warmup: Table) -> None:
        """Walk the first ``FMT_WARM_LADDER_MAX`` bucket rungs with tiled
        warmup rows so the first odd-sized live request after the swap
        finds its executable already compiled (and, with a warm-artifact
        store active, already persisted).  Only runs when a store is
        active — an in-memory deploy keeps today's single-shape warmup.
        Per-rung failures degrade (counter + flight event): the live-
        sample warmup above already proved the model serves."""
        if warmstart.active() is None:
            return
        from flink_ml_tpu.utils.compile_cache import BATCH_BUCKET_LADDER

        max_rungs = knobs.knob_int("FMT_WARM_LADDER_MAX")
        if max_rungs <= 0:
            return
        n = warmup.num_rows()
        cols = {
            name: np.asarray(warmup.col(name))
            for name in warmup.schema.field_names
        }
        for rung in BATCH_BUCKET_LADDER[:max_rungs]:
            if rung == bucket_batch_rows(n):
                continue  # the live-sample warmup above covered this rung
            idx = np.arange(rung) % n
            try:
                tiled = Table.from_columns(
                    warmup.schema,
                    {name: v[idx] for name, v in cols.items()},
                )
                candidate.transform(tiled)
                obs.counter_add("serving.warm_ladder_rungs")
            except Exception as exc:
                obs.counter_add("serving.warm_ladder_failures")
                obs.flight.record(
                    "serving.warm_ladder_failure", rung=int(rung),
                    error=type(exc).__name__, detail=str(exc)[:200],
                )

    @property
    def previous_version(self) -> Optional[str]:
        """Label of the version a :meth:`rollback` would reactivate."""
        with self._lock:
            if len(self._retained) < 2:
                return None
            return self._retained[-2].version

    def rollback(self, warmup: Optional[Table] = None) -> ModelVersion:
        """Redeploy the previously retained version through the full swap
        contract.

        NOT a pointer flip: the previous version re-enters through
        :meth:`deploy` — a path-sourced version is re-loaded and
        integrity-re-verified from its artifact (which may have rotted on
        disk since it first served), ``warmup`` pre-warms its plans off
        the hot path, ``deploy_in_progress`` degrades ``/readyz`` for the
        duration, and the pointer swaps atomically.  On success the
        rolled-away-from version is dropped from the retained set (a
        second rollback steps FURTHER back, not onto the version just
        rejected); on failure the current version keeps serving and the
        retained set is untouched.
        """
        with self._lock:
            if len(self._retained) < 2:
                raise RuntimeError(
                    "no previous version retained to roll back to"
                )
            bad = self._retained[-1]
            prev = self._retained[-2]
        target = prev.source_path if prev.source_path else prev.model
        deployed = self.deploy(target, prev.version, warmup=warmup)
        with self._lock:
            # deploy() appended the fresh redeploy; drop the version we
            # rolled away from AND the stale copy of the target so the
            # retained tail reads [..., older, redeployed]
            self._retained = [
                v for v in self._retained if v is not bad and v is not prev
            ]
        obs.counter_add("serving.rollbacks")
        obs.flight.record("serving.rollback", version=deployed.version,
                          rolled_back=bad.version)
        return deployed

    def snapshot(self) -> Dict[str, Optional[str]]:
        with self._lock:
            return {
                "active": self._active.version if self._active else None,
                "history": list(self._history),
            }
