"""Table schema toolbox — TableUtil.java parity.

Temp names (getTempTableName:42-44), column index/type lookup with
case-insensitive matching (:54-69), type predicates (:147-182), assertion
helpers (:184-259), typed column selection (:261-371), and the markdown
pretty-printer (format*:372-424).
"""

from __future__ import annotations

import uuid
from typing import List, Sequence

from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table


def get_temp_table_name() -> str:
    return ("temp_" + uuid.uuid4().hex).lower()


def find_col_index(schema_or_cols, target_col: str) -> int:
    if isinstance(schema_or_cols, Schema):
        return schema_or_cols.find_col_index(target_col)
    if target_col is None:
        raise ValueError("targetCol is null!")
    for i, c in enumerate(schema_or_cols):
        if c.lower() == target_col.lower():
            return i
    return -1


def find_col_indices(schema_or_cols, target_cols: Sequence[str]) -> List[int]:
    return [find_col_index(schema_or_cols, c) for c in target_cols]


def find_col_type(schema: Schema, target_col: str):
    i = schema.find_col_index(target_col)
    return None if i < 0 else schema.field_type(i)


def is_supported_numeric_type(t: str) -> bool:
    return DataTypes.is_numeric(t)


def is_string(t: str) -> bool:
    return DataTypes.is_string(t)


def is_vector(t: str) -> bool:
    return DataTypes.is_vector(t)


def assert_selected_col_exist(schema_or_cols, *selected_cols: str) -> None:
    """TableUtil.assertSelectedColExist (:184-205)."""
    for c in selected_cols:
        if c is not None and find_col_index(schema_or_cols, c) < 0:
            raise ValueError(f" col is not exist {c}")


def assert_numerical_cols(schema: Schema, *cols: str) -> None:
    for c in cols:
        if c is None:
            continue
        t = find_col_type(schema, c)
        if t is None or not DataTypes.is_numeric(t):
            raise ValueError(f"col type must be number {c}")


def assert_string_cols(schema: Schema, *cols: str) -> None:
    for c in cols:
        if c is None:
            continue
        t = find_col_type(schema, c)
        if t is None or not DataTypes.is_string(t):
            raise ValueError(f"col type must be string {c}")


def assert_vector_cols(schema: Schema, *cols: str) -> None:
    for c in cols:
        if c is None:
            continue
        t = find_col_type(schema, c)
        if t is None or not DataTypes.is_vector(t):
            raise ValueError(f"col type must be vector {c}")


def get_numeric_cols(schema: Schema, exclude_cols: Sequence[str] = ()) -> List[str]:
    """Names of numeric columns minus exclusions (TableUtil.java:261-295)."""
    excl = {c.lower() for c in exclude_cols}
    return [
        n
        for n, t in zip(schema.field_names, schema.field_types)
        if DataTypes.is_numeric(t) and n.lower() not in excl
    ]


def get_string_cols(schema: Schema, exclude_cols: Sequence[str] = ()) -> List[str]:
    excl = {c.lower() for c in exclude_cols}
    return [
        n
        for n, t in zip(schema.field_names, schema.field_types)
        if DataTypes.is_string(t) and n.lower() not in excl
    ]


def get_categorical_cols(
    schema: Schema, feature_cols: Sequence[str], categorical_cols: Sequence[str] = None
) -> List[str]:
    """String-typed feature cols plus user-declared categorical cols
    (TableUtil.getCategoricalCols semantics: declared ones must be features)."""
    feats = list(feature_cols)
    declared = list(categorical_cols or [])
    for c in declared:
        if find_col_index(feats, c) < 0:
            raise ValueError(f"categoricalCols must be included in featureCols: {c}")
    out = []
    for c in feats:
        t = find_col_type(schema, c)
        if (t is not None and DataTypes.is_string(t)) or find_col_index(declared, c) >= 0:
            out.append(c)
    return out


def format_title(col_names: Sequence[str]) -> str:
    """Markdown header row (TableUtil.formatTitle:372-395)."""
    return (
        "|" + "|".join(col_names) + "|\n" + "|" + "|".join("---" for _ in col_names) + "|"
    )


def format_rows(rows: Sequence[Sequence]) -> str:
    return "\n".join(
        "|" + "|".join("null" if v is None else str(v) for v in row) + "|" for row in rows
    )


def format(table: Table, max_rows: int = 20) -> str:
    """Markdown rendering of a table prefix (TableUtil.format:414-424)."""
    rows = table.slice_rows(0, max_rows).to_rows()
    return format_title(table.schema.field_names) + "\n" + format_rows(rows)
