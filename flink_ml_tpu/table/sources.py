"""Table sources — bounded and unbounded.

Bounded sources materialize a full columnar Table (the analog of a Flink batch
source feeding `env.readCsvFile`, LinearRegression.java:91-102).  Unbounded
sources yield ``(event_time, row)`` pairs for the streaming driver, which
assigns windows the way IncrementalLearningSkeleton assigns event-time
tumbling windows (IncrementalLearningSkeleton.java:67-68).

CSV and LibSVM parsing route through the native C++ loader when it is built
(``flink_ml_tpu.native``), with a pure-Python fallback.
"""

from __future__ import annotations

import csv
from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from flink_ml_tpu.ops.codec import parse_vector
from flink_ml_tpu.ops.vector import SparseVector
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table


class BoundedSource:
    """A source whose ``read()`` returns the complete Table."""

    def read(self) -> Table:  # pragma: no cover - interface
        raise NotImplementedError

    def schema(self) -> Schema:  # pragma: no cover - interface
        raise NotImplementedError


class CollectionSource(BoundedSource):
    def __init__(self, rows: Sequence[Sequence], schema: Schema):
        self._schema = schema
        self._table = Table.from_rows(rows, schema)

    def read(self) -> Table:
        return self._table

    def schema(self) -> Schema:
        return self._schema


class CsvSource(BoundedSource):
    def __init__(
        self,
        path: str,
        schema: Schema,
        delimiter: str = ",",
        skip_header: bool = False,
    ):
        self.path = path
        self._schema = schema
        self.delimiter = delimiter
        self.skip_header = skip_header

    def schema(self) -> Schema:
        return self._schema

    def read(self) -> Table:
        names = self._schema.field_names
        types = self._schema.field_types
        cells = _read_csv_cells(self.path, self.delimiter, self.skip_header, len(names))
        cols = {n: [] for n in names}
        for raw in cells:
            for name, typ, cell in zip(names, types, raw):
                cols[name].append(_parse_cell(cell, typ))
        return Table.from_columns(self._schema, cols)


class LibSvmSource(BoundedSource):
    """LibSVM/SVMlight text: ``label idx:val idx:val ...`` with 1-based or
    0-based indices; produces (label DOUBLE, features SPARSE_VECTOR)."""

    def __init__(self, path: str, n_features: Optional[int] = None, zero_based: bool = False):
        self.path = path
        self.n_features = n_features
        self.zero_based = zero_based
        self._schema = Schema(["label", "features"], [DataTypes.DOUBLE, DataTypes.SPARSE_VECTOR])

    def schema(self) -> Schema:
        return self._schema

    def read(self) -> Table:
        native = _native_lib()
        if native is not None:
            labels, vecs = native.read_libsvm(self.path, self.n_features, self.zero_based)
            return Table.from_columns(self._schema, {"label": labels, "features": vecs})
        labels: List[float] = []
        vecs: List[SparseVector] = []
        max_idx = -1
        offset = 0 if self.zero_based else 1
        with open(self.path) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                parts = line.split()
                labels.append(float(parts[0]))
                idx = np.array([int(p.split(":", 1)[0]) - offset for p in parts[1:]], dtype=np.int64)
                val = np.array([float(p.split(":", 1)[1]) for p in parts[1:]])
                if idx.size:
                    max_idx = max(max_idx, int(idx.max()))
                vecs.append((idx, val))
        dim = self.n_features if self.n_features is not None else max_idx + 1
        sparse = [SparseVector(dim, i, v) for i, v in vecs]
        return Table.from_columns(self._schema, {"label": labels, "features": sparse})


class UnboundedSource:
    """A source of timestamped records, consumed by the streaming driver.

    ``stream()`` yields ``(event_time_ms, row_tuple)`` in event-time order per
    producer (the driver handles windowing + watermarks).
    """

    def stream(self) -> Iterator[Tuple[int, Tuple]]:  # pragma: no cover - interface
        raise NotImplementedError

    def schema(self) -> Schema:  # pragma: no cover - interface
        raise NotImplementedError


class GeneratorSource(UnboundedSource):
    """Wraps a generator function into an unbounded source.

    ``gen`` is called with no args and must yield ``(event_time_ms, row)``.
    A ``linear_timestamps`` helper covers the reference's LinearTimestamp
    assigner (IncrementalLearningSkeleton.java:144-158): record i gets time
    ``i * interval_ms``.
    """

    def __init__(self, gen: Callable[[], Iterator[Tuple[int, Tuple]]], schema: Schema):
        self._gen = gen
        self._schema = schema

    def stream(self) -> Iterator[Tuple[int, Tuple]]:
        return self._gen()

    def schema(self) -> Schema:
        return self._schema

    @staticmethod
    def linear_timestamps(rows: Sequence[Tuple], interval_ms: int, schema: Schema) -> "GeneratorSource":
        def gen():
            for i, row in enumerate(rows):
                yield i * interval_ms, tuple(row)

        return GeneratorSource(gen, schema)


# -- helpers -----------------------------------------------------------------


def _native_lib():
    try:
        from flink_ml_tpu import native

        return native if native.available() else None
    except Exception:
        return None


def _read_csv_cells(path: str, delimiter: str, skip_header: bool, arity: int):
    native = _native_lib()
    if native is not None:
        rows = native.read_csv(path, delimiter, skip_header, arity)
        if rows is not None:
            return rows
        # None: input not representable in the native transport (control
        # bytes inside quoted cells) — parse it with the pure reader below
    out = []
    with open(path, newline="") as f:
        reader = csv.reader(f, delimiter=delimiter)
        for i, row in enumerate(reader):
            if skip_header and i == 0:
                continue
            if not row:
                continue
            if len(row) != arity:
                raise ValueError(
                    f"{path}: row {i} has {len(row)} fields, schema expects {arity}"
                )
            out.append(row)
    return out


def _parse_cell(cell: str, typ: str):
    cell = cell.strip()
    if typ == DataTypes.STRING:
        return cell
    if cell == "" or cell.lower() == "null":
        return None if typ == DataTypes.STRING else _null_numeric(typ)
    if DataTypes.is_vector(typ):
        return parse_vector(cell)
    if typ == DataTypes.BOOLEAN:
        return cell.lower() in ("true", "1")
    if typ in (DataTypes.INT, DataTypes.LONG):
        return int(cell)
    return float(cell)


def _null_numeric(typ: str):
    return np.nan if typ in (DataTypes.DOUBLE, DataTypes.FLOAT) else 0
