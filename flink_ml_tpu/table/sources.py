"""Table sources — bounded and unbounded.

Bounded sources materialize a full columnar Table (the analog of a Flink batch
source feeding `env.readCsvFile`, LinearRegression.java:91-102).  Unbounded
sources yield ``(event_time, row)`` pairs for the streaming driver, which
assigns windows the way IncrementalLearningSkeleton assigns event-time
tumbling windows (IncrementalLearningSkeleton.java:67-68).

CSV and LibSVM parsing route through the native C++ loader when it is built
(``flink_ml_tpu.native``), with a pure-Python fallback.
"""

from __future__ import annotations

import contextlib
import csv
import os
import shutil
import tempfile
from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from flink_ml_tpu import obs
from flink_ml_tpu.ops.codec import parse_vector
from flink_ml_tpu.ops.vector import SparseVector
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table


class BoundedSource:
    """A source whose ``read()`` returns the complete Table.

    ``read_chunks(max_rows)`` is the out-of-core protocol: yield the same
    rows in the same order as ``read()``, as Tables of at most ``max_rows``
    rows each, without ever materializing the full dataset (file sources
    stream; the default slices a materialized read for in-memory sources).
    This is the analog of the reference's partitioned file read
    (LinearRegression.java:91-102 — `env.readCsvFile` produces a partitioned
    DataSet so no node holds the whole input).
    """

    def read(self) -> Table:  # pragma: no cover - interface
        raise NotImplementedError

    def schema(self) -> Schema:  # pragma: no cover - interface
        raise NotImplementedError

    def read_chunks(self, max_rows: int) -> Iterator[Table]:
        if max_rows <= 0:
            raise ValueError("max_rows must be positive")
        table = self.read()
        yield from table.iter_batches(max_rows)


class CollectionSource(BoundedSource):
    def __init__(self, rows: Sequence[Sequence], schema: Schema):
        self._schema = schema
        self._table = Table.from_rows(rows, schema)

    def read(self) -> Table:
        return self._table

    def schema(self) -> Schema:
        return self._schema


class CsvSource(BoundedSource):
    def __init__(
        self,
        path: str,
        schema: Schema,
        delimiter: str = ",",
        skip_header: bool = False,
    ):
        self.path = path
        self._schema = schema
        self.delimiter = delimiter
        self.skip_header = skip_header

    def schema(self) -> Schema:
        return self._schema

    def read(self) -> Table:
        names = self._schema.field_names
        types = self._schema.field_types
        cells = _read_csv_cells(self.path, self.delimiter, self.skip_header, len(names))
        cols = {n: [] for n in names}
        for raw in cells:
            for name, typ, cell in zip(names, types, raw):
                cols[name].append(_parse_cell(cell, typ))
        return Table.from_columns(self._schema, cols)

    def read_chunks(self, max_rows: int) -> Iterator[Table]:
        """Stream the file as Tables of at most ``max_rows`` rows — host
        residency is bounded by one chunk, never the whole file.

        All-float schemas stream through the native C++ doubles parser
        (one (rows, arity) float64 matrix per chunk, no per-cell Python);
        a non-numeric cell mid-stream falls back to the pure parser from
        that exact row.  Other schemas use the same pure-Python parser as
        ``read()``'s fallback (:func:`_iter_csv_rows`), so the streamed and
        materialized row streams cannot drift."""
        if max_rows <= 0:
            raise ValueError("max_rows must be positive")
        names = self._schema.field_names
        types = self._schema.field_types
        skip_rows = 0
        native = _native_lib()
        if native is not None and native.streaming_available() and all(
            t in (DataTypes.DOUBLE, DataTypes.FLOAT) for t in types
        ):
            try:
                for chunk in native.iter_csv_doubles(
                    self.path, self.delimiter, self.skip_header,
                    len(names), max_rows,
                ):
                    yield Table.from_columns(
                        self._schema,
                        {n: chunk[:, j] for j, n in enumerate(names)},
                    )
                return
            except native.NativeFallback as fb:
                skip_rows = fb.rows_delivered  # resume with the pure parser

        cols = {n: [] for n in names}
        count = 0
        for i, raw in enumerate(_iter_csv_rows(
            self.path, self.delimiter, self.skip_header, len(names)
        )):
            if i < skip_rows:
                continue
            for name, typ, cell in zip(names, types, raw):
                cols[name].append(_parse_cell(cell, typ))
            count += 1
            if count == max_rows:
                yield Table.from_columns(self._schema, cols)
                cols = {n: [] for n in names}
                count = 0
        if count:
            yield Table.from_columns(self._schema, cols)


class LibSvmSource(BoundedSource):
    """LibSVM/SVMlight text: ``label idx:val idx:val ...`` with 1-based or
    0-based indices; produces (label DOUBLE, features SPARSE_VECTOR)."""

    def __init__(self, path: str, n_features: Optional[int] = None, zero_based: bool = False):
        self.path = path
        self.n_features = n_features
        self.zero_based = zero_based
        self._schema = Schema(["label", "features"], [DataTypes.DOUBLE, DataTypes.SPARSE_VECTOR])

    def schema(self) -> Schema:
        return self._schema

    def read(self) -> Table:
        native = _native_lib()
        if native is not None:
            labels, vecs = native.read_libsvm(self.path, self.n_features, self.zero_based)
            return Table.from_columns(self._schema, {"label": labels, "features": vecs})
        labels: List[float] = []
        vecs: List = []
        max_idx = -1
        for label, idx, val in _iter_libsvm_rows(self.path, self.zero_based):
            labels.append(label)
            if idx.size:
                max_idx = max(max_idx, int(idx.max()))
            vecs.append((idx, val))
        dim = self.n_features if self.n_features is not None else max_idx + 1
        sparse = [SparseVector(dim, i, v) for i, v in vecs]
        return Table.from_columns(self._schema, {"label": labels, "features": sparse})

    def read_chunks(self, max_rows: int) -> Iterator[Table]:
        """Stream the file as chunks of at most ``max_rows`` rows, via the
        same parser as ``read()``'s pure-Python path (:func:`_iter_libsvm_rows`).

        Requires ``n_features``: the global dimension cannot be inferred
        without a full pass, and out-of-core training must know the model
        width up front (Criteo-style hashed feature spaces fix it anyway).
        """
        if max_rows <= 0:
            raise ValueError("max_rows must be positive")
        if self.n_features is None:
            raise ValueError(
                "chunked LibSVM reads require n_features (the global feature "
                "dimension cannot be inferred without materializing the file)"
            )
        dim = self.n_features
        native = _native_lib()
        if native is not None and native.streaming_available():
            from flink_ml_tpu.ops.batch import CsrRows

            for labels, indptr, indices, values in native.iter_libsvm_chunks(
                self.path, dim, self.zero_based, max_rows
            ):
                # the pure path's SparseVector constructor rejects indices
                # beyond the declared size at parse time; match it
                if indices.size and int(indices.max()) >= dim:
                    raise ValueError(
                        f"{self.path}: feature index {int(indices.max())} out "
                        f"of range for declared size {dim}"
                    )
                # CSR-backed column: zero per-row Python between the C++
                # parser and the vectorized minibatch packer
                rows = CsrRows(dim, indptr, indices, values)
                yield Table.from_columns(
                    self._schema, {"label": labels, "features": rows}
                )
            return
        labels: List[float] = []
        vecs: List[SparseVector] = []
        for label, idx, val in _iter_libsvm_rows(self.path, self.zero_based):
            labels.append(label)
            vecs.append(SparseVector(dim, idx, val))
            if len(labels) == max_rows:
                yield Table.from_columns(
                    self._schema, {"label": labels, "features": vecs}
                )
                labels, vecs = [], []
        if labels:
            yield Table.from_columns(
                self._schema, {"label": labels, "features": vecs}
            )


class ShardedSource(BoundedSource):
    """A bounded source over an ordered list of file shards.

    The analog of the reference reading a directory of part-files as one
    partitioned DataSet: ``read()`` concatenates all shards (only for
    datasets that fit), ``read_chunks`` streams shard after shard so host
    residency stays bounded by one chunk regardless of total size.

    ``ShardedSource.glob(pattern, make_source)`` builds one from a filename
    pattern, sorted for a deterministic row order.
    """

    def __init__(self, sources: Sequence[BoundedSource]):
        if not sources:
            raise ValueError("ShardedSource needs at least one shard")
        schemas = {
            (tuple(s.schema().field_names), tuple(s.schema().field_types))
            for s in sources
        }
        if len(schemas) > 1:
            raise ValueError(f"shard schemas differ: {schemas}")
        self.sources = list(sources)

    def schema(self) -> Schema:
        return self.sources[0].schema()

    def read(self) -> Table:
        return Table.concat([s.read() for s in self.sources])

    def read_chunks(self, max_rows: int) -> Iterator[Table]:
        for source in self.sources:
            yield from source.read_chunks(max_rows)

    @staticmethod
    def glob(pattern: str, make_source: Callable[[str], BoundedSource]) -> "ShardedSource":
        import glob as _glob

        paths = sorted(_glob.glob(pattern))
        if not paths:
            raise FileNotFoundError(f"no files match {pattern!r}")
        return ShardedSource([make_source(p) for p in paths])


class ChunkedTable:
    """A lazy, source-backed table: the out-of-core input to Estimator.fit.

    Wraps a :class:`BoundedSource` plus a chunk-row cap.  Training drivers
    iterate ``chunks()`` (each chunk a bounded materialized Table) and never
    hold more than ~two chunks at once (one being packed, one in flight to
    the device).  ``materialize()`` exists for small-data escape hatches and
    tests — production out-of-core paths must not call it.

    ``spill=True`` lets multi-epoch trainers write packed binary blocks to
    local disk on the first epoch and stream those on later epochs instead
    of re-parsing text (lib/out_of_core.BlockSpill) — one packed copy of
    the dataset on disk buys near-device-rate epochs after the first.
    """

    is_chunked = True

    def __init__(self, source: BoundedSource, chunk_rows: int, spill: bool = False):
        if chunk_rows <= 0:
            raise ValueError("chunk_rows must be positive")
        self.source = source
        self.chunk_rows = int(chunk_rows)
        self.spill = bool(spill)

    @property
    def schema(self) -> Schema:
        return self.source.schema()

    def chunks(self) -> Iterator[Table]:
        if not obs.enabled():
            return self.source.read_chunks(self.chunk_rows)
        return self._counted_chunks()

    def _counted_chunks(self) -> Iterator[Table]:
        for t in self.source.read_chunks(self.chunk_rows):
            obs.counter_add("source.chunks_parsed")
            obs.counter_add("source.rows_parsed", t.num_rows())
            yield t

    def materialize(self) -> Table:
        return self.source.read()

    def __repr__(self) -> str:
        return f"ChunkedTable({type(self.source).__name__}, chunk_rows={self.chunk_rows})"


class TransformedChunkedTable:
    """A ChunkedTable viewed through a Transformer — the lazy forward edge of
    a multi-stage out-of-core pipeline (``Pipeline.fit`` over chunked input).

    Each ``chunks()`` iteration replays the base source and maps the stage's
    ``transform1`` over every chunk, so host residency stays one chunk and
    multi-epoch consumers (trainer drivers) see a re-iterable stream.  With
    ``spill`` on, the *downstream trainer* spills post-transform packed
    blocks, so later epochs skip both the parse and the transform.
    """

    is_chunked = True

    def __init__(self, base, stage):
        self.base = base
        self.stage = stage
        self.chunk_rows = base.chunk_rows
        self.spill = getattr(base, "spill", False)
        self._schema: Optional[Schema] = None

    @property
    def schema(self) -> Schema:
        # the output schema is data-dependent (OutputColsHelper merge), so it
        # is probed by transforming one chunk — once per fit, cached
        if self._schema is None:
            chunks = self.chunks()
            try:
                first = next(iter(chunks), None)
            finally:
                chunks.close()  # release the base source's file handle now
            if first is None:
                raise ValueError("cannot infer schema of an empty chunked table")
            self._schema = first.schema
        return self._schema

    def chunks(self) -> Iterator[Table]:
        # one streamed-transform implementation: the stage's own
        # transform_chunks (the streamed-inference path) is the per-chunk loop
        return self.stage.transform_chunks(self.base)

    def materialize(self) -> Table:
        return self.stage.transform1(self.base.materialize())

    def __repr__(self) -> str:
        return f"TransformedChunkedTable({self.base!r} -> {type(self.stage).__name__})"


class UnboundedSource:
    """A source of timestamped records, consumed by the streaming driver.

    ``stream()`` yields ``(event_time_ms, row_tuple)`` in event-time order per
    producer (the driver handles windowing + watermarks).

    ``stream_chunks()`` is the optional COLUMNAR batch protocol: yield
    ``(ts_array, {col_name: column})`` blocks whose timestamps are
    non-decreasing within and across blocks (vector columns may be
    matrix-backed ``(n, d)`` arrays).  A source that implements it feeds the
    streaming driver's vectorized span path — zero per-record Python on
    ingest.  Return ``None`` (the default) when the source cannot guarantee
    time order; the driver then falls back to the per-record merge loop,
    which handles out-of-order arrival via watermarks/lateness.
    """

    def stream(self) -> Iterator[Tuple[int, Tuple]]:  # pragma: no cover - interface
        raise NotImplementedError

    def schema(self) -> Schema:  # pragma: no cover - interface
        raise NotImplementedError

    def stream_chunks(self, max_rows: int = 8192):
        return None


def columnize_rows(rows: Sequence[Tuple], schema: Schema) -> dict:
    """Row tuples -> columnar dict per the Table column conventions
    (dense-vector columns stack into one matrix when widths agree)."""
    from flink_ml_tpu.ops.vector import DenseVector

    names = schema.field_names
    is_vec = [DataTypes.is_vector(t) for t in schema.field_types]
    if not rows:
        return {n: [] for n in names}
    out = {}
    for n, vec, col in zip(names, is_vec, zip(*rows)):
        if not vec:
            out[n] = np.asarray(col)
            continue
        if col and all(type(v) is DenseVector for v in col):
            try:
                arr = np.asarray([v.values for v in col])
            except ValueError:  # ragged widths refuse to stack
                out[n] = list(col)
                continue
            if arr.ndim == 2:
                out[n] = arr
                continue
        out[n] = list(col)
    return out


def chunk_row_iter(ts, cols, schema: Schema) -> Iterator[Tuple[int, Tuple]]:
    """Decode one columnar chunk back to ``(ts, row_tuple)`` records — the
    per-record fallback view of the chunk protocol."""
    from flink_ml_tpu.ops.vector import DenseVector

    names = schema.field_names
    is_vec = [DataTypes.is_vector(t) for t in schema.field_types]
    mats = []
    for n, vec in zip(names, is_vec):
        col = cols[n]
        if vec and isinstance(col, np.ndarray) and col.ndim == 2:
            mats.append(("mat", col))
        else:
            mats.append(("col", col))
    for i in range(len(ts)):
        row = tuple(
            DenseVector(c[i]) if kind == "mat" else c[i] for kind, c in mats
        )
        yield int(ts[i]), row


class GeneratorSource(UnboundedSource):
    """Wraps a generator function into an unbounded source.

    ``gen`` is called with no args and must yield ``(event_time_ms, row)``.
    A ``linear_timestamps`` helper covers the reference's LinearTimestamp
    assigner (IncrementalLearningSkeleton.java:144-158): record i gets time
    ``i * interval_ms``.

    ``time_ordered=True`` declares the generator yields non-decreasing
    timestamps, unlocking ``stream_chunks`` (batched columnar ingest); the
    driver validates the claim and fails loudly on violation.  NOTE the
    latency trade-off: the chunk view buffers ``chunk_rows`` records before
    the driver sees them, so a LIVE source that trickles records should
    either set ``chunk_rows`` to roughly its expected rows-per-window or
    leave ``time_ordered=False`` (the per-record merge loop fires windows
    at record granularity).  Bounded replays (``linear_timestamps``) have
    no liveness, so buffering costs nothing.
    """

    def __init__(self, gen: Callable[[], Iterator[Tuple[int, Tuple]]], schema: Schema,
                 time_ordered: bool = False, chunk_rows: int = 8192):
        if chunk_rows <= 0:
            raise ValueError("chunk_rows must be positive")
        self._gen = gen
        self._schema = schema
        self._time_ordered = time_ordered
        self.chunk_rows = int(chunk_rows)

    def stream(self) -> Iterator[Tuple[int, Tuple]]:
        return self._gen()

    def schema(self) -> Schema:
        return self._schema

    def stream_chunks(self, max_rows: Optional[int] = None):
        if not self._time_ordered:
            return None
        step = int(max_rows) if max_rows else self.chunk_rows

        def chunks():
            ts_buf: List[int] = []
            rows_buf: List[Tuple] = []
            for ts, row in self._gen():
                ts_buf.append(ts)
                rows_buf.append(tuple(row))
                if len(ts_buf) >= step:
                    yield (np.asarray(ts_buf, np.int64),
                           columnize_rows(rows_buf, self._schema))
                    ts_buf, rows_buf = [], []
            if ts_buf:
                yield (np.asarray(ts_buf, np.int64),
                       columnize_rows(rows_buf, self._schema))

        return chunks()

    @staticmethod
    def linear_timestamps(rows: Sequence[Tuple], interval_ms: int, schema: Schema) -> "GeneratorSource":
        def gen():
            for i, row in enumerate(rows):
                yield i * interval_ms, tuple(row)

        return GeneratorSource(gen, schema, time_ordered=True)


class ColumnarUnboundedSource(UnboundedSource):
    """Time-ordered unbounded source backed by columnar arrays — the
    zero-per-record ingest path for the streaming driver's vectorized span
    processing.  ``columns`` maps schema field names to equal-length
    columns; dense-vector columns may be ``(n, d)`` matrices (zero-copy all
    the way into the window update's ``features_dense``)."""

    def __init__(self, timestamps, columns: dict, schema: Schema,
                 chunk_rows: int = 8192):
        ts = np.asarray(timestamps, np.int64)
        if ts.ndim != 1:
            raise ValueError("timestamps must be 1-D")
        if np.any(np.diff(ts) < 0):
            raise ValueError(
                "ColumnarUnboundedSource requires non-decreasing timestamps "
                "(use a per-record UnboundedSource for out-of-order streams)"
            )
        for name in schema.field_names:
            if name not in columns:
                raise ValueError(f"missing column {name!r}")
            if len(columns[name]) != len(ts):
                raise ValueError(
                    f"column {name!r} length {len(columns[name])} != "
                    f"{len(ts)} timestamps"
                )
        if chunk_rows <= 0:
            raise ValueError("chunk_rows must be positive")
        self._ts = ts
        self._cols = {n: columns[n] for n in schema.field_names}
        self._schema = schema
        self.chunk_rows = int(chunk_rows)

    def schema(self) -> Schema:
        return self._schema

    def stream_chunks(self, max_rows: Optional[int] = None):
        step = int(max_rows) if max_rows else self.chunk_rows

        def chunks():
            for a in range(0, len(self._ts), step):
                b = a + step
                yield (self._ts[a:b],
                       {n: c[a:b] for n, c in self._cols.items()})

        return chunks()

    def stream(self) -> Iterator[Tuple[int, Tuple]]:
        for ts, cols in self.stream_chunks():
            yield from chunk_row_iter(ts, cols, self._schema)


class QueueUnboundedSource(UnboundedSource):
    """Live queue-fed chunk source — the unbounded stream a PROCESS feeds
    while a consumer (the streaming driver, a continuous-learning loop)
    trains from it concurrently.

    ``feed(cols)`` enqueues one time-ordered chunk, auto-timestamped on a
    fixed ``interval_ms`` grid continuing from the previous feed
    (``feed_chunk(ts, cols)`` takes explicit timestamps); ``close()``
    ends the stream.  A consumer blocked between feeds parks on the
    queue — zero CPU — which is what makes this the label-stream shape
    for serving-adjacent training loops.  One-shot, single-consumer.
    """

    def __init__(self, schema: Schema, interval_ms: int = 50):
        import queue

        if interval_ms <= 0:
            raise ValueError("interval_ms must be positive")
        self._schema = schema
        self._interval_ms = int(interval_ms)
        self._q: "queue.Queue" = queue.Queue()
        self._next_ts = 0

    def feed(self, cols: dict) -> None:
        """Enqueue one chunk, timestamped after everything fed so far."""
        n = len(next(iter(cols.values())))
        ts = self._next_ts + np.arange(n, dtype=np.int64) * self._interval_ms
        self.feed_chunk(ts, cols)

    def feed_chunk(self, ts, cols: dict) -> None:
        """Enqueue one chunk with explicit (non-decreasing) timestamps."""
        ts = np.asarray(ts, np.int64)
        if len(ts) == 0:
            return
        if int(ts[0]) < self._next_ts or np.any(np.diff(ts) < 0):
            raise ValueError(
                "fed timestamps must be non-decreasing across feeds "
                "(the chunk protocol's time-order contract)"
            )
        self._next_ts = int(ts[-1]) + self._interval_ms
        self._q.put((ts, cols))

    def close(self) -> None:
        """End the stream: the consumer's iterator finishes after
        draining everything fed before the close."""
        self._q.put(None)

    def schema(self) -> Schema:
        return self._schema

    def stream_chunks(self, max_rows: Optional[int] = None):
        def chunks():
            while True:
                item = self._q.get()
                if item is None:
                    return
                ts, cols = item
                if max_rows is None:
                    yield ts, cols
                    continue
                step = int(max_rows)
                for a in range(0, len(ts), step):
                    b = a + step
                    yield ts[a:b], {k: v[a:b] for k, v in cols.items()}

        return chunks()

    def stream(self) -> Iterator[Tuple[int, Tuple]]:
        for ts, cols in self.stream_chunks():
            yield from chunk_row_iter(ts, cols, self._schema)


# -- helpers -----------------------------------------------------------------


def _native_lib():
    try:
        from flink_ml_tpu import native

        return native if native.available() else None
    except Exception:
        return None


def _iter_csv_rows(path: str, delimiter: str, skip_header: bool, arity: int):
    """The one pure-Python CSV row stream: ``read()`` (native-loader
    fallback) and ``read_chunks`` both consume it, so the materialized and
    streamed row sequences are the same parser's output by construction."""
    with open(path, newline="") as f:
        reader = csv.reader(f, delimiter=delimiter)
        for i, row in enumerate(reader):
            if skip_header and i == 0:
                continue
            if not row:
                continue
            if len(row) != arity:
                raise ValueError(
                    f"{path}: row {i} has {len(row)} fields, schema expects {arity}"
                )
            yield row


def _iter_libsvm_rows(path: str, zero_based: bool):
    """The one pure-Python LibSVM row stream (``label idx:val ...`` with
    ``#`` comments): yields ``(label, indices, values)``; shared by
    ``read()``'s fallback and ``read_chunks``."""
    offset = 0 if zero_based else 1
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            idx = np.array(
                [int(p.split(":", 1)[0]) - offset for p in parts[1:]],
                dtype=np.int64,
            )
            val = np.array([float(p.split(":", 1)[1]) for p in parts[1:]])
            yield float(parts[0]), idx, val


def _read_csv_cells(path: str, delimiter: str, skip_header: bool, arity: int):
    native = _native_lib()
    if native is not None:
        rows = native.read_csv(path, delimiter, skip_header, arity)
        if rows is not None:
            return rows
        # None: input not representable in the native transport (control
        # bytes inside quoted cells) — parse it with the pure reader below
    return list(_iter_csv_rows(path, delimiter, skip_header, arity))


def _parse_cell(cell: str, typ: str):
    cell = cell.strip()
    if typ == DataTypes.STRING:
        return cell
    if cell == "" or cell.lower() == "null":
        return None if typ == DataTypes.STRING else _null_numeric(typ)
    if DataTypes.is_vector(typ):
        return parse_vector(cell)
    if typ == DataTypes.BOOLEAN:
        return cell.lower() in ("true", "1")
    if typ in (DataTypes.INT, DataTypes.LONG):
        return int(cell)
    return float(cell)


def _null_numeric(typ: str):
    return np.nan if typ in (DataTypes.DOUBLE, DataTypes.FLOAT) else 0

def _atomic_np_save(path: str, arr) -> None:
    """Raw .npy write with tmp-file + rename atomicity (shared by the
    packed BlockSpill and the parsed ChunkSpillCache)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:  # file handle: np.save can't rename it
        np.save(f, arr)
    os.replace(tmp, path)


class ChunkSpillCache:
    """Binary replay cache of PARSED source chunks — one text parse total.

    Fit paths with a layout pre-pass (the hot/cold frequency scan, the
    multi-process shape/count scans, the KMeans reservoir init) used to
    read the text source twice before the packed :class:`BlockSpill` took
    over: once to scan, once to pack.  Out-of-core means every pass is a
    full disk/network read — never pay two.  Wrapping the chunked table in
    this cache records each parsed chunk's columns as raw ``.npy`` during
    the FIRST full iteration (the scan), then replays memory-mapped binary
    for every later iteration — the pack pass reads pages, not text.

    Cacheable columns: numeric/bool/string ndarrays, matrix-backed
    dense-vector columns, and CSR-backed sparse columns (``CsrRows``).  A
    chunk with any other column shape (per-row ``SparseVector`` objects,
    ragged widths) disables the cache for the whole stream — consumers
    just re-parse, correctness unaffected.  A partial iteration (sampled
    ``estimate_nnz_pad``, schema peeks) leaves the cache incomplete and is
    re-recorded by the next full pass.

    Disk transiently holds this raw copy alongside the packed BlockSpill;
    both live in per-fit temporary directories (:func:`chunk_cache`), and
    nested caches are suppressed so at most ONE raw copy exists per fit.
    """

    is_chunked = True

    def __init__(self, base, directory: str):
        import os

        self.base = base
        self.chunk_rows = base.chunk_rows
        self.spill = getattr(base, "spill", False)
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._complete = False
        self._disabled = False
        self._chunks: list = []  # per chunk: (schema, [(name, descriptor)])

    @property
    def schema(self):
        return self.base.schema

    def materialize(self):
        return self.base.materialize()

    def chunks(self):
        if self._complete:
            return self._replay()
        if self._disabled:
            return self.base.chunks()
        return self._record()

    def _path(self, i: int, j: int) -> str:
        import os

        return os.path.join(self.directory, f"chunk-{i:06d}-{j:02d}.npy")

    def _record(self):
        # descriptors accumulate LOCALLY and publish to self._chunks only
        # when the base iterator is exhausted: an abandoned partial
        # recording generator (sampled pre-scans, schema peeks) that is
        # later resumed — or a second interleaved chunks() iteration —
        # must never splice its pass's metadata into another pass's replay
        # sequence
        chunks: list = []
        base_iter = self.base.chunks()
        i = 0
        for t in base_iter:
            with obs.phase("spill.record_chunk"):
                descs = self._try_save(t, i)
            if descs is None:
                # uncacheable column shape: disable and keep serving the
                # rest of this pass straight from the same base iterator
                # (chunks already consumed cannot be re-read mid-pass)
                self._disabled = True
                obs.counter_add("spill.cache_disabled")
                yield t
                yield from base_iter
                return
            chunks.append((t.schema, descs))
            obs.counter_add("spill.chunks_recorded")
            i += 1
            yield t
        self._chunks = chunks
        self._complete = True

    def _try_save(self, t: Table, i: int):
        """Per-chunk column descriptors, or None when any column shape is
        uncacheable."""
        from flink_ml_tpu.ops.batch import CsrRows

        descs = []
        j = 0
        for name in t.schema.field_names:
            col = t.col(name)
            if isinstance(col, CsrRows):
                paths = []
                for arr in (col.indptr, col.indices, col.values):
                    p = self._path(i, j)
                    _atomic_np_save(p, np.ascontiguousarray(arr))
                    paths.append(p)
                    j += 1
                descs.append((name, ("csr", col.dim, paths)))
            elif isinstance(col, np.ndarray) and col.dtype != object:
                p = self._path(i, j)
                _atomic_np_save(p, np.ascontiguousarray(col))
                j += 1
                descs.append((name, ("arr", p)))
            elif (
                isinstance(col, np.ndarray) and col.dtype == object
                and len(col) and all(isinstance(x, str) for x in col)
            ):
                # string columns (categorical CSV) promote to fixed-width
                # unicode — npy-serializable, replayed as '<U' arrays that
                # downstream stringify/indexing consume unchanged
                p = self._path(i, j)
                _atomic_np_save(p, np.asarray(col, dtype=str))
                j += 1
                descs.append((name, ("arr", p)))
            else:
                return None
        return descs

    def _replay(self):
        from flink_ml_tpu.ops.batch import CsrRows

        for schema, descs in self._chunks:
            with obs.phase("spill.replay_chunk"):
                cols = {}
                for name, d in descs:
                    if d[0] == "csr":
                        _, dim, paths = d
                        indptr, indices, values = (
                            np.load(p, mmap_mode="r") for p in paths
                        )
                        cols[name] = CsrRows(dim, indptr, indices, values)
                    else:
                        cols[name] = np.load(d[1], mmap_mode="r")
                table = Table.from_columns(schema, cols)
            obs.counter_add("spill.chunks_replayed")
            yield table


def _has_cache_below(table) -> bool:
    """True when the table's base chain already bottoms out in a
    ChunkSpillCache — nesting a second cache would hold another full
    binary copy of the dataset on disk for a transform-replay saving that
    rarely justifies it (the text parse is already amortized)."""
    seen: set = set()
    t = table
    while t is not None and id(t) not in seen:
        if isinstance(t, ChunkSpillCache):
            return True
        seen.add(id(t))
        t = getattr(t, "base", None)
    return False


@contextlib.contextmanager
def chunk_cache(table, enabled: bool = True):
    """Scope a :class:`ChunkSpillCache` over a chunked table for one fit;
    a no-op when ``enabled`` is false, the table is not chunked (or not
    spill-enabled — single-pass fits have nothing to amortize), or a cache
    already exists below it (:func:`_has_cache_below`)."""
    import shutil
    import tempfile

    if (
        not enabled
        or not getattr(table, "is_chunked", False)
        or not getattr(table, "spill", False)
        or _has_cache_below(table)
    ):
        yield table
        return
    directory = tempfile.mkdtemp(prefix="fmt_chunkcache_")
    try:
        yield ChunkSpillCache(table, directory)
    finally:
        shutil.rmtree(directory, ignore_errors=True)
