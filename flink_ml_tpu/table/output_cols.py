"""OutputColsHelper — merge operator output into the input table.

Rule-for-rule parity with OutputColsHelper.java:32-52:
  * reserved cols default to *all* input cols;
  * reserved cols come ahead of the operator's output cols in the result;
  * an output col whose name collides with an input col overrides it *in place*
    (takes the input col's position, with the output type/values);
  * reserved cols keep their input order.

The reference applies these per-row (getResultRow:179); here the merge is one
columnar operation over whole batches.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from flink_ml_tpu.table.schema import Schema
from flink_ml_tpu.table.table import Table


class OutputColsHelper:
    def __init__(
        self,
        input_schema: Schema,
        output_col_names: Sequence[str],
        output_col_types: Sequence[str],
        reserved_col_names: Optional[Sequence[str]] = None,
    ):
        if isinstance(output_col_names, str):
            raise TypeError("output_col_names must be a sequence of names")
        if len(output_col_names) != len(output_col_types):
            raise ValueError("output names/types must align")
        self.input_schema = input_schema
        self.output_col_names = list(output_col_names)
        self.output_col_types = list(output_col_types)

        in_names = input_schema.field_names
        in_types = input_schema.field_types
        # reserved matching is case-insensitive like all other column lookup
        reserved = {
            n.lower()
            for n in (in_names if reserved_col_names is None else reserved_col_names)
        }

        # name collision is case-insensitive, matching Schema/Table lookup —
        # an output col spelled 'Sum' overrides an input col 'sum' in place
        # rather than silently shadowing behind it
        out_lower = {}
        for j, n in enumerate(self.output_col_names):
            if n.lower() in out_lower:
                raise ValueError(
                    f"output col names collide case-insensitively: {n!r}"
                )
            out_lower[n.lower()] = j

        # walk input order assigning result slots (OutputColsHelper.java:118-135)
        result_names: List[str] = []
        result_types: List[str] = []
        self._reserved_input_cols: List[str] = []
        placed = set()
        for i, name in enumerate(in_names):
            j = out_lower.get(name.lower())
            if j is not None:
                placed.add(j)
                result_names.append(self.output_col_names[j])
                result_types.append(self.output_col_types[j])
                continue
            if name.lower() in reserved:
                self._reserved_input_cols.append(name)
                result_names.append(name)
                result_types.append(in_types[i])
        for j, name in enumerate(self.output_col_names):
            if j not in placed:
                result_names.append(name)
                result_types.append(self.output_col_types[j])
        self._result_schema = Schema(result_names, result_types)

    def get_reserved_cols(self) -> List[str]:
        return list(self._reserved_input_cols)

    def get_result_schema(self) -> Schema:
        return self._result_schema

    def get_result_table(self, input_table: Table, output_cols) -> Table:
        """Columnar analog of getResultRow: merge whole output columns in."""
        missing = [n for n in self.output_col_names if n not in output_cols]
        if missing:
            raise ValueError(f"operator did not produce output cols {missing}")
        data = {}
        for name in self._result_schema.field_names:
            if name in self.output_col_names:
                data[name] = output_cols[name]
            else:
                data[name] = input_table.col(name)
        return Table.from_columns(self._result_schema, data)
