"""Cross-fit device slab pool — the warm-fit placement cache (ISSUE 2).

The round-5 bench showed a full ``fit()`` spends ~100 ms on host-side pack
plus host->device placement that is repeated even when the SAME table is
fit again (hyperparameter sweeps, warm restarts, CV folds) — the fused
device program itself runs in under a millisecond per epoch.  The
reference design this repo reproduces (PAPER.md §4: broadcast-model bulk
iteration) materializes the training set once and re-iterates; per-fit
re-placement is overhead the architecture never intended.

This module generalizes the per-``Table``-instance ``cached_pack`` memo
into a first-class, PROCESS-WIDE pool of placed training batches:

  * **keying** — ``(table content identity, mesh, layout/pack variant)``.
    Content identity is buffer identity: a token of each column's backing
    buffer (address, shape, strides, dtype) plus a weakref guard, so two
    Table objects sharing column buffers (selects, re-wraps — the immutable
    Table contract) hit the same slab, and a token can never outlive the
    buffer it describes (dead weakref => the entry silently drops);
  * **budget** — entries are LRU-evicted once the pool exceeds
    ``FMT_SLAB_POOL_BUDGET_MB`` (default 4096).  Multi-process the budget
    is agreed once via :func:`~flink_ml_tpu.parallel.mesh.agree_max` (the
    same divergence class PR 1 fixed for ``hotSlabMode``: per-process env
    drift must not produce per-process cache behavior);
  * **multi-process hit agreement** — builders may dispatch collective
    device programs (the hot-slab densify); a process that hit the pool
    while a peer missed would skip its half of the collective and hang the
    mesh.  Under ``jax.process_count() > 1`` every lookup agrees hit/miss
    via ``agree_max`` — any miss forces a (re)build everywhere (miss wins
    ties, mirroring the hotSlabMode rule);
  * **refcounting** — drivers pin a checked-out slab for the duration of
    the device call (:meth:`SlabPool.pinned`); eviction skips pinned
    entries and never calls ``.delete()`` — it only drops the pool's
    reference, so a buffer still referenced by an in-flight program (or a
    donating ``donate_argnums=(0,)`` dispatch) can never be freed under it;
  * **telemetry** — hits/misses/evictions/bytes-placed land in the obs
    registry (``slab_pool.*``), so every fit RunReport carries its own
    pool delta and the warm-path CI gate can assert the hit branch.

Placement itself is double-buffered and chunked
(:func:`~flink_ml_tpu.parallel.mesh.shard_batch_prefetched`): host staging
of slice N+1 overlaps the async H2D DMA of slice N, the ``_prefetch``
idiom from ``lib/out_of_core.py``.

``FMT_SLAB_POOL=0`` disables pooling entirely (every lookup builds) — the
bench uses it for the uncached-parity comparison.
"""

from __future__ import annotations

import contextlib
import threading
import warnings
import weakref
from collections import OrderedDict
from typing import Callable, Optional, Tuple

import numpy as np

from flink_ml_tpu import obs
from flink_ml_tpu.utils import knobs

__all__ = [
    "SlabPool",
    "array_token",
    "enabled",
    "evict_for_pressure",
    "place_batch",
    "pool",
    "pool_active",
    "pytree_nbytes",
    "reset_pool",
    "table_token",
]


def enabled() -> bool:
    """Pooling on?  ``FMT_SLAB_POOL=0`` turns every lookup into a build."""
    return knobs.knob_bool("FMT_SLAB_POOL")


#: cross-process agreement on the on/off switch (None = unresolved).  The
#: master switch must not drift per process any more than the budget may:
#: a process with FMT_SLAB_POOL=0 would skip the hit/miss agreement its
#: peers block in — a hang.  Disabled wins ties (any process off => all
#: off), resolved lazily at the first AGREED lookup so the collective fires
#: at an aligned point.
_AGREED_ENABLED: Optional[bool] = None


def _agreed_enabled() -> bool:
    global _AGREED_ENABLED
    if _AGREED_ENABLED is None:
        from flink_ml_tpu.parallel.mesh import agree_max

        (any_disabled,) = agree_max(int(not enabled()))
        _AGREED_ENABLED = not any_disabled
    return _AGREED_ENABLED


# -- content identity tokens --------------------------------------------------


#: per-window sample size of the mutation canary; arrays at or under
#: 4 windows hash in full
_CANARY_WINDOW = 16 << 10


def _canary(a: np.ndarray) -> int:
    """Cheap content checksum folded into the identity token: CRC of the
    head/middle/tail byte windows (whole buffer when small).  Tables are
    immutable BY CONTRACT, but a zero-copy column shares the caller's
    buffer — someone normalizing it in place and re-wrapping a fresh Table
    would otherwise HIT on pure buffer identity and silently train on the
    pre-mutation slab.  The canary turns any bulk in-place mutation into a
    key change (stale entries then age out through the dead/budget
    sweeps); byte-surgical edits inside unsampled windows remain the
    caller's contract violation."""
    import zlib

    try:
        if a.ndim == 0:
            return zlib.crc32(a.tobytes())
        if not a.flags.c_contiguous:
            # strided view: hash a bounded head-row copy, never O(n) bytes
            a = np.ascontiguousarray(a[: min(a.shape[0], 4096)])
        flat = a.reshape(-1).view(np.uint8)
    except (ValueError, TypeError):  # object dtype etc: identity only
        return 0
    n = flat.size
    if n <= 4 * _CANARY_WINDOW:
        return zlib.crc32(flat.tobytes())
    w = _CANARY_WINDOW
    mid = (n // 2) - w // 2
    sample = np.concatenate(
        [flat[:w], flat[mid : mid + w], flat[n - w :]]
    )
    return zlib.crc32(sample.tobytes())


def array_token(a, refs: list):
    """Identity token for one host column/array + weakref liveness guards.

    Buffer identity stands in for content identity: Tables are immutable
    values sharing column buffers across transformations, so (owner id,
    data address, shape, strides, dtype) pins exact content while the
    owner lives.  ``refs`` receives a weakref per owning buffer — a pool
    entry whose guards die is discarded on lookup, so a recycled id/address
    can never resurrect a stale slab.  A sampled content canary
    (:func:`_canary`) guards the remaining hole — in-place mutation of a
    shared buffer.  Equal content in DIFFERENT buffers misses (rebuild) —
    safe, just cold."""
    from flink_ml_tpu.ops.batch import CsrRows

    if isinstance(a, CsrRows):
        return ("csr", a.dim,
                array_token(a.indptr, refs),
                array_token(a.indices, refs),
                array_token(a.values, refs))
    if isinstance(a, np.ndarray):
        base = a
        while isinstance(getattr(base, "base", None), np.ndarray):
            base = base.base
        try:
            refs.append(weakref.ref(base))
        except TypeError:  # exotic buffer owner: identity only, no guard
            pass
        data = a.__array_interface__.get("data") or (0, True)
        canary = _canary(a) if a.dtype != object else 0
        return ("nd", id(base), int(data[0]), a.shape, str(a.dtype),
                a.strides, canary)
    try:
        refs.append(weakref.ref(a))
    except TypeError:
        pass
    try:
        size = len(a)
    except TypeError:
        size = -1
    return ("obj", id(a), size)


def table_token(table, cols=None) -> Tuple[tuple, list]:
    """Content-identity token for a Table: one column token per field, in
    schema order.  Returns ``(token, weakref guards)``.

    ``cols`` restricts the token to the columns a layout actually reads
    (feature + label): a ``select()``/``with_column()`` re-wrap sharing
    those buffers then still HITS, and unused columns of wide tables never
    pay the canary pass.  Defaults to every schema field."""
    refs: list = []
    if cols is None:
        names = table.schema.field_names
    else:
        names = [table.schema.resolve(c) for c in cols if c is not None]
    token = tuple(
        (name, array_token(table.col(name), refs)) for name in names
    )
    return token, refs


def pytree_nbytes(value) -> int:
    """Total backing bytes of a pytree of host/device arrays."""
    import jax

    return sum(
        int(getattr(leaf, "nbytes", 0) or 0)
        for leaf in jax.tree_util.tree_leaves(value)
    )


# -- the pool -----------------------------------------------------------------


class _Entry:
    __slots__ = ("value", "nbytes", "refs", "pins")

    def __init__(self, value, nbytes: int, refs: list):
        self.value = value
        self.nbytes = int(nbytes)
        self.refs = list(refs)
        self.pins = 0

    def alive(self) -> bool:
        return all(r() is not None for r in self.refs)


class SlabPool:
    """Process-wide budgeted LRU cache of placed training batches."""

    def __init__(self, budget_bytes: Optional[int] = None):
        self._lock = threading.RLock()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._by_value: dict = {}  # id(entry.value) -> key (pin lookup)
        self._budget = budget_bytes
        #: keys whose source buffers were garbage-collected — appended by
        #: weakref DEATH CALLBACKS (no locking: list.append is atomic under
        #: the GIL, and a GC callback must never take the pool lock), and
        #: drained under the lock at the next pool access.  Without this, a
        #: dropped table's device slab would persist until the next insert
        #: — a lifetime regression vs the per-Table cached_pack it replaces
        #: (whose slab died with the table).
        self._dead_keys: list = []
        #: entries displaced from the table while PINNED (replaced under a
        #: running device call): the pool must keep referencing them until
        #: the pin releases — the documented pin invariant — then the next
        #: drain lets them go
        self._displaced: list = []
        #: eviction listeners (ISSUE 20: the tenant registry's reason-coded
        #: fault-out events).  Drops queue ``(key, reason, nbytes)`` under
        #: the lock; listeners fire OUTSIDE it — one may re-enter the pool
        #: to fault an entry back in
        self._listeners: list = []
        self._events: list = []
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # -- budget ---------------------------------------------------------------

    def budget_bytes(self, collective_ok: bool = True) -> int:
        """``FMT_SLAB_POOL_BUDGET_MB`` (default 4096), agreed ONCE across
        processes via ``agree_max`` — divergent per-process budgets would
        evict (and later re-place, possibly with collectives) on different
        fits, the hotSlabMode divergence class PR 1 fixed.

        ``collective_ok=False`` (the ``agreed=False`` insert path —
        inference, contractually collective-free) must not fire the
        agreement: if unresolved, the LOCAL env value is used uncached and
        the agreement happens at the next training-path access."""
        if self._budget is None:
            import jax

            mb = knobs.knob_int("FMT_SLAB_POOL_BUDGET_MB")
            if jax.process_count() > 1 and not collective_ok:
                return mb << 20  # local, uncached: no collective here
            from flink_ml_tpu.parallel.mesh import agree_max

            (mb,) = agree_max(mb)
            self._budget = mb << 20
        return self._budget

    # -- core -----------------------------------------------------------------

    def counters(self) -> Tuple[int, int]:
        """(hits, misses) monotonic totals — per-fit deltas come from
        subtracting a snapshot taken at fit start."""
        with self._lock:
            return self.hits, self.misses

    def _guarded_refs(self, key, refs) -> list:
        """Re-wrap the token pass's weakrefs with death callbacks that
        queue ``key`` for reaping — the callback only appends (atomic, no
        lock), the drop happens at the next locked pool access."""
        dead = self._dead_keys
        out = []
        for r in refs:
            obj = r() if isinstance(r, weakref.ref) else None
            if obj is None:
                out.append(r)  # already dead: entry invalid from birth
                continue
            out.append(
                weakref.ref(obj, lambda _r, _k=key: dead.append(_k))
            )
        return out

    def _drain_dead_locked(self) -> None:
        """Reap entries whose source buffers were GC'd (under the lock).

        A dead entry that is still PINNED cannot drop yet (the pin
        invariant) — its key goes BACK on the queue so the drain after
        the pin releases reclaims it.  The old code popped and discarded
        the key, so a buffer that died mid-pin left a permanently
        unreapable entry whose bytes squatted the budget alongside its
        replacement's — the double-count that evicted innocent entries
        under a tight ``FMT_SLAB_POOL_BUDGET_MB``."""
        retry: list = []
        while self._dead_keys:
            key = self._dead_keys.pop()
            entry = self._entries.get(key)
            if entry is None or entry.alive():
                continue  # already dropped, or the key was re-inserted
            if entry.pins > 0:
                retry.append(key)
                continue
            self._drop_locked(key, entry, reason="dead")
        if retry:
            self._dead_keys.extend(retry)
        if self._displaced:
            self._displaced = [e for e in self._displaced if e.pins > 0]

    def _lookup_locked(self, key) -> Optional[_Entry]:
        """Hit path under the lock: validates liveness, refreshes LRU."""
        self._drain_dead_locked()
        entry = self._entries.get(key)
        if entry is None:
            return None
        if not entry.alive():
            # dead-but-pinned: a miss, but the pool's reference stays until
            # the in-flight device call releases the pin (the pin invariant
            # _drain_dead_locked/_evict_over_budget_locked also honor)
            if entry.pins == 0:
                self._drop_locked(key, entry, reason="dead")
            return None
        self._entries.move_to_end(key)
        return entry

    def _drop_locked(self, key, entry: _Entry,
                     reason: Optional[str] = None) -> None:
        self._entries.pop(key, None)
        self._by_value.pop(id(entry.value), None)
        self.bytes -= entry.nbytes
        if reason is not None and self._listeners:
            self._events.append((key, reason, entry.nbytes))

    # -- eviction listeners ---------------------------------------------------

    def add_eviction_listener(self, fn: Callable) -> None:
        """Register ``fn(key, reason, nbytes)`` to observe entry drops
        (reasons: ``dead`` / ``budget`` / ``pressure`` / ``replaced`` /
        ``explicit``).  Listeners fire outside the pool lock."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_eviction_listener(self, fn: Callable) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def _notify_evictions(self) -> None:
        """Deliver queued drop events outside the lock — a listener may
        re-enter the pool (a tenant registry faulting a model back in),
        and must never be able to break the drop that notified it."""
        with self._lock:
            if not self._events:
                return
            events, self._events = self._events, []
        for fn in list(self._listeners):
            for key, reason, nbytes in events:
                try:
                    fn(key, reason, nbytes)
                except Exception:  # noqa: BLE001 - advisory telemetry
                    pass

    def discard(self, key, reason: str = "explicit") -> bool:
        """Drop ONE entry by key (the tenant registry's resident-cap
        fault-out).  Honors the pin invariant — a pinned entry stays put
        and ``False`` comes back."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None or entry.pins > 0:
                return False
            self._drop_locked(key, entry, reason=reason)
            self.evictions += 1
            obs.counter_add("slab_pool.evictions")
            self._record_gauges_locked()
        self._notify_evictions()
        return True

    def get_or_build(self, key, builder: Callable, refs=(),
                     nbytes: Optional[int] = None, agreed: bool = True):
        """The one lookup: pooled value on a hit, ``builder()`` on a miss.

        ``refs`` are the weakref guards from the token pass (content
        identity holds only while the source buffers live).  Multi-process,
        hit/miss is AGREED across processes first — any miss rebuilds
        everywhere, so collective-bearing builders stay aligned.

        ``agreed=False`` skips every cross-process collective for this
        lookup — REQUIRED on paths the multi-process contract declares
        collective-free (inference: each process scores its own rows on its
        own local mesh, with per-process batch counts no peer mirrors).
        Only safe when the builder itself dispatches nothing collective."""
        import jax

        from flink_ml_tpu.fault.injection import maybe_fail
        from flink_ml_tpu.fault.retry import with_retry

        multi = jax.process_count() > 1 and agreed
        if not (_agreed_enabled() if multi else enabled()):
            return builder()
        try:
            maybe_fail("slab.lookup")
            with self._lock:
                entry = self._lookup_locked(key)
        except Exception as exc:  # noqa: BLE001 - transient-only, see below
            # graceful degradation, for EVERY pool consumer (training
            # wrappers, KNN model load, the batched-apply path): the pool
            # is an optimization, never a correctness dependency, so a
            # TRANSIENT failure of the pool machinery itself builds
            # direct.  Gated off agreed multi-process lookups — peers
            # already synchronized on this lookup's hit/miss, and a
            # unilateral local fallback would desync the collective
            # schedule.  Non-transient errors are real bugs: re-raise.
            from flink_ml_tpu.fault.retry import is_transient

            if multi or not is_transient(exc):
                raise
            obs.counter_add("fault.fallbacks")
            obs.counter_add("fault.fallbacks.slab_pool")
            warnings.warn(
                f"slab-pool lookup failed transiently ({exc!r}); falling "
                "back to direct placement for this call",
                RuntimeWarning,
                stacklevel=3,
            )
            return builder()
        local_hit = entry is not None
        if multi:
            from flink_ml_tpu.parallel.mesh import agree_max

            (any_miss,) = agree_max(int(not local_hit))
            if any_miss:
                local_hit = False  # rebuild with the peers: miss wins ties
        if local_hit:
            with self._lock:
                self.hits += 1
            obs.counter_add("slab_pool.hits")
            return entry.value
        import time

        t0 = time.perf_counter()
        # outside the lock: placement is the slow part.  Cold placement is
        # a transient-failure surface (device OOM blips, tunneled-backend
        # hiccups, injected chaos) — retried with backoff; single-process
        # only, because a multi-process builder's collectives must dispatch
        # exactly once per peer agreement round
        if jax.process_count() == 1:
            value = with_retry(builder, "slab.build")
        else:
            value = builder()
        # the pack+place cost a warm fit skips — recorded HERE because
        # estimator paths resolve placement before the fused driver runs
        # (its own train.place covers only driver-internal placement)
        obs.observe("slab_pool.build", time.perf_counter() - t0)
        if nbytes is None:
            nbytes = pytree_nbytes(value)
        with self._lock:
            self.misses += 1
            old = self._entries.get(key)
            if old is not None and old.pins > 0:
                # replaced while a device call still runs over it: park the
                # entry so the pool keeps its reference until the pin drops
                self._displaced.append(old)
                self._by_value.pop(id(old.value), None)
                self._entries.pop(key, None)
                self.bytes -= old.nbytes
            elif old is not None:
                self._drop_locked(key, old, reason="replaced")
            self._entries[key] = _Entry(
                value, nbytes, self._guarded_refs(key, refs)
            )
            self._by_value[id(value)] = key
            self.bytes += nbytes
            self._evict_over_budget_locked(keep=key, collective_ok=multi or
                                    jax.process_count() == 1)
            obs.counter_add("slab_pool.misses")
            obs.counter_add("slab_pool.bytes_placed", nbytes)
            self._record_gauges_locked()
        self._notify_evictions()
        return value

    def _evict_over_budget_locked(self, keep=None, collective_ok: bool = True) -> None:
        """LRU eviction down to the budget; pinned entries and ``keep``
        (the entry just produced) are never evicted.  Eviction only drops
        the pool's reference — the runtime frees device memory when the
        last holder (an in-flight program included) lets go."""
        # dead sweep first: entries whose source buffers died can never be
        # hit again (their keys carry recycled identities), but only a
        # lookup of the SAME key would notice — transient-batch entries get
        # unique keys, so without this sweep they would pin device memory
        # until budget pressure
        for key, entry in list(self._entries.items()):
            if not entry.alive() and entry.pins == 0:
                self._drop_locked(key, entry, reason="dead")
        budget = self.budget_bytes(collective_ok)
        if self.bytes <= budget:
            return
        for key in list(self._entries):
            if self.bytes <= budget:
                break
            entry = self._entries[key]
            if key == keep or entry.pins > 0:
                continue
            self._drop_locked(key, entry, reason="budget")
            self.evictions += 1
            obs.counter_add("slab_pool.evictions")

    def _record_gauges_locked(self) -> None:
        obs.gauge_set("slab_pool.bytes", float(self.bytes))
        obs.gauge_set("slab_pool.entries", float(len(self._entries)))

    @contextlib.contextmanager
    def pinned(self, value):
        """Refcount a checked-out slab for the duration of a device call:
        while pinned, eviction keeps the entry (and thus a live reference),
        so no donation or budget pressure can free the buffers under the
        running program.  A no-op for values the pool does not own."""
        with self._lock:
            key = self._by_value.get(id(value))
            entry = self._entries.get(key) if key is not None else None
            if entry is not None:
                entry.pins += 1
        try:
            yield
        finally:
            if entry is not None:
                with self._lock:
                    entry.pins -= 1

    def evict_for_pressure(self) -> int:
        """Drop EVERY unpinned entry under device memory pressure (ISSUE
        9) and return the bytes released.  The pool is an optimization,
        never a correctness dependency: on an allocator OOM the pressure
        layer frees cached slabs first — the cheapest HBM to reclaim —
        before shrinking the failing batch.  Pinned entries (in-flight
        device calls) keep their reference, honoring the pin invariant;
        the runtime frees device memory when the last holder lets go."""
        with self._lock:
            dropped = 0
            for key, entry in list(self._entries.items()):
                if entry.pins > 0:
                    continue
                dropped += entry.nbytes
                self._drop_locked(key, entry, reason="pressure")
                self.evictions += 1
            if dropped:
                obs.counter_add("slab_pool.pressure_evictions")
                obs.counter_add("slab_pool.pressure_evicted_bytes", dropped)
                self._record_gauges_locked()
        self._notify_evictions()
        return dropped

    def reap(self) -> None:
        """Drop entries whose source buffers died (queued by the weakref
        death callbacks).  O(queued keys), no-op when nothing died — cheap
        enough for paths that never otherwise touch the pool (the batched
        inference loop calls it per batch), so a dropped training table's
        slab cannot sit in device memory for the process lifetime just
        because no later fit happened to run."""
        with self._lock:
            self._drain_dead_locked()
        self._notify_evictions()

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._by_value.clear()
            self.bytes = 0
            self._record_gauges_locked()


_POOL: Optional[SlabPool] = None


def pool() -> SlabPool:
    """The process-wide default pool."""
    global _POOL
    if _POOL is None:
        _POOL = SlabPool()
    return _POOL


def reset_pool() -> None:
    """Drop the default pool (tests; bench uncached runs)."""
    global _POOL
    _POOL = None


def evict_for_pressure() -> int:
    """Module-level pressure-eviction entry point: drop every unpinned
    slab from the default pool (no-op — and no pool construction — when
    none exists yet).  Returns bytes released."""
    if _POOL is None:
        return 0
    return _POOL.evict_for_pressure()


# -- placement entry points ---------------------------------------------------


def pool_active(agreed: bool = True) -> bool:
    """Should a caller tokenize + consult the pool at all?  The cheap
    front gate: with pooling off, the token pass (weakref chasing + CRC
    canaries) would be pure waste.  ``agreed`` lookups resolve the
    CROSS-PROCESS switch (a locally-disabled process must still join its
    peers' hit/miss agreement decision — or rather, force it off for
    everyone); collective-free lookups read the local env only."""
    import jax

    if jax.process_count() > 1 and agreed:
        return _agreed_enabled()
    return enabled()


def get_or_place(table, layout_key, mesh, builder: Callable, cols=None):
    """Pool a device placement keyed by TABLE CONTENT + mesh + layout.

    The estimator-facing entry point: re-fitting the same table content
    (same object or a column-sharing copy) with the same layout and mesh
    returns the already-placed batch; anything else builds.  ``builder``
    produces the placed pytree (and may itself dispatch device programs —
    multi-process alignment is handled by the pool's hit agreement).
    ``cols`` names the columns the layout reads (see
    :func:`table_token`)."""
    if not pool_active():
        return builder()
    token, refs = table_token(table, cols=cols)
    return pool().get_or_build(
        ("table", token, mesh, layout_key), builder, refs=refs
    )


def place_batch(mesh, batch, axis: str = "data"):
    """Pooled :func:`~flink_ml_tpu.parallel.mesh.shard_batch_prefetched`.

    Keyed by the identity of the host leaves — callers that re-place the
    SAME host arrays (a retained MinibatchStack across fits) hit; transient
    arrays miss, and their entries self-drop when the weakref guards die.
    The placement itself is double-buffered/chunked single-process."""
    import jax

    from flink_ml_tpu.parallel.mesh import shard_batch_prefetched

    if not pool_active():
        return shard_batch_prefetched(mesh, batch, axis=axis)
    leaves, treedef = jax.tree_util.tree_flatten(batch)
    refs: list = []
    token = tuple(array_token(leaf, refs) for leaf in leaves)
    # transient pool-machinery failures degrade to a direct placement
    # inside get_or_build — the pool is never a correctness dependency
    return pool().get_or_build(
        ("place", mesh, axis, treedef, token),
        lambda: shard_batch_prefetched(mesh, batch, axis=axis),
        refs=refs,
    )
