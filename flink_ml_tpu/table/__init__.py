"""table — the columnar data plane.

Replaces the reference's Flink ``Table`` substrate plus its schema/conversion
utilities (``TableUtil.java``, ``OutputColsHelper.java``,
``DataStreamConversionUtil.java``) with a host-side columnar table designed to
feed TPU batches: columns are numpy arrays, vector columns pack to dense
``(batch, dim)`` arrays or ``CsrBatch`` without per-row hops, and unbounded
sources present the windowed mini-batch protocol the streaming driver consumes
(IncrementalLearningSkeleton.java:61-83 shape).
"""

from flink_ml_tpu.table.schema import DataTypes, Schema  # noqa: F401
from flink_ml_tpu.table.table import Table  # noqa: F401
from flink_ml_tpu.table.output_cols import OutputColsHelper  # noqa: F401
from flink_ml_tpu.table import table_util  # noqa: F401
from flink_ml_tpu.table.sources import (  # noqa: F401
    BoundedSource,
    CollectionSource,
    CsvSource,
    LibSvmSource,
    UnboundedSource,
    GeneratorSource,
)
