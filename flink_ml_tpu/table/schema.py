"""Schema and data types.

The type vocabulary mirrors what the reference's TableUtil recognizes
(TableUtil.java:147-182: supported numeric types, string, vector) and the
Flink TypeInformation constants in VectorTypes.java:28-42.  Column lookup is
case-insensitive, exactly like TableUtil.findColIndex (TableUtil.java:54-69).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np


class DataTypes:
    DOUBLE = "DOUBLE"
    FLOAT = "FLOAT"
    INT = "INT"
    LONG = "LONG"
    BOOLEAN = "BOOLEAN"
    STRING = "STRING"
    VECTOR = "VECTOR"
    DENSE_VECTOR = "DENSE_VECTOR"
    SPARSE_VECTOR = "SPARSE_VECTOR"

    _NUMERIC = {DOUBLE, FLOAT, INT, LONG}
    _VECTOR = {VECTOR, DENSE_VECTOR, SPARSE_VECTOR}

    _ALL = _NUMERIC | _VECTOR | {BOOLEAN, STRING}

    @classmethod
    def normalize(cls, t: str) -> str:
        """Canonical (upper-case) type name; unknown names fail loudly."""
        canon = t.upper()
        if canon not in cls._ALL:
            raise ValueError(f"unknown data type {t!r}; one of {sorted(cls._ALL)}")
        return canon

    @classmethod
    def is_numeric(cls, t: str) -> bool:
        """TableUtil.isSupportedNumericType analog (TableUtil.java:147-158)."""
        return t.upper() in cls._NUMERIC

    @classmethod
    def is_string(cls, t: str) -> bool:
        return t.upper() == cls.STRING

    @classmethod
    def is_vector(cls, t: str) -> bool:
        return t.upper() in cls._VECTOR

    @staticmethod
    def numpy_dtype(t: str):
        return {
            DataTypes.DOUBLE: np.float64,
            DataTypes.FLOAT: np.float32,
            DataTypes.INT: np.int32,
            DataTypes.LONG: np.int64,
            DataTypes.BOOLEAN: np.bool_,
        }.get(DataTypes.normalize(t), object)


class Schema:
    """Ordered (name, type) fields with case-insensitive name lookup."""

    __slots__ = ("_names", "_types", "_lower_index")

    def __init__(self, names: Sequence[str], types: Sequence[str]):
        if len(names) != len(types):
            raise ValueError("names and types must align")
        self._names = list(names)
        self._types = [DataTypes.normalize(t) for t in types]
        self._lower_index: Dict[str, int] = {}
        for i, n in enumerate(self._names):
            low = n.lower()
            # first occurrence wins on case-insensitive duplicates, matching the
            # linear scan in TableUtil.findColIndex
            self._lower_index.setdefault(low, i)

    @staticmethod
    def of(*fields: Tuple[str, str]) -> "Schema":
        return Schema([f[0] for f in fields], [f[1] for f in fields])

    @property
    def field_names(self) -> List[str]:
        return list(self._names)

    @property
    def field_types(self) -> List[str]:
        return list(self._types)

    def __len__(self) -> int:
        return len(self._names)

    def find_col_index(self, name: str) -> int:
        """Case-insensitive index, -1 when absent (TableUtil.java:54-69)."""
        if name is None:
            raise ValueError("target col is None")
        return self._lower_index.get(name.lower(), -1)

    def contains(self, name: str) -> bool:
        return self.find_col_index(name) >= 0

    def field_name(self, i: int) -> str:
        return self._names[i]

    def field_type(self, i: int) -> str:
        return self._types[i]

    def type_of(self, name: str) -> str:
        i = self.find_col_index(name)
        if i < 0:
            raise ValueError(f"column {name!r} not found in schema {self._names}")
        return self._types[i]

    def resolve(self, name: str) -> str:
        """Canonical column name (schema spelling) for a case-insensitive match."""
        i = self.find_col_index(name)
        if i < 0:
            raise ValueError(f"column {name!r} not found in schema {self._names}")
        return self._names[i]

    def select(self, names: Sequence[str]) -> "Schema":
        idx = [self.find_col_index(n) for n in names]
        missing = [n for n, i in zip(names, idx) if i < 0]
        if missing:
            raise ValueError(f"columns {missing} not found in schema {self._names}")
        return Schema([self._names[i] for i in idx], [self._types[i] for i in idx])

    def to_dict(self) -> Dict[str, List[str]]:
        return {"names": list(self._names), "types": list(self._types)}

    @staticmethod
    def from_dict(d: Dict) -> "Schema":
        return Schema(d["names"], d["types"])

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Schema)
            and self._names == other._names
            and self._types == other._types
        )

    def __repr__(self) -> str:
        cols = ", ".join(f"{n}:{t}" for n, t in zip(self._names, self._types))
        return f"Schema({cols})"
