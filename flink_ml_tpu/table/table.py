"""Columnar Table — the value that flows between pipeline stages.

The reference moves data between stages as Flink ``Table`` objects and crosses
to per-record DataStreams for compute (DataStreamConversionUtil.java:47-130).
Here the table *is already columnar*: each column is a numpy array, so the
device hop is a single ``jnp.asarray`` / ``CsrBatch.from_vectors`` per batch —
no row-at-a-time boundary anywhere (the TPU-first replacement for the
row-mapper hot loop, SURVEY.md §3.2).

Tables are immutable values: every transformation returns a new Table sharing
column buffers where possible.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from flink_ml_tpu.ops.batch import CsrBatch, CsrRows, dense_batch
from flink_ml_tpu.ops.vector import DenseVector, SparseVector, Vector
from flink_ml_tpu.table.schema import DataTypes, Schema

#: Bound on per-table memoized packings: each entry can pin a full
#: device-layout copy of the dataset (host or HBM), so a hyperparameter sweep
#: over layout-affecting params (batch size, mesh) must evict old layouts
#: instead of accumulating one resident copy per config.
_PACK_CACHE_CAPACITY = 4  # host pack + device placement for ~2 configs


class Table:
    __slots__ = ("_schema", "_cols", "_num_rows", "_pack_cache")

    def __init__(self, schema: Schema, cols: Dict[str, np.ndarray]):
        self._schema = schema
        self._cols = cols
        lengths = {len(c) for c in cols.values()}  # CsrRows defines __len__
        if len(lengths) > 1:
            raise ValueError(f"ragged columns: lengths {lengths}")
        self._num_rows = lengths.pop() if lengths else 0
        self._pack_cache: OrderedDict = OrderedDict()

    def cached_pack(self, key, builder):
        """Memoize a device-layout packing of this (immutable) table.

        Training drivers pack rows into device-major stacks (and place them on
        the mesh) before the first epoch; re-fitting the same table
        (hyperparameter sweeps, warmup + measure benches) would otherwise
        re-pack AND re-transfer identical bytes — on tunneled devices the
        host->device hop dominates the whole fit.  ``key`` must capture
        everything the layout depends on (columns, batch size, mesh, dtype).

        LRU-bounded to ``_PACK_CACHE_CAPACITY`` entries: evicting a device
        placement drops the last reference to its HBM buffers, so sweeps over
        layout-affecting params cannot pin one dataset copy per config.
        """
        if key in self._pack_cache:
            self._pack_cache.move_to_end(key)
            return self._pack_cache[key]
        value = builder()
        self._pack_cache[key] = value
        while len(self._pack_cache) > _PACK_CACHE_CAPACITY:
            self._pack_cache.popitem(last=False)
        return value

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_columns(schema: Schema, cols: Dict[str, Sequence]) -> "Table":
        data = {}
        for name, typ in zip(schema.field_names, schema.field_types):
            if name not in cols:
                raise ValueError(f"missing column {name!r}")
            data[name] = _as_column(cols[name], typ)
        return Table(schema, data)

    @staticmethod
    def from_rows(rows: Sequence[Sequence], schema: Schema) -> "Table":
        cols: Dict[str, List] = {n: [] for n in schema.field_names}
        for row in rows:
            if len(row) != len(schema):
                raise ValueError(f"row arity {len(row)} != schema arity {len(schema)}")
            for name, value in zip(schema.field_names, row):
                cols[name].append(value)
        return Table.from_columns(schema, cols)

    # -- basic accessors ----------------------------------------------------

    @property
    def schema(self) -> Schema:
        return self._schema

    def num_rows(self) -> int:
        return self._num_rows

    def __len__(self) -> int:
        return self._num_rows

    def col(self, name: str) -> np.ndarray:
        """Column buffer by (case-insensitive) name."""
        return self._cols[self._schema.resolve(name)]

    def to_rows(self) -> List[Tuple]:
        names = self._schema.field_names
        columns = [
            _rowwise_view(self._cols[n], self._schema.type_of(n)) for n in names
        ]
        return [tuple(c[i] for c in columns) for i in range(self._num_rows)]

    # -- relational ops ------------------------------------------------------

    def select(self, names: Sequence[str]) -> "Table":
        sub = self._schema.select(names)
        return Table(sub, {n: self._cols[n] for n in sub.field_names})

    def with_column(self, name: str, typ: str, values) -> "Table":
        """Append (or replace) a column, returning a new Table."""
        values = _as_column(values, typ)
        if self._cols and len(values) != self._num_rows:
            raise ValueError("column length mismatch")
        names, types = self._schema.field_names, self._schema.field_types
        cols = dict(self._cols)
        idx = self._schema.find_col_index(name)
        if idx >= 0:
            canonical = names[idx]
            types[idx] = typ
            cols[canonical] = values
        else:
            names.append(name)
            types.append(typ)
            cols[name] = values
        return Table(Schema(names, types), cols)

    def slice_rows(self, start: int, stop: int) -> "Table":
        return Table(
            self._schema, {n: c[start:stop] for n, c in self._cols.items()}
        )

    def take_rows(self, indices) -> "Table":
        idx = np.asarray(indices, dtype=np.int64)
        return Table(self._schema, {n: c[idx] for n, c in self._cols.items()})

    def filter_rows(self, mask) -> "Table":
        mask = np.asarray(mask, dtype=bool)
        return Table(self._schema, {n: c[mask] for n, c in self._cols.items()})

    @staticmethod
    def concat(tables: Sequence["Table"]) -> "Table":
        if not tables:
            raise ValueError("concat of zero tables")
        schema = tables[0].schema
        for t in tables[1:]:
            if t.schema != schema:
                raise ValueError("schema mismatch in concat")
        cols = {}
        for n in schema.field_names:
            arrays = [t._cols[n] for t in tables]
            if any(isinstance(a, CsrRows) for a in arrays):
                if all(isinstance(a, CsrRows) for a in arrays):
                    cols[n] = CsrRows.concat(arrays)
                else:  # mixed CSR/object sparse columns: normalize to objects
                    obj = np.empty(sum(len(a) for a in arrays), dtype=object)
                    i = 0
                    for a in arrays:
                        for v in a:
                            obj[i] = v
                            i += 1
                    cols[n] = obj
                continue
            ndims = {a.ndim for a in arrays}
            if len(ndims) > 1:
                # mixed matrix-backed and object-backed vector columns:
                # normalize to object rows (correctness over speed — concat
                # of mixed layouts is not a hot path)
                typ = schema.type_of(n)
                parts = []
                for a in arrays:
                    view = _rowwise_view(a, typ)
                    obj = np.empty(len(a), dtype=object)
                    for i in range(len(a)):
                        obj[i] = view[i]
                    parts.append(obj)
                arrays = parts
            cols[n] = np.concatenate(arrays)
        return Table(schema, cols)

    def iter_batches(self, batch_size: int) -> Iterator["Table"]:
        for start in range(0, self._num_rows, batch_size):
            yield self.slice_rows(start, min(start + batch_size, self._num_rows))

    # -- device bridging -----------------------------------------------------

    def features_dense(self, col: str, dim: Optional[int] = None) -> np.ndarray:
        """A vector column as a ``(rows, dim)`` float array, ready for jnp.asarray."""
        typ = self._schema.type_of(col)
        values = self.col(col)
        if DataTypes.is_vector(typ):
            if isinstance(values, CsrRows):
                # vectorized densify (duplicate indices sum, out-of-range
                # raises — same semantics as the per-row path)
                return values.to_dense(dim)
            if isinstance(values, np.ndarray) and values.ndim == 2:
                # matrix-backed column: already the device layout, zero-copy
                if dim is not None and values.shape[1] != dim:
                    if values.shape[1] > dim:
                        # mirror dense_batch: rows wider than the requested
                        # dim are a loud dimension mismatch, never truncated
                        raise ValueError(
                            f"column {col!r} holds {values.shape[1]}-dim "
                            f"vectors; requested dim={dim}"
                        )
                    out = np.zeros((values.shape[0], dim), dtype=values.dtype)
                    out[:, : values.shape[1]] = values
                    return out
                return values
            return dense_batch(list(values), dim)
        return np.asarray(values, dtype=np.float64).reshape(self._num_rows, 1)

    def features_csr(self, col: str, n_cols: int, pad_multiple: int = 1024) -> CsrBatch:
        """A (sparse-)vector column as a CsrBatch for the device sparse path."""
        column = self.col(col)
        if isinstance(column, CsrRows):
            return CsrBatch.from_csr_rows(
                column, n_cols=n_cols, pad_multiple=pad_multiple
            )
        vectors = []
        for v in self.col(col):
            if isinstance(v, SparseVector):
                vectors.append(v)
            elif isinstance(v, Vector):
                dv = v.to_dense()
                nz = np.nonzero(dv.values)[0]
                vectors.append(SparseVector(dv.size(), nz, dv.values[nz]))
            else:
                raise TypeError(f"column {col!r} does not hold vectors")
        return CsrBatch.from_vectors(vectors, n_cols=n_cols, pad_multiple=pad_multiple)

    def numeric_matrix(self, cols: Sequence[str]) -> np.ndarray:
        """Numeric columns stacked into a ``(rows, len(cols))`` float array."""
        arrays = []
        for c in cols:
            if not DataTypes.is_numeric(self._schema.type_of(c)):
                raise ValueError(f"column {c!r} is not numeric")
            arrays.append(np.asarray(self.col(c), dtype=np.float64))
        return np.stack(arrays, axis=1) if arrays else np.zeros((self._num_rows, 0))

    def __repr__(self) -> str:
        return f"Table({self._schema!r}, rows={self._num_rows})"


def _as_column(values, typ: str) -> np.ndarray:
    dtype = DataTypes.numpy_dtype(typ)
    if dtype is object:
        if typ.upper() == DataTypes.SPARSE_VECTOR and isinstance(values, CsrRows):
            # CSR-backed sparse column: contiguous arrays, lazy row views —
            # the sparse counterpart of the matrix-backed dense fast path
            return values
        if (
            typ.upper() in (DataTypes.DENSE_VECTOR, DataTypes.VECTOR)
            and isinstance(values, np.ndarray)
            and values.ndim == 2
        ):
            # matrix fast path is DENSE only: a 2D array for a SPARSE_VECTOR
            # column would silently reroute fit/persistence to dense codecs
            # matrix-backed dense-vector column: one contiguous (rows, dim)
            # float array instead of rows of DenseVector objects.  The fast
            # path for million-row dense workloads — features_dense returns
            # it zero-copy; row-level views wrap rows lazily (_rowwise_view).
            if values.dtype not in (np.float32, np.float64):
                values = values.astype(np.float64)
            return values
        arr = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            arr[i] = v
        if DataTypes.is_vector(typ):
            for v in arr:
                if v is not None and not isinstance(v, Vector):
                    raise TypeError(f"vector column holds non-vector {type(v).__name__}")
        return arr
    return np.asarray(values, dtype=dtype)


class _rowwise_view:
    """Row accessor over a column buffer: matrix-backed vector columns yield
    DenseVector rows lazily so row-level consumers (to_rows, codecs) see the
    same value types as object-backed columns."""

    __slots__ = ("_col", "_wrap")

    def __init__(self, col: np.ndarray, typ: str):
        self._col = col
        self._wrap = (
            DataTypes.is_vector(typ)
            and isinstance(col, np.ndarray)
            and col.ndim == 2
        )

    def __getitem__(self, i):
        return DenseVector(self._col[i]) if self._wrap else self._col[i]
