"""iteration — the runtime the reference only specified.

The reference ships the FLIP-176 iteration API as javadoc + ``return null``
(Iterations.java:89,112).  This package implements those semantics for real:

* :func:`iterate_bounded` — bounded iteration with replayed/streamed inputs,
  epoch watermarks, per-epoch listener callbacks, ALL_ROUND/PER_ROUND operator
  lifecycles, and both termination modes (no feedback records; empty
  termination-criteria output) plus max-epoch (Iterations.java:38-49,93-96).
* :func:`iterate_unbounded` / :class:`StreamingDriver` — the unbounded online
  path: event-time tumbling windows over unbounded sources, per-window model
  updates, concurrent prediction against the freshest model
  (IncrementalLearningSkeleton.java:61-83 shape).
* :mod:`device` — the fast path where an epoch is one compiled step on
  device (`lax.fori_loop` / `lax.while_loop` with on-device convergence),
  which is what algorithm Estimators actually use for bounded training.
"""

from flink_ml_tpu.iteration.config import IterationConfig, OperatorLifeCycle  # noqa: F401
from flink_ml_tpu.iteration.listener import IterationListener  # noqa: F401
from flink_ml_tpu.iteration.bounded import (  # noqa: F401
    IterationBodyResult,
    ReplayableInputs,
    iterate_bounded,
)
from flink_ml_tpu.iteration.device import train_epochs, train_until  # noqa: F401
from flink_ml_tpu.iteration.unbounded import StreamingDriver, iterate_unbounded  # noqa: F401
