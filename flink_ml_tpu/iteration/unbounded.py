"""Unbounded iteration — the streaming mini-batch driver.

Implements the reference's unbounded topology (Iterations.iterateUnboundedStreams
spec, Iterations.java:87-90, and the IncrementalLearningSkeleton shape,
:61-83): a training stream is cut into event-time tumbling windows; each fired
window updates the model (PartialModelBuilder:161-174); a concurrent
prediction stream is served by the *freshest* model (Predictor CoMap:182-211).

TPU-first realization: the driver merges the timestamped streams
deterministically on the host, fires windows when the watermark passes the
window end, and batches all prediction records that fall between two model
updates into one device call — behaviorally identical to per-record CoMap
(every record sees exactly the model that was current at its event time) but
executed as batched XLA instead of a per-record hot loop.

Two ingest paths, same semantics (equivalence-tested record for record):

* **Vectorized span path** — sources that guarantee time order and speak the
  columnar chunk protocol (``UnboundedSource.stream_chunks``, e.g.
  ``ColumnarUnboundedSource``) are processed span-by-span with zero
  per-record Python: window grouping is one ``np.unique`` over window ends,
  prediction/flush cutoffs are ``searchsorted``, and window tables are
  concatenated column slices (matrix-backed vector columns ride zero-copy
  into the update).  This is the hot path — ~40x the merge loop's host
  throughput.
* **Per-record merge loop** — the general path: out-of-order streams
  (watermarks + allowed lateness + late-data side output) and checkpointed
  runs (the snapshot cut is defined per consumed record).

Robustness (the two pieces the reference delegates to Flink's runtime):

* **Bounded out-of-orderness** — ``allowed_lateness_ms`` holds the watermark
  ``L`` behind the max event time seen (the
  BoundedOutOfOrdernessTimestampExtractor the reference's examples assign,
  IncrementalLearningSkeleton.java:144-158 assigns timestamps + watermarks),
  so multiple windows stay open concurrently and a record up to ``L`` late
  still lands in its correct window; records later than that are routed to
  ``StreamingResult.late_records`` (Flink's late-data side output) instead
  of silently corrupting a window.
* **Checkpoint/resume** — with a
  :class:`~flink_ml_tpu.iteration.checkpoint.CheckpointConfig` the driver
  snapshots (model state, watermark, open window buffers, pending
  predictions, stream position) every N fired windows; a killed run resumed
  over the same (replayable) sources fast-forwards to the recorded position
  and continues bit-identically.  The snapshot covers the *continuation*:
  every model update, window firing, and prediction emitted after the
  resume point is bit-identical to the uninterrupted run's.  Outputs
  already **emitted** before the cut — served predictions and the
  ``keep_model_history`` trail — are downstream-owned and are not replayed
  (Flink sink semantics: a restored job does not re-emit records its sinks
  already consumed), so a resumed ``StreamingResult`` lists only
  post-resume emissions.  ``late_records`` is the one output carried in
  the snapshot: the side output is reported exactly once, at stream end,
  so pre-cut lates would otherwise vanish from the final report.

Epoch accounting: window N's model update is epoch N; listeners receive epoch
watermarks exactly as in the bounded runtime.
"""

from __future__ import annotations

import bisect
import heapq
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from flink_ml_tpu.iteration.listener import IterationListener, ListenerContext
from flink_ml_tpu.ops.vector import DenseVector
from flink_ml_tpu.table.schema import Schema
from flink_ml_tpu.table.table import Table
from flink_ml_tpu.table.sources import UnboundedSource


@dataclass
class StreamingResult:
    final_state: Any
    windows_fired: int
    predictions: List[Tuple[int, Any]]  # (event_time, predicted value) per record
    listener_context: ListenerContext
    model_updates: List[Tuple[int, Any]] = field(default_factory=list)  # (window_end, state)
    #: per-window StepMetrics (SURVEY §5.5): wall time + rows per fired window
    metrics: Any = None
    #: training records that arrived after their window closed (beyond the
    #: allowed lateness) — the late-data side output, never silently dropped
    late_records: List[Tuple[int, Tuple]] = field(default_factory=list)


class _ColumnBuffer:
    """Window/prediction record buffer with a bulk columnar fire path.

    The driver exists to replace the reference's per-record CoMap hot loop
    (IncrementalLearningSkeleton.java:182-211), so its own buffering must
    stay off the per-record path: the hot loop is ONE list append of the
    row tuple; all columnar work happens per fired batch — ``zip(*rows)``
    transposes at C speed and a dense-vector column stacks into one
    matrix-backed ``(n, d)`` array, so the fired Table skips from_rows'
    per-cell work AND the update fn's ``features_dense`` becomes zero-copy
    instead of re-densifying 1000 DenseVector objects per window.
    """

    def __init__(self, schema: Schema):
        from flink_ml_tpu.table.schema import DataTypes

        self.schema = schema
        self._names = schema.field_names
        self._vec = [DataTypes.is_vector(t) for t in schema.field_types]
        self.rows: List[Tuple] = []

    def __len__(self) -> int:
        return len(self.rows)

    def append(self, row) -> None:
        row = tuple(row)  # no-op copy when row is already a tuple
        if len(row) != len(self._names):
            raise ValueError(
                f"row arity {len(row)} != schema arity {len(self._names)}"
            )
        self.rows.append(row)

    def insert(self, i: int, row) -> None:
        row = tuple(row)
        if len(row) != len(self._names):
            raise ValueError(
                f"row arity {len(row)} != schema arity {len(self._names)}"
            )
        self.rows.insert(i, row)

    @staticmethod
    def _column(col: tuple, is_vec: bool):
        if not is_vec:
            return np.asarray(col)
        if col and all(type(v) is DenseVector for v in col):
            try:
                arr = np.asarray([v.values for v in col])
            except ValueError:  # ragged widths refuse to stack (numpy >=1.24)
                return list(col)
            if arr.ndim == 2:
                return arr  # matrix-backed dense-vector column
        return list(col)  # sparse / mixed widths: object column

    def take(self, cut: Optional[int] = None) -> Table:
        """Table of rows [0:cut] (default: all), removed from the buffer."""
        rows = self.rows[:cut] if cut is not None else self.rows
        self.rows = self.rows[cut:] if cut is not None else []
        if not rows:
            return Table.from_columns(
                self.schema, {n: [] for n in self._names}
            )
        cols = {
            n: self._column(col, vec)
            for n, vec, col in zip(self._names, self._vec, zip(*rows))
        }
        return Table.from_columns(self.schema, cols)

    def row_tuples(self) -> List[Tuple]:
        """Rows as tuples (snapshot codec path — rare, off the hot loop)."""
        return list(self.rows)


def _concat_col(segs: List, is_vector: bool = False):
    """Concatenate column segments (ndarray -> np.concatenate, list -> +).

    Adjacent chunks of the same vector column may columnize differently
    (matrix-backed vs object list — e.g. one ragged or sparse row in one
    chunk); the mixed/ragged fallback re-wraps matrix rows as DenseVectors
    so the result is a valid object vector column, never bare 1-D arrays.
    """
    if len(segs) == 1:
        return segs[0]
    if all(isinstance(s, np.ndarray) for s in segs):
        try:
            return np.concatenate(segs)
        except ValueError:
            pass  # ragged widths across chunks: object-column fallback
    out: List = []
    for s in segs:
        if is_vector and isinstance(s, np.ndarray) and s.ndim == 2:
            out.extend(DenseVector(r) for r in s)
        else:
            out.extend(s)
    return out


class _ChunkCursor:
    """Buffered reader over a ``stream_chunks()`` iterator.

    Validates the protocol's time-order contract (within and across chunks)
    and hands out prefix spans by timestamp horizon — the vectorized
    driver's only per-chunk bookkeeping."""

    def __init__(self, chunk_iter):
        self._it = iter(chunk_iter)
        self.ts: Optional[np.ndarray] = None
        self.cols: Optional[dict] = None
        self.exhausted = False
        self._last_seen: Optional[int] = None

    def ensure(self) -> bool:
        """Buffer a non-empty chunk if none held; False once exhausted."""
        while not self.exhausted and (self.ts is None or len(self.ts) == 0):
            nxt = next(self._it, None)
            if nxt is None:
                self.exhausted = True
                self.ts = None
                self.cols = None
                return False
            ts, cols = nxt
            ts = np.asarray(ts, np.int64)
            if len(ts) == 0:
                continue
            if (
                (self._last_seen is not None and int(ts[0]) < self._last_seen)
                or np.any(np.diff(ts) < 0)
            ):
                raise ValueError(
                    "stream_chunks yielded out-of-order timestamps; the "
                    "chunk protocol requires non-decreasing event time — "
                    "use the per-record UnboundedSource.stream() path for "
                    "out-of-order streams"
                )
            self._last_seen = int(ts[-1])
            self.ts, self.cols = ts, cols
        return self.ts is not None and len(self.ts) > 0

    @property
    def buffered_last(self) -> int:
        return int(self.ts[-1])

    def take_upto(self, horizon: int):
        """Split off the buffered prefix with ts <= horizon."""
        cut = int(np.searchsorted(self.ts, horizon, side="right"))
        out = (self.ts[:cut], {k: v[:cut] for k, v in self.cols.items()})
        self.ts = self.ts[cut:]
        self.cols = {k: v[cut:] for k, v in self.cols.items()}
        return out


class _PendingPredictions:
    """Pending prediction records as columnar segments, served by
    event-time cutoff — the vectorized replacement for the per-record
    sorted-insert pending buffer (arrival is time-ordered here, so
    segments are globally sorted by construction)."""

    def __init__(self, schema: Schema):
        from flink_ml_tpu.table.schema import DataTypes

        self.schema = schema
        self._is_vec = {
            n: DataTypes.is_vector(t)
            for n, t in zip(schema.field_names, schema.field_types)
        }
        self._segs: List[Tuple[np.ndarray, dict]] = []
        self.count = 0

    def append(self, ts: np.ndarray, cols: dict) -> None:
        if len(ts):
            self._segs.append((ts, cols))
            self.count += len(ts)

    def cut(self, before_ts: Optional[int] = None,
            max_rows: Optional[int] = None):
        """Remove and return ``(ts_array, cols)`` for records with
        ts < before_ts (all records when None), capped at ``max_rows``."""
        take_ts: List[np.ndarray] = []
        take_cols: List[dict] = []
        budget = self.count if max_rows is None else int(max_rows)
        while self._segs and budget > 0:
            ts, cols = self._segs[0]
            n = len(ts) if before_ts is None else int(
                np.searchsorted(ts, before_ts, side="left")
            )
            n = min(n, budget)
            if n == 0:
                break
            if n == len(ts):
                self._segs.pop(0)
                take_ts.append(ts)
                take_cols.append(cols)
            else:
                take_ts.append(ts[:n])
                take_cols.append({k: v[:n] for k, v in cols.items()})
                self._segs[0] = (
                    ts[n:], {k: v[n:] for k, v in cols.items()}
                )
            budget -= n
            self.count -= n
        if not take_ts:
            return None
        names = self.schema.field_names
        return (
            np.concatenate(take_ts),
            {
                n: _concat_col([c[n] for c in take_cols], self._is_vec[n])
                for n in names
            },
        )


def _merge_streams(streams: Sequence[Iterator]) -> Iterator:
    """Deterministic k-way merge by (event_time, kind), stream-stable ties.

    For time-ordered sources this is an exact event-time merge (training
    sorts before prediction at equal timestamps, so a model update at time T
    serves a prediction at time T — matching connect() delivering the model
    first).  For out-of-order sources ``heapq.merge`` degrades gracefully to
    a deterministic head-of-stream arrival order, which the watermark
    machinery then handles; rows are never compared (the key excludes them).
    """
    return heapq.merge(*streams, key=lambda e: (e[0], e[1]))


class StreamingDriver:
    """Event-time tumbling-window trainer with a concurrent prediction path.

    ``update(state, window_table, epoch) -> state`` fires per completed window
    (the PartialModelBuilder role).  ``predict(state, batch_table) ->
    sequence`` serves the prediction stream with the current model (the
    Predictor role); it may return any per-row sequence (list/array).
    """

    def __init__(
        self,
        window_ms: int,
        keep_model_history: bool = False,
        prediction_flush_rows: int = 8192,
        allowed_lateness_ms: int = 0,
    ):
        if window_ms <= 0:
            raise ValueError("window_ms must be positive")
        if allowed_lateness_ms < 0:
            raise ValueError("allowed_lateness_ms must be >= 0")
        self.window_ms = int(window_ms)
        self.keep_model_history = keep_model_history
        # predictions sharing one model version can flush early in batches of
        # this size — bounds prediction latency on long-running streams
        self.prediction_flush_rows = prediction_flush_rows
        self.allowed_lateness_ms = int(allowed_lateness_ms)

    def run(
        self,
        initial_state: Any,
        training_source: UnboundedSource,
        update: Callable[[Any, Table, int], Any],
        prediction_source: Optional[UnboundedSource] = None,
        predict: Optional[Callable[[Any, Table], Sequence]] = None,
        listeners: Sequence[IterationListener] = (),
        max_windows: Optional[int] = None,
        checkpoint=None,
    ) -> StreamingResult:
        if (prediction_source is None) != (predict is None):
            raise ValueError("prediction_source and predict must be given together")

        # time-ordered sources that speak the columnar chunk protocol take
        # the vectorized span path: zero per-record Python on ingest
        # (windowing/cutoffs are searchsorted over chunk arrays).  The
        # per-record merge loop below remains the path for out-of-order
        # streams (watermarks/lateness) and for checkpointed runs (the
        # snapshot cut is defined per consumed record).
        if checkpoint is None:
            train_chunks = (
                training_source.stream_chunks()
                if hasattr(training_source, "stream_chunks") else None
            )
            if train_chunks is not None:
                pred_chunks = (
                    prediction_source.stream_chunks()
                    if prediction_source is not None else None
                )
                if prediction_source is None or pred_chunks is not None:
                    return self._run_vectorized(
                        initial_state, training_source, update,
                        prediction_source, predict, listeners, max_windows,
                        train_chunks, pred_chunks,
                    )

        from flink_ml_tpu.utils.metrics import StepMetrics

        context = ListenerContext()
        state = initial_state
        window_ms = self.window_ms
        lateness = self.allowed_lateness_ms
        train_schema = training_source.schema()
        metrics = StepMetrics("stream_train")

        TRAIN, PREDICT = 0, 1
        streams: List[Iterator] = [
            ((ts, TRAIN, row) for ts, row in training_source.stream())
        ]
        if prediction_source is not None:
            streams.append(((ts, PREDICT, row) for ts, row in prediction_source.stream()))
        merged = _merge_streams(streams)

        # open windows keyed by window end; several stay open when the
        # watermark lags max event time by the allowed lateness.  Buffers
        # are columnar (_ColumnBuffer) — the hot loop appends values, never
        # builds row objects or per-row Tables.
        open_windows: dict = {}
        pending_ts: List[int] = []
        pending_buf = (
            _ColumnBuffer(prediction_source.schema())
            if prediction_source is not None else None
        )
        predictions: List[Tuple[int, Any]] = []
        model_updates: List[Tuple[int, Any]] = []
        late_records: List[Tuple[int, Tuple]] = []
        watermark: Optional[int] = None
        epoch = 0
        consumed = 0  # records taken from the merged stream (for resume)
        last_snapshot_epoch = -1
        stopped = False

        if checkpoint is not None:
            restored = self._restore(checkpoint, state, train_schema,
                                     prediction_source)
            if restored is not None:
                (state, epoch, watermark, restored_windows,
                 restored_pending, late_records, skip) = restored
                for end, rows in restored_windows.items():
                    buf = open_windows[end] = _ColumnBuffer(train_schema)
                    for row in rows:
                        buf.append(row)
                for ts, row in restored_pending:
                    pending_ts.append(ts)
                    pending_buf.append(row)
                for _ in range(skip):
                    if next(merged, None) is None:
                        break  # replayed stream shorter than the snapshot cut
                consumed = skip

        def flush_predictions(before_ts: Optional[int] = None):
            """Serve pending predictions with the current model; with
            ``before_ts`` only those event-timed before it (they precede the
            imminent model update in event time)."""
            if predict is None or not pending_ts:
                return
            if before_ts is None:
                cut = len(pending_ts)
            else:
                # pending is kept event-time-sorted at insertion, so the
                # cutoff is one bisect — a saturated buffer of past-watermark
                # predictions costs O(log n) comparisons per record (O(n)
                # shift only on out-of-order mid-list inserts), not a
                # rebuilt O(n) filter
                cut = bisect.bisect_left(pending_ts, before_ts)
                if cut == 0:
                    return
            ts_batch = pending_ts[:cut]
            del pending_ts[:cut]
            batch = pending_buf.take(cut)
            outs = list(predict(state, batch))
            if len(outs) != len(ts_batch):
                raise ValueError(
                    f"predict returned {len(outs)} values for a batch of "
                    f"{len(ts_batch)} rows"
                )
            predictions.extend(zip(ts_batch, outs))

        def fire_window(end_ts: int):
            nonlocal state, epoch, stopped
            # predictions timestamped before this window's close see the old model
            flush_predictions(before_ts=end_ts)
            buf = open_windows.pop(end_ts)
            n_rows = len(buf)
            metrics.start_step()
            table = buf.take()
            state = update(state, table, epoch)
            metrics.end_step(samples=n_rows, window_end=end_ts)
            if self.keep_model_history:
                model_updates.append((end_ts, state))
            for listener in listeners:
                listener.on_epoch_watermark_incremented(epoch, context)
            epoch += 1
            if max_windows is not None and epoch >= max_windows:
                stopped = True

        def fire_ready():
            """Fire every open window whose end the watermark passed, in
            event-time order."""
            while not stopped:
                ready = [e for e in open_windows if watermark is not None and e <= watermark]
                if not ready:
                    return
                fire_window(min(ready))

        for ts, kind, row in merged:
            consumed += 1
            new_wm = ts - lateness
            if watermark is None or new_wm > watermark:
                watermark = new_wm
            if kind == TRAIN:
                end = (ts // window_ms + 1) * window_ms
                if watermark is not None and end <= watermark:
                    # the watermark passed this window's end (it fired, or
                    # would have fired empty): beyond the allowed lateness —
                    # side output, loudly kept (Flink's isWindowLate rule)
                    late_records.append((ts, tuple(row)))
                else:
                    buf = open_windows.get(end)
                    if buf is None:
                        buf = open_windows[end] = _ColumnBuffer(train_schema)
                    buf.append(row)
            else:
                # kept ts-sorted so flush cutoffs are a bisect; arrival is
                # near-ordered, so the insert lands at (or near) the tail
                i = bisect.bisect_right(pending_ts, ts)
                if i == len(pending_ts):
                    pending_ts.append(ts)
                    pending_buf.append(row)
                else:
                    pending_ts.insert(i, ts)
                    pending_buf.insert(i, row)
            fire_ready()
            if stopped:
                break
            if len(pending_ts) >= self.prediction_flush_rows:
                # an early flush may only serve predictions whose model is
                # final: a record at t must see every window with end <= t
                # fired first.  After fire_ready() every window with
                # end <= watermark HAS fired, and no window with
                # end <= watermark can still open (later trains there would
                # be late), so the watermark is exactly the safe horizon.
                # Bounding by min(open_windows) instead would be wrong
                # twice over: a window with an earlier end than any open one
                # can still open while the watermark lags by the allowed
                # lateness, and before fire_ready() an about-to-fire window
                # would be skipped.  Pending predictions past the watermark
                # stay buffered — bounded by the lateness horizon, not by
                # prediction_flush_rows.
                flush_predictions(
                    before_ts=watermark + 1 if watermark is not None else None
                )
            if (
                checkpoint is not None
                and epoch > 0
                and epoch % checkpoint.every_n_epochs == 0
                and epoch != last_snapshot_epoch
            ):
                pred_schema = (
                    prediction_source.schema()
                    if prediction_source is not None else None
                )
                pending_rows = (
                    list(zip(pending_ts, pending_buf.row_tuples()))
                    if pending_buf is not None else []
                )
                self._snapshot(checkpoint, state, epoch, watermark,
                               open_windows, pending_rows,
                               late_records, consumed,
                               train_schema, pred_schema)
                last_snapshot_epoch = epoch

        # end of streams: every still-open window fires (the watermark
        # advances to infinity), then remaining predictions flush
        if not stopped:
            watermark = None
            for end in sorted(open_windows):
                if stopped:
                    break
                fire_window(end)
        flush_predictions()

        for listener in listeners:
            listener.on_iteration_terminated(context)
        return StreamingResult(
            final_state=state,
            windows_fired=epoch,
            predictions=predictions,
            listener_context=context,
            model_updates=model_updates,
            metrics=metrics,
            late_records=late_records,
        )

    # -- vectorized span path -------------------------------------------------

    def _run_vectorized(
        self,
        initial_state: Any,
        training_source: UnboundedSource,
        update: Callable[[Any, Table, int], Any],
        prediction_source: Optional[UnboundedSource],
        predict: Optional[Callable[[Any, Table], Sequence]],
        listeners: Sequence[IterationListener],
        max_windows: Optional[int],
        train_chunks,
        pred_chunks,
    ) -> StreamingResult:
        """The driver's hot path for time-ordered columnar sources.

        Behaviorally identical to the per-record merge loop (same
        StreamingResult record for record) but executed as span processing:
        each iteration takes the records up to the merge horizon (the
        smaller of the two cursors' buffered max timestamps), groups train
        rows into windows with one ``np.unique`` over window ends, and
        serves prediction segments by ``searchsorted`` event-time cutoffs —
        a prediction at time t sees exactly the model current after every
        window with end <= t fired, the same contract the per-record loop
        enforces record by record.  Ordered streams can never produce late
        records (a record's window end is strictly ahead of the watermark
        it advances), so ``late_records`` is empty by construction.
        """
        from flink_ml_tpu.utils.metrics import StepMetrics

        context = ListenerContext()
        state = initial_state
        window_ms = self.window_ms
        lateness = self.allowed_lateness_ms
        train_schema = training_source.schema()
        metrics = StepMetrics("stream_train")
        predictions: List[Tuple[int, Any]] = []
        model_updates: List[Tuple[int, Any]] = []
        pend = (
            _PendingPredictions(prediction_source.schema())
            if prediction_source is not None else None
        )
        open_ends: List[int] = []  # sorted open window ends
        win_bufs: dict = {}        # end -> [(n_rows, cols_segment), ...]
        epoch = 0
        stopped = False

        tr = _ChunkCursor(train_chunks)
        pr = _ChunkCursor(pred_chunks) if pred_chunks is not None else None

        def serve(cut) -> None:
            """One predict() call over a removed pending slice."""
            if cut is None:
                return
            ts_arr, cols = cut
            outs = list(predict(state, Table.from_columns(pend.schema, cols)))
            if len(outs) != len(ts_arr):
                raise ValueError(
                    f"predict returned {len(outs)} values for a batch of "
                    f"{len(ts_arr)} rows"
                )
            predictions.extend(zip(ts_arr.tolist(), outs))

        from flink_ml_tpu.table.schema import DataTypes

        train_isvec = {
            n: DataTypes.is_vector(t)
            for n, t in zip(train_schema.field_names, train_schema.field_types)
        }

        def fire(end: int) -> None:
            nonlocal state, epoch, stopped
            # predictions timestamped before this window's close see the
            # old model (flush_predictions(before_ts=end) in the per-record
            # loop)
            if pend is not None:
                serve(pend.cut(before_ts=end))
            segs = win_bufs.pop(end)
            n_rows = sum(n for n, _ in segs)
            metrics.start_step()
            cols = {
                name: _concat_col(
                    [c[name] for _, c in segs], train_isvec[name]
                )
                for name in train_schema.field_names
            }
            state = update(state, Table.from_columns(train_schema, cols), epoch)
            metrics.end_step(samples=n_rows, window_end=end)
            if self.keep_model_history:
                model_updates.append((end, state))
            for listener in listeners:
                listener.on_epoch_watermark_incremented(epoch, context)
            epoch += 1
            if max_windows is not None and epoch >= max_windows:
                stopped = True

        while not stopped:
            t_ok = tr.ensure()
            p_ok = pr.ensure() if pr is not None else False
            if not t_ok and not p_ok:
                break
            if t_ok and p_ok:
                horizon = min(tr.buffered_last, pr.buffered_last)
            elif t_ok:
                horizon = tr.buffered_last
            else:
                horizon = pr.buffered_last
            if t_ok:
                ts_t, cols_t = tr.take_upto(horizon)
            else:
                ts_t, cols_t = np.empty(0, np.int64), {}
            ts_p = None
            if pr is not None and p_ok:
                ts_p, cols_p = pr.take_upto(horizon)
                pend.append(ts_p, cols_p)
            if len(ts_t):
                ends = (ts_t // window_ms + 1) * window_ms
                uniq, starts = np.unique(ends, return_index=True)
                bounds = np.append(starts, len(ts_t))
                for i in range(len(uniq)):
                    end = int(uniq[i])
                    a, b = int(bounds[i]), int(bounds[i + 1])
                    buf = win_bufs.get(end)
                    if buf is None:
                        win_bufs[end] = buf = []
                        bisect.insort(open_ends, end)
                    buf.append(
                        (b - a, {k: v[a:b] for k, v in cols_t.items()})
                    )
            watermark = horizon - lateness
            while open_ends and open_ends[0] <= watermark and not stopped:
                end = open_ends.pop(0)
                fire(end)
                if stopped and pend is not None:
                    # the per-record loop stops consuming at the exact
                    # record whose arrival fired this window (the first
                    # with ts >= end + lateness — necessarily in this
                    # span); serve exactly the predictions consumed by
                    # then: ts strictly before it, plus the firing record
                    # itself when that record IS a prediction
                    fire_at = end + lateness
                    cand = []
                    j = int(np.searchsorted(ts_t, fire_at, side="left"))
                    if j < len(ts_t):
                        cand.append((int(ts_t[j]), 0))
                    if ts_p is not None:
                        j = int(np.searchsorted(ts_p, fire_at, side="left"))
                        if j < len(ts_p):
                            cand.append((int(ts_p[j]), 1))
                    if cand:
                        t_fire, kind = min(cand)
                        serve(pend.cut(before_ts=t_fire))
                        if kind == 1:
                            serve(pend.cut(max_rows=1))
            if stopped:
                break
            if pend is not None and pend.count >= self.prediction_flush_rows:
                # early flush: every window with end <= watermark has fired
                # and none can still open there, so the watermark is the
                # safe horizon (see the per-record loop's rationale)
                serve(pend.cut(before_ts=watermark + 1))

        if not stopped:
            # end of streams: every still-open window fires in event-time
            # order (the watermark advances to infinity), then remaining
            # predictions flush with the final state
            while open_ends and not stopped:
                fire(open_ends.pop(0))
            if pend is not None:
                serve(pend.cut())

        for listener in listeners:
            listener.on_iteration_terminated(context)
        return StreamingResult(
            final_state=state,
            windows_fired=epoch,
            predictions=predictions,
            listener_context=context,
            model_updates=model_updates,
            metrics=metrics,
            late_records=[],
        )

    # -- snapshot/restore -----------------------------------------------------

    def _snapshot(self, checkpoint, state, epoch, watermark,
                  open_windows, pending_predictions, late_records, consumed,
                  train_schema, pred_schema):
        """Persist a consistent cut of the stream computation: everything
        needed to continue as if never killed (model state as npz leaves;
        positions and codec-encoded buffers in the JSON sidecar)."""
        from flink_ml_tpu.iteration.checkpoint import (
            prune_checkpoints,
            save_checkpoint,
        )
        from flink_ml_tpu.utils.persistence import encode_row

        meta = {
            "stream": {
                "watermark": watermark,
                "consumed": consumed,
                "windows": {
                    str(end): [
                        encode_row(r, train_schema) for r in buf.row_tuples()
                    ]
                    for end, buf in open_windows.items()
                },
                "pending_predictions": [
                    [ts, encode_row(r, pred_schema)]
                    for ts, r in pending_predictions
                ],
                # the side output is reported exactly once (at stream end),
                # so pre-cut lates must ride the snapshot; served
                # predictions / model history are NOT carried — they were
                # already emitted downstream (see module docstring)
                "late": [
                    [ts, encode_row(r, train_schema)] for ts, r in late_records
                ],
            }
        }
        save_checkpoint(checkpoint.directory, epoch - 1, state, meta=meta)
        prune_checkpoints(checkpoint.directory, checkpoint.keep)

    def _restore(self, checkpoint, like_state, train_schema, prediction_source):
        from flink_ml_tpu.iteration.checkpoint import (
            latest_checkpoint,
            load_checkpoint,
        )
        from flink_ml_tpu.utils.persistence import decode_row

        latest = latest_checkpoint(checkpoint.directory)
        if latest is None:
            return None
        state, meta = load_checkpoint(latest, like=like_state)
        stream = meta.get("stream", {})
        epoch = int(meta["epoch"]) + 1
        pred_schema = (
            prediction_source.schema() if prediction_source is not None else None
        )
        open_windows = {
            int(end): [decode_row(r, train_schema) for r in rows]
            for end, rows in stream.get("windows", {}).items()
        }
        pending = [
            (int(ts), decode_row(r, pred_schema))
            for ts, r in stream.get("pending_predictions", [])
        ]
        late = [
            (int(ts), decode_row(r, train_schema))
            for ts, r in stream.get("late", [])
        ]
        return (
            state,
            epoch,
            stream.get("watermark"),
            open_windows,
            pending,
            late,
            int(stream.get("consumed", 0)),
        )


def iterate_unbounded(
    initial_state: Any,
    training_source: UnboundedSource,
    update: Callable[[Any, Table, int], Any],
    window_ms: int = 5000,
    keep_model_history: bool = False,
    prediction_flush_rows: int = 8192,
    allowed_lateness_ms: int = 0,
    **run_kwargs,
) -> StreamingResult:
    """Functional entry point (Iterations.iterateUnboundedStreams analog)."""
    driver = StreamingDriver(
        window_ms,
        keep_model_history=keep_model_history,
        prediction_flush_rows=prediction_flush_rows,
        allowed_lateness_ms=allowed_lateness_ms,
    )
    return driver.run(initial_state, training_source, update, **run_kwargs)
