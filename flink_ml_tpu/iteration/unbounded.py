"""Unbounded iteration — the streaming mini-batch driver.

Implements the reference's unbounded topology (Iterations.iterateUnboundedStreams
spec, Iterations.java:87-90, and the IncrementalLearningSkeleton shape,
:61-83): a training stream is cut into event-time tumbling windows; each fired
window updates the model (PartialModelBuilder:161-174); a concurrent
prediction stream is served by the *freshest* model (Predictor CoMap:182-211).

TPU-first realization: the driver merges the timestamped streams
deterministically on the host, fires windows when the watermark passes the
window end, and batches all prediction records that fall between two model
updates into one device call — behaviorally identical to per-record CoMap
(every record sees exactly the model that was current at its event time) but
executed as batched XLA instead of a per-record hot loop.

Two ingest paths, same semantics (equivalence-tested record for record):

* **Vectorized span path** — sources that guarantee time order and speak the
  columnar chunk protocol (``UnboundedSource.stream_chunks``, e.g.
  ``ColumnarUnboundedSource``) are processed span-by-span with zero
  per-record Python: window grouping is one ``np.unique`` over window ends,
  prediction/flush cutoffs are ``searchsorted``, and window tables are
  concatenated column slices (matrix-backed vector columns ride zero-copy
  into the update).  This is the hot path — ~40x the merge loop's host
  throughput.
* **Per-record merge loop** — the general path: out-of-order streams
  (watermarks + allowed lateness + late-data side output).

Checkpointing works on BOTH paths without leaving them (the fast path is
the durable path): the span driver snapshots at span boundaries — a span
is a prefix of the deterministic (ts, kind) merge — and the per-record
loop at record boundaries.  Snapshots are columnar (buffers ride the
checkpoint npz as arrays) and record the cut both as a merged-record
count and as per-source counts, so either driver resumes either's
snapshot.

Robustness (the two pieces the reference delegates to Flink's runtime):

* **Bounded out-of-orderness** — ``allowed_lateness_ms`` holds the watermark
  ``L`` behind the max event time seen (the
  BoundedOutOfOrdernessTimestampExtractor the reference's examples assign,
  IncrementalLearningSkeleton.java:144-158 assigns timestamps + watermarks),
  so multiple windows stay open concurrently and a record up to ``L`` late
  still lands in its correct window; records later than that are routed to
  ``StreamingResult.late_records`` (Flink's late-data side output) instead
  of silently corrupting a window.
* **Checkpoint/resume** — with a
  :class:`~flink_ml_tpu.iteration.checkpoint.CheckpointConfig` the driver
  snapshots (model state, watermark, open window buffers, pending
  predictions, stream position) every N fired windows; a killed run resumed
  over the same (replayable) sources fast-forwards to the recorded position
  and continues bit-identically.  The snapshot covers the *continuation*:
  every model update, window firing, and prediction emitted after the
  resume point is bit-identical to the uninterrupted run's.  Outputs
  already **emitted** before the cut — served predictions and the
  ``keep_model_history`` trail — are downstream-owned and are not replayed
  (Flink sink semantics: a restored job does not re-emit records its sinks
  already consumed), so a resumed ``StreamingResult`` lists only
  post-resume emissions.  ``late_records`` is the one output carried in
  the snapshot: the side output is reported exactly once, at stream end,
  so pre-cut lates would otherwise vanish from the final report.

Epoch accounting: window N's model update is epoch N; listeners receive epoch
watermarks exactly as in the bounded runtime.
"""

from __future__ import annotations

import bisect
import functools
import heapq
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from flink_ml_tpu import obs
from flink_ml_tpu.iteration.listener import IterationListener, ListenerContext
from flink_ml_tpu.ops.vector import DenseVector
from flink_ml_tpu.table.schema import Schema
from flink_ml_tpu.table.table import Table
from flink_ml_tpu.table.sources import UnboundedSource


@dataclass
class StreamingResult:
    final_state: Any
    windows_fired: int
    predictions: List[Tuple[int, Any]]  # (event_time, predicted value) per record
    listener_context: ListenerContext
    model_updates: List[Tuple[int, Any]] = field(default_factory=list)  # (window_end, state)
    #: per-window StepMetrics (SURVEY §5.5): wall time + rows per fired window
    metrics: Any = None
    #: training records that arrived after their window closed (beyond the
    #: allowed lateness) — the late-data side output, never silently dropped
    late_records: List[Tuple[int, Tuple]] = field(default_factory=list)


class _ColumnBuffer:
    """Window/prediction record buffer with a bulk columnar fire path.

    The driver exists to replace the reference's per-record CoMap hot loop
    (IncrementalLearningSkeleton.java:182-211), so its own buffering must
    stay off the per-record path: the hot loop is ONE list append of the
    row tuple; all columnar work happens per fired batch — ``zip(*rows)``
    transposes at C speed and a dense-vector column stacks into one
    matrix-backed ``(n, d)`` array, so the fired Table skips from_rows'
    per-cell work AND the update fn's ``features_dense`` becomes zero-copy
    instead of re-densifying 1000 DenseVector objects per window.
    """

    def __init__(self, schema: Schema):
        from flink_ml_tpu.table.schema import DataTypes

        self.schema = schema
        self._names = schema.field_names
        self._vec = [DataTypes.is_vector(t) for t in schema.field_types]
        self.rows: List[Tuple] = []

    def __len__(self) -> int:
        return len(self.rows)

    def append(self, row) -> None:
        row = tuple(row)  # no-op copy when row is already a tuple
        if len(row) != len(self._names):
            raise ValueError(
                f"row arity {len(row)} != schema arity {len(self._names)}"
            )
        self.rows.append(row)

    def insert(self, i: int, row) -> None:
        row = tuple(row)
        if len(row) != len(self._names):
            raise ValueError(
                f"row arity {len(row)} != schema arity {len(self._names)}"
            )
        self.rows.insert(i, row)

    @staticmethod
    def _column(col: tuple, is_vec: bool):
        if not is_vec:
            return np.asarray(col)
        if col and all(type(v) is DenseVector for v in col):
            try:
                arr = np.asarray([v.values for v in col])
            except ValueError:  # ragged widths refuse to stack (numpy >=1.24)
                return list(col)
            if arr.ndim == 2:
                return arr  # matrix-backed dense-vector column
        return list(col)  # sparse / mixed widths: object column

    def take(self, cut: Optional[int] = None) -> Table:
        """Table of rows [0:cut] (default: all), removed from the buffer."""
        rows = self.rows[:cut] if cut is not None else self.rows
        self.rows = self.rows[cut:] if cut is not None else []
        if not rows:
            return Table.from_columns(
                self.schema, {n: [] for n in self._names}
            )
        cols = {
            n: self._column(col, vec)
            for n, vec, col in zip(self._names, self._vec, zip(*rows))
        }
        return Table.from_columns(self.schema, cols)

    def row_tuples(self) -> List[Tuple]:
        """Rows as tuples (snapshot codec path — rare, off the hot loop)."""
        return list(self.rows)

    def columns(self) -> Tuple[int, dict]:
        """``(n_rows, cols)`` without consuming the buffer (snapshot path:
        the same bulk transpose as :meth:`take`, but non-destructive)."""
        if not self.rows:
            return 0, {n: [] for n in self._names}
        cols = {
            n: self._column(col, vec)
            for n, vec, col in zip(self._names, self._vec, zip(*self.rows))
        }
        return len(self.rows), cols


def _concat_col(segs: List, is_vector: bool = False):
    """Concatenate column segments (ndarray -> np.concatenate, list -> +).

    Adjacent chunks of the same vector column may columnize differently
    (matrix-backed vs object list — e.g. one ragged or sparse row in one
    chunk); the mixed/ragged fallback re-wraps matrix rows as DenseVectors
    so the result is a valid object vector column, never bare 1-D arrays.
    """
    if len(segs) == 1:
        return segs[0]
    if all(isinstance(s, np.ndarray) for s in segs):
        try:
            return np.concatenate(segs)
        except ValueError:
            pass  # ragged widths across chunks: object-column fallback
    out: List = []
    for s in segs:
        if is_vector and isinstance(s, np.ndarray) and s.ndim == 2:
            out.extend(DenseVector(r) for r in s)
        else:
            out.extend(s)
    return out


class _ChunkCursor:
    """Buffered reader over a ``stream_chunks()`` iterator.

    Validates the protocol's time-order contract (within and across chunks)
    and hands out prefix spans by timestamp horizon — the vectorized
    driver's only per-chunk bookkeeping."""

    def __init__(self, chunk_iter):
        self._it = iter(chunk_iter)
        self.ts: Optional[np.ndarray] = None
        self.cols: Optional[dict] = None
        self.exhausted = False
        self._last_seen: Optional[int] = None

    def ensure(self) -> bool:
        """Buffer a non-empty chunk if none held; False once exhausted."""
        while not self.exhausted and (self.ts is None or len(self.ts) == 0):
            nxt = next(self._it, None)
            if nxt is None:
                self.exhausted = True
                self.ts = None
                self.cols = None
                return False
            ts, cols = nxt
            ts = np.asarray(ts, np.int64)
            if len(ts) == 0:
                continue
            if (
                (self._last_seen is not None and int(ts[0]) < self._last_seen)
                or np.any(np.diff(ts) < 0)
            ):
                raise ValueError(
                    "stream_chunks yielded out-of-order timestamps; the "
                    "chunk protocol requires non-decreasing event time — "
                    "use the per-record UnboundedSource.stream() path for "
                    "out-of-order streams"
                )
            self._last_seen = int(ts[-1])
            self.ts, self.cols = ts, cols
        return self.ts is not None and len(self.ts) > 0

    @property
    def buffered_last(self) -> int:
        return int(self.ts[-1])

    def take_upto(self, horizon: int):
        """Split off the buffered prefix with ts <= horizon."""
        cut = int(np.searchsorted(self.ts, horizon, side="right"))
        out = (self.ts[:cut], {k: v[:cut] for k, v in self.cols.items()})
        self.ts = self.ts[cut:]
        self.cols = {k: v[cut:] for k, v in self.cols.items()}
        return out

    def skip_rows(self, n: int) -> None:
        """Drop the next ``n`` records (checkpoint resume fast-forward: the
        snapshot records per-source consumed counts, and chunk streams are
        replayed from the start)."""
        while n > 0 and self.ensure():
            k = min(n, len(self.ts))
            self.ts = self.ts[k:]
            self.cols = {c: v[k:] for c, v in self.cols.items()}
            n -= k
        if n > 0:
            raise ValueError(
                f"resume position is {n} records past the end of the "
                "replayed stream — the source is shorter than at snapshot "
                "time (sources must be replayable for checkpointed runs)"
            )


class _PendingPredictions:
    """Pending prediction records as columnar segments, served by
    event-time cutoff — the vectorized replacement for the per-record
    sorted-insert pending buffer (arrival is time-ordered here, so
    segments are globally sorted by construction)."""

    def __init__(self, schema: Schema):
        from flink_ml_tpu.table.schema import DataTypes

        self.schema = schema
        self._is_vec = {
            n: DataTypes.is_vector(t)
            for n, t in zip(schema.field_names, schema.field_types)
        }
        self._segs: List[Tuple[np.ndarray, dict]] = []
        self.count = 0

    def append(self, ts: np.ndarray, cols: dict) -> None:
        if len(ts):
            self._segs.append((ts, cols))
            self.count += len(ts)

    def cut(self, before_ts: Optional[int] = None,
            max_rows: Optional[int] = None):
        """Remove and return ``(ts_array, cols)`` for records with
        ts < before_ts (all records when None), capped at ``max_rows``."""
        take_ts: List[np.ndarray] = []
        take_cols: List[dict] = []
        budget = self.count if max_rows is None else int(max_rows)
        while self._segs and budget > 0:
            ts, cols = self._segs[0]
            n = len(ts) if before_ts is None else int(
                np.searchsorted(ts, before_ts, side="left")
            )
            n = min(n, budget)
            if n == 0:
                break
            if n == len(ts):
                self._segs.pop(0)
                take_ts.append(ts)
                take_cols.append(cols)
            else:
                take_ts.append(ts[:n])
                take_cols.append({k: v[:n] for k, v in cols.items()})
                self._segs[0] = (
                    ts[n:], {k: v[n:] for k, v in cols.items()}
                )
            budget -= n
            self.count -= n
        if not take_ts:
            return None
        names = self.schema.field_names
        return (
            np.concatenate(take_ts),
            {
                n: _concat_col([c[n] for c in take_cols], self._is_vec[n])
                for n in names
            },
        )

    def peek_all(self):
        """All pending records as ``(ts_array, cols)`` WITHOUT consuming
        them (snapshot payload), or None when empty."""
        if not self._segs:
            return None
        names = self.schema.field_names
        return (
            np.concatenate([ts for ts, _ in self._segs]),
            {
                n: _concat_col(
                    [c[n] for _, c in self._segs], self._is_vec[n]
                )
                for n in names
            },
        )


def _encode_buffer_cols(prefix: str, cols: dict, schema: Schema,
                        aux: dict) -> dict:
    """Encode one columnar buffer for a snapshot.

    ndarray columns (scalar columns, matrix-backed dense-vector columns)
    ride the checkpoint npz verbatim under ``prefix.name`` — the vectorized
    fast path, no per-row work.  Object vector columns (sparse/ragged) fall
    back to per-row codec strings; plain python lists go into the JSON
    sidecar.  Returns the JSON-side column spec.
    """
    from flink_ml_tpu.ops.codec import vector_to_string
    from flink_ml_tpu.table.schema import DataTypes

    spec: dict = {}
    for name, typ in zip(schema.field_names, schema.field_types):
        v = cols[name]
        if isinstance(v, np.ndarray) and v.dtype != object:
            key = f"{prefix}.{name}"
            aux[key] = v
            spec[name] = {"kind": "npz"}
        elif DataTypes.is_vector(typ):
            spec[name] = {
                "kind": "vec_rows",
                "rows": [None if x is None else vector_to_string(x) for x in v],
            }
        else:
            from flink_ml_tpu.utils.persistence import _encode_value

            spec[name] = {
                "kind": "list",
                "values": [_encode_value(x, typ) for x in v],
            }
    return spec


def _decode_buffer_cols(prefix: str, spec: dict, schema: Schema,
                        aux: dict) -> dict:
    """Inverse of :func:`_encode_buffer_cols`."""
    from flink_ml_tpu.ops.codec import parse_vector
    from flink_ml_tpu.utils.persistence import _decode_value

    cols: dict = {}
    for name, typ in zip(schema.field_names, schema.field_types):
        s = spec[name]
        if s["kind"] == "npz":
            cols[name] = aux[f"{prefix}.{name}"]
        elif s["kind"] == "vec_rows":
            cols[name] = [
                None if x is None else parse_vector(x) for x in s["rows"]
            ]
        else:
            cols[name] = [_decode_value(x, typ) for x in s["values"]]
    return cols


def _cols_to_rows(n: int, cols: dict, schema: Schema) -> List[Tuple]:
    """Columnar buffer -> row tuples (per-record-loop restore): rows of a
    matrix-backed vector column come back as DenseVectors."""
    from flink_ml_tpu.table.schema import DataTypes

    per_col = []
    for name, typ in zip(schema.field_names, schema.field_types):
        v = cols[name]
        if (
            DataTypes.is_vector(typ)
            and isinstance(v, np.ndarray) and v.ndim == 2
        ):
            per_col.append([DenseVector(r) for r in v])
        else:
            per_col.append(list(v))
    return list(zip(*per_col)) if per_col else [()] * n


def _own_state(state):
    """Driver-thread defensive copy of mutable state leaves before handing
    the pytree to the background snapshot writer: jax arrays are immutable
    (and fetched on the writer thread, off the hot path), but a user update
    fn that mutates a numpy leaf in place would otherwise race the write."""
    import jax

    return jax.tree_util.tree_map(
        lambda a: a.copy() if isinstance(a, np.ndarray) else a, state
    )


class _AsyncCheckpointer:
    """Background snapshot writer — Flink-style asynchronous checkpointing
    with at most one snapshot in flight.

    The driver thread only BUILDS the payload (cheap columnar views /
    fresh arrays); the device-state fetch (`np.asarray` on jax arrays —
    ~100 ms per call on a tunneled backend) and the npz/json writes happen
    on the writer thread while the stream keeps processing.  A snapshot
    requested while the previous one is still writing is skipped (Flink's
    max-concurrent-checkpoints=1), which self-rate-limits to what the
    storage path sustains.  Failures warn rather than kill the stream; the
    final pending write is drained before the run returns.
    """

    def __init__(self):
        from concurrent.futures import ThreadPoolExecutor

        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="stream-ckpt"
        )
        self._pending = None

    def can_submit(self) -> bool:
        """True when no snapshot is in flight — callers gate PAYLOAD
        CONSTRUCTION on this, so a busy writer costs the hot loop one
        method call, not a discarded payload build."""
        return self._pending is None or self._pending.done()

    def submit(self, fn) -> bool:
        """Run ``fn`` on the writer thread; False when one is in flight."""
        if self._pending is not None:
            if not self._pending.done():
                return False
            self._check(self._pending)
        self._pending = self._executor.submit(fn)
        return True

    @staticmethod
    def _check(future) -> None:
        err = future.exception()
        if err is not None:
            import warnings

            warnings.warn(
                f"streaming snapshot failed (stream continues without this "
                f"checkpoint): {err!r}",
                stacklevel=3,
            )

    def drain(self) -> None:
        """Wait for the in-flight snapshot to commit (end of run)."""
        if self._pending is not None:
            from concurrent.futures import wait as _wait

            _wait([self._pending])
            self._check(self._pending)
            self._pending = None
        self._executor.shutdown(wait=True)


def _nothing_to_save() -> None:
    """Preempted before the first window fired (epoch 0): the snapshot
    format is keyed by completed epochs, and a restart from scratch over
    replayable sources IS the committed resume point — the emergency
    epilogue commits nothing and still exits cleanly."""


def _preempted() -> bool:
    """Has a SIGTERM landed in the current preemption scope?  (Lazy
    import, memoized: the per-record loop polls this per record.)"""
    global _GUARD
    if _GUARD is None:
        from flink_ml_tpu.fault import guard

        _GUARD = guard
    return _GUARD.preempted()


_GUARD = None


def _merge_streams(streams: Sequence[Iterator]) -> Iterator:
    """Deterministic k-way merge by (event_time, kind), stream-stable ties.

    For time-ordered sources this is an exact event-time merge (training
    sorts before prediction at equal timestamps, so a model update at time T
    serves a prediction at time T — matching connect() delivering the model
    first).  For out-of-order sources ``heapq.merge`` degrades gracefully to
    a deterministic head-of-stream arrival order, which the watermark
    machinery then handles; rows are never compared (the key excludes them).
    """
    return heapq.merge(*streams, key=lambda e: (e[0], e[1]))


class StreamingDriver:
    """Event-time tumbling-window trainer with a concurrent prediction path.

    ``update(state, window_table, epoch) -> state`` fires per completed window
    (the PartialModelBuilder role).  ``predict(state, batch_table) ->
    sequence`` serves the prediction stream with the current model (the
    Predictor role); it may return any per-row sequence (list/array).
    """

    def __init__(
        self,
        window_ms: int,
        keep_model_history: bool = False,
        prediction_flush_rows: int = 8192,
        allowed_lateness_ms: int = 0,
    ):
        if window_ms <= 0:
            raise ValueError("window_ms must be positive")
        if allowed_lateness_ms < 0:
            raise ValueError("allowed_lateness_ms must be >= 0")
        self.window_ms = int(window_ms)
        self.keep_model_history = keep_model_history
        # predictions sharing one model version can flush early in batches of
        # this size — bounds prediction latency on long-running streams
        self.prediction_flush_rows = prediction_flush_rows
        self.allowed_lateness_ms = int(allowed_lateness_ms)

    def run(
        self,
        initial_state: Any,
        training_source: UnboundedSource,
        update: Callable[[Any, Table, int], Any],
        prediction_source: Optional[UnboundedSource] = None,
        predict: Optional[Callable[[Any, Table], Sequence]] = None,
        listeners: Sequence[IterationListener] = (),
        max_windows: Optional[int] = None,
        checkpoint=None,
    ) -> StreamingResult:
        """Drive the stream to completion (see class docstring).

        With a checkpoint config the run executes inside the preemption
        scope (the fault layer's contract for every checkpointed driver):
        a SIGTERM is polled at record/span boundaries, an emergency
        snapshot commits synchronously, and :class:`~flink_ml_tpu.fault.
        guard.Preempted` exits the process cleanly — a restarted run over
        the same (replayable) sources resumes bit-identically.
        """
        if checkpoint is None:
            return self._run(initial_state, training_source, update,
                             prediction_source, predict, listeners,
                             max_windows, checkpoint)
        from flink_ml_tpu.fault import guard

        with guard.preemption_scope():
            return self._run(initial_state, training_source, update,
                             prediction_source, predict, listeners,
                             max_windows, checkpoint)

    def _run(
        self,
        initial_state: Any,
        training_source: UnboundedSource,
        update: Callable[[Any, Table, int], Any],
        prediction_source: Optional[UnboundedSource] = None,
        predict: Optional[Callable[[Any, Table], Sequence]] = None,
        listeners: Sequence[IterationListener] = (),
        max_windows: Optional[int] = None,
        checkpoint=None,
    ) -> StreamingResult:
        if (prediction_source is None) != (predict is None):
            raise ValueError("prediction_source and predict must be given together")

        # time-ordered sources that speak the columnar chunk protocol take
        # the vectorized span path: zero per-record Python on ingest
        # (windowing/cutoffs are searchsorted over chunk arrays), with or
        # without checkpointing — snapshots are columnar and cut at span
        # boundaries (VERDICT r4 #2: the fast path IS the durable path).
        # The per-record merge loop below remains the path for
        # out-of-order streams (watermarks/lateness/late side output).
        train_chunks = (
            training_source.stream_chunks()
            if hasattr(training_source, "stream_chunks") else None
        )
        if train_chunks is not None:
            pred_chunks = (
                prediction_source.stream_chunks()
                if prediction_source is not None else None
            )
            if prediction_source is None or pred_chunks is not None:
                return self._run_vectorized(
                    initial_state, training_source, update,
                    prediction_source, predict, listeners, max_windows,
                    train_chunks, pred_chunks, checkpoint,
                )

        from flink_ml_tpu.utils.metrics import StepMetrics

        context = ListenerContext()
        state = initial_state
        window_ms = self.window_ms
        lateness = self.allowed_lateness_ms
        train_schema = training_source.schema()
        metrics = StepMetrics("stream_train")

        TRAIN, PREDICT = 0, 1
        streams: List[Iterator] = [
            ((ts, TRAIN, row) for ts, row in training_source.stream())
        ]
        if prediction_source is not None:
            streams.append(((ts, PREDICT, row) for ts, row in prediction_source.stream()))
        merged = _merge_streams(streams)

        # open windows keyed by window end; several stay open when the
        # watermark lags max event time by the allowed lateness.  Buffers
        # are columnar (_ColumnBuffer) — the hot loop appends values, never
        # builds row objects or per-row Tables.
        open_windows: dict = {}
        pending_ts: List[int] = []
        pending_buf = (
            _ColumnBuffer(prediction_source.schema())
            if prediction_source is not None else None
        )
        predictions: List[Tuple[int, Any]] = []
        model_updates: List[Tuple[int, Any]] = []
        late_records: List[Tuple[int, Tuple]] = []
        watermark: Optional[int] = None
        epoch = 0
        consumed = 0  # records taken from the merged stream (for resume)
        consumed_train = 0  # per-source counts: the span driver's resume cut
        consumed_pred = 0
        last_snapshot_epoch = -1
        last_snapshot_time = time.monotonic()
        stopped = False

        if checkpoint is not None:
            pred_schema = (
                prediction_source.schema()
                if prediction_source is not None else None
            )
            restored = self._load_snapshot(checkpoint, state, train_schema,
                                           pred_schema)
            if restored is not None:
                state = restored["state"]
                epoch = restored["epoch"]
                watermark = restored["watermark"]
                late_records = restored["late"]
                for end, (n, cols) in restored["windows"].items():
                    buf = open_windows[end] = _ColumnBuffer(train_schema)
                    for row in _cols_to_rows(n, cols, train_schema):
                        buf.append(row)
                if restored["pending"] is not None and pending_buf is not None:
                    ts_arr, cols = restored["pending"]
                    pred_schema_ = pending_buf.schema
                    rows = _cols_to_rows(len(ts_arr), cols, pred_schema_)
                    for ts, row in zip(ts_arr.tolist(), rows):
                        pending_ts.append(int(ts))
                        pending_buf.append(row)
                skip = restored["consumed"]
                for done in range(skip):
                    if next(merged, None) is None:
                        # same loud contract as _ChunkCursor.skip_rows: a
                        # short replay would otherwise "resume" into a
                        # silently empty continuation
                        raise ValueError(
                            f"resume position is {skip - done} records "
                            "past the end of the replayed stream — the "
                            "source is shorter than at snapshot time "
                            "(sources must be replayable for checkpointed "
                            "runs)"
                        )
                consumed = skip
                consumed_train = restored["consumed_train"]
                consumed_pred = restored["consumed_pred"]

        def flush_predictions(before_ts: Optional[int] = None):
            """Serve pending predictions with the current model; with
            ``before_ts`` only those event-timed before it (they precede the
            imminent model update in event time)."""
            if predict is None or not pending_ts:
                return
            if before_ts is None:
                cut = len(pending_ts)
            else:
                # pending is kept event-time-sorted at insertion, so the
                # cutoff is one bisect — a saturated buffer of past-watermark
                # predictions costs O(log n) comparisons per record (O(n)
                # shift only on out-of-order mid-list inserts), not a
                # rebuilt O(n) filter
                cut = bisect.bisect_left(pending_ts, before_ts)
                if cut == 0:
                    return
            ts_batch = pending_ts[:cut]
            del pending_ts[:cut]
            batch = pending_buf.take(cut)
            outs = list(predict(state, batch))
            if len(outs) != len(ts_batch):
                raise ValueError(
                    f"predict returned {len(outs)} values for a batch of "
                    f"{len(ts_batch)} rows"
                )
            predictions.extend(zip(ts_batch, outs))

        def fire_window(end_ts: int):
            nonlocal state, epoch, stopped
            # predictions timestamped before this window's close see the old model
            flush_predictions(before_ts=end_ts)
            buf = open_windows.pop(end_ts)
            n_rows = len(buf)
            metrics.start_step()
            table = buf.take()
            state = update(state, table, epoch)
            metrics.end_step(samples=n_rows, window_end=end_ts)
            obs.counter_add("iteration.unbounded.windows")
            obs.counter_add("iteration.unbounded.rows", n_rows)
            # feedback-queue depth: windows still buffering + predictions
            # awaiting a final model — the driver's backlog at this fire
            obs.gauge_set("iteration.unbounded.open_windows",
                          len(open_windows))
            obs.gauge_set("iteration.unbounded.pending_predictions",
                          len(pending_ts))
            if self.keep_model_history:
                model_updates.append((end_ts, state))
            for listener in listeners:
                listener.on_epoch_watermark_incremented(epoch, context)
            epoch += 1
            if max_windows is not None and epoch >= max_windows:
                stopped = True

        def fire_ready():
            """Fire every open window whose end the watermark passed, in
            event-time order."""
            while not stopped:
                ready = [e for e in open_windows if watermark is not None and e <= watermark]
                if not ready:
                    return
                fire_window(min(ready))

        def record_snapshot():
            """The snapshot payload at the CURRENT record boundary, as the
            writer-thread callable — shared by the periodic submit and the
            preemption path so both commit the same consistent cut."""
            pred_schema = (
                prediction_source.schema()
                if prediction_source is not None else None
            )
            pending = None
            if pending_buf is not None:
                _, pcols = pending_buf.columns()
                pending = (np.asarray(pending_ts, np.int64), pcols)
            return functools.partial(
                self._snapshot,
                checkpoint, _own_state(state), epoch, watermark,
                {end: buf.columns()
                 for end, buf in open_windows.items()},
                pending, list(late_records), consumed,
                consumed_train, consumed_pred, train_schema,
                pred_schema,
            )

        ckptr = _AsyncCheckpointer() if checkpoint is not None else None
        try:
            for ts, kind, row in merged:
                if checkpoint is not None and _preempted():
                    # a record boundary is a consistent cut: commit the
                    # emergency snapshot synchronously (behind any
                    # in-flight periodic write) and exit cleanly
                    ckptr.drain()
                    self._emergency(
                        record_snapshot() if epoch > 0 else _nothing_to_save
                    )
                consumed += 1
                new_wm = ts - lateness
                if watermark is None or new_wm > watermark:
                    watermark = new_wm
                if kind == TRAIN:
                    consumed_train += 1
                    end = (ts // window_ms + 1) * window_ms
                    if watermark is not None and end <= watermark:
                        # the watermark passed this window's end (it fired, or
                        # would have fired empty): beyond the allowed lateness —
                        # side output, loudly kept (Flink's isWindowLate rule)
                        late_records.append((ts, tuple(row)))
                    else:
                        buf = open_windows.get(end)
                        if buf is None:
                            buf = open_windows[end] = _ColumnBuffer(train_schema)
                        buf.append(row)
                else:
                    consumed_pred += 1
                    # kept ts-sorted so flush cutoffs are a bisect; arrival is
                    # near-ordered, so the insert lands at (or near) the tail
                    i = bisect.bisect_right(pending_ts, ts)
                    if i == len(pending_ts):
                        pending_ts.append(ts)
                        pending_buf.append(row)
                    else:
                        pending_ts.insert(i, ts)
                        pending_buf.insert(i, row)
                fire_ready()
                if stopped:
                    break
                if len(pending_ts) >= self.prediction_flush_rows:
                    # an early flush may only serve predictions whose model is
                    # final: a record at t must see every window with end <= t
                    # fired first.  After fire_ready() every window with
                    # end <= watermark HAS fired, and no window with
                    # end <= watermark can still open (later trains there would
                    # be late), so the watermark is exactly the safe horizon.
                    # Bounding by min(open_windows) instead would be wrong
                    # twice over: a window with an earlier end than any open one
                    # can still open while the watermark lags by the allowed
                    # lateness, and before fire_ready() an about-to-fire window
                    # would be skipped.  Pending predictions past the watermark
                    # stay buffered — bounded by the lateness horizon, not by
                    # prediction_flush_rows.
                    flush_predictions(
                        before_ts=watermark + 1 if watermark is not None else None
                    )
                if (
                    checkpoint is not None
                    and epoch > 0
                    and epoch % checkpoint.every_n_epochs == 0
                    and epoch != last_snapshot_epoch
                    and (time.monotonic() - last_snapshot_time
                         >= checkpoint.min_interval_s)
                    and ckptr.can_submit()
                ):
                    submitted = ckptr.submit(record_snapshot())
                    if submitted:
                        last_snapshot_epoch = epoch
                        last_snapshot_time = time.monotonic()

            # end of streams: every still-open window fires (the watermark
            # advances to infinity), then remaining predictions flush
            if not stopped:
                watermark = None
                for end in sorted(open_windows):
                    if stopped:
                        break
                    fire_window(end)
            flush_predictions()
        finally:
            # wait for the in-flight background snapshot to commit —
            # also on a crash, so a kill-and-restart resumes from it
            if ckptr is not None:
                ckptr.drain()

        for listener in listeners:
            listener.on_iteration_terminated(context)
        return StreamingResult(
            final_state=state,
            windows_fired=epoch,
            predictions=predictions,
            listener_context=context,
            model_updates=model_updates,
            metrics=metrics,
            late_records=late_records,
        )

    # -- vectorized span path -------------------------------------------------

    def _run_vectorized(
        self,
        initial_state: Any,
        training_source: UnboundedSource,
        update: Callable[[Any, Table, int], Any],
        prediction_source: Optional[UnboundedSource],
        predict: Optional[Callable[[Any, Table], Sequence]],
        listeners: Sequence[IterationListener],
        max_windows: Optional[int],
        train_chunks,
        pred_chunks,
        checkpoint=None,
    ) -> StreamingResult:
        """The driver's hot path for time-ordered columnar sources.

        Behaviorally identical to the per-record merge loop (same
        StreamingResult record for record) but executed as span processing:
        each iteration takes the records up to the merge horizon (the
        smaller of the two cursors' buffered max timestamps), groups train
        rows into windows with one ``np.unique`` over window ends, and
        serves prediction segments by ``searchsorted`` event-time cutoffs —
        a prediction at time t sees exactly the model current after every
        window with end <= t fired, the same contract the per-record loop
        enforces record by record.  Ordered streams can never produce late
        records (a record's window end is strictly ahead of the watermark
        it advances), so new ``late_records`` are impossible by
        construction (a resumed per-record snapshot may carry some).

        Checkpointing does NOT leave this path (VERDICT r4 #2): snapshots
        cut at span boundaries — a span is a prefix of the deterministic
        (ts, kind) merge, so the columnar buffers (open window segments,
        pending predictions) plus per-source consumed counts ARE the
        snapshot payload, written columnar into the checkpoint npz.
        """
        from flink_ml_tpu.utils.metrics import StepMetrics

        context = ListenerContext()
        state = initial_state
        window_ms = self.window_ms
        lateness = self.allowed_lateness_ms
        train_schema = training_source.schema()
        metrics = StepMetrics("stream_train")
        predictions: List[Tuple[int, Any]] = []
        model_updates: List[Tuple[int, Any]] = []
        pend = (
            _PendingPredictions(prediction_source.schema())
            if prediction_source is not None else None
        )
        open_ends: List[int] = []  # sorted open window ends
        win_bufs: dict = {}        # end -> [(n_rows, cols_segment), ...]
        epoch = 0
        stopped = False
        late_records: List[Tuple[int, Tuple]] = []
        consumed_train = 0
        consumed_pred = 0
        last_snapshot_epoch = -1
        last_snapshot_time = time.monotonic()

        tr = _ChunkCursor(train_chunks)
        pr = _ChunkCursor(pred_chunks) if pred_chunks is not None else None

        if checkpoint is not None:
            restored = self._load_snapshot(
                checkpoint, state, train_schema,
                pend.schema if pend is not None else None,
            )
            if restored is not None:
                state = restored["state"]
                epoch = restored["epoch"]
                late_records = restored["late"]
                for end, (n, cols) in sorted(restored["windows"].items()):
                    win_bufs[end] = [(n, cols)]
                    open_ends.append(end)
                if restored["pending"] is not None and pend is not None:
                    ts_arr, cols = restored["pending"]
                    pend.append(ts_arr, cols)
                # fast-forward the replayed chunk streams to the cut
                tr.skip_rows(restored["consumed_train"])
                if pr is not None:
                    pr.skip_rows(restored["consumed_pred"])
                consumed_train = restored["consumed_train"]
                consumed_pred = restored["consumed_pred"]

        def serve(cut) -> None:
            """One predict() call over a removed pending slice."""
            if cut is None:
                return
            ts_arr, cols = cut
            outs = list(predict(state, Table.from_columns(pend.schema, cols)))
            if len(outs) != len(ts_arr):
                raise ValueError(
                    f"predict returned {len(outs)} values for a batch of "
                    f"{len(ts_arr)} rows"
                )
            predictions.extend(zip(ts_arr.tolist(), outs))

        from flink_ml_tpu.table.schema import DataTypes

        train_isvec = {
            n: DataTypes.is_vector(t)
            for n, t in zip(train_schema.field_names, train_schema.field_types)
        }

        def fire(end: int) -> None:
            nonlocal state, epoch, stopped
            # predictions timestamped before this window's close see the
            # old model (flush_predictions(before_ts=end) in the per-record
            # loop)
            if pend is not None:
                serve(pend.cut(before_ts=end))
            segs = win_bufs.pop(end)
            n_rows = sum(n for n, _ in segs)
            metrics.start_step()
            cols = {
                name: _concat_col(
                    [c[name] for _, c in segs], train_isvec[name]
                )
                for name in train_schema.field_names
            }
            state = update(state, Table.from_columns(train_schema, cols), epoch)
            metrics.end_step(samples=n_rows, window_end=end)
            obs.counter_add("iteration.unbounded.windows")
            obs.counter_add("iteration.unbounded.rows", n_rows)
            obs.gauge_set("iteration.unbounded.open_windows", len(win_bufs))
            obs.gauge_set(
                "iteration.unbounded.pending_predictions",
                pend.count if pend is not None else 0,
            )
            if self.keep_model_history:
                model_updates.append((end, state))
            for listener in listeners:
                listener.on_epoch_watermark_incremented(epoch, context)
            epoch += 1
            if max_windows is not None and epoch >= max_windows:
                stopped = True

        def span_snapshot(watermark):
            """The snapshot payload at the CURRENT span boundary, as the
            writer-thread callable: the open window segments and pending
            buffer are already columnar — they go into the snapshot npz
            as-is.  Shared by the periodic submit and the preemption path
            so both commit the same consistent merge-prefix cut."""
            windows_cols = {
                end: (
                    sum(n for n, _ in segs),
                    {
                        name: _concat_col(
                            [c[name] for _, c in segs],
                            train_isvec[name],
                        )
                        for name in train_schema.field_names
                    },
                )
                for end, segs in win_bufs.items()
            }
            return functools.partial(
                self._snapshot,
                checkpoint, _own_state(state), epoch, watermark,
                windows_cols,
                pend.peek_all() if pend is not None else None,
                list(late_records), consumed_train + consumed_pred,
                consumed_train, consumed_pred, train_schema,
                pend.schema if pend is not None else None,
            )

        ckptr = _AsyncCheckpointer() if checkpoint is not None else None
        try:
            while not stopped:
                t_ok = tr.ensure()
                p_ok = pr.ensure() if pr is not None else False
                if not t_ok and not p_ok:
                    break
                if t_ok and p_ok:
                    horizon = min(tr.buffered_last, pr.buffered_last)
                elif t_ok:
                    horizon = tr.buffered_last
                else:
                    horizon = pr.buffered_last
                if t_ok:
                    ts_t, cols_t = tr.take_upto(horizon)
                    consumed_train += len(ts_t)
                else:
                    ts_t, cols_t = np.empty(0, np.int64), {}
                ts_p = None
                if pr is not None and p_ok:
                    ts_p, cols_p = pr.take_upto(horizon)
                    consumed_pred += len(ts_p)
                    pend.append(ts_p, cols_p)
                if len(ts_t):
                    ends = (ts_t // window_ms + 1) * window_ms
                    uniq, starts = np.unique(ends, return_index=True)
                    bounds = np.append(starts, len(ts_t))
                    for i in range(len(uniq)):
                        end = int(uniq[i])
                        a, b = int(bounds[i]), int(bounds[i + 1])
                        buf = win_bufs.get(end)
                        if buf is None:
                            win_bufs[end] = buf = []
                            bisect.insort(open_ends, end)
                        buf.append(
                            (b - a, {k: v[a:b] for k, v in cols_t.items()})
                        )
                watermark = horizon - lateness
                while open_ends and open_ends[0] <= watermark and not stopped:
                    end = open_ends.pop(0)
                    fire(end)
                    if stopped and pend is not None:
                        # the per-record loop stops consuming at the exact
                        # record whose arrival fired this window (the first
                        # with ts >= end + lateness — necessarily in this
                        # span); serve exactly the predictions consumed by
                        # then: ts strictly before it, plus the firing record
                        # itself when that record IS a prediction
                        fire_at = end + lateness
                        cand = []
                        j = int(np.searchsorted(ts_t, fire_at, side="left"))
                        if j < len(ts_t):
                            cand.append((int(ts_t[j]), 0))
                        if ts_p is not None:
                            j = int(np.searchsorted(ts_p, fire_at, side="left"))
                            if j < len(ts_p):
                                cand.append((int(ts_p[j]), 1))
                        if cand:
                            t_fire, kind = min(cand)
                            serve(pend.cut(before_ts=t_fire))
                            if kind == 1:
                                serve(pend.cut(max_rows=1))
                if stopped:
                    break
                if pend is not None and pend.count >= self.prediction_flush_rows:
                    # early flush: every window with end <= watermark has fired
                    # and none can still open there, so the watermark is the
                    # safe horizon (see the per-record loop's rationale)
                    serve(pend.cut(before_ts=watermark + 1))
                if (
                    checkpoint is not None
                    and epoch > 0
                    and epoch - last_snapshot_epoch >= checkpoint.every_n_epochs
                    and (time.monotonic() - last_snapshot_time
                         >= checkpoint.min_interval_s)
                    and ckptr.can_submit()
                ):
                    submitted = ckptr.submit(span_snapshot(watermark))
                    if submitted:
                        last_snapshot_epoch = epoch
                        last_snapshot_time = time.monotonic()
                if checkpoint is not None and _preempted():
                    # a span boundary is a consistent cut too: commit the
                    # emergency snapshot synchronously (behind any
                    # in-flight periodic write) and exit cleanly
                    ckptr.drain()
                    self._emergency(
                        span_snapshot(watermark) if epoch > 0
                        else _nothing_to_save
                    )

            if not stopped:
                # end of streams: every still-open window fires in event-time
                # order (the watermark advances to infinity), then remaining
                # predictions flush with the final state
                while open_ends and not stopped:
                    fire(open_ends.pop(0))
                if pend is not None:
                    serve(pend.cut())
        finally:
            # wait for the in-flight background snapshot to commit —
            # also on a crash, so a kill-and-restart resumes from it
            if ckptr is not None:
                ckptr.drain()

        for listener in listeners:
            listener.on_iteration_terminated(context)
        return StreamingResult(
            final_state=state,
            windows_fired=epoch,
            predictions=predictions,
            listener_context=context,
            model_updates=model_updates,
            metrics=metrics,
            late_records=late_records,
        )

    @staticmethod
    def _emergency(save_fn) -> None:
        """The preemption epilogue: commit the caller's snapshot payload
        synchronously and exit cleanly.  Never returns —
        :func:`~flink_ml_tpu.fault.guard.emergency_save` raises
        :class:`~flink_ml_tpu.fault.guard.Preempted` once the save
        commits, and the run's ``finally`` drains on the way out."""
        from flink_ml_tpu.fault import guard

        guard.emergency_save(save_fn)

    # -- snapshot/restore -----------------------------------------------------

    def _snapshot(self, checkpoint, state, epoch, watermark, windows_cols,
                  pending, late_records, consumed, consumed_train,
                  consumed_pred, train_schema, pred_schema):
        """Persist a consistent cut of the stream computation: everything
        needed to continue as if never killed.

        The payload is COLUMNAR (VERDICT r4 #2): window/pending buffers ride
        the checkpoint npz as arrays — the snapshot path does no per-row
        work for array-backed columns, so the vectorized span driver stays
        vectorized with checkpointing on.  ``windows_cols`` maps window end
        -> ``(n_rows, cols)``; ``pending`` is ``(ts_array, cols)`` or None.
        The cut is recorded both as a merged-record count (``consumed``, the
        per-record loop's skip) and per-source counts (``consumed_train`` /
        ``consumed_pred``, the span driver's skip) — a span boundary is a
        prefix of the deterministic (ts, kind) merge, so the two describe
        the same cut and either driver can resume either's snapshot.
        """
        from flink_ml_tpu.iteration.checkpoint import (
            prune_checkpoints,
            save_checkpoint,
        )
        from flink_ml_tpu.utils.persistence import encode_row

        aux: dict = {}
        windows_meta = {}
        for end, (n, cols) in windows_cols.items():
            windows_meta[str(end)] = {
                "n": int(n),
                "cols": _encode_buffer_cols(
                    f"w{end}", cols, train_schema, aux
                ),
            }
        pending_meta = None
        if pending is not None and pred_schema is not None:
            ts_arr, cols = pending
            if len(ts_arr):
                aux["__pending_ts__"] = np.asarray(ts_arr, np.int64)
                pending_meta = {
                    "n": int(len(ts_arr)),
                    "cols": _encode_buffer_cols("p", cols, pred_schema, aux),
                }
        meta = {
            "stream": {
                "watermark": watermark,
                "consumed": int(consumed),
                "consumed_train": int(consumed_train),
                "consumed_pred": int(consumed_pred),
                "windows": windows_meta,
                "pending": pending_meta,
                # the side output is reported exactly once (at stream end),
                # so pre-cut lates must ride the snapshot; served
                # predictions / model history are NOT carried — they were
                # already emitted downstream (see module docstring)
                "late": [
                    [ts, encode_row(r, train_schema)] for ts, r in late_records
                ],
            }
        }
        save_checkpoint(
            checkpoint.directory, epoch - 1, state, meta=meta, aux=aux
        )
        prune_checkpoints(checkpoint.directory, checkpoint.keep)

    def _load_snapshot(self, checkpoint, like_state, train_schema,
                       pred_schema):
        """Latest snapshot as a columnar dict, or None.  Keys: ``state``,
        ``epoch``, ``watermark``, ``windows`` (end -> (n, cols)),
        ``pending`` ((ts, cols) or None), ``late``, ``consumed``,
        ``consumed_train``, ``consumed_pred``."""
        from flink_ml_tpu.iteration.checkpoint import (
            latest_checkpoint,
            load_aux,
            load_checkpoint,
        )
        from flink_ml_tpu.utils.persistence import decode_row

        latest = latest_checkpoint(checkpoint.directory)
        if latest is None:
            return None
        state, meta = load_checkpoint(latest, like=like_state)
        stream = meta.get("stream", {})
        if "consumed_train" not in stream:
            raise ValueError(
                f"streaming snapshot {latest} predates the columnar "
                "snapshot format and cannot be resumed; delete the "
                "checkpoint directory to start fresh"
            )
        aux = load_aux(latest)
        windows = {}
        for end_s, w in stream.get("windows", {}).items():
            end = int(end_s)
            windows[end] = (
                int(w["n"]),
                _decode_buffer_cols(f"w{end}", w["cols"], train_schema, aux),
            )
        pending = None
        pm = stream.get("pending")
        if pm is not None and pred_schema is not None:
            pending = (
                np.asarray(aux["__pending_ts__"], np.int64),
                _decode_buffer_cols("p", pm["cols"], pred_schema, aux),
            )
        late = [
            (int(ts), decode_row(r, train_schema))
            for ts, r in stream.get("late", [])
        ]
        return {
            "state": state,
            "epoch": int(meta["epoch"]) + 1,
            "watermark": stream.get("watermark"),
            "windows": windows,
            "pending": pending,
            "late": late,
            "consumed": int(stream.get("consumed", 0)),
            "consumed_train": int(stream["consumed_train"]),
            "consumed_pred": int(stream.get("consumed_pred", 0)),
        }


def iterate_unbounded(
    initial_state: Any,
    training_source: UnboundedSource,
    update: Callable[[Any, Table, int], Any],
    window_ms: int = 5000,
    keep_model_history: bool = False,
    prediction_flush_rows: int = 8192,
    allowed_lateness_ms: int = 0,
    **run_kwargs,
) -> StreamingResult:
    """Functional entry point (Iterations.iterateUnboundedStreams analog)."""
    driver = StreamingDriver(
        window_ms,
        keep_model_history=keep_model_history,
        prediction_flush_rows=prediction_flush_rows,
        allowed_lateness_ms=allowed_lateness_ms,
    )
    return driver.run(initial_state, training_source, update, **run_kwargs)
