"""Unbounded iteration — the streaming mini-batch driver.

Implements the reference's unbounded topology (Iterations.iterateUnboundedStreams
spec, Iterations.java:87-90, and the IncrementalLearningSkeleton shape,
:61-83): a training stream is cut into event-time tumbling windows; each fired
window updates the model (PartialModelBuilder:161-174); a concurrent
prediction stream is served by the *freshest* model (Predictor CoMap:182-211).

TPU-first realization: the driver merges the timestamped streams
deterministically on the host, fires windows when the watermark (max event
time seen) passes the window end, and batches all prediction records that fall
between two model updates into one device call — behaviorally identical to
per-record CoMap (every record sees exactly the model that was current at its
event time) but executed as batched XLA instead of a per-record hot loop.

Epoch accounting: window N's model update is epoch N; listeners receive epoch
watermarks exactly as in the bounded runtime.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, List, Optional, Sequence, Tuple

from flink_ml_tpu.iteration.listener import IterationListener, ListenerContext
from flink_ml_tpu.table.table import Table
from flink_ml_tpu.table.sources import UnboundedSource


@dataclass
class StreamingResult:
    final_state: Any
    windows_fired: int
    predictions: List[Tuple[int, Any]]  # (event_time, predicted value) per record
    listener_context: ListenerContext
    model_updates: List[Tuple[int, Any]] = field(default_factory=list)  # (window_end, state)
    #: per-window StepMetrics (SURVEY §5.5): wall time + rows per fired window
    metrics: Any = None


class StreamingDriver:
    """Event-time tumbling-window trainer with a concurrent prediction path.

    ``update(state, window_table, epoch) -> state`` fires per completed window
    (the PartialModelBuilder role).  ``predict(state, batch_table) ->
    sequence`` serves the prediction stream with the current model (the
    Predictor role); it may return any per-row sequence (list/array).
    """

    def __init__(
        self,
        window_ms: int,
        keep_model_history: bool = False,
        prediction_flush_rows: int = 8192,
    ):
        if window_ms <= 0:
            raise ValueError("window_ms must be positive")
        self.window_ms = int(window_ms)
        self.keep_model_history = keep_model_history
        # predictions sharing one model version can flush early in batches of
        # this size — bounds prediction latency on long-running streams
        self.prediction_flush_rows = prediction_flush_rows

    def run(
        self,
        initial_state: Any,
        training_source: UnboundedSource,
        update: Callable[[Any, Table, int], Any],
        prediction_source: Optional[UnboundedSource] = None,
        predict: Optional[Callable[[Any, Table], Sequence]] = None,
        listeners: Sequence[IterationListener] = (),
        max_windows: Optional[int] = None,
    ) -> StreamingResult:
        if (prediction_source is None) != (predict is None):
            raise ValueError("prediction_source and predict must be given together")

        from flink_ml_tpu.utils.metrics import StepMetrics

        context = ListenerContext()
        state = initial_state
        window_ms = self.window_ms
        train_schema = training_source.schema()
        metrics = StepMetrics("stream_train")

        # merge the two timestamped streams; training sorts before prediction
        # at equal timestamps so a model update at time T serves a prediction
        # at time T (matching connect() delivering the model first)
        TRAIN, PREDICT = 0, 1
        streams: List[Iterator] = [
            ((ts, TRAIN, row) for ts, row in training_source.stream())
        ]
        if prediction_source is not None:
            streams.append(((ts, PREDICT, row) for ts, row in prediction_source.stream()))
        merged = heapq.merge(*streams, key=lambda e: (e[0], e[1]))

        window_rows: List[Tuple] = []
        window_end: Optional[int] = None  # current window is [window_end-w, window_end)
        pending_predictions: List[Tuple[int, Tuple]] = []
        predictions: List[Tuple[int, Any]] = []
        model_updates: List[Tuple[int, Any]] = []
        epoch = 0
        stopped = False

        def flush_predictions():
            if not pending_predictions or predict is None:
                return
            batch = Table.from_rows(
                [row for _, row in pending_predictions], prediction_source.schema()
            )
            outs = list(predict(state, batch))
            if len(outs) != len(pending_predictions):
                raise ValueError(
                    f"predict returned {len(outs)} values for a batch of "
                    f"{len(pending_predictions)} rows"
                )
            for (ts, _), out in zip(pending_predictions, outs):
                predictions.append((ts, out))
            pending_predictions.clear()

        def fire_window(end_ts: int):
            nonlocal state, epoch, stopped
            # predictions timestamped before this window's close see the old model
            flush_predictions()
            metrics.start_step()
            n_rows = len(window_rows)
            table = Table.from_rows(window_rows, train_schema)
            window_rows.clear()
            state = update(state, table, epoch)
            metrics.end_step(samples=n_rows, window_end=end_ts)
            if self.keep_model_history:
                model_updates.append((end_ts, state))
            for listener in listeners:
                listener.on_epoch_watermark_incremented(epoch, context)
            epoch += 1
            if max_windows is not None and epoch >= max_windows:
                stopped = True

        for ts, kind, row in merged:
            if window_end is None:
                window_end = (ts // window_ms + 1) * window_ms
            # the watermark (= ts, streams are time-ordered) may close windows
            while ts >= window_end and not stopped:
                if window_rows:
                    fire_window(window_end)
                # empty window: no model update, the watermark still advances
                window_end += window_ms
            if stopped:
                break
            if kind == TRAIN:
                window_rows.append(tuple(row))
            else:
                pending_predictions.append((ts, tuple(row)))
                if len(pending_predictions) >= self.prediction_flush_rows:
                    flush_predictions()

        # end of streams: fire the final partial window, then flush predictions
        if not stopped and window_rows:
            fire_window(window_end if window_end is not None else window_ms)
        flush_predictions()

        for listener in listeners:
            listener.on_iteration_terminated(context)
        return StreamingResult(
            final_state=state,
            windows_fired=epoch,
            predictions=predictions,
            listener_context=context,
            model_updates=model_updates,
            metrics=metrics,
        )


def iterate_unbounded(
    initial_state: Any,
    training_source: UnboundedSource,
    update: Callable[[Any, Table, int], Any],
    window_ms: int = 5000,
    keep_model_history: bool = False,
    prediction_flush_rows: int = 8192,
    **run_kwargs,
) -> StreamingResult:
    """Functional entry point (Iterations.iterateUnboundedStreams analog)."""
    driver = StreamingDriver(
        window_ms,
        keep_model_history=keep_model_history,
        prediction_flush_rows=prediction_flush_rows,
    )
    return driver.run(initial_state, training_source, update, **run_kwargs)
