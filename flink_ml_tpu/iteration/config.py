"""Iteration configuration — IterationConfig.java parity plus runtime knobs."""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional


class OperatorLifeCycle(enum.Enum):
    """IterationConfig.OperatorLifeCycle (IterationConfig.java:54-61).

    ALL_ROUND: body state persists across epochs (operators live the whole
    iteration).  PER_ROUND: the body is re-created every epoch (the reference
    re-creates the per-round subgraph, IterationBody.forEachRound).
    """

    ALL_ROUND = "all_round"
    PER_ROUND = "per_round"


@dataclass
class IterationConfig:
    operator_life_cycle: OperatorLifeCycle = OperatorLifeCycle.ALL_ROUND
    # Safety bound on epochs; None = run until a termination condition fires.
    max_epochs: Optional[int] = None

    @staticmethod
    def new_builder() -> "IterationConfigBuilder":
        return IterationConfigBuilder()


class IterationConfigBuilder:
    """Fluent builder (IterationConfig.java:32-50)."""

    def __init__(self) -> None:
        self._life_cycle = OperatorLifeCycle.ALL_ROUND
        self._max_epochs: Optional[int] = None

    def set_operator_life_cycle(self, lc: OperatorLifeCycle) -> "IterationConfigBuilder":
        self._life_cycle = lc
        return self

    def set_max_epochs(self, n: Optional[int]) -> "IterationConfigBuilder":
        self._max_epochs = n
        return self

    def build(self) -> IterationConfig:
        return IterationConfig(self._life_cycle, self._max_epochs)
