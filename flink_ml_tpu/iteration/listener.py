"""IterationListener — per-epoch progress callbacks (IterationListener.java).

``on_epoch_watermark_incremented(epoch_watermark, context)`` fires when epoch
``epoch_watermark`` has fully finished across the (virtual) parallel subtasks
— on TPU the aligned progress barrier degenerates to the completion of the
epoch's device step (the ICI collective is the barrier).  The final call uses
the terminating epoch, then ``on_iteration_terminated(context)`` fires once
(IterationListener.java:40-59).  ``context.output(tag, value)`` collects
side outputs per epoch.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, List


class ListenerContext:
    """Side-output collector handed to listener callbacks (Context:65-73)."""

    def __init__(self) -> None:
        self._outputs: Dict[str, List[Any]] = defaultdict(list)

    def output(self, tag: str, value: Any) -> None:
        self._outputs[tag].append(value)

    def get_outputs(self, tag: str) -> List[Any]:
        return list(self._outputs[tag])


class IterationListener:
    def on_epoch_watermark_incremented(
        self, epoch_watermark: int, context: ListenerContext
    ) -> None:  # pragma: no cover - interface default
        pass

    def on_iteration_terminated(self, context: ListenerContext) -> None:  # pragma: no cover
        pass
