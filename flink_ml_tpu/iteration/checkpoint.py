"""Training checkpoint/resume — the piece the reference left to Flink.

The reference delegates failure recovery entirely to Flink's checkpoint
machinery (SURVEY.md §5.3: no ml-module code participates); the build
decision is periodic param snapshots to host storage plus deterministic
data-order replay.  A checkpoint is one ``.npz`` of the parameter pytree's
leaves plus a JSON sidecar (epoch, losses so far, user metadata); resume
loads the latest epoch and replays the remaining epochs — with the fixed
packing order and seeds, an interrupted-and-resumed run produces the same
parameters as an uninterrupted one (tested).
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from flink_ml_tpu.fault.injection import maybe_fail
from flink_ml_tpu.fault.retry import with_retry

_META_SUFFIX = ".meta.json"
_DATA_SUFFIX = ".npz"
_NAME_RE = re.compile(r"^epoch_(\d+)\.npz$")


#: npz key prefix for auxiliary arrays riding in the snapshot alongside the
#: parameter leaves (the streaming driver's columnar window/pending buffers)
_AUX_PREFIX = "aux:"


@dataclass
class CheckpointConfig:
    """Where and how often to snapshot (every_n_epochs counts completed epochs).

    ``min_interval_s`` additionally rate-limits snapshots by wall time —
    Flink's checkpoint cadence is an interval, not a per-window count
    (`/root/reference/pom.xml:396-401` randomizes interval-driven
    checkpointing in tests); with the default 0.0 every eligible epoch
    snapshots."""

    directory: str
    every_n_epochs: int = 1
    keep: int = 3  # retain at most this many snapshots (oldest pruned)
    min_interval_s: float = 0.0


def save_checkpoint(directory: str, epoch: int, params, meta: Optional[Dict] = None,
                    aux: Optional[Dict[str, np.ndarray]] = None) -> str:
    """Snapshot a parameter pytree after ``epoch`` completed.

    Writes are atomic (temp file + rename) and ordered DATA FIRST, meta
    last as the commit record: a crash mid-save leaves the previous
    snapshot intact and never a half-written latest, and a crash between
    the two renames leaves an npz whose sidecar is missing — still a
    complete, loadable snapshot (``load_checkpoint`` derives the epoch
    from the filename; only the loss-history nicety is lost).  The old
    meta-first order instead left an orphan SIDECAR describing data that
    never existed, which nothing ever cleaned up
    (:func:`latest_checkpoint` now sweeps those).  Both writes ride the
    transient-failure retry policy (``fault.retry``): checkpoint I/O on
    network filesystems blips, and losing a snapshot to one EIO turns a
    recoverable preemption into a from-scratch rerun.  ``aux`` arrays are
    stored in the same npz under a reserved prefix (one atomic commit for
    params + buffers) and read back with :func:`load_aux`.
    """
    os.makedirs(directory, exist_ok=True)
    leaves = [np.asarray(x) for x in jax.tree_util.tree_leaves(params)]
    path = os.path.join(directory, f"epoch_{epoch}{_DATA_SUFFIX}")

    def write_data():
        maybe_fail("ckpt.save")
        data_tmp = path + ".tmp"
        with open(data_tmp, "wb") as f:
            np.savez(f, *leaves,
                     **{_AUX_PREFIX + k: np.asarray(v)
                        for k, v in (aux or {}).items()})
        os.replace(data_tmp, path)

    def write_meta():
        meta_tmp = path + _META_SUFFIX + ".tmp"
        with open(meta_tmp, "w") as f:
            json.dump({"epoch": epoch, **(meta or {})}, f)
        os.replace(meta_tmp, path + _META_SUFFIX)

    with_retry(write_data, "ckpt.save")
    with_retry(write_meta, "ckpt.save")
    return path


def load_checkpoint(path: str, like) -> Tuple[Any, Dict]:
    """Load a snapshot back into the structure AND leaf dtypes of ``like``.

    The dtype restore is what makes resume BIT-identical: under x64 the
    save path fetches f32 training params as f64 (exact), and resuming
    with f64 leaves would re-run the remaining epochs in double precision
    — a run that never crashed computed them in f32.  Casting back to the
    dtype training uses (f64 -> f32 of an exactly-held f32 value is
    lossless) makes the resumed tail reproduce the uninterrupted run's
    arithmetic exactly."""
    with np.load(path) as data:
        leaves = [
            data[k] for k in data.files if not k.startswith(_AUX_PREFIX)
        ]
    like_leaves = jax.tree_util.tree_leaves(like)
    if len(like_leaves) != len(leaves):
        raise ValueError(
            f"checkpoint {path} has {len(leaves)} leaves, expected "
            f"{len(like_leaves)}"
        )
    leaves = [
        np.asarray(leaf, dtype=ref.dtype)
        if getattr(ref, "dtype", None) is not None else leaf
        for leaf, ref in zip(leaves, like_leaves)
    ]
    treedef = jax.tree_util.tree_structure(like)
    params = jax.tree_util.tree_unflatten(treedef, leaves)
    meta_path = path + _META_SUFFIX
    meta: Dict = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    if "epoch" not in meta:
        # sidecar lost/absent: the epoch is authoritative in the filename
        m = _NAME_RE.match(os.path.basename(path))
        if m:
            meta["epoch"] = int(m.group(1))
    return params, meta


def load_aux(path: str) -> Dict[str, np.ndarray]:
    """Auxiliary arrays stored with :func:`save_checkpoint`'s ``aux``."""
    with np.load(path, allow_pickle=False) as data:
        return {
            k[len(_AUX_PREFIX):]: data[k]
            for k in data.files if k.startswith(_AUX_PREFIX)
        }


def latest_checkpoint(directory: str) -> Optional[str]:
    """Path of the highest-epoch snapshot, or None.

    The scan also sweeps crash leftovers: ``.tmp`` staging files and
    orphan ``.meta.json`` sidecars whose npz never committed (the
    meta-first write order of earlier versions could strand those; with
    the current data-first order they cannot recur, but directories
    written by older code — or crashed mid-save — still carry them).  An
    npz WITHOUT a sidecar is a valid committed snapshot and is kept."""
    if not os.path.isdir(directory):
        return None
    names = os.listdir(directory)
    present = set(names)
    for name in names:
        if name.endswith(".tmp"):
            _remove_quiet(os.path.join(directory, name))
        elif name.endswith(_META_SUFFIX):
            data_name = name[: -len(_META_SUFFIX)]
            if _NAME_RE.match(data_name) and data_name not in present:
                # orphan sidecar: meta committed but its data never did
                _remove_quiet(os.path.join(directory, name))
    best_epoch, best = -1, None
    for name in names:
        m = _NAME_RE.match(name)
        if m and int(m.group(1)) > best_epoch:
            best_epoch = int(m.group(1))
            best = os.path.join(directory, name)
    return best


def _remove_quiet(path: str) -> None:
    try:
        os.remove(path)
    except OSError:
        pass  # concurrent sweep/prune; the file being gone is the goal


def checkpoint_path_for_epoch(directory: str, epoch: int) -> str:
    """Path of a specific epoch's snapshot (existence not checked)."""
    return os.path.join(directory, f"epoch_{epoch}{_DATA_SUFFIX}")


def agreed_latest_checkpoint(directory: str) -> Optional[str]:
    """Multi-process-safe :func:`latest_checkpoint`: the COMMON resume
    point across all processes.

    Each process snapshots independently (Flink's coordinated checkpoints
    have a JobManager to align them; here alignment happens at restore): a
    worker killed mid-save leaves the fleet with different newest epochs,
    and resuming each process from its own latest would desynchronize the
    lockstep collective schedule — a silent divergence or a deadlock.  The
    processes agree on the MINIMUM available newest epoch (one collective)
    and every process loads exactly that snapshot; ``keep`` > 1 (the
    default) retains the window that makes the agreed epoch available on
    the processes that had already moved ahead.  Single-process reduces to
    :func:`latest_checkpoint`.
    """
    latest = latest_checkpoint(directory)
    if jax.process_count() <= 1:
        return latest
    from flink_ml_tpu.parallel.mesh import agree_max

    local_epoch = -1
    if latest is not None:
        m = _NAME_RE.match(os.path.basename(latest))
        if m:
            local_epoch = int(m.group(1))
    # agree on the minimum via max of negatives
    (neg_min,) = agree_max(-local_epoch)
    agreed = -int(neg_min)
    if agreed < 0:
        return None
    path = checkpoint_path_for_epoch(directory, agreed)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"coordinated resume needs epoch {agreed} (the fleet minimum) "
            f"but {path} is missing — it was pruned; raise "
            "CheckpointConfig.keep so slower processes' epochs stay "
            "available"
        )
    return path


def prune_checkpoints(directory: str, keep: int) -> None:
    """Delete all but the newest ``keep`` snapshots."""
    if keep <= 0 or not os.path.isdir(directory):
        return
    found: List[Tuple[int, str]] = []
    for name in os.listdir(directory):
        m = _NAME_RE.match(name)
        if m:
            found.append((int(m.group(1)), os.path.join(directory, name)))
    for _, path in sorted(found)[:-keep]:
        os.remove(path)
        meta = path + _META_SUFFIX
        if os.path.exists(meta):
            os.remove(meta)
