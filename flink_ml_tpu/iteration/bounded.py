"""Bounded iteration runtime — implements Iterations.iterateBoundedStreamsUntilTermination.

Semantics implemented from the reference's spec (the entry point itself is
``return null``, Iterations.java:107-113):

* **Inputs** (Iterations.java javadoc): ``variables`` — the initial values of
  the feedback state; ``data`` — bounded inputs, each either *replayed* every
  epoch or *streamed once* in epoch 0 (ReplayableDataStreamList.java:40-81).
* **Epoch algebra** (Iterations.java:38-49): the initial variable values carry
  epoch 0; each pass of the body that emits feedback increments the epoch.
  Epoch N's watermark fires when the body finishes pass N — listeners receive
  it via ``on_epoch_watermark_incremented`` (the per-round barrier).
* **Termination** (Iterations.java:93-96; IterationBodyResult.java:44-48):
  the iteration stops when (a) the body emits no feedback (None), (b) the
  termination-criteria output is empty for a round, or (c) ``max_epochs`` is
  reached.
* **Lifecycles** (IterationConfig.java:54-61): ALL_ROUND calls one body object
  every epoch (it may keep state); PER_ROUND re-creates the body from a
  factory each epoch.

The body is a host-level protocol; algorithm hot loops use
:mod:`flink_ml_tpu.iteration.device` (one epoch == one compiled device step)
and surface through this runtime for listener/termination semantics.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

from flink_ml_tpu import obs
from flink_ml_tpu.iteration.config import IterationConfig, OperatorLifeCycle
from flink_ml_tpu.iteration.listener import IterationListener, ListenerContext
from flink_ml_tpu.table.table import Table


@dataclass
class ReplayableInputs:
    """Which bounded inputs are replayed each epoch vs streamed once
    (ReplayableDataStreamList.java:40-44)."""

    replayed: Dict[str, Any] = field(default_factory=dict)
    non_replayed: Dict[str, Any] = field(default_factory=dict)

    @staticmethod
    def replay(**inputs) -> "ReplayableInputs":
        return ReplayableInputs(replayed=dict(inputs))

    @staticmethod
    def no_replay(**inputs) -> "ReplayableInputs":
        return ReplayableInputs(non_replayed=dict(inputs))

    def and_replay(self, **inputs) -> "ReplayableInputs":
        self.replayed.update(inputs)
        return self

    def and_no_replay(self, **inputs) -> "ReplayableInputs":
        self.non_replayed.update(inputs)
        return self


@dataclass
class IterationBodyResult:
    """Body output per epoch (IterationBodyResult.java:30-59).

    ``feedback``: next epoch's variable values; None signals natural end.
    ``outputs``: values surfaced out of the iteration (last one wins per key,
    or accumulate — the runtime collects them all, keyed by epoch).
    ``termination_criteria``: when given, an empty value (len()==0 or falsy)
    terminates the iteration after this epoch.
    """

    feedback: Optional[Any] = None
    outputs: Optional[Dict[str, Any]] = None
    termination_criteria: Optional[Any] = None


BodyFn = Callable[[Any, Dict[str, Any], int], IterationBodyResult]


@dataclass
class IterationResult:
    final_variables: Any
    epochs_run: int
    outputs_per_epoch: List[Dict[str, Any]]
    listener_context: ListenerContext

    def last_output(self, key: str, default=None):
        for outputs in reversed(self.outputs_per_epoch):
            if outputs and key in outputs:
                return outputs[key]
        return default


def _criteria_empty(criteria: Any) -> bool:
    if criteria is None:
        return False  # absent criteria stream never terminates
    if isinstance(criteria, Table):
        return criteria.num_rows() == 0
    try:
        return len(criteria) == 0
    except TypeError:
        return not bool(criteria)


def iterate_bounded(
    variables: Any,
    data: Optional[ReplayableInputs],
    body: Union[BodyFn, Callable[[], BodyFn]],
    config: Optional[IterationConfig] = None,
    listeners: Sequence[IterationListener] = (),
) -> IterationResult:
    """Run the bounded iteration to termination.

    ``body(variables, inputs, epoch)`` receives the current variable values,
    a dict of inputs (replayed inputs every epoch; non-replayed only in epoch
    0), and the epoch number; it returns an :class:`IterationBodyResult`.
    Under PER_ROUND, ``body`` must be a zero-arg factory returning a fresh
    body callable each epoch.
    """
    config = config or IterationConfig()
    data = data or ReplayableInputs()
    context = ListenerContext()
    per_round = config.operator_life_cycle == OperatorLifeCycle.PER_ROUND
    if per_round:
        body_factory = body
    else:
        body_fn = body

    outputs_per_epoch: List[Dict[str, Any]] = []
    epoch = 0
    current = variables
    while True:
        if config.max_epochs is not None and epoch >= config.max_epochs:
            break
        inputs = dict(data.replayed)
        if epoch == 0:
            inputs.update(data.non_replayed)
        fn = body_factory() if per_round else body_fn
        result = fn(current, inputs, epoch)
        if not isinstance(result, IterationBodyResult):
            raise TypeError("iteration body must return IterationBodyResult")
        outputs_per_epoch.append(result.outputs or {})
        obs.counter_add("iteration.bounded.epochs")

        # the epoch watermark for this round: all work of `epoch` is complete
        for listener in listeners:
            listener.on_epoch_watermark_incremented(epoch, context)

        if result.feedback is None:
            epoch += 1
            break
        current = result.feedback
        epoch += 1
        if _criteria_empty(result.termination_criteria):
            break

    for listener in listeners:
        listener.on_iteration_terminated(context)
    return IterationResult(
        final_variables=current,
        epochs_run=epoch,
        outputs_per_epoch=outputs_per_epoch,
        listener_context=context,
    )
