"""Device-side epoch loops — the compiled fast path of the iteration runtime.

One epoch ≡ one step of a compiled loop (SURVEY.md §2.2 build implication).
Two shapes:

* :func:`train_epochs` — fixed epoch count: ``lax.scan``/``fori_loop`` over the
  epoch body, entirely on device; the epoch watermark degenerates to the
  implicit barrier of the in-step collective.
* :func:`train_until` — convergence-tested: ``lax.while_loop`` whose predicate
  evaluates the termination criterion on device (e.g. parameter delta below
  tol), realizing the reference's "termination-criteria stream empty in a
  round" (IterationBodyResult.java:44-48) as a device-friendly scalar test —
  the criteria count is a psum'd scalar; 0 means stop.

Both take ``step(state, epoch) -> state`` functions that are jit-traceable;
data must already live in the closure or the state (replayed inputs are
device-resident across epochs — no host round-trips between rounds).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp


def train_epochs(
    step: Callable[[Any, jnp.ndarray], Any],
    state: Any,
    num_epochs: int,
    unroll: int = 1,
) -> Any:
    """Run ``step`` for a fixed number of epochs inside one compiled loop."""

    def body(carry, epoch):
        return step(carry, epoch), None

    final, _ = jax.lax.scan(body, state, jnp.arange(num_epochs), unroll=unroll)
    return final


def train_until(
    step: Callable[[Any, jnp.ndarray], Any],
    state: Any,
    should_continue: Callable[[Any, jnp.ndarray], jnp.ndarray],
    max_epochs: int,
) -> Tuple[Any, jnp.ndarray]:
    """Run ``step`` until ``should_continue(state, epoch)`` is False on device.

    Returns (final_state, epochs_run).  The whole loop is one XLA while_loop:
    no host sync per epoch, convergence is read back exactly once at the end.
    """

    def cond(carry):
        state, epoch = carry
        return jnp.logical_and(epoch < max_epochs, should_continue(state, epoch))

    def body(carry):
        state, epoch = carry
        return step(state, epoch), epoch + 1

    final_state, epochs = jax.lax.while_loop(cond, body, (state, jnp.asarray(0)))
    return final_state, epochs
